"""Benchmark suite: every BASELINE.json config, one JSON line each.

``bench.py`` is the driver-facing single-metric benchmark (the 8-shard
flagship); this suite covers the full config list for the record:

1. single-node linear regression (the collapsed demo pair);
2. 8-shard federated linear regression, psum-aggregated logp+grad;
3. hierarchical radon GLM, one shard per county group;
4. Lotka-Volterra ODE param estimation, [theta] -> [LL, dLL] per shard;
5. 64-shard federated logistic regression + a full NUTS posterior.

Plus three net-new configs with no reference or BASELINE analog:

6. T=4096 LGSSM logp+grad via the O(log T) parallel-in-time Kalman
   filter — baselined against the classic O(T) sequential scan filter
   measured in the same run (the parallel construction must beat the
   thing it replaces, else it is pointless);
7. a *compute-bound* config: 8-shard wide logistic regression with 64
   vectorized chains, so the hot op is a real (n, d) @ (d, chains)
   MXU matmul instead of a launch-bound matvec — baselined at 5% MFU
   (an eval rate below that means the chip is idling, whatever the
   evals/s says);
9. ChEES-HMC at 16 lockstep chains — baselined against the SAME run's
   NUTS min-ESS/s (the cross-chain sampler must beat tree-doubling in
   its intended many-chains regime);
10. federated exact GP, 8 shards x 256 points — the heaviest dense
    linear algebra in the package (batched 256x256 Cholesky +
    triangular solves per eval), baselined at 5% MFU like the other
    compute-bound config;
11. the HOST-federation lane: real gRPC + npwire round-trips/s against
    a spawned localhost worker — the surface that is the reference's
    entire hot path, baselined at its structural ~1 ms/call floor.
12. parallel tempering on a 16-sigma bimodal (the round-4 flagship
    sampler, previously unbenchmarked): RANK-NORMALIZED cold-chain
    min-ESS/s + per-chain mode-balance error, baselined against NUTS
    with overdispersed inits measured in the same run — NUTS provably
    cannot cross between modes, its rank-normalized ESS collapses when
    its chains disagree, and its per-chain balance error (~0.5: every
    chain stuck) is the negative control saying whose ESS to believe
    (nominal non-rank ESS is deliberately not the metric: a mode-stuck
    chain fakes it).

Every record carries ``flops_per_eval`` (XLA's exact cost-model count
of the compiled executable — flopcount.py), achieved ``flops_per_sec``,
and ``mfu`` with its basis, so no evals/s number is quotable without
its compute-utilization context (round-1 VERDICT: raw evals/s on a
few-kFLOP eval is launch overhead, not framework speed).

Each config measures sequential dependent logp+grad evals/s (the NUTS
consumption pattern, chained in one lax.scan, like bench.py); config 5
also reports end-to-end NUTS samples/s against an explicit driver-set
target.  Run: ``python bench_suite.py``.
"""

import json
import os
import sys
import time

from bench import (
    NORTH_STAR,
    MeasurementIntegrityError,
    make_chained,
    measure_rate,
    preflight,
)

# Driver-set explicit targets for the configs the north star does not
# cover (round-1 VERDICT: a null vs_baseline makes "fast enough"
# unfalsifiable).  Values are deliberately round and documented here —
# the point is an explicit pass/fail line, not a derivation.
NUTS_TARGET_SAMPLES_PER_SEC = 50.0  # 4x200 draws, warm executable, < 16 s
COMPUTE_BOUND_TARGET_MFU = 0.05  # below 5% MFU the chip is idling


def physics_gate(flops_per_eval, rate):
    """The shared mfu>1.5 physics gate: >150% of hardware peak means
    the MEASUREMENT, not the machine, is broken (first live capture: a
    degenerate chain recorded mfu=25685).  Raises the integrity type so
    callers can route to a fallback instead of misreading it as a
    backend failure."""
    from pytensor_federated_tpu.flopcount import mfu as _mfu_fields

    m = _mfu_fields(flops_per_eval, rate).get("mfu")
    if m is not None and m > 1.5:
        raise MeasurementIntegrityError(
            f"implausible mfu {m} — refusing to record a rate above "
            "hardware peak"
        )


def _rate(fn_flat, flat0, *, unroll=8, **sizing):
    # Same two-stage sizing as the driver metric (bench.measure_rate),
    # with lighter floors/targets so the suite stays quick.  One
    # compile per config (dynamic trip count serves all three stages).
    kw = dict(n_cal=500, floor=2_000, mid_wall=0.3, target_wall=1.0)
    kw.update(sizing)
    r, n, _wall = measure_rate(
        make_chained(fn_flat, unroll=unroll), flat0, **kw
    )
    return r, n


def _flat_fn(logp_fn, params):
    """Flat-vector value_and_grad of ``logp_fn`` at ``params``."""
    import jax
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(params)

    def fn(x):
        return jax.value_and_grad(lambda v: logp_fn(unravel(v)))(x)

    return fn, flat0


def _flat(model):
    return _flat_fn(model.logp, model.init_params())


# Module-level (multiprocessing-spawn needs a picklable target): one
# worker node serving the reference demo's logp+grad shape over the
# host lane (gRPC + npwire).
def _bench_serve_node(port):
    import logging

    import numpy as np

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    def compute(x):
        x = np.asarray(x)
        return [
            np.asarray(-np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    def compute_batch(requests):
        # Vectorized over the coalesced window: one numpy pass for K
        # requests — what the micro-batcher dispatches when a wire
        # batch frame (or concurrent RPCs) stack up (service/batching).
        xs = np.stack([np.asarray(r[0]) for r in requests])
        logps = -np.sum((xs - 3.0) ** 2, axis=1)
        grads = (-2.0 * (xs - 3.0)).astype(xs.dtype)
        return [
            [np.asarray(lp), g] for lp, g in zip(logps, grads)
        ]

    compute.batch = compute_batch

    from pytensor_federated_tpu.service import run_node

    # inline_compute: this compute is ~6 us of numpy — exactly the
    # documented fast-compute case where the executor handoff would
    # dominate (docs/performance.md "host lane budget").
    run_node(compute, "127.0.0.1", port, inline_compute=True)


def _bench_serve_tcp_gateway_node(port):
    """Config 18's pool replica: the quad compute over the raw TCP
    npwire lane (what the gateway fronts), thread-per-connection so
    the direct-dial control can hold hundreds of connections, with
    the vectorized ``.batch`` variant so coalesced gateway windows
    dispatch as one numpy pass."""
    import logging

    import numpy as np

    logging.basicConfig(level=logging.WARNING)

    def compute(x):
        x = np.asarray(x)
        return [
            np.asarray(-np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    def compute_batch(requests):
        xs = np.stack([np.asarray(r[0]) for r in requests])
        logps = -np.sum((xs - 3.0) ** 2, axis=1)
        grads = (-2.0 * (xs - 3.0)).astype(xs.dtype)
        return [[np.asarray(lp), g] for lp, g in zip(logps, grads)]

    compute.batch = compute_batch

    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    serve_tcp_once(compute, "127.0.0.1", port, concurrent=True)


def _bench_serve_slow_node(port, delay_s):
    """The DEGRADED pool member for config 13: same logp+grad shape,
    but every compute blocks the event loop for ``delay_s`` (inline +
    sleep, no vectorized variant) — the stand-in for a node that is
    wedged-ish/overloaded: serial, ~1/delay_s req/s, and its GetLoad
    replies queue behind the sleeps."""
    import logging
    import time as _time

    import numpy as np

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    def compute(x):
        _time.sleep(delay_s)
        x = np.asarray(x)
        return [
            np.asarray(-np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port, inline_compute=True)


def _bench_serve_degraded_node(port, delay_s):
    """Config 17's DEGRADED pool member: executor-mode service (not
    inline) with a single-worker default executor and a slow compute —
    concurrent RPCs decode promptly on the loop, then QUEUE behind the
    one busy worker, so the node's pftpu_server_queue_wait_seconds
    histogram (not just compute) carries the degradation.  That is the
    fleet-observability scenario: the collector must show WHERE the
    latency lives, and here it demonstrably lives in queue wait on
    this replica."""
    import asyncio
    import logging
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    def compute(x):
        _time.sleep(delay_s)
        x = np.asarray(x)
        return [
            np.asarray(-np.sum((x - 3.0) ** 2)),
            (-2.0 * (x - 3.0)).astype(x.dtype),
        ]

    async def main():
        from pytensor_federated_tpu.service import serve

        loop = asyncio.get_running_loop()
        loop.set_default_executor(ThreadPoolExecutor(max_workers=1))
        server = await serve(compute, "127.0.0.1", port, max_batch=1)
        await server.wait_for_termination()

    asyncio.run(main())


def _bench_serve_fed_node(port):
    """Config 14's node: the fed wire contract ``(p, x, y) ->
    [logp, grad_p, grad_x, grad_y]`` as pure numpy (no per-request jax
    dispatch), so both lanes measure transport+driver overhead, not
    node-side compute variance."""
    import logging

    import numpy as np

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    def compute(p, x, y):
        p = np.asarray(p)
        x = np.asarray(x)
        y = np.asarray(y)
        r = y - p[0] - p[1] * x
        return [
            np.asarray(-np.sum(r * r), np.float32),
            np.asarray([2.0 * np.sum(r), 2.0 * np.sum(r * x)], np.float32),
            (2.0 * p[1] * r).astype(np.float32),
            (-2.0 * r).astype(np.float32),
        ]

    from pytensor_federated_tpu.service import run_node

    run_node(compute, "127.0.0.1", port)


def _bench_serve_partition_leaves(ports):
    """Config 19's leaf nodes: ``(w, s) -> [logp, grad]`` with a WIDE
    gradient (``len(w)`` elements — the bandwidth-wall shape) and a
    per-shard pseudo-dataset derived from the scalar ``s``, so the
    request ships one parameter vector + one scalar and the REPLY
    carries the full gradient.  One subprocess serves several ports on
    threads (64 leaf processes would thrash a 2-core container; the
    parallelism under test is the DRIVER's fan-in, not leaf compute)."""
    import logging
    import threading as _threading

    import numpy as np

    logging.basicConfig(level=logging.WARNING)

    def compute(w, s):
        w = np.asarray(w)
        d = np.sin(np.arange(w.size) * (1.0 + float(np.asarray(s))))
        r = w - d
        return [np.asarray(-0.5 * np.sum(r * r)), -r]

    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    threads = [
        _threading.Thread(
            target=serve_tcp_once,
            args=(compute, "127.0.0.1", p),
            kwargs=dict(concurrent=True),
            daemon=True,
        )
        for p in ports
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _bench_serve_partition_mid(port, leaf_ports):
    """Config 19's mid-tier aggregator: forwards reduce windows to its
    leaf pool and ships ONE partial sum upstream
    (routing.make_aggregator_compute — the tree lane)."""
    import logging

    logging.basicConfig(level=logging.WARNING)

    from pytensor_federated_tpu.routing import (
        NodePool,
        PooledArraysClient,
        make_aggregator_compute,
    )
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    pool = NodePool(
        [("127.0.0.1", p) for p in leaf_ports], transport="tcp"
    )
    child = PooledArraysClient(pool)
    serve_tcp_once(
        make_aggregator_compute(child, window=8),
        "127.0.0.1",
        port,
        concurrent=True,
    )


def _free_ports(n):
    """Reserve-then-release n ephemeral localhost ports (the shared
    bind/close pattern configs 19/20 and tools/chaos_run.py use)."""
    import socket as _socket

    socks, ports = [], []
    for _ in range(n):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _bench_serve_ppl_node(port):
    """Config 20's replica: the ppl-compiled radon per-shard
    ``[logp, *grads]`` compute (ISSUE 15) — built from the SAME model
    definition the driver compiles (``ppl.radon``), so driver and
    node cannot drift."""
    import logging

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu import ppl
    from pytensor_federated_tpu.ppl.radon import make_radon_example
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    model, args, _ = make_radon_example(16, seed=12)
    compiled = ppl.compile(model, args)
    serve_tcp_once(
        compiled.node_compute(), "127.0.0.1", port, concurrent=True
    )


def _bench_serve_zero_control(ports):
    """Config 21's control replicas: the DRIVER-CENTRIC per-shard
    ``[logp, *grads]`` compute for the radon-64 model — the full
    gradient crosses the wire home every window.  One subprocess
    serves several ports on threads (config-19 leaf pattern)."""
    import logging
    import threading as _threading

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu import ppl
    from pytensor_federated_tpu.ppl.radon import make_radon_example
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    model, args, _ = make_radon_example(64, mean_obs=8, seed=21)
    compiled = ppl.compile(model, args)
    compute = compiled.node_compute()
    threads = [
        _threading.Thread(
            target=serve_tcp_once,
            args=(compute, "127.0.0.1", p),
            kwargs=dict(concurrent=True),
            daemon=True,
        )
        for p in ports
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _bench_serve_zero_owner(ports, store_root):
    """Config 21's OWNER replicas (ISSUE 16): the radon-64
    sharded-optimizer update compute over a shared checkpoint store —
    the node differentiates the same neg-ELBO the driver lane would,
    applies adam on its owned shard, and replies only
    ``[loss, update_slice]``.  Several ports per subprocess; one
    compute instance serves them all (shards own disjoint partitions,
    so checkpoint files never collide)."""
    import logging
    import threading as _threading

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu import ppl
    from pytensor_federated_tpu.optim import ShardStore
    from pytensor_federated_tpu.ppl.radon import make_radon_example
    from pytensor_federated_tpu.ppl.svi import make_sharded_update_compute
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    model, args, _ = make_radon_example(64, mean_obs=8, seed=21)
    compiled = ppl.compile(model, args)
    compute = make_sharded_update_compute(
        compiled, ShardStore(store_root), learning_rate=5e-2, n_mc=2
    )
    threads = [
        _threading.Thread(
            target=serve_tcp_once,
            args=(compute, "127.0.0.1", p),
            kwargs=dict(concurrent=True),
            daemon=True,
        )
        for p in ports
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _bench_serve_linalg_node(port, n, b):
    """Config 23's block-store replica (ISSUE 19): the stateful
    blocked-linalg compute (tiles resident node-side, panel ops by
    block id) over TCP — a spawn target, so it must live at module
    level."""
    import logging

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    from pytensor_federated_tpu.linalg import (
        BlockLayout,
        make_block_store_compute,
    )
    from pytensor_federated_tpu.service.tcp import serve_tcp_once

    lay = BlockLayout(n, n, b, b)
    serve_tcp_once(
        make_block_store_compute(lay), "127.0.0.1", port, concurrent=True
    )


def _bench_serve_shm_node(port, use_suffstats, transport="shm"):
    """Config 15's shm node: the C++ node's EXACT Gaussian linreg
    logp+grad contract ``(a, b, sigma, x, y) -> [logp, g_a, g_b]`` in
    numpy.  With ``use_suffstats`` the node memoizes the six data
    reductions (n, Σx, Σy, Σx², Σy², Σxy) PER RESIDENT DATA BUFFER —
    keyed on the arena address of the zero-copy request views, which
    the pinned-slot protocol keeps stable for the connection's
    lifetime — so repeated-data calls collapse to O(1) scalar math.
    That caching is the shm lane's structural capability: a byte-wire
    peer re-decodes fresh bytes every call and has no data identity to
    key on."""
    import logging

    import numpy as np

    logging.basicConfig(level=logging.WARNING)
    from pytensor_federated_tpu.utils import force_cpu_backend

    force_cpu_backend()

    memo = {}

    def stats_for(x, y):
        # The address alone is only stable for PINNED slots; a
        # recycled transient slot can reuse an address for different
        # data, so the key carries a content fingerprint (head/mid/
        # tail samples) — and the cross-lane equality gate backstops.
        def fp(a):
            n = len(a)
            return (
                a[0], a[n // 2], a[n - 1],
                float(a[: min(8, n)].sum()),
            ) if n else ()

        key = (
            x.__array_interface__["data"][0],
            y.__array_interface__["data"][0],
            x.nbytes,
            fp(x),
            fp(y),
        )
        s = memo.get(key)
        if s is None:
            s = (
                float(len(x)),
                float(np.sum(x)),
                float(np.sum(y)),
                float(np.dot(x, x)),
                float(np.dot(y, y)),
                float(np.dot(x, y)),
            )
            memo[key] = s
        return s

    def compute(a, b, sigma, x, y):
        a = float(np.asarray(a))
        b = float(np.asarray(b))
        sigma = float(np.asarray(sigma))
        x = np.asarray(x)
        y = np.asarray(y)
        inv_var = 1.0 / (sigma * sigma)
        log_norm = -np.log(sigma) - 0.5 * np.log(2.0 * np.pi)
        if use_suffstats:
            n, sx, sy, sxx, syy, sxy = stats_for(x, y)
            ss_resid = (
                syy - 2.0 * a * sy - 2.0 * b * sxy
                + 2.0 * a * b * sx + a * a * n + b * b * sxx
            )
            s_resid = sy - a * n - b * sx
            s_resid_x = sxy - a * sx - b * sxx
        else:
            resid = y - (a + b * x)
            n = float(len(x))
            ss_resid = float(np.dot(resid, resid))
            s_resid = float(np.sum(resid))
            s_resid_x = float(np.dot(resid, x))
        return [
            np.asarray(-0.5 * ss_resid * inv_var + n * log_norm),
            np.asarray(s_resid * inv_var),
            np.asarray(s_resid_x * inv_var),
        ]

    if transport == "ring":
        from pytensor_federated_tpu.service.ring import serve_ring

        serve_ring(compute, "127.0.0.1", port)
    else:
        from pytensor_federated_tpu.service.shm import serve_shm

        serve_shm(compute, "127.0.0.1", port)


def main():
    preflight()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytensor_federated_tpu.flopcount import mfu as mfu_fields
    from pytensor_federated_tpu.flopcount import xla_flops_per_eval
    from pytensor_federated_tpu.models.glm import (
        HierarchicalRadonGLM,
        generate_radon_data,
    )
    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )
    from pytensor_federated_tpu.models.logistic import (
        FederatedLogisticRegression,
        generate_logistic_data,
    )
    from pytensor_federated_tpu.models.ode import make_lv_model

    results = []

    def record(
        config,
        value,
        unit="evals/s",
        baseline_rate=NORTH_STAR,
        baseline_desc="north star 50k evals/s (BASELINE.json)",
        flops_per_eval=None,
        **extra,
    ):
        line = {
            "config": config,
            "value": round(value, 1),
            "unit": unit,
            "vs_baseline": (
                round(value / baseline_rate, 3) if baseline_rate else None
            ),
            "baseline": baseline_desc,
            "backend": jax.default_backend(),
            **mfu_fields(flops_per_eval, value),
            **extra,
        }
        # Backstop physics gate (shared implementation; configs with a
        # fallback path call it earlier, inside their own try scope).
        physics_gate(flops_per_eval, value)
        results.append(line)
        print(json.dumps(line))
        # Persist INCREMENTALLY and ATOMICALLY: a later assertion
        # failure must not discard completed configs, and a crash
        # mid-write must not clobber the previous complete file.  A
        # --only run writes a .partial file: a filtered run must never
        # replace the full record.
        out = (
            "BENCH_SUITE.json" if only is None
            else "BENCH_SUITE.partial.json"
        )
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(results, f, indent=1)
        os.replace(tmp, out)

    def bench_config(config, fn, x0):
        fl = xla_flops_per_eval(fn, x0)
        r, n = _rate(fn, x0)
        record(config, r, flops_per_eval=fl, n=n)
        return r, fl

    # Failure isolation (round-3: an exception in config 7 killed the
    # first live TPU capture and lost configs 7-9): each config runs
    # under a guard that logs the traceback and moves on, so one broken
    # config cannot cost the others' artifacts — and the process exits
    # only after every config's device work has settled, never
    # mid-TPU-call (the wedge scenario, CLAUDE.md).
    failures = []
    only = None
    if "--only" in sys.argv:
        try:
            only = sys.argv[sys.argv.index("--only") + 1].lower()
        except IndexError:
            print("usage: bench_suite.py [--only <config-substring>]",
                  file=sys.stderr)
            return 2

    def guard(name, fn):
        if only is not None and only not in name.lower():
            return
        try:
            fn()
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)
            print(f"# CONFIG FAILED: {name}", file=sys.stderr)
            failures.append(name)

    # Cross-config values (configs 8/9 reuse config 5's model + FLOP
    # count, 9 baselines against 8's ESS rate); a missing key means the
    # producing config failed and the consumer records its own failure.
    shared = {}

    # 1. single-node linear regression (demo pair collapsed; one shard).
    def _c1():
        data1, _ = generate_node_data(1, n_obs=64, seed=11)
        fn, x0 = _flat(FederatedLinearRegression(data1))
        bench_config("single-node linear regression (demo pair)", fn, x0)

    guard("single-node linear", _c1)

    # 2. 8-shard federated linear regression (the bench.py flagship).
    def _c2():
        data8, _ = generate_node_data(8, n_obs=64, seed=123)
        fn, x0 = _flat(FederatedLinearRegression(data8))
        bench_config(
            "8-shard federated linear regression (psum logp+grad)", fn, x0
        )

    guard("8-shard linear", _c2)

    # 3. hierarchical radon GLM, one shard per county group.
    def _c3():
        datag, _ = generate_radon_data(16, seed=12)
        fn, x0 = _flat(HierarchicalRadonGLM(datag))
        bench_config("hierarchical radon GLM (16 county shards)", fn, x0)

    guard("radon GLM", _c3)

    # 4. Lotka-Volterra ODE: [theta] -> [LL, dLL] per shard.
    def _c4():
        lv, _ = make_lv_model(8)
        fn, x0 = _flat(lv)
        fl = xla_flops_per_eval(fn, x0)
        r, n = _rate(fn, x0)
        record(
            "Lotka-Volterra ODE param estimation (8 shards)",
            r,
            flops_per_eval=fl,
            n=n,
            note="each eval is 128 SEQUENTIAL RK4 steps (fwd+bwd), so "
            "the rate is loop-latency-bound, not compute-bound — "
            "structurally ~100x deeper than the linear configs sharing "
            "this 50k baseline",
        )

    guard("LV ODE", _c4)

    # 5. 64-shard federated logistic regression; evals/s + NUTS samples/s.
    # Three EXACT impls race behind an equality gate (same tolerances as
    # bench.py's candidate gate): the plain vmapped model, the
    # partial-suffstats form (y-linear term folded to build-time
    # constants), and the flattened single-matvec form
    # (models/logistic.py).
    def _c5():
        # Built inside the guard (round-3 ADVICE: a construction failure
        # here must not kill the whole suite before config 1 runs);
        # configs 8/9 pick the model up from shared{}.
        datal, _ = generate_logistic_data(
            n_shards=64, n_obs=64, n_features=8
        )
        model5 = FederatedLogisticRegression(datal)
        fn5, x5 = _flat(model5)
        fn5s, _ = _flat(FederatedLogisticRegression(datal, use_suffstats=True))
        fn5f, _ = _flat(FederatedLogisticRegression(datal, flatten=True))
        x5p = x5 + 0.1 * jnp.arange(x5.shape[0], dtype=x5.dtype)
        for probe in (x5, x5p):
            va, ga = fn5(probe)
            for fn_c in (fn5s, fn5f):
                vb, gb = fn_c(probe)
                np.testing.assert_allclose(float(va), float(vb), rtol=2e-4)
                np.testing.assert_allclose(
                    np.asarray(ga), np.asarray(gb), rtol=2e-3, atol=1e-3
                )
        fl_eval5 = xla_flops_per_eval(fn5, x5)
        best5 = {"rate": -1.0}
        impls5 = {"vmapped": fn5, "suffstats": fn5s, "flat": fn5f}
        for name, fn in impls5.items():
            fl = fl_eval5 if fn is fn5 else xla_flops_per_eval(fn, x5)
            r, n = _rate(fn, x5)
            print(f"# 64-shard logistic impl {name}: {r:,.1f} evals/s",
                  file=sys.stderr)
            if r > best5["rate"]:
                best5 = {"name": name, "rate": r, "n": n, "fl": fl}
        # The while-loop's per-iteration overhead is a live candidate
        # for this config's cap (bench.py's flagship u32 reasoning):
        # race a 32x-unrolled chain of the u8 WINNER only — one extra
        # fresh compile, not three; on the tunneled TPU each fresh
        # compile costs 20-40 s of capture window and one more
        # exposure to a remote-compile outage (CLAUDE.md round-3
        # findings).  Numerics identical by make_chained's contract;
        # FLOPs accounted via the same base fn.
        r32, n32 = _rate(impls5[best5["name"]], x5, unroll=32)
        print(
            f"# 64-shard logistic impl {best5['name']}-u32: "
            f"{r32:,.1f} evals/s",
            file=sys.stderr,
        )
        if r32 > best5["rate"]:
            best5 = {
                "name": best5["name"] + "-u32",
                "rate": r32,
                "n": n32,
                "fl": best5["fl"],
            }
        record(
            "64-shard federated logistic regression (logp+grad)",
            best5["rate"],
            flops_per_eval=best5["fl"],
            n=best5["n"],
            impl=best5["name"],
        )
        # Publish for configs 8/9 only now, with the equality gate and
        # rate measurement behind us: a consumer finding these keys may
        # assume c5 VALIDATED the model, not merely constructed it.
        shared["model5"] = model5
        shared["fl_eval5"] = fl_eval5

    guard("64-shard logistic", _c5)

    # 6. Long-context LGSSM: the O(log T) parallel-in-time filter vs the
    # classic sequential scan it replaces, measured in the same run on
    # the same backend — vs_baseline > 1 means parallel-in-time pays.
    def _c6():
        from pytensor_federated_tpu.models.statespace import (
            generate_lgssm_data,
            kalman_logp_parallel,
            kalman_logp_seq,
        )

        y_ss, p_ss = generate_lgssm_data(T=4096)
        sizing6 = dict(n_cal=20, floor=50, mid_wall=0.5, target_wall=1.5)

        def measure_pair(precision):
            """Seq baseline AND parallel filter, SAME precision — the
            config's meaning ('parallel-in-time pays') must never be
            confounded with the precision ladder."""
            kwp = {} if precision is None else {"precision": precision}
            fn_seq, flat_seq = _flat_fn(
                lambda p: kalman_logp_seq(p, y_ss, **kwp), p_ss
            )
            r_seq, _ = _rate(fn_seq, flat_seq, **sizing6)
            fn_ss, flat_ss = _flat_fn(
                lambda p: kalman_logp_parallel(p, y_ss, **kwp), p_ss
            )
            fl6 = xla_flops_per_eval(fn_ss, flat_ss)
            r6, n6 = _rate(fn_ss, flat_ss, **sizing6)
            physics_gate(fl6, r6)
            return r_seq, fl6, r6, n6

        # Default precision first; if EITHER measurement trips an
        # INTEGRITY guard (the first TPU capture: reduced-precision
        # matmul compositions degenerated the chain until XLA hoisted
        # the eval — a physically impossible 6.8e11 evals/s), redo the
        # whole pair under the verified-engaging strict policy, with
        # the impl field saying so (tools/diag_tpu.out; precision.py).
        # ONLY MeasurementIntegrityError routes to the fallback: a
        # JaxRuntimeError (also a RuntimeError) means the backend
        # itself failed — retrying with a FRESH strict compile into
        # e.g. a remote-compile outage would double the cost the
        # per-config guard bounds.
        try:
            impl6 = "default-precision"
            r_seq, fl6, r6, n6 = measure_pair(None)
        except MeasurementIntegrityError as e:
            print(
                f"# kalman default-precision refused ({e}); "
                "re-measuring under precision='strict'",
                file=sys.stderr,
            )
            impl6 = "f32-strict"
            r_seq, fl6, r6, n6 = measure_pair("strict")
        record(
            "LGSSM T=4096 logp+grad (parallel-in-time Kalman)",
            r6,
            baseline_rate=r_seq,
            baseline_desc=(
                f"sequential-scan Kalman filter, same run, same "
                f"precision ({r_seq:.1f} evals/s)"
            ),
            flops_per_eval=fl6,
            n=n6,
            impl=impl6,
        )

    guard("LGSSM parallel Kalman", _c6)

    # 7. Compute-bound config: wide logistic regression, 64 chains
    # evaluated in one vmapped batch, so the likelihood is an
    # (8, 4096, 512) @ (512, 64) batched matmul — arithmetic intensity
    # ~chains FLOP/byte instead of the matvec's 0.5.  Target: 5% MFU.
    n_chains = 64

    def batched_flat(model):
        fn1, x1 = _flat(model)
        vm = jax.vmap(fn1)

        def fn(x):
            # Sum the per-chain values so the chained runner's scalar
            # accumulator type-checks; the gradient stays (chains, d).
            v, g = vm(x)
            return v.sum(), g

        return fn, vm, x1

    def _c7():
        # Built inside the guard: the 8x4096x512 wide data is the
        # likeliest construction OOM in the suite (round-3 ADVICE).
        dataw, _ = generate_logistic_data(
            n_shards=8, n_obs=4096, n_features=512, seed=77
        )
        fnw, vm32, xw1 = batched_flat(FederatedLogisticRegression(dataw))
        fnw16, vm16, _ = batched_flat(
            FederatedLogisticRegression(dataw, compute_dtype=jnp.bfloat16)
        )
        # The GUARANTEED-accurate reference: the 6-pass bf16x3 split
        # (precision.py) — true-f32 on any backend, including the chip
        # whose plain f32 matmul is bf16-accurate (the first capture's
        # gate failure was the "f32" reference itself being degraded,
        # tools/diag_tpu.out).  It also RACES below: the measured cost
        # of guaranteed accuracy is part of the record.
        fnws, vms, _ = batched_flat(
            FederatedLogisticRegression(
                dataw, compute_dtype="float32_strict"
            )
        )
        key = jax.random.PRNGKey(3)
        xw = xw1[None, :] + 0.01 * jax.random.normal(
            key, (n_chains, xw1.shape[0]), xw1.dtype
        )
        # Accuracy gates, all anchored on the STRICT reference.  bf16
        # gets its accuracy contract (8 mantissa bits ~ 1e-2, pinned in
        # tests/test_mixed_precision.py); plain f32 gets the same loose
        # gate, NOT the exact 2e-4 one, because on this TPU plain f32
        # IS bf16-level — the gate must hold on both backends.  Checked
        # PER CHAIN (no cross-chain cancellation) and on the gradients,
        # since the raced function's gradient drives the chained
        # trajectory — the bench.py gate convention.
        val_s, grad_s = vms(xw)
        for other_vm in (vm32, vm16):
            val_o, grad_o = other_vm(xw)
            np.testing.assert_allclose(
                np.asarray(val_o), np.asarray(val_s), rtol=2e-2
            )
            np.testing.assert_allclose(
                np.asarray(grad_o),
                np.asarray(grad_s),
                rtol=5e-2,
                atol=5e-2 * float(jnp.max(jnp.abs(grad_s))),
            )
        best = {"rate": -1.0}
        impl_rates = {}
        for name, fn in {
            "f32": fnw,
            "bf16-matmul": fnw16,
            "f32-strict": fnws,
        }.items():
            fl = xla_flops_per_eval(fn, xw)
            r, n = _rate(
                fn, xw, n_cal=5, floor=10, mid_wall=0.5, target_wall=1.5
            )
            print(
                f"# wide-logistic impl {name}: {r:,.1f} batched evals/s",
                file=sys.stderr,
            )
            impl_rates[name] = round(r, 1)
            if r > best["rate"]:
                best = {"name": name, "rate": r, "n": n, "fl": fl}
        peak_rate = None
        if best["fl"]:
            from pytensor_federated_tpu.flopcount import peak_flops

            peak, _basis = peak_flops()
            peak_rate = COMPUTE_BOUND_TARGET_MFU * peak / best["fl"]
        record(
            "wide logistic 8x4096x512, 64 vectorized chains (compute-bound)",
            best["rate"],
            unit="batched evals/s",
            baseline_rate=peak_rate,
            baseline_desc=f"{COMPUTE_BOUND_TARGET_MFU:.0%} MFU",
            flops_per_eval=best["fl"],
            n=best["n"],
            impl=best["name"],
            impl_rates=impl_rates,
            note="gates anchored on the f32-strict (bf16x3 split) "
            "reference; impl_rates carries the accuracy-vs-speed "
            "ladder measured in this run",
        )

    guard("wide logistic compute-bound", _c7)

    # 8. Full NUTS posterior on config 5, against an explicit target.
    def _c8():
        from pytensor_federated_tpu.samplers import sample

        model5 = shared["model5"]  # KeyError if c5 failed

        def run_nuts(seed):
            return sample(
                model5.logp,
                model5.init_params(),
                key=jax.random.PRNGKey(seed),
                num_warmup=200,
                num_samples=200,
                num_chains=4,
                jitter=0.1,
            )

        # Cold run: pays compile (on TPU a 20-40 s remote compile —
        # rating that would measure the compiler, not the sampler).
        # Warm run with identical static shapes reuses the executable;
        # THAT is the rated wall.  Both are recorded.
        t0 = time.perf_counter()
        res = run_nuts(0)
        jax.block_until_ready(res.samples)
        wall_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        res = run_nuts(1)
        jax.block_until_ready(res.samples)
        wall = time.perf_counter() - t0
        n_draws = 4 * 200
        summ = res.summary()
        rhat = float(np.asarray(summ["rhat"]["w"]).max())
        # Leapfrog-eval lower bound from the kept draws' tree depths (a
        # depth-k NUTS tree costs 2^k - 1 gradient evals); warmup evals
        # are not tracked, so the MFU here is an explicit lower bound.
        depth_raw = res.stats.get("depth") if res.stats else None
        fl_sample = None
        fl_eval5 = shared.get("fl_eval5")
        if fl_eval5 is not None and depth_raw is not None:
            n_evals_lb = float(np.sum(2.0 ** np.asarray(depth_raw) - 1.0))
            fl_sample = fl_eval5 * n_evals_lb / n_draws
        # Effective samples per second: raw samples/s can hide an
        # autocorrelated chain; min-ESS/wall cannot.
        ess_min = float(
            min(np.min(np.asarray(v)) for v in summ["ess"].values())
        )
        record(
            "64-shard logistic: full NUTS posterior",
            n_draws / wall,
            unit="samples/s",
            baseline_rate=NUTS_TARGET_SAMPLES_PER_SEC,
            baseline_desc=(
                f"driver-set target {NUTS_TARGET_SAMPLES_PER_SEC:.0f} "
                "samples/s, warm executable, incl. warmup"
            ),
            flops_per_eval=fl_sample,
            wall_s=round(wall, 2),
            wall_cold_s=round(wall_cold, 2),
            note="warm-run rate (cold run incl. compile in wall_cold_s); "
            "flops/mfu are draw-phase lower bounds",
            max_rhat=round(rhat, 4),
            min_ess_per_sec=round(ess_min / wall, 1),
        )
        assert rhat < 1.2, f"NUTS did not converge: max rhat {rhat}"
        shared["nuts_ess_rate"] = ess_min / wall

    guard("NUTS posterior", _c8)

    # 9. ChEES-HMC on the same posterior at 16 lockstep chains,
    # baselined against THIS run's NUTS min-ESS/s: the cross-chain
    # sampler must beat the tree-doubling one in its intended regime
    # (many cheap parallel chains — the accelerator-native shape).
    def _c9():
        from pytensor_federated_tpu.samplers import chees_sample

        nuts_ess_rate = shared["nuts_ess_rate"]  # KeyError if c8 failed
        model5 = shared["model5"]
        n_chees_chains = 16

        def run_chees(seed):
            return chees_sample(
                model5.logp,
                model5.init_params(),
                key=jax.random.PRNGKey(seed),
                num_warmup=200,
                num_samples=200,
                num_chains=n_chees_chains,
                jitter=0.1,
            )

        res_c = run_chees(0)
        jax.block_until_ready(res_c.samples)  # cold: compile
        t0 = time.perf_counter()
        res_c = run_chees(1)
        jax.block_until_ready(res_c.samples)
        wall_c = time.perf_counter() - t0
        summ_c = res_c.summary()
        ess_min_c = float(
            min(np.min(np.asarray(v)) for v in summ_c["ess"].values())
        )
        rhat_c = float(np.asarray(summ_c["rhat"]["w"]).max())
        # gradient-eval rate LOWER BOUND: n_steps covers only the draw
        # phase while wall_c includes warmup (like the NUTS entry's
        # bound)
        n_steps_c = np.asarray(res_c.stats["n_steps"])  # (chains, draws)
        grads_per_sec = (
            float(n_steps_c[0].sum()) * n_chees_chains / wall_c
        )
        # FLOP accounting (round-2 VERDICT: no entry may give up on
        # it): each leapfrog gradient is one chain's logp+grad, whose
        # exact XLA count is fl_eval5, so achieved FLOP/s = fl_eval5 *
        # grads/s — a lower bound for the same reason grads/s is one.
        # Override the per-"eval" field: the record's value is ESS/s,
        # so flops_per_eval is reported per GRADIENT (the unit that
        # makes sense here).
        chees_mfu = mfu_fields(shared.get("fl_eval5"), grads_per_sec)
        record(
            "64-shard logistic: ChEES-HMC posterior (16 lockstep chains)",
            ess_min_c / wall_c,
            unit="min-ESS/s",
            baseline_rate=nuts_ess_rate,
            baseline_desc=(
                f"NUTS min-ESS/s, same run ({nuts_ess_rate:.1f}), "
                "4 chains vs ChEES's 16 — the ratio includes the extra "
                "chain parallelism ChEES is designed to exploit"
            ),
            wall_s=round(wall_c, 2),
            max_rhat=round(rhat_c, 4),
            leapfrog_grads_per_sec=round(grads_per_sec, 1),
            note="warm executable; grads/s is a draw-phase lower bound; "
            "flops_per_eval is per leapfrog GRADIENT (value is ESS/s); "
            "flops/mfu are draw-phase lower bounds",
            **chees_mfu,
        )
        assert rhat_c < 1.2, f"ChEES did not converge: max rhat {rhat_c}"

    guard("ChEES posterior", _c9)

    # 10. Federated exact GP: 8 shards x 256 points, batched dense
    # Cholesky — the most MXU-shaped family in the package (round-2
    # VERDICT item 4: it had correctness tests but no perf number).
    # Same compute-bound convention as config 7: the pass line is 5%
    # MFU, so the entry is falsifiable on any backend.
    def _c10():
        from pytensor_federated_tpu.models.gp import (
            FederatedExactGP,
            generate_gp_data,
        )

        datag10, _ = generate_gp_data(8, n_obs=256, seed=9)
        fn10, x10 = _flat(FederatedExactGP(datag10))
        fl10 = xla_flops_per_eval(fn10, x10)
        r10, n10 = _rate(fn10, x10, n_cal=5, floor=10, mid_wall=0.5,
                         target_wall=1.5)
        peak_rate10 = None
        if fl10:
            from pytensor_federated_tpu.flopcount import peak_flops

            peak10, _ = peak_flops()
            peak_rate10 = COMPUTE_BOUND_TARGET_MFU * peak10 / fl10
        record(
            "federated exact GP 8x256 logp+grad (batched Cholesky)",
            r10,
            baseline_rate=peak_rate10,
            baseline_desc=f"{COMPUTE_BOUND_TARGET_MFU:.0%} MFU",
            flops_per_eval=fl10,
            n=n10,
        )

    guard("exact GP", _c10)

    # 11. Host-federation lane: logp+grad round-trips/s over the real
    # gRPC + npwire transport on localhost — the surface that IS the
    # reference's entire hot path (serialize -> HTTP/2 -> compute ->
    # serialize back per call, reference: service.py:150-158).  The
    # baseline is the reference's structural per-call floor: ~1 ms of
    # serialize + two network legs + Python dispatch => 1,000 calls/s
    # (driver-set; the reference publishes no number, BASELINE.md).
    # This lane is host-side by design — the TPU never appears — so the
    # record says so instead of carrying meaningless FLOP fields.
    def _c11():
        import multiprocessing as mp
        import socket
        import time as _time

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        port = free_port()
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=_bench_serve_node, args=(port,), daemon=True
        )
        proc.start()
        try:
            import asyncio

            from pytensor_federated_tpu.service import (
                ArraysToArraysServiceClient,
                get_loads_async,
            )

            deadline = _time.time() + 30.0

            async def wait_up():
                while _time.time() < deadline:
                    loads = await get_loads_async(
                        [("127.0.0.1", port)], timeout=1.0
                    )
                    if loads[0] is not None:
                        return
                    await asyncio.sleep(0.2)
                raise TimeoutError("bench node did not come up")

            asyncio.run(wait_up())
            client = ArraysToArraysServiceClient("127.0.0.1", port)
            x = np.zeros(3, np.float32)
            client.evaluate(x)  # connect + warm
            t0 = _time.perf_counter()
            n = 0
            while _time.perf_counter() - t0 < 1.5:
                client.evaluate(x)
                n += 1
            wall = _time.perf_counter() - t0
            rate_grpc = n / wall

            # Pipelined mode (evaluate_many, window=32): the windowed
            # throughput the reference's one-in-flight lock-step design
            # cannot express — recorded as an extra field; the headline
            # stays the per-call rate for comparability with the
            # reference's structural floor.  Own try: a failure in this
            # newer path must cost only this field, never the
            # already-measured per-call and C++ lanes (the round-3
            # lesson: an outage costs only the un-run parts).
            rate_pipelined = None
            try:
                reqs = [(x,)] * 256
                # batch=False pins this lane to per-call frames — the
                # batched lane below measures the new wire against it.
                client.evaluate_many(reqs, window=32, batch=False)  # warm
                t0 = _time.perf_counter()
                n_p = 0
                while _time.perf_counter() - t0 < 1.5:
                    client.evaluate_many(reqs, window=32, batch=False)
                    n_p += len(reqs)
                rate_pipelined = n_p / (_time.perf_counter() - t0)
            except Exception:
                import traceback

                traceback.print_exc(file=sys.stderr)
                print("# pipelined lane failed; keeping per-call record",
                      file=sys.stderr)

            # Batched pipelined mode (ISSUE 3): the window rides wire
            # BATCH FRAMES — one transport message, one server decode
            # loop and one vectorized dispatch per 32 requests — after
            # the client reads the server's GetLoad capability.  Own
            # try: per-lane failure isolation, like every lane here.
            rate_batched = None
            try:
                reqs = [(x,)] * 256
                client.evaluate_many(reqs, window=32, batch=True)  # warm
                t0 = _time.perf_counter()
                n_b = 0
                while _time.perf_counter() - t0 < 1.5:
                    client.evaluate_many(reqs, window=32, batch=True)
                    n_b += len(reqs)
                rate_batched = n_b / (_time.perf_counter() - t0)
            except Exception:
                import traceback

                traceback.print_exc(file=sys.stderr)
                print("# batched lane failed; keeping pipelined record",
                      file=sys.stderr)

            # Second lane: the native C++ worker over the raw-TCP
            # npwire framing (native/cpp_node.cpp) — the transport the
            # native runtime ships; raced for the record like the
            # on-device impl races (compute is trivial in both lanes,
            # so the number is transport cost either way).
            rate_cpp, n_cpp, rate_cpp_pipe = None, None, None
            rate_cpp_batched = None
            import shutil
            import subprocess as sp

            native = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "native")
            binary = os.path.join(native, "cpp_node")
            if shutil.which("make") and shutil.which("g++"):
                sp.run(["make", "-C", native], check=True,
                       capture_output=True)
            if os.path.exists(binary):
                from pytensor_federated_tpu.service import TcpArraysClient

                cport = free_port()
                cproc = sp.Popen(
                    [binary, str(cport)], stdout=sp.PIPE,
                    stderr=sp.STDOUT, text=True,
                )
                try:
                    line = cproc.stdout.readline()
                    if "listening" not in line:
                        raise RuntimeError(f"cpp_node: {line!r}")
                    tclient = TcpArraysClient("127.0.0.1", cport)
                    args = (
                        np.float64(0.7), np.float64(1.9), np.float64(0.5),
                        np.zeros(64), np.zeros(64),
                    )
                    tclient.evaluate(*args)  # connect + warm
                    t0 = _time.perf_counter()
                    n_cpp = 0
                    while _time.perf_counter() - t0 < 1.5:
                        tclient.evaluate(*args)
                        n_cpp += 1
                    rate_cpp = n_cpp / (_time.perf_counter() - t0)
                    # Pipelined C++ lane (own try, like the python one;
                    # rate_cpp_pipe pre-initialized to None above).  On
                    # LOCALHOST this lane is syscall-bound, so the
                    # window buys only ~1.1-1.3x; the field exists
                    # because over a real network the same window hides
                    # the RTT entirely.
                    try:
                        reqs_t = [args] * 512
                        tclient.evaluate_many(
                            reqs_t, window=64, batch=False
                        )
                        t0 = _time.perf_counter()
                        n_tp = 0
                        while _time.perf_counter() - t0 < 1.5:
                            tclient.evaluate_many(
                                reqs_t, window=64, batch=False
                            )
                            n_tp += len(reqs_t)
                        rate_cpp_pipe = n_tp / (
                            _time.perf_counter() - t0
                        )
                    except Exception:
                        import traceback

                        traceback.print_exc(file=sys.stderr)
                        print("# cpp pipelined lane failed; keeping "
                              "per-call record", file=sys.stderr)
                    # Batched C++ lane: the same window packed into
                    # npwire batch frames (the node answers the
                    # zero-item probe).  Syscall count drops from one
                    # per call to one per 32 requests.
                    try:
                        reqs_t = [args] * 512
                        tclient.evaluate_many(
                            reqs_t, window=64, batch=True
                        )
                        t0 = _time.perf_counter()
                        n_tb = 0
                        while _time.perf_counter() - t0 < 1.5:
                            tclient.evaluate_many(
                                reqs_t, window=64, batch=True
                            )
                            n_tb += len(reqs_t)
                        rate_cpp_batched = n_tb / (
                            _time.perf_counter() - t0
                        )
                    except Exception:
                        import traceback

                        traceback.print_exc(file=sys.stderr)
                        print("# cpp batched lane failed; keeping "
                              "pipelined record", file=sys.stderr)
                    tclient.close()
                finally:
                    cproc.kill()
                    cproc.wait()
            for lane, r in (("python-grpc", rate_grpc),
                            ("python-grpc-pipelined-w32", rate_pipelined),
                            ("python-grpc-batched", rate_batched),
                            ("cpp-tcp", rate_cpp),
                            ("cpp-tcp-pipelined-w64", rate_cpp_pipe),
                            ("cpp-tcp-batched", rate_cpp_batched)):
                if r is not None:
                    print(f"# host lane {lane}: {r:,.1f} round-trips/s",
                          file=sys.stderr)
            # ISSUE 3 acceptance line, computed where the artifact can
            # carry it: the batched pipelined lane vs the same-container
            # non-batched pipelined rate.
            batched_speedup = (
                None
                if rate_batched is None or not rate_pipelined
                else round(rate_batched / rate_pipelined, 2)
            )
            best_rate = max(rate_grpc, rate_cpp or 0.0)
            record(
                "host-lane logp+grad round-trips (localhost worker)",
                best_rate,
                unit="round-trips/s",
                baseline_rate=1000.0,
                baseline_desc=(
                    "reference's structural per-call floor: ~1 ms "
                    "serialize + 2 network legs + dispatch (driver-set; "
                    "reference publishes no number)"
                ),
                n=n,
                impl="cpp-tcp" if (rate_cpp or 0.0) > rate_grpc
                else "python-grpc",
                python_grpc_rps=round(rate_grpc, 1),
                python_grpc_pipelined_w32_rps=(
                    None if rate_pipelined is None
                    else round(rate_pipelined, 1)
                ),
                python_grpc_batched_w32_rps=(
                    None if rate_batched is None
                    else round(rate_batched, 1)
                ),
                batched_vs_pipelined=batched_speedup,
                cpp_tcp_rps=None if rate_cpp is None else round(rate_cpp, 1),
                cpp_tcp_pipelined_w64_rps=(
                    None if rate_cpp_pipe is None
                    else round(rate_cpp_pipe, 1)
                ),
                cpp_tcp_batched_w64_rps=(
                    None if rate_cpp_batched is None
                    else round(rate_cpp_batched, 1)
                ),
                note="host-transport lane: the chip never appears, so "
                "FLOP/MFU fields do not apply (per-call lanes are "
                "lock-step like reference service.py:150-158; batched "
                "lanes ride wire batch frames + server micro-batching)",
            )
        finally:
            proc.terminate()
            proc.join(timeout=5)

    guard("host transport lane", _c11)

    # 12. Parallel tempering vs NUTS on a well-separated bimodal
    # (round-4's flagship sampler, round-4 verdict item 5: it had
    # correctness tests but zero perf artifacts).  Target: an 8-dim
    # equal mixture of N(-4*1, 0.5^2 I) and N(+4*1, 0.5^2 I) — the
    # tempering test suite's 16-sigma positive control scaled up
    # (tests/test_tempering.py:20-27).
    #
    # Metric design: a mode-stuck sampler's NOMINAL ESS is a lie — a
    # chain that never leaves one mode looks beautifully mixed to a
    # plain ESS estimator.  So the rated quantity is RANK-NORMALIZED
    # split min-ESS/s (the standard multimodality-aware diagnostic:
    # chains stuck in different modes collapse it toward zero), NUTS
    # gets the textbook-correct setup (overdispersed inits covering
    # both modes, 4 chains), and the per-chain mode-balance error —
    # each chain's own |P(right mode) - 1/2|, max over chains — is the
    # negative control proving WHY its rank-ESS collapses: every NUTS
    # chain is stuck (~0.5) while every PT cold chain mixes (~0).
    # ESS/s normalizes by wall time, so the budget comparison is
    # inherent in the unit.
    def _c12():
        from pytensor_federated_tpu.samplers import sample
        from pytensor_federated_tpu.samplers.tempering import pt_sample

        dim = 8
        sep, width = 4.0, 0.5

        def mix_logp(params):
            x = params["x"]
            la = -0.5 * jnp.sum(((x + sep) / width) ** 2)
            lb = -0.5 * jnp.sum(((x - sep) / width) ** 2)
            return jnp.logaddexp(la, lb)

        n_warm, n_draws = 500, 1000
        n_leapfrog, n_temps, n_stacks = 8, 8, 2  # shared w/ FLOP count
        init = {"x": jnp.zeros(dim)}

        def run_pt(seed):
            return pt_sample(
                mix_logp,
                init,
                key=jax.random.PRNGKey(seed),
                num_warmup=n_warm,
                num_samples=n_draws,
                num_temps=n_temps,
                beta_min=0.01,
                num_chains=n_stacks,
                num_leapfrog=n_leapfrog,
            )

        def run_nuts(seed):
            # jitter=5: inits overdispersed across both basins — the
            # best practice a migrating user would follow.  NUTS still
            # cannot CROSS between modes; overdispersion just ensures
            # the chains disagree so rank-normalized ESS exposes it.
            return sample(
                mix_logp,
                init,
                key=jax.random.PRNGKey(seed),
                num_warmup=n_warm,
                num_samples=n_draws,
                num_chains=4,
                jitter=5.0,
            )

        def per_chain_balance_error(draws):
            # draws: (chains, draws, dim); a draw's mode is the sign of
            # its mean coordinate (modes sit at +/- sep * ones).  Max
            # over chains: ONE stuck chain is a failed sampler.
            side = np.asarray(draws).mean(axis=-1) > 0  # (chains, draws)
            per_chain = np.abs(side.mean(axis=1) - 0.5)
            return float(per_chain.max())

        def rank_min_ess_rate(res, wall):
            summ = res.summary(rank_normalized=True)
            ess = float(
                min(np.min(np.asarray(v)) for v in summ["ess"].values())
            )
            return ess / wall, float(np.asarray(summ["rhat"]["x"]).max())

        # cold (compile) then warm (rated) — the suite convention.
        res_pt = run_pt(0)
        jax.block_until_ready(res_pt.samples)
        t0 = time.perf_counter()
        res_pt = run_pt(1)
        jax.block_until_ready(res_pt.samples)
        wall_pt = time.perf_counter() - t0
        pt_ess_rate, pt_rhat = rank_min_ess_rate(res_pt, wall_pt)
        pt_balance = per_chain_balance_error(res_pt.samples["x"])

        res_n = run_nuts(0)
        jax.block_until_ready(res_n.samples)
        t0 = time.perf_counter()
        res_n = run_nuts(1)
        jax.block_until_ready(res_n.samples)
        wall_n = time.perf_counter() - t0
        nuts_ess_rate, nuts_rhat = rank_min_ess_rate(res_n, wall_n)
        nuts_balance = per_chain_balance_error(res_n.samples["x"])

        # FLOP accounting: each tempering iteration costs num_leapfrog
        # HMC gradients per rung per stack; grads/s is a draw-phase
        # lower bound (warmup excluded from the count, included in
        # wall — same convention as configs 8/9).
        fn12, x12 = _flat_fn(mix_logp, init)
        fl12 = xla_flops_per_eval(fn12, x12)
        grads = float(n_leapfrog * n_temps * n_stacks * n_draws)
        # Integrity guard on the hand-rolled timing (CLAUDE.md: every
        # rate must carry one — the chip can return without executing,
        # collapsing wall_pt to an impossible rate).
        physics_gate(fl12, grads / wall_pt)
        pt_mfu = mfu_fields(fl12, grads / wall_pt)
        record(
            "16-sigma bimodal: parallel tempering vs NUTS",
            pt_ess_rate,
            unit="rank-normalized min-ESS/s",
            baseline_rate=nuts_ess_rate,
            baseline_desc=(
                f"NUTS rank-normalized min-ESS/s, same run, "
                f"overdispersed inits ({nuts_ess_rate:.2f}; its "
                f"max rhat {nuts_rhat:.2f}) — mode-stuck by "
                f"construction: per-chain balance error "
                f"{nuts_balance:.3f} vs PT's {pt_balance:.3f} "
                "(the negative control)"
            ),
            wall_s=round(wall_pt, 2),
            mode_balance_error=round(pt_balance, 4),
            nuts_mode_balance_error=round(nuts_balance, 4),
            max_rhat=round(pt_rhat, 4),
            note="flops_per_eval is per leapfrog GRADIENT (value is "
            "ESS/s); grads/s and mfu are draw-phase lower bounds; "
            "nominal (non-rank) ESS is deliberately NOT the metric — "
            "a mode-stuck chain fakes it",
            **pt_mfu,
        )
        # The claims the config exists to make, enforced: every PT
        # cold chain visits both modes near 50/50; every NUTS chain is
        # stuck in one.  Thresholds leave real margin over the CPU
        # measurement (PT 0.136, NUTS 0.500 — suite_cpu_r05.jsonl): a
        # backend-numerics shift on the scarce first TPU capture must
        # not fail the config over a threshold artifact, only over a
        # qualitative break.
        assert pt_balance < 0.3, f"PT mode balance off: {pt_balance}"
        assert nuts_balance > 0.35, (
            f"negative control failed: NUTS balance {nuts_balance}"
        )

    guard("parallel tempering bimodal", _c12)

    # 13. Replica-POOL routing lane (ISSUE 4): the host lane served by
    # a 3-replica pool with one member degraded to ~20 req/s (50 ms
    # serial compute — the slow/wedged-node failure mode).  The rated
    # quantity is DEGRADED-pool throughput vs the same run's
    # all-healthy pool (acceptance: >= 0.7 — routing must shift work
    # off the slow member), with two control lanes in the record: a
    # client PINNED to the slow node (the pre-pool architecture, which
    # collapses to the slow node's serial rate) and per-call tail
    # latency with hedging off vs on (the hedge must cut the p99 that
    # the slow member injects).
    def _c13():
        import multiprocessing as mp
        import socket
        import time as _time

        import asyncio

        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service import (
            ArraysToArraysServiceClient,
            get_loads_async,
        )

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        slow_delay_s = 0.05
        fast_ports = [free_port() for _ in range(3)]
        slow_port = free_port()
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_bench_serve_node, args=(p,), daemon=True
            )
            for p in fast_ports
        ] + [
            ctx.Process(
                target=_bench_serve_slow_node,
                args=(slow_port, slow_delay_s),
                daemon=True,
            )
        ]
        for p in procs:
            p.start()
        try:
            deadline = _time.time() + 60.0

            async def wait_up():
                ports = fast_ports + [slow_port]
                while _time.time() < deadline:
                    loads = await get_loads_async(
                        [("127.0.0.1", p) for p in ports], timeout=1.0
                    )
                    if all(l is not None for l in loads):
                        return
                    await asyncio.sleep(0.2)
                raise TimeoutError("pool bench nodes did not come up")

            asyncio.run(wait_up())
            x = np.zeros(3, np.float32)
            reqs = [(x,)] * 256

            def pooled_rps(ports):
                pool = NodePool(
                    [("127.0.0.1", p) for p in ports],
                    breaker_kwargs=dict(
                        failure_threshold=2, backoff_s=0.5
                    ),
                )
                client = PooledArraysClient(pool)
                # Warm: connect + teach the EWMA partitioner who is
                # slow (the first window pays the slow shard once).
                client.evaluate_many(reqs, window=32)
                client.evaluate_many(reqs, window=32)
                t0 = _time.perf_counter()
                n = 0
                while _time.perf_counter() - t0 < 1.5:
                    client.evaluate_many(reqs, window=32)
                    n += len(reqs)
                return n / (_time.perf_counter() - t0)

            rate_healthy = pooled_rps(fast_ports)
            rate_degraded = pooled_rps(fast_ports[:2] + [slow_port])

            # Control lane: the pre-pool architecture — one client
            # pinned to the slow node (reference: pinned-after-connect,
            # service.py:240-263) collapses to its serial rate.
            pinned = ArraysToArraysServiceClient("127.0.0.1", slow_port)
            pinned.evaluate(x)  # connect + warm
            t0 = _time.perf_counter()
            n_pin = 0
            while _time.perf_counter() - t0 < 1.5:
                pinned.evaluate(x)
                n_pin += 1
            rate_pinned = n_pin / (_time.perf_counter() - t0)

            # Tail-latency lanes: round-robin over [fast, fast, slow]
            # so every third call hits the slow member; the hedge must
            # cut the p99 that member injects.  hedge_quantile=0.5:
            # the latency mix is bimodal (~1 ms vs ~50 ms), so the
            # median is the honest "usual call" deadline.
            def percall_p99_ms(hedge):
                pool = NodePool(
                    [
                        ("127.0.0.1", fast_ports[0]),
                        ("127.0.0.1", fast_ports[1]),
                        ("127.0.0.1", slow_port),
                    ],
                    policy="round_robin",
                )
                client = PooledArraysClient(
                    pool, hedge=hedge, hedge_quantile=0.5
                )
                # Warmup OUTSIDE the measurement: the hedge deadline is
                # estimated from observed latencies, so the first few
                # calls of a fresh client are structurally unhedged —
                # rating them would measure the estimator's fill time,
                # not the steady-state tail.
                for _ in range(12):
                    client.evaluate(x)
                lat = []
                for i in range(150):
                    t0 = _time.perf_counter()
                    client.evaluate(x)
                    lat.append(_time.perf_counter() - t0)
                lat.sort()
                return 1e3 * lat[int(0.99 * len(lat)) - 1]

            p99_unhedged_ms = percall_p99_ms(False)
            p99_hedged_ms = percall_p99_ms(True)

            for lane, r in (
                ("pool-3-healthy", rate_healthy),
                ("pool-1-of-3-degraded", rate_degraded),
                ("pinned-to-degraded", rate_pinned),
            ):
                print(f"# pool lane {lane}: {r:,.1f} round-trips/s",
                      file=sys.stderr)
            print(
                f"# pool tail p99: unhedged {p99_unhedged_ms:.1f} ms, "
                f"hedged {p99_hedged_ms:.1f} ms",
                file=sys.stderr,
            )
            record(
                "replica-pool routing (3 replicas, 1 slow/degraded)",
                rate_degraded,
                unit="round-trips/s",
                baseline_rate=rate_healthy,
                baseline_desc=(
                    f"all-healthy 3-replica pool, same run "
                    f"({rate_healthy:,.1f} rps); acceptance line: "
                    "degraded >= 0.7x healthy"
                ),
                pool_healthy_rps=round(rate_healthy, 1),
                pool_degraded_rps=round(rate_degraded, 1),
                pinned_to_degraded_rps=round(rate_pinned, 1),
                p99_unhedged_ms=round(p99_unhedged_ms, 2),
                p99_hedged_ms=round(p99_hedged_ms, 2),
                hedge_tail_cut=round(
                    p99_unhedged_ms / max(p99_hedged_ms, 1e-9), 2
                ),
                note="host-transport lane (no FLOP fields); degraded "
                "member serves ~20 req/s serial; the pinned lane is "
                "the pre-pool architecture collapsing onto it, the "
                "hedged lane fires a second replica at the observed "
                "median-latency deadline",
            )
            assert rate_degraded >= 0.7 * rate_healthy, (
                f"degraded pool {rate_degraded:.1f} rps < 70% of "
                f"healthy {rate_healthy:.1f} rps"
            )
            assert p99_hedged_ms < p99_unhedged_ms, (
                f"hedging did not cut tail latency "
                f"({p99_hedged_ms:.1f} vs {p99_unhedged_ms:.1f} ms)"
            )
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    guard("replica pool routing", _c13)

    # 14. Fed primitive lane (ISSUE 6): the SAME per-shard logp+grad
    # round driven through fed.program(PoolPlacement) — trace, window
    # plan, interpreter, pure_callback — vs the direct evaluate_many
    # fan-out it lowers to.  Rated: primitive-lane shard evals/s;
    # baseline: the direct lane, same pool, same requests, same node
    # compute.  Acceptance: >= 0.9x, i.e. the unified IR costs < 10%.
    def _c14():
        import asyncio
        import multiprocessing as mp
        import socket
        import time as _time

        from pytensor_federated_tpu import fed
        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service import get_loads_async

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        ports = [free_port() for _ in range(2)]
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_bench_serve_fed_node, args=(p,), daemon=True
            )
            for p in ports
        ]
        for p in procs:
            p.start()
        try:
            deadline = _time.time() + 60.0

            async def wait_up():
                while _time.time() < deadline:
                    loads = await get_loads_async(
                        [("127.0.0.1", p) for p in ports], timeout=1.0
                    )
                    if all(l is not None for l in loads):
                        return
                    await asyncio.sleep(0.2)
                raise TimeoutError("fed bench nodes did not come up")

            asyncio.run(wait_up())

            n_shards, dim, window = 64, 16, 32
            rng = np.random.default_rng(14)
            x = jnp.asarray(rng.normal(size=(n_shards, dim)).astype(np.float32))
            y = jnp.asarray(rng.normal(size=(n_shards, dim)).astype(np.float32))
            params = jnp.asarray(np.float32([0.3, -0.8]))

            def shard_logp(p, xs, ys):
                return -jnp.sum((ys - p[0] - p[1] * xs) ** 2)

            def model(p):
                pb = fed.fed_broadcast(p, n_shards)
                lps = fed.fed_map(
                    lambda s: shard_logp(s[0], s[1], s[2]), (pb, x, y)
                )
                return fed.fed_sum(lps)

            pool = NodePool([("127.0.0.1", p) for p in ports])
            client = PooledArraysClient(pool)
            run = fed.program(
                model, fed.PoolPlacement(client, window=window)
            )

            p_np = np.asarray(params)
            requests = [
                (p_np, np.asarray(x[i]), np.asarray(y[i]))
                for i in range(n_shards)
            ]

            def direct_eval():
                replies = client.evaluate_many(requests, window=window)
                return float(np.sum([r[0] for r in replies]))

            # Warm both lanes: connections, EWMA, the program's traced
            # jaxpr cache.
            v_prim = float(run(params))
            v_direct = direct_eval()
            # Equality gate: the IR must compute the SAME number the
            # direct lane does (bench convention: a lane that drifts
            # numerically is measuring a different computation).
            assert abs(v_prim - v_direct) <= 1e-4 * max(
                1.0, abs(v_direct)
            ), (v_prim, v_direct)

            def rate_once(fn, budget_s=1.0):
                t0 = _time.perf_counter()
                n = 0
                while _time.perf_counter() - t0 < budget_s:
                    fn()
                    n += n_shards
                return n / (_time.perf_counter() - t0)

            # Interleaved best-of-3 per lane: the two lanes differ by
            # well under the run-to-run drift of a loaded container,
            # so a single back-to-back pass can swing the ratio either
            # way; alternating passes and taking each lane's best
            # cancels the drift (same max-over-candidates convention
            # as the impl-race configs).
            prim_eval = lambda: run(params)
            rates_d, rates_p = [], []
            for _ in range(3):
                rates_d.append(rate_once(direct_eval))
                rates_p.append(rate_once(prim_eval))
            rate_direct = max(rates_d)
            rate_prim = max(rates_p)
            overhead = 1.0 - rate_prim / rate_direct
            print(
                f"# fed primitive lane: {rate_prim:,.1f} shard evals/s "
                f"vs direct {rate_direct:,.1f} "
                f"(IR overhead {100 * overhead:.1f}%)",
                file=sys.stderr,
            )
            record(
                "fed primitive lane vs direct fanout (pool, 64 shards)",
                rate_prim,
                unit="shard evals/s",
                baseline_rate=rate_direct,
                baseline_desc=(
                    f"direct evaluate_many over the same 2-replica "
                    f"pool, same requests ({rate_direct:,.1f}); "
                    "acceptance line: primitive lane >= 0.9x"
                ),
                primitive_lane_rps=round(rate_prim, 1),
                direct_lane_rps=round(rate_direct, 1),
                ir_overhead_frac=round(overhead, 4),
                note="host-transport lane (no FLOP fields); the "
                "primitive lane pays trace-cache lookup, window "
                "planning, interpreter walk, and pure_callback per "
                "evaluation on top of the identical wire round",
            )
            assert rate_prim >= 0.9 * rate_direct, (
                f"primitive lane {rate_prim:.1f} < 90% of direct "
                f"{rate_direct:.1f} shard evals/s"
            )
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    guard("fed primitive lane", _c14)

    # 15. Zero-copy shm transport vs the C++ TCP lane (ISSUE 9): the
    # SAME Gaussian-linreg node contract served over (a) the repo's
    # fastest byte wire — cpp_node + TCP batch frames, the 36,443 rps
    # round-6 record lane — and (b) the shared-memory arena doorbell,
    # measured in the same container on the same workload, equal
    # numerical results gated first.  The workload repeats the SAME
    # data arrays per call (the federated access pattern: per-node
    # data is constant, only params move), which is exactly what the
    # shm lane's pinned descriptors + node-side data-identity caching
    # exploit and what a byte wire structurally cannot: it re-ships
    # and re-decodes every byte, every call.  Ratios, not absolutes,
    # carry the acceptance (container throttling moves all lanes
    # together, docs/performance.md).
    def _c15():
        import multiprocessing as mp
        import shutil
        import socket as _socket
        import subprocess as sp
        import time as _time

        from pytensor_federated_tpu.service import TcpArraysClient
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        # The record-lane shape (config 11: scalars + 64-point data)
        # and a bandwidth shape where bytes-moved dominates.
        rng = np.random.default_rng(9)
        shapes = {
            "n64": 64,
            "n16k": 16384,
        }
        args_by_shape = {}
        for name, n in shapes.items():
            x = rng.normal(size=n)
            y = 0.7 + 1.9 * x + rng.normal(size=n)
            args_by_shape[name] = (
                np.asarray(np.float64(0.7)),
                np.asarray(np.float64(1.9)),
                np.asarray(np.float64(0.5)),
                x,
                y,
            )

        # window=256: both lanes pack 32-request batch frames, so this
        # allows 8 frames in flight (the shm lane caps in-flight
        # FRAMES at window/chunk to bound unacked reply-arena bytes).
        def rate_lane(client, args, seconds=1.5, window=256, n_reqs=512):
            reqs = [args] * n_reqs
            client.evaluate_many(reqs, window=window, batch=True)  # warm
            t0 = _time.perf_counter()
            done = 0
            while _time.perf_counter() - t0 < seconds:
                client.evaluate_many(reqs, window=window, batch=True)
                done += n_reqs
            return done / (_time.perf_counter() - t0)

        # -- C++ TCP batched lane (the byte-wire champion) ------------
        native = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "native"
        )
        binary = os.path.join(native, "cpp_node")
        if shutil.which("make") and shutil.which("g++"):
            sp.run(["make", "-C", native], check=True, capture_output=True)
        cpp_rates = {}
        cpp_vals = {}
        cproc = None
        tclient = None
        if os.path.exists(binary):
            cport = free_port()
            cproc = sp.Popen(
                [binary, str(cport)], stdout=sp.PIPE,
                stderr=sp.STDOUT, text=True,
            )
            try:
                line = cproc.stdout.readline()
                if "listening" not in line:
                    raise RuntimeError(f"cpp_node: {line!r}")
                tclient = TcpArraysClient("127.0.0.1", cport)
                for name, args in args_by_shape.items():
                    cpp_vals[name] = [
                        np.asarray(v) for v in tclient.evaluate(*args)
                    ]
                    # Own try per lane: a failure here must still
                    # leave the shm lane's record (round-3 lesson).
                    try:
                        cpp_rates[name] = rate_lane(tclient, args)
                    except Exception:
                        import traceback

                        traceback.print_exc(file=sys.stderr)
                        print(f"# cpp lane failed on {name}",
                              file=sys.stderr)
            finally:
                if tclient is not None:
                    tclient.close()
                cproc.kill()
                cproc.wait()

        # -- shm lanes (suffstats-cached + plain, for transparency) ---
        def shm_rates(use_suffstats):
            ctx = mp.get_context("spawn")
            port = free_port()
            proc = ctx.Process(
                target=_bench_serve_shm_node,
                args=(port, use_suffstats),
                daemon=True,
            )
            proc.start()
            rates, vals = {}, {}
            try:
                client = ShmArraysClient(
                    "127.0.0.1", port,
                    connect_timeout_s=2.0, connect_retries=60,
                    connect_backoff_s=0.25,
                )
                deadline = _time.time() + 60
                while True:
                    try:
                        client.ping()
                        break
                    except (ConnectionError, OSError):
                        if _time.time() > deadline or not proc.is_alive():
                            raise
                        _time.sleep(0.25)
                for name, args in args_by_shape.items():
                    vals[name] = [
                        np.asarray(v) for v in client.evaluate(*args)
                    ]
                    rates[name] = rate_lane(client, args)
                client.close()
            finally:
                proc.terminate()
                proc.join(timeout=10)
            return rates, vals

        shm_cached, shm_vals = shm_rates(True)
        shm_plain, _plain_vals = shm_rates(False)

        # Equality gate FIRST: both lanes computed the same numbers
        # (suffstats reassociate the sums — 1e-9-grade fp drift on
        # these magnitudes; rtol 1e-6 is the strict-f8 line).
        for name in shapes:
            if name in cpp_vals:
                for a, b in zip(cpp_vals[name], shm_vals[name]):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b), rtol=1e-6
                    )

        ratio = (
            None
            if "n64" not in cpp_rates or not cpp_rates["n64"]
            else round(shm_cached["n64"] / cpp_rates["n64"], 2)
        )
        ratio_bw = (
            None
            if "n16k" not in cpp_rates or not cpp_rates["n16k"]
            else round(shm_cached["n16k"] / cpp_rates["n16k"], 2)
        )
        for lane, rates in (
            ("cpp-tcp-batched", cpp_rates),
            ("shm-batched", shm_cached),
            ("shm-batched-nocache", shm_plain),
        ):
            for name, r in rates.items():
                print(f"# colocated lane {lane}/{name}: {r:,.1f} rps",
                      file=sys.stderr)
        record(
            "colocated shm vs cpp-tcp-batched (zero-copy lane)",
            shm_cached["n16k"],
            unit="round-trips/s",
            baseline_rate=36443.0,
            baseline_desc=(
                "cpp-tcp-batched round-6 record, 36,443 rps "
                "(tools/suite_cpu_r06_host.jsonl, 1 KiB requests) — "
                "the byte-wire ceiling; the headline here is the "
                "production-width shape (256 KiB/request), where "
                "bytes-moved caps the byte wire and shm stays flat"
            ),
            # Production-width shape (n=16384: 256 KiB/request on the
            # byte wire, descriptors only on shm) — the acceptance
            # lane: ISSUE 9's motivation is that at width, bytes
            # moved per eval caps throughput.
            shm_rps=round(shm_cached["n16k"], 1),
            shm_nocache_rps=round(shm_plain["n16k"], 1),
            cpp_tcp_batched_rps=(
                None if "n16k" not in cpp_rates
                else round(cpp_rates["n16k"], 1)
            ),
            shm_vs_cpp_tcp_batched=ratio_bw,
            # Small-payload control (n=64: the record lane's own 1 KiB
            # shape) — syscall/loop-bound, where the C++ node's
            # per-item floor beats any python server; reported for
            # honesty, not acceptance.
            shm_small_rps=round(shm_cached["n64"], 1),
            shm_small_nocache_rps=round(shm_plain["n64"], 1),
            cpp_tcp_batched_small_rps=(
                None if "n64" not in cpp_rates
                else round(cpp_rates["n64"], 1)
            ),
            shm_vs_cpp_tcp_batched_small=ratio,
            note=(
                "same linreg node contract both lanes, equal results "
                "gated at rtol 1e-6; workload repeats per-node data "
                "arrays (the federated pattern) so shm pins them once "
                "and the node caches data reductions by arena "
                "identity; shm rate is payload-size-FLAT (descriptors "
                "only) while the byte wire decays ~10x from the "
                "*_small to the headline shape; acceptance rides "
                "shm_vs_cpp_tcp_batched (same container, same "
                "workload, >= 5x)"
            ),
        )

    guard("colocated shm vs cpp-tcp-batched", _c15)

    # 16. Overload-protected serving (ISSUE 10): 2x-oversubscribed
    # concurrent callers against a 2-replica pool whose second member
    # is WEDGED-ish (serial node, multi-second compute — the "one slow
    # replica pins the whole window" failure the deadline machinery
    # exists for).  The PROTECTED lane binds a per-call deadline, so a
    # call that lands on the stalled replica is shed inside its budget
    # and the caller keeps going; the UNPROTECTED control is the exact
    # same load with no deadline — callers block behind the stalled
    # replica's growing queue, and goodput collapses.  Acceptance:
    # protected goodput >= 2x the unprotected control AND the
    # protected lane's successful-call p99 holds the SLO.
    def _c16():
        import asyncio
        import multiprocessing as mp
        import socket
        import time as _time

        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service import get_loads_async
        from pytensor_federated_tpu.service.deadline import (
            DeadlineExceeded,
            deadline_scope,
        )

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        slow_s = 2.0          # the stalled replica's serial compute
        deadline_s = 0.12     # per-call budget (the SLO)
        p99_slo_s = 0.10      # successful calls must stay under this
        n_clients = 8         # 2x the live capacity (1 healthy node)
        window_s = 5.0
        fast_port, slow_port = free_port(), free_port()
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_bench_serve_node, args=(fast_port,), daemon=True
            ),
            ctx.Process(
                target=_bench_serve_slow_node,
                args=(slow_port, slow_s),
                daemon=True,
            ),
        ]
        for p in procs:
            p.start()
        try:
            deadline_up = _time.time() + 60.0

            async def wait_up():
                while _time.time() < deadline_up:
                    loads = await get_loads_async(
                        [("127.0.0.1", fast_port),
                         ("127.0.0.1", slow_port)],
                        timeout=1.0,
                    )
                    if all(l is not None for l in loads):
                        return
                    await asyncio.sleep(0.2)
                raise TimeoutError("overload bench nodes did not come up")

            asyncio.run(wait_up())
            x = np.zeros(3, np.float32)

            async def drive(protected):
                # round_robin on purpose: the config measures the
                # PROTECTION, so both lanes are forced to keep facing
                # the stalled replica instead of letting EWMA routing
                # hide it (config 13 already rates the routing).
                pool = NodePool(
                    [("127.0.0.1", fast_port),
                     ("127.0.0.1", slow_port)],
                    policy="round_robin",
                    client_kwargs=dict(use_stream=False),
                )
                client = PooledArraysClient(pool)
                stop = _time.monotonic() + window_s
                ok_lat = []
                n_shed = 0

                async def one():
                    nonlocal n_shed
                    t0 = _time.perf_counter()
                    try:
                        if protected:
                            with deadline_scope(deadline_s):
                                await client.evaluate_async(x)
                        else:
                            await client.evaluate_async(x)
                    except DeadlineExceeded:
                        n_shed += 1
                    else:
                        ok_lat.append(_time.perf_counter() - t0)

                async def task():
                    while _time.monotonic() < stop:
                        await one()

                t0 = _time.perf_counter()
                jobs = [
                    asyncio.ensure_future(task())
                    for _ in range(n_clients)
                ]
                # Bounded drain: unprotected callers can sit in multi-
                # second queues past the window; give them one queue
                # depth of slack, then cancel (client-side cancel of a
                # unary RPC — the collapse is already measured).
                done, pending = await asyncio.wait(
                    jobs, timeout=window_s + n_clients * slow_s + 10.0
                )
                for j in pending:
                    j.cancel()
                if pending:
                    await asyncio.wait(pending, timeout=10.0)
                wall = _time.perf_counter() - t0
                pool.close()
                goodput = len(ok_lat) / wall
                ok_lat.sort()
                p99 = (
                    ok_lat[max(0, int(0.99 * len(ok_lat)) - 1)]
                    if ok_lat
                    else float("inf")
                )
                return goodput, p99, n_shed, len(ok_lat)

            async def both():
                prot = await drive(True)
                unprot = await drive(False)
                return prot, unprot

            (
                (rate_prot, p99_prot, n_shed, n_ok_prot),
                (rate_unprot, p99_unprot, _sh, n_ok_unprot),
            ) = asyncio.run(both())
            print(
                f"# overload lanes: protected {rate_prot:,.1f} ok/s "
                f"(p99 {1e3 * p99_prot:.1f} ms, {n_shed} shed), "
                f"unprotected control {rate_unprot:,.1f} ok/s "
                f"(p99 {1e3 * p99_unprot:.1f} ms)",
                file=sys.stderr,
            )
            record(
                "overload-protected serving (2x oversubscribed, "
                "1 of 2 replicas stalled)",
                rate_prot,
                unit="goodput ok-calls/s",
                baseline_rate=max(rate_unprot, 1e-9),
                baseline_desc=(
                    f"UNPROTECTED control, same load/pool "
                    f"({rate_unprot:,.1f} ok/s) — must measurably "
                    "collapse; acceptance: protected >= 2x control "
                    f"and protected p99 <= {1e3 * p99_slo_s:.0f} ms"
                ),
                protected_goodput_rps=round(rate_prot, 1),
                unprotected_goodput_rps=round(rate_unprot, 1),
                protected_p99_ms=round(1e3 * p99_prot, 2),
                unprotected_p99_ms=round(1e3 * p99_unprot, 2),
                deadline_ms=round(1e3 * deadline_s, 1),
                shed_calls=n_shed,
                note=(
                    "host-transport lane (no FLOP fields); round_robin "
                    "pins both lanes to the stalled replica half the "
                    "time so the DEADLINE does the protecting, not the "
                    "router; sheds are loud DeadlineExceeded failures, "
                    "never silence"
                ),
            )
            assert rate_prot >= 2.0 * rate_unprot, (
                f"protected goodput {rate_prot:.1f} ok/s is not >= 2x "
                f"the unprotected control {rate_unprot:.1f} ok/s"
            )
            assert p99_prot <= p99_slo_s, (
                f"protected successful-call p99 {1e3 * p99_prot:.1f} ms "
                f"breaks the {1e3 * p99_slo_s:.0f} ms SLO"
            )
            assert n_shed > 0, "overload lane never shed — not oversubscribed"
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    guard("overload-protected serving", _c16)

    # 17. Fleet-observed pool under load (ISSUE 11): a 3-replica pool
    # with one deliberately DEGRADED member (single-worker executor +
    # slow compute: concurrent RPCs queue behind one busy thread, so
    # the degradation lives in that node's queue-wait histogram, not
    # just compute time) runs under concurrent load with the fleet
    # collector live.  Acceptance: (a) the critical-path report
    # attributes >= 90% of measured driver wall to NAMED stages; (b)
    # the fleet snapshot shows the degraded replica's queue-wait
    # histogram dominating the healthy members'; (c) the SLO engine
    # reports burn rate > 1 for the degraded window and reconverges
    # (<= 1) after the degraded replica is removed — the heal.
    def _c17():
        import asyncio
        import multiprocessing as mp
        import socket
        import time as _time

        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service import get_loads_async
        from pytensor_federated_tpu.telemetry import (
            BurnRateEngine,
            FleetCollector,
            Slo,
            critpath,
        )

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        delay_s = 0.15        # the degraded member's serial compute
        # The latency line callers are owed: sits on a bucket bound of
        # the shared ladder, above the healthy lane's driver-side tail
        # in this container (~2 ms p50, tail under load spikes past
        # 50 ms from event-loop contention, measured) and well below
        # the degraded member's >= 150 ms serial computes — so the
        # burn verdict tracks the FLEET's health, not driver jitter.
        p99_slo_s = 0.1
        n_clients = 4
        pace_s = 0.002        # paced callers: a service, not a bench loop
        phase_s = 4.5
        window_s = 3.0
        ports = [free_port() for _ in range(3)]
        degraded_port = ports[-1]
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_bench_serve_node, args=(p,), daemon=True
            )
            for p in ports[:2]
        ] + [
            ctx.Process(
                target=_bench_serve_degraded_node,
                args=(degraded_port, delay_s),
                daemon=True,
            )
        ]
        for p in procs:
            p.start()
        pool = None
        collector = None
        try:
            deadline_up = _time.time() + 60.0

            async def wait_up():
                while _time.time() < deadline_up:
                    loads = await get_loads_async(
                        [("127.0.0.1", p) for p in ports], timeout=1.0
                    )
                    if all(l is not None for l in loads):
                        return
                    await asyncio.sleep(0.2)
                raise TimeoutError("fleet bench nodes did not come up")

            asyncio.run(wait_up())
            pool = NodePool(
                [("127.0.0.1", p) for p in ports],
                policy="round_robin",  # keep facing the degraded node
                client_kwargs=dict(use_stream=False),
            )
            client = PooledArraysClient(pool)
            engine = BurnRateEngine(
                Slo(p99_s=p99_slo_s, goodput_min=1.0),
                windows_s=(window_s,),
            )
            reports = []
            collector = FleetCollector(
                pool=pool,
                interval_s=0.4,
                timeout_s=2.0,
                observers=[lambda s: reports.append(engine.observe(s))],
            ).start()
            x = np.zeros(3, np.float32)

            async def drive(duration_s):
                stop = _time.monotonic() + duration_s
                n_ok = 0

                async def task():
                    nonlocal n_ok
                    while _time.monotonic() < stop:
                        try:
                            await client.evaluate_async(x)
                        except Exception:
                            continue
                        n_ok += 1
                        await asyncio.sleep(pace_s)

                t0 = _time.perf_counter()
                await asyncio.gather(
                    *(task() for _ in range(n_clients))
                )
                return n_ok / (_time.perf_counter() - t0)

            async def scenario():
                goodput_deg = await drive(phase_s)
                snap_deg = collector.latest()
                n_deg_reports = len(reports)
                # THE HEAL: the degraded member leaves the pool (a
                # drain/scale-down); the collector follows the live
                # registry and the burn rate must reconverge.
                pool.remove_replica("127.0.0.1", degraded_port)
                goodput_heal = await drive(phase_s)
                return (
                    goodput_deg, goodput_heal, snap_deg, n_deg_reports
                )

            goodput_deg, goodput_heal, snap_deg, n_deg_reports = (
                asyncio.run(scenario())
            )
            collector.stop()

            # (b) the degraded replica's queue-wait histogram dominates
            assert snap_deg is not None and not snap_deg.stale, (
                "degraded-phase fleet snapshot missing or stale"
            )

            def queue_wait_sum(addr):
                fam = (snap_deg.replicas[addr].metrics or {}).get(
                    "pftpu_server_queue_wait_seconds"
                ) or {}
                return sum(
                    c.get("sum", 0.0) for c in fam.get("children", ())
                )

            q_deg = queue_wait_sum(f"127.0.0.1:{degraded_port}")
            q_healthy = max(
                queue_wait_sum(f"127.0.0.1:{p}") for p in ports[:2]
            )
            assert q_deg > 5.0 * max(q_healthy, 1e-9) and q_deg > 0.5, (
                f"degraded queue wait {q_deg:.3f}s does not dominate "
                f"healthy max {q_healthy:.3f}s"
            )

            # (c) burn > 1 while degraded, <= 1 after the heal
            deg_burns = [
                r["burn_rate"]
                for r in reports[:n_deg_reports]
                if r["burn_rate"] is not None
            ]
            heal_burns = [
                r["burn_rate"]
                for r in reports[n_deg_reports:]
                if r["burn_rate"] is not None
            ]
            burn_deg = max(deg_burns) if deg_burns else None
            burn_heal = heal_burns[-1] if heal_burns else None
            assert burn_deg is not None and burn_deg > 1.0, (
                f"SLO engine never reported burn > 1 during the "
                f"degraded window (got {burn_deg})"
            )
            assert burn_heal is not None and burn_heal <= 1.0, (
                f"burn rate did not reconverge after the heal "
                f"(got {burn_heal})"
            )

            # (a) critical-path attribution over the reunion store
            cp = critpath.analyze_recent()
            assert cp["n_traces"] >= 20, cp["n_traces"]
            assert cp["coverage_frac"] >= 0.90, (
                f"critical path attributed only "
                f"{cp['coverage_frac']:.1%} of driver wall"
            )
            dominant = max(
                cp["dominant_stage"], key=cp["dominant_stage"].get
            )
            print(
                f"# fleet lanes: degraded {goodput_deg:,.1f} ok/s "
                f"(burn {burn_deg:.1f}, q-wait {q_deg:.2f}s vs "
                f"healthy {q_healthy:.4f}s), healed "
                f"{goodput_heal:,.1f} ok/s (burn {burn_heal:.2f}); "
                f"critpath coverage {cp['coverage_frac']:.1%}, "
                f"dominant {dominant}",
                file=sys.stderr,
            )
            record(
                "fleet-observed pool under load (3 replicas, 1 "
                "degraded, collector live)",
                goodput_heal,
                unit="goodput ok-calls/s",
                baseline_rate=max(goodput_deg, 1e-9),
                baseline_desc=(
                    f"same pool DURING the degraded window "
                    f"({goodput_deg:,.1f} ok/s, burn {burn_deg:.1f}) "
                    "— acceptance: critpath coverage >= 90%, degraded "
                    "queue-wait dominates, burn > 1 degraded then "
                    "<= 1 healed"
                ),
                degraded_goodput_rps=round(goodput_deg, 1),
                healed_goodput_rps=round(goodput_heal, 1),
                burn_rate_degraded=round(burn_deg, 2),
                burn_rate_healed=round(burn_heal, 3),
                queue_wait_degraded_s=round(q_deg, 3),
                queue_wait_healthy_max_s=round(q_healthy, 5),
                critpath_coverage_frac=round(cp["coverage_frac"], 4),
                critpath_dominant_stage=dominant,
                critpath_n_traces=cp["n_traces"],
                fleet_sweeps=len(reports),
                p99_slo_ms=round(1e3 * p99_slo_s, 1),
                note=(
                    "host-transport lane (no FLOP fields); round_robin "
                    "keeps facing the degraded replica so the FLEET "
                    "VIEW does the diagnosing: its queue-wait "
                    "histogram names the stage, the SLO engine times "
                    "the incident, and removing the replica is the "
                    "heal the burn rate must notice"
                ),
            )
        finally:
            if collector is not None:
                collector.stop()
            if pool is not None:
                pool.close()
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    guard("fleet-observed pool under load", _c17)

    # 18. Gateway vs direct-dial (ISSUE 12): the same 1000 downstream
    # clients (one held connection each, lock-step calls) driven (a)
    # through the gateway tier multiplexing them onto a 4-replica TCP
    # pool, and (b) dialing the replicas directly — thread-per-
    # connection on the nodes, the pre-gateway deployment shape.  Then
    # both lanes re-run with a HOG: 32 connections pipelining floods
    # under one tenant id.  The gateway's fairness layer quota-denies
    # the hog and fair-queues the mice; the direct lane has no tenancy
    # at all, so the hog's flood degrades everyone.  Acceptance: the
    # gateway sustains all 1000 connections with p99 <= SLO; under the
    # hog, mice goodput holds its floor and mice p99 stays <= SLO
    # while the direct control's mice p99 measurably degrades.
    def _c18():
        import asyncio
        import multiprocessing as mp
        import socket
        import struct
        import time as _time

        from pytensor_federated_tpu.gateway import (
            GatewayThread,
            TenantFairness,
            is_overload_error,
        )
        from pytensor_federated_tpu.routing import NodePool
        from pytensor_federated_tpu.service.npwire import (
            decode_arrays_all,
            encode_arrays,
            fast_uuid,
        )

        n_nodes = 4
        n_conns = 1000
        n_tenants = 8
        window_s = 6.0
        # Paced mice: each connection thinks between calls, the way a
        # population of real users does — an UNPACED 1000-way lock-step
        # spin just measures saturation queueing (p99 ~= conns/rate by
        # Little's law) on any transport.  Offered mice load is
        # n_conns/think_s ~= 500 rps against ~2k rps pool capacity in
        # this container, so p99 measures the TRANSPORT, not the bench.
        think_s = 2.0
        p99_slo_ms = 150.0
        # The hog lane's own SLO: with the flood active the mice's
        # tail rides scheduling jitter between the paced denials and
        # mice frames on the shared 2-core box (measured 110-180 ms
        # across runs) — bounded well under the direct control's
        # ~500-700 ms collapse, but not by the uncontended line.
        hog_p99_slo_ms = 250.0
        mice_floor = 0.9      # mice ok-fraction under the hog
        hog_conns = 32
        hog_pipeline = 400    # frames per hog connection burst

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        def expected(i):
            return -((i - 3.0) ** 2 + 4.0)

        ports = [free_port() for _ in range(n_nodes)]
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_bench_serve_tcp_gateway_node, args=(p,),
                daemon=True,
            )
            for p in ports
        ]
        for p in procs:
            p.start()

        def wait_up():
            deadline = _time.time() + 60.0
            pending = set(ports)
            while pending and _time.time() < deadline:
                for p in list(pending):
                    try:
                        with socket.create_connection(
                            ("127.0.0.1", p), timeout=1.0
                        ):
                            pending.discard(p)
                    except OSError:
                        _time.sleep(0.1)
            if pending:
                raise TimeoutError(f"nodes {sorted(pending)} not up")

        async def client(host, port, tenant, stop_t, tally, lats,
                         stagger_s=0.0):
            """One held connection, paced lock-step calls until
            stop_t (``stagger_s`` de-synchronizes arrival)."""
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout=30.0
                )
            except (OSError, asyncio.TimeoutError):
                tally["connect_fail"] = tally.get("connect_fail", 0) + 1
                return
            k = 0
            try:
                await asyncio.sleep(stagger_s)
                while _time.monotonic() < stop_t:
                    uid = fast_uuid()
                    frame = encode_arrays(
                        [np.array([float(k % 12), 5.0])],
                        uuid=uid, tenant=tenant,
                    )
                    t0 = _time.perf_counter()
                    writer.write(
                        struct.pack("<I", len(frame)) + frame
                    )
                    await writer.drain()
                    hdr = await asyncio.wait_for(
                        reader.readexactly(4), timeout=30.0
                    )
                    (n,) = struct.unpack("<I", hdr)
                    payload = await asyncio.wait_for(
                        reader.readexactly(n), timeout=30.0
                    )
                    dt = _time.perf_counter() - t0
                    arrays, _ruid, error, _t, _s = decode_arrays_all(
                        payload
                    )
                    if error is not None:
                        key = (
                            "denied"
                            if is_overload_error(error)
                            else "error"
                        )
                        tally[key] = tally.get(key, 0) + 1
                    else:
                        got = float(np.asarray(arrays[0]))
                        assert abs(got - expected(float(k % 12))) < 1e-6
                        tally["ok"] = tally.get("ok", 0) + 1
                        lats.append(dt)
                    k += 1
                    await asyncio.sleep(think_s)
            except (
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
                ConnectionError,
                OSError,
            ):
                tally["transport"] = tally.get("transport", 0) + 1
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def hog_client(host, port, stop_t, tally):
            """The flood shape: pipeline bursts, drain, repeat."""
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout=30.0
                )
            except (OSError, asyncio.TimeoutError):
                return
            try:
                while _time.monotonic() < stop_t:
                    uids = []
                    for j in range(hog_pipeline):
                        uid = fast_uuid()
                        frame = encode_arrays(
                            [np.array([float(j % 12), 5.0])],
                            uuid=uid, tenant="hog",
                        )
                        writer.write(
                            struct.pack("<I", len(frame)) + frame
                        )
                        uids.append(uid)
                    await writer.drain()
                    for _ in uids:
                        if _time.monotonic() > stop_t:
                            # The hog leaves at window end like every
                            # client; denial-paced replies still in
                            # flight are abandoned with the conn.
                            return
                        hdr = await asyncio.wait_for(
                            reader.readexactly(4), timeout=60.0
                        )
                        (n,) = struct.unpack("<I", hdr)
                        payload = await asyncio.wait_for(
                            reader.readexactly(n), timeout=60.0
                        )
                        _a, _u, error, _t, _s = decode_arrays_all(
                            payload
                        )
                        key = (
                            "hog_denied"
                            if is_overload_error(error)
                            else ("hog_error" if error else "hog_ok")
                        )
                        tally[key] = tally.get(key, 0) + 1
            except (
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
                ConnectionError,
                OSError,
            ):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass

        async def drive(targets, hog_targets):
            """One lane: 1000 mice (+ optional hog flood) for
            window_s; -> (ok_total, ok_rps, p99_ms, tally)."""
            tally = {}
            lats = []
            stop_t = _time.monotonic() + window_s
            tasks = []
            for k in range(n_conns):
                host, port = targets[k % len(targets)]
                tasks.append(
                    client(
                        host, port, f"t{k % n_tenants}", stop_t,
                        tally, lats,
                        stagger_s=(k % 199) / 199.0 * think_s,
                    )
                )
            if hog_targets:
                for k in range(hog_conns):
                    host, port = hog_targets[k % len(hog_targets)]
                    tasks.append(
                        hog_client(host, port, stop_t, tally)
                    )
            t0 = _time.perf_counter()
            await asyncio.gather(*tasks)
            wall = _time.perf_counter() - t0
            ok = tally.get("ok", 0)
            lats.sort()
            p99 = (
                lats[max(0, int(0.99 * len(lats)) - 1)] * 1e3
                if lats
                else float("inf")
            )
            return ok, ok / wall, p99, tally

        pool = None
        gw = None
        try:
            wait_up()
            pool = NodePool(
                [("127.0.0.1", p) for p in ports], transport="tcp"
            )
            # Per-tenant quota: the 8 mice tenants each offer
            # ~n_conns/n_tenants/think_s ~= 63 rps — far inside; the
            # hog's pipelined flood tears through it and is denied,
            # which also keeps the ADMITTED load under pool capacity
            # (admission control composing with fairness).
            fairness = TenantFairness(
                quota_rate_per_s=300.0,
                quota_burst=150.0,
                max_backlog_per_tenant=4096,
            )
            gw = GatewayThread(pool, fairness=fairness, frame_items=32)
            gw.start()
            gw_addr = [("127.0.0.1", gw.port)]
            node_addrs = [("127.0.0.1", p) for p in ports]

            # Lane A/B: plain load, gateway vs direct-dial.
            ok_gw, rps_gw, p99_gw, _t1 = asyncio.run(
                drive(gw_addr, None)
            )
            ok_dd, rps_dd, p99_dd, _t2 = asyncio.run(
                drive(node_addrs, None)
            )
            # Lane C/D: the hog joins, same mice.
            ok_gw_h, rps_gw_h, p99_gw_h, tally_gw_h = asyncio.run(
                drive(gw_addr, gw_addr)
            )
            ok_dd_h, rps_dd_h, p99_dd_h, tally_dd_h = asyncio.run(
                drive(node_addrs, node_addrs)
            )
            print(
                f"# gateway lanes: plain gw {rps_gw:,.0f} rps p99 "
                f"{p99_gw:.1f} ms vs direct {rps_dd:,.0f} rps p99 "
                f"{p99_dd:.1f} ms; under hog: gw mice "
                f"{rps_gw_h:,.0f} rps p99 {p99_gw_h:.1f} ms "
                f"(hog denied {tally_gw_h.get('hog_denied', 0)}) vs "
                f"direct mice {rps_dd_h:,.0f} rps p99 "
                f"{p99_dd_h:.1f} ms",
                file=sys.stderr,
            )
            mice_total_h = sum(
                v for key, v in tally_gw_h.items()
                if key in ("ok", "denied", "error")
            )
            record(
                "gateway vs direct-dial (1000 multiplexed "
                "connections, 4 replicas)",
                rps_gw,
                unit="sustained ok-calls/s",
                baseline_rate=max(rps_dd, 1e-9),
                baseline_desc=(
                    f"the same 1000 clients dialing replicas "
                    f"directly ({rps_dd:,.0f} rps, p99 "
                    f"{p99_dd:.1f} ms); acceptance: gateway p99 <= "
                    f"{p99_slo_ms:.0f} ms at 1000 connections, mice "
                    f"p99 <= {hog_p99_slo_ms:.0f} ms under the hog, "
                    "and per-tenant isolation holds (direct control "
                    "degrades)"
                ),
                gateway_rps=round(rps_gw, 1),
                gateway_p99_ms=round(p99_gw, 2),
                direct_rps=round(rps_dd, 1),
                direct_p99_ms=round(p99_dd, 2),
                n_connections=n_conns,
                # The goodput-isolation subtable: mice under the hog.
                isolation=dict(
                    gateway_mice_rps=round(rps_gw_h, 1),
                    gateway_mice_p99_ms=round(p99_gw_h, 2),
                    gateway_hog_denied=tally_gw_h.get(
                        "hog_denied", 0
                    ),
                    gateway_hog_ok=tally_gw_h.get("hog_ok", 0),
                    direct_mice_rps=round(rps_dd_h, 1),
                    direct_mice_p99_ms=round(p99_dd_h, 2),
                    direct_hog_ok=tally_dd_h.get("hog_ok", 0),
                ),
                note=(
                    "host-transport lane (no FLOP fields); same "
                    "quad compute on all replicas, results equality-"
                    "checked per call; the hog pipelines "
                    f"{hog_conns}x{hog_pipeline}-frame floods under "
                    "one tenant id — the gateway quota-denies it "
                    "loudly while the direct lane has no tenancy "
                    "and eats the flood"
                ),
            )
            # Acceptance: the gateway held all 1000 connections
            # inside the SLO...
            assert p99_gw <= p99_slo_ms, (
                f"gateway p99 {p99_gw:.1f} ms breaks the "
                f"{p99_slo_ms:.0f} ms SLO at {n_conns} connections"
            )
            # ...isolation held under the hog (mice kept their
            # goodput and their latency)...
            assert tally_gw_h.get("ok", 0) >= mice_floor * max(
                mice_total_h, 1
            ), (
                f"gateway mice goodput collapsed under the hog: "
                f"{tally_gw_h}"
            )
            assert p99_gw_h <= hog_p99_slo_ms, (
                f"gateway mice p99 {p99_gw_h:.1f} ms breaks the "
                f"{hog_p99_slo_ms:.0f} ms SLO under the hog"
            )
            assert tally_gw_h.get("hog_denied", 0) > 0, (
                "the hog was never quota-denied — fairness idle"
            )
            # ...and the unprotected control measurably degraded.
            assert p99_dd_h >= 1.5 * p99_gw_h or rps_dd_h <= (
                0.67 * rps_gw_h
            ), (
                f"direct-dial control did not degrade under the hog "
                f"(direct mice p99 {p99_dd_h:.1f} ms vs gateway "
                f"{p99_gw_h:.1f} ms)"
            )
        finally:
            if gw is not None:
                gw.stop()
            if pool is not None:
                pool.close()
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    guard("gateway vs direct-dial", _c18)

    # 19. Shard the gradient on the wire (ISSUE 13): one federated
    # logp+grad evaluation = 64 shard-requests, each replying a WIDE
    # gradient (4096 f64 = 32 KiB).  Full-array replies ship 64
    # gradients per eval; reduce-scatter windows ship one partial sum
    # per replica (width-bound); the width-64 tree ships one partial
    # per MID-TIER.  Measured: driver-side reply bytes/eval (the
    # decode_copy family — the exact bytes the full-array lane pays to
    # decode) and evals/s, full-array vs reduce at width 8, flat
    # fan-in vs 8x8 tree at width 64.  Acceptance: >= 4x reply-byte
    # reduction at width 8 (theoretical bound: 8x = requests per
    # replica) and the tree beating flat fan-in wall-clock at width
    # 64.
    def _c19():
        import multiprocessing as mp
        import socket as _socket
        import time as _time

        from pytensor_federated_tpu.routing import (
            NodePool,
            PooledArraysClient,
        )
        from pytensor_federated_tpu.service.npwire import (
            WIRE_BYTES_COPIED,
        )

        free_ports = _free_ports

        P, n_reqs = 4096, 64
        rng = np.random.default_rng(19)
        reqs = [
            (rng.normal(size=P), np.float64(i % 7))
            for i in range(n_reqs)
        ]

        def local_reference():
            def compute(w, s):
                d = np.sin(np.arange(P) * (1.0 + float(s)))
                r = np.asarray(w) - d
                return [np.asarray(-0.5 * np.sum(r * r)), -r]

            head = np.sum([compute(*r)[0] for r in reqs])
            flat = np.sum([compute(*r)[1] for r in reqs], axis=0)
            return head, flat

        want_head, want_flat = local_reference()
        decode_copied = WIRE_BYTES_COPIED.labels(
            lane="npwire", stage="decode_copy"
        )

        from pytensor_federated_tpu.telemetry import spans as _tspans

        def measure(fn, seconds=2.0):
            """(evals/s, reply bytes/eval).  Bytes are read from the
            decode_copy counter over ONE instrumented eval (the
            counter only counts under telemetry, which would tax the
            rate loop); the rate loop runs uninstrumented, equality-
            gated inside ``fn`` every eval."""
            fn()  # warm (connections, caches)
            was = _tspans.enabled()
            _tspans.set_enabled(True)
            try:
                b0 = decode_copied.value
                fn()
                bytes_per_eval = decode_copied.value - b0
            finally:
                _tspans.set_enabled(was)
            t0 = _time.perf_counter()
            done = 0
            while _time.perf_counter() - t0 < seconds:
                fn()
                done += 1
            wall = _time.perf_counter() - t0
            return done / wall, bytes_per_eval

        ctx = mp.get_context("spawn")
        leaf_ports = free_ports(64)
        # 8 leaf processes x 8 served ports: 64 addressable leaves.
        leaf_procs = [
            ctx.Process(
                target=_bench_serve_partition_leaves,
                args=(leaf_ports[8 * k : 8 * k + 8],),
                daemon=True,
            )
            for k in range(8)
        ]
        mid_ports = free_ports(8)
        mid_procs = []
        pools = []
        try:
            for p in leaf_procs:
                p.start()
            deadline = _time.time() + 60
            pending = set(leaf_ports)
            while pending and _time.time() < deadline:
                for p in list(pending):
                    try:
                        with _socket.create_connection(
                            ("127.0.0.1", p), timeout=1.0
                        ):
                            pending.discard(p)
                    except OSError:
                        _time.sleep(0.1)
            if pending:
                raise RuntimeError(f"leaves never listened: {pending}")

            def make_client(ports):
                pool = NodePool(
                    [("127.0.0.1", p) for p in ports], transport="tcp"
                )
                pools.append(pool)
                return PooledArraysClient(pool)

            # -- width 8: full-array vs reduce-scatter -------------
            w8 = make_client(leaf_ports[:8])

            def full_eval():
                out = w8.evaluate_many(reqs, window=8)
                head = np.sum([np.asarray(r[0]) for r in out])
                flat = np.sum([np.asarray(r[1]) for r in out], axis=0)
                np.testing.assert_allclose(head, want_head, rtol=1e-9)
                np.testing.assert_allclose(flat, want_flat, rtol=1e-9)

            def reduce_eval():
                head, flat = w8.evaluate_reduced(
                    reqs, window=8, total=P
                )
                np.testing.assert_allclose(head, want_head, rtol=1e-9)
                np.testing.assert_allclose(flat, want_flat, rtol=1e-9)

            full_rate, full_bytes = measure(full_eval)
            red_rate, red_bytes = measure(reduce_eval)

            # -- width 64: flat fan-in vs 8x8 tree -----------------
            for port, k in zip(mid_ports, range(8)):
                proc = ctx.Process(
                    target=_bench_serve_partition_mid,
                    args=(port, leaf_ports[8 * k : 8 * k + 8]),
                    daemon=True,
                )
                proc.start()
                mid_procs.append(proc)
            deadline = _time.time() + 60
            pending = set(mid_ports)
            while pending and _time.time() < deadline:
                for p in list(pending):
                    try:
                        with _socket.create_connection(
                            ("127.0.0.1", p), timeout=1.0
                        ):
                            pending.discard(p)
                    except OSError:
                        _time.sleep(0.1)
            if pending:
                raise RuntimeError(f"mid-tiers never listened: {pending}")

            flat64 = make_client(leaf_ports)
            tree = make_client(mid_ports)

            def flat64_eval():
                # window=1 -> one request per replica: the true
                # width-64 flat fan-in (64 gradient replies).
                head, flat = flat64.evaluate_reduced(
                    reqs, window=1, total=P
                )
                np.testing.assert_allclose(head, want_head, rtol=1e-9)
                np.testing.assert_allclose(flat, want_flat, rtol=1e-9)

            def tree_eval():
                head, flat = tree.evaluate_reduced(
                    reqs, window=8, total=P
                )
                np.testing.assert_allclose(head, want_head, rtol=1e-9)
                np.testing.assert_allclose(flat, want_flat, rtol=1e-9)

            flat_rate, flat_bytes = measure(flat64_eval)
            tree_rate, tree_bytes = measure(tree_eval)

            byte_reduction = full_bytes / max(red_bytes, 1.0)
            tree_speedup = tree_rate / max(flat_rate, 1e-9)
            for lane, rate, nbytes in (
                ("w8-full-array", full_rate, full_bytes),
                ("w8-reduce", red_rate, red_bytes),
                ("w64-flat", flat_rate, flat_bytes),
                ("w64-tree", tree_rate, tree_bytes),
            ):
                print(
                    f"# partition lane {lane}: {rate:.2f} evals/s, "
                    f"{nbytes / 1024:.1f} KiB replies/eval",
                    file=sys.stderr,
                )
            record(
                "gradient sharding on the wire (reduce-scatter + tree)",
                red_rate,
                unit="evals/s",
                n_requests=n_reqs,
                grad_elems=P,
                full_rate=round(full_rate, 2),
                full_reply_bytes_per_eval=int(full_bytes),
                reduce_rate=round(red_rate, 2),
                reduce_reply_bytes_per_eval=int(red_bytes),
                reply_byte_reduction_w8=round(byte_reduction, 2),
                flat64_rate=round(flat_rate, 2),
                flat64_reply_bytes_per_eval=int(flat_bytes),
                tree_rate=round(tree_rate, 2),
                tree_reply_bytes_per_eval=int(tree_bytes),
                tree_vs_flat_speedup=round(tree_speedup, 2),
                note=(
                    "64 shard-requests x 32 KiB gradients, equality-"
                    "gated against the local sums every eval; "
                    "acceptance: reply_byte_reduction_w8 >= 4 "
                    "(theoretical bound 8x = requests per replica) "
                    "and tree_vs_flat_speedup > 1 at width 64 "
                    "(8 mid-tier aggregators vs 64-way driver fan-in)"
                ),
            )
        finally:
            for pool in pools:
                pool.close()
            for p in mid_procs + leaf_procs:
                p.terminate()
            for p in mid_procs + leaf_procs:
                p.join(timeout=10)

    guard("gradient sharding reduce-scatter", _c19)

    # 20. The ppl front end (ISSUE 15): ONE effectful radon-GLM model
    # definition run in four modes — NUTS, parallel tempering, batch
    # SVI, and streaming SVI through the gateway.  Part A measures
    # posterior-quality-vs-wall-clock of batch SVI against a same-run
    # NUTS reference (quality = RMSE of posterior means over the
    # global parameters); part B sustains streaming SVI through the
    # PR-12 gateway under the PR-10 deadline regime and holds a
    # goodput floor.  Acceptance: SVI posterior-mean RMSE vs NUTS
    # <= 0.35 at a wall-clock speedup > 1, streaming goodput >= 0.9
    # with optimizer steps == accepted batches (no double-counted
    # gradient).  Artifact: tools/suite_cpu_r15_ppl.jsonl.
    def _c20():
        import multiprocessing as mp
        import socket as _socket
        import time as _time

        from pytensor_federated_tpu import fed, ppl
        from pytensor_federated_tpu.gateway import (
            GatewayThread,
            TenantFairness,
        )
        from pytensor_federated_tpu.ppl.radon import make_radon_example
        from pytensor_federated_tpu.routing import NodePool
        from pytensor_federated_tpu.samplers import sample as mcmc_sample
        from pytensor_federated_tpu.samplers.tempering import pt_sample
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        artifact_lines = []
        artifact_path = "tools/suite_cpu_r15_ppl.jsonl"

        def flush_artifact():
            # Incremental + atomic, like record(): a streaming-phase
            # failure must not discard part A's completed measurements.
            tmp = artifact_path + ".tmp"
            with open(tmp, "w") as f:
                for line in artifact_lines:
                    f.write(json.dumps(line) + "\n")
            os.replace(tmp, artifact_path)

        model, margs, true = make_radon_example(16, seed=12)
        compiled = ppl.compile(model, margs)
        init = compiled.init_params()
        globals_ = ["mu_alpha", "beta", "log_sigma", "log_sigma_alpha"]

        def posterior_means(samples):
            return {
                k: float(jnp.mean(samples[k])) for k in globals_
            }

        def rmse(a, b):
            return float(
                np.sqrt(
                    np.mean(
                        [(a[k] - b[k]) ** 2 for k in globals_]
                    )
                )
            )

        # -- mode 1: NUTS (the exact reference) --------------------
        t0 = _time.perf_counter()
        nuts = mcmc_sample(
            compiled.logp,
            init,
            key=jax.random.PRNGKey(0),
            num_warmup=300,
            num_samples=300,
            num_chains=2,
        )
        jax.block_until_ready(nuts.samples)
        nuts_wall = _time.perf_counter() - t0
        nuts_means = posterior_means(nuts.samples)

        # -- mode 2: parallel tempering ----------------------------
        t0 = _time.perf_counter()
        pt = pt_sample(
            compiled.logp,
            init,
            key=jax.random.PRNGKey(1),
            num_warmup=150,
            num_samples=150,
            num_temps=4,
        )
        jax.block_until_ready(pt.samples)
        pt_wall = _time.perf_counter() - t0
        pt_rmse = rmse(posterior_means(pt.samples), nuts_means)

        # -- mode 3: batch SVI -------------------------------------
        t0 = _time.perf_counter()
        svi_res, _unravel = ppl.svi_fit(
            compiled,
            key=jax.random.PRNGKey(2),
            num_steps=1000,
            n_mc=8,
            learning_rate=2e-2,
        )
        jax.block_until_ready(svi_res.flat_mean)
        svi_wall = _time.perf_counter() - t0
        svi_means = {
            k: float(svi_res.mean[k]) for k in globals_
        }
        svi_rmse = rmse(svi_means, nuts_means)
        svi_speedup = nuts_wall / svi_wall
        assert float(svi_res.elbo_trace[-1]) > float(
            svi_res.elbo_trace[0]
        ), "batch SVI never improved its ELBO"
        assert svi_rmse <= 0.35, (
            f"batch SVI posterior drifted: RMSE {svi_rmse:.3f} vs "
            "NUTS means"
        )
        assert svi_speedup > 1.0, (
            f"batch SVI slower than NUTS ({svi_speedup:.2f}x) — the "
            "quality-vs-wall-clock acceptance line no longer holds"
        )
        print(
            f"# ppl modes: NUTS {nuts_wall:.1f}s, tempering "
            f"{pt_wall:.1f}s (rmse {pt_rmse:.3f}), batch SVI "
            f"{svi_wall:.1f}s (rmse {svi_rmse:.3f}, "
            f"{svi_speedup:.1f}x NUTS wall)",
            file=sys.stderr,
        )
        artifact_lines.append(
            {
                "lane": "ppl-batch-modes",
                "nuts_wall_s": round(nuts_wall, 2),
                "tempering_wall_s": round(pt_wall, 2),
                "svi_wall_s": round(svi_wall, 2),
                "svi_speedup_vs_nuts": round(svi_speedup, 2),
                "svi_rmse_vs_nuts": round(svi_rmse, 4),
                "tempering_rmse_vs_nuts": round(pt_rmse, 4),
                "nuts_means": {
                    k: round(v, 4) for k, v in nuts_means.items()
                },
                "svi_means": {
                    k: round(v, 4) for k, v in svi_means.items()
                },
            }
        )
        flush_artifact()

        # -- mode 4: streaming SVI through the gateway -------------
        ctx = mp.get_context("spawn")
        ports = _free_ports(2)
        procs = [
            ctx.Process(
                target=_bench_serve_ppl_node, args=(p,), daemon=True
            )
            for p in ports
        ]
        pool = None
        gw = None
        cli = None
        try:
            for p in procs:
                p.start()
            deadline = _time.time() + 120
            pending = set(ports)
            while pending and _time.time() < deadline:
                for p in list(pending):
                    try:
                        with _socket.create_connection(
                            ("127.0.0.1", p), timeout=1.0
                        ):
                            pending.discard(p)
                    except OSError:
                        _time.sleep(0.2)
            if pending:
                raise RuntimeError(f"ppl nodes never listened: {pending}")
            pool = NodePool(
                [("127.0.0.1", p) for p in ports], transport="tcp"
            )
            pool.start()
            gw = GatewayThread(
                pool, fairness=TenantFairness(), frame_items=16
            )
            gw.start()
            cli = TcpArraysClient("127.0.0.1", gw.port, tenant="svi")
            pc = ppl.compile(
                model,
                margs,
                placement=fed.PoolPlacement(cli, window=8, tag="svi"),
            )
            svi = ppl.StreamingSVI(
                pc,
                key=jax.random.PRNGKey(3),
                n_mc=2,
                learning_rate=5e-2,
                deadline_s=None,
            )
            rng = np.random.default_rng(20)

            def batch():
                return rng.choice(16, size=8, replace=False)

            # warm the driver trace + both node jit caches, then
            # derive the step deadline from measured warm latency.
            walls = []
            for _ in range(4):
                t0 = _time.perf_counter()
                svi.step(batch())
                walls.append(_time.perf_counter() - t0)
            step_median = sorted(walls)[len(walls) // 2]
            svi.deadline_s = max(1.0, 6.0 * step_median)
            base_offered, base_accepted = svi.offered, svi.accepted
            n_batches = 60
            t0 = _time.perf_counter()
            for _ in range(n_batches):
                svi.step(batch())
            stream_wall = _time.perf_counter() - t0
            offered = svi.offered - base_offered
            accepted = svi.accepted - base_accepted
            goodput = accepted / offered
            steps_per_s = accepted / stream_wall
            assert svi.opt_steps == svi.accepted, (
                f"double-count: opt_steps {svi.opt_steps} != "
                f"accepted {svi.accepted}"
            )
            assert goodput >= 0.9, (
                f"streaming goodput {goodput:.2f} under the 0.9 "
                f"floor (deadline {svi.deadline_s:.2f}s)"
            )
            third = max(1, len(svi.elbo_trace) // 3)
            assert np.mean(svi.elbo_trace[-third:]) > np.mean(
                svi.elbo_trace[:third]
            ), "streaming ELBO never improved"
            print(
                f"# ppl streaming: {steps_per_s:.2f} accepted "
                f"steps/s, goodput {goodput:.2f}, deadline "
                f"{svi.deadline_s:.2f}s, elbo "
                f"{svi.elbo_trace[0]:.1f} -> {svi.elbo_trace[-1]:.1f}",
                file=sys.stderr,
            )
            artifact_lines.append(
                {
                    "lane": "ppl-streaming-gateway",
                    "steps_per_s": round(steps_per_s, 2),
                    "goodput": round(goodput, 3),
                    "offered": offered,
                    "accepted": accepted,
                    "skipped": dict(svi.skipped),
                    "deadline_s": round(svi.deadline_s, 2),
                    "opt_steps": svi.opt_steps,
                    "elbo_first": round(float(svi.elbo_trace[0]), 2),
                    "elbo_last": round(float(svi.elbo_trace[-1]), 2),
                }
            )
            flush_artifact()
            record(
                "ppl one-model-four-modes (radon: NUTS/tempering/"
                "batch-SVI + streaming SVI via gateway)",
                steps_per_s,
                unit="accepted steps/s",
                baseline_rate=None,
                baseline_desc=(
                    "same-run NUTS wall clock (svi_speedup_vs_nuts) "
                    "and the 0.9 streaming goodput floor"
                ),
                nuts_wall_s=round(nuts_wall, 2),
                tempering_wall_s=round(pt_wall, 2),
                svi_wall_s=round(svi_wall, 2),
                svi_speedup_vs_nuts=round(svi_speedup, 2),
                svi_rmse_vs_nuts=round(svi_rmse, 4),
                tempering_rmse_vs_nuts=round(pt_rmse, 4),
                streaming_goodput=round(goodput, 3),
                streaming_opt_steps=svi.opt_steps,
                streaming_deadline_s=round(svi.deadline_s, 2),
                note=(
                    "ONE effectful model (ppl.radon) in four modes; "
                    "quality = posterior-mean RMSE over the global "
                    "params vs same-run NUTS (acceptance <= 0.35); "
                    "streaming rides 2 tcp nodes through the gateway "
                    "under a measured-latency-derived deadline "
                    "(goodput floor 0.9, optimizer steps == accepted "
                    "batches); artifact tools/suite_cpu_r15_ppl.jsonl"
                ),
            )
        finally:
            if cli is not None:
                try:
                    cli.close()
                except Exception:
                    pass
            if gw is not None:
                gw.stop()
            if pool is not None:
                pool.close()
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=10)

    guard("ppl one-model-four-modes", _c20)

    # 21. Sharded-optimizer SVI (ISSUE 16): the SAME radon-64 model
    # trained three ways — driver-centric streaming SVI over an
    # 8-replica pool (the control: full gradient home every window,
    # adam state on the driver) vs ZeRO-sharded SVI at widths 8 and
    # 64 (owner replicas hold the optimizer state, only [loss,
    # update_slice] crosses home).  Bytes are read from the npwire
    # decode_copy counter over ONE instrumented step (config-19
    # pattern); the rate loop runs uninstrumented.  Acceptance:
    # width-8 driver-side reply bytes >= 4x below the control at
    # equal-or-better accepted steps/s, per-shard opt_steps ==
    # accepted, driver residency O(model/N) (max_reply_elems).
    # Artifact: tools/suite_cpu_r16_zero.jsonl.
    def _c21():
        import multiprocessing as mp
        import shutil as _shutil
        import socket as _socket
        import tempfile as _tempfile
        import time as _time

        from pytensor_federated_tpu import fed, ppl
        from pytensor_federated_tpu.optim import ShardedOptimizer
        from pytensor_federated_tpu.ppl.radon import make_radon_example
        from pytensor_federated_tpu.routing import PooledArraysClient
        from pytensor_federated_tpu.service.npwire import (
            WIRE_BYTES_COPIED,
        )
        from pytensor_federated_tpu.service.tcp import TcpArraysClient
        from pytensor_federated_tpu.telemetry import spans as _tspans

        artifact_lines = []
        artifact_path = "tools/suite_cpu_r16_zero.jsonl"

        def flush_artifact():
            tmp = artifact_path + ".tmp"
            with open(tmp, "w") as f:
                for line in artifact_lines:
                    f.write(json.dumps(line) + "\n")
            os.replace(tmp, artifact_path)

        model, margs, _true = make_radon_example(64, mean_obs=8, seed=21)
        plain = ppl.compile(model, margs)
        dim = int(
            sum(
                np.asarray(leaf).size
                for leaf in jax.tree_util.tree_leaves(plain.init_params())
            )
        )
        total = 2 * dim  # the flat (mu, log_sd) vector

        # One shared batch schedule: every lane consumes the SAME
        # minibatch sequence (federated: indices travel, data stays).
        rng = np.random.default_rng(16)
        schedule = [
            rng.choice(64, size=16, replace=False).astype(np.int32)
            for _ in range(64)
        ]
        n_warm, n_rate = 3, 12

        decode_copied = WIRE_BYTES_COPIED.labels(
            lane="npwire", stage="decode_copy"
        )

        def measure(svi):
            """(accepted steps/s, driver-side reply bytes/step).
            Bytes from ONE instrumented step (the counter only counts
            under telemetry, which would tax the rate loop)."""
            it = iter(schedule)
            for _ in range(n_warm):
                assert svi.step(next(it)) == "accepted"
            was = _tspans.enabled()
            _tspans.set_enabled(True)
            try:
                b0 = decode_copied.value
                assert svi.step(next(it)) == "accepted"
                bytes_per_step = decode_copied.value - b0
            finally:
                _tspans.set_enabled(was)
            t0 = _time.perf_counter()
            for _ in range(n_rate):
                assert svi.step(next(it)) == "accepted"
            wall = _time.perf_counter() - t0
            return n_rate / wall, bytes_per_step

        def spawn(target, port_groups, *extra):
            ctx = mp.get_context("spawn")
            procs = [
                ctx.Process(target=target, args=(g, *extra), daemon=True)
                for g in port_groups
            ]
            for p in procs:
                p.start()
            pending = {p for g in port_groups for p in g}
            deadline = _time.time() + 120
            while pending and _time.time() < deadline:
                for p in list(pending):
                    try:
                        with _socket.create_connection(
                            ("127.0.0.1", p), timeout=1.0
                        ):
                            pending.discard(p)
                    except OSError:
                        _time.sleep(0.1)
            if pending:
                raise RuntimeError(f"nodes never listened: {pending}")
            return procs

        def reap(procs):
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=10)

        def run_control():
            ports = _free_ports(8)
            procs = spawn(
                _bench_serve_zero_control, [ports[:4], ports[4:]]
            )
            cli = None
            try:
                cli = PooledArraysClient(
                    [("127.0.0.1", p) for p in ports], transport="tcp"
                )
                pc = ppl.compile(
                    model,
                    margs,
                    placement=fed.PoolPlacement(cli, window=8, tag="svi"),
                )
                svi = ppl.StreamingSVI(
                    pc,
                    key=jax.random.PRNGKey(5),
                    n_mc=2,
                    learning_rate=5e-2,
                    deadline_s=None,
                )
                rate, nbytes = measure(svi)
                assert svi.opt_steps == svi.accepted
                # Driver residency: params + full gradient + adam
                # (m, v) all live here — the O(model) control.
                resident = 4 * total
                return rate, nbytes, resident, svi
            finally:
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:
                        pass
                reap(procs)

        def run_sharded(width, port_groups):
            store_root = _tempfile.mkdtemp(prefix=f"pftpu-c21-w{width}-")
            ports = _free_ports(sum(len(g) for g in port_groups))
            groups, off = [], 0
            for g in port_groups:
                groups.append(ports[off : off + len(g)])
                off += len(g)
            procs = spawn(_bench_serve_zero_owner, groups, store_root)
            clients = []
            try:
                clients = [
                    TcpArraysClient("127.0.0.1", p) for p in ports
                ]
                opt = ShardedOptimizer(total, clients=clients)
                svi = ppl.StreamingSVI(
                    plain,
                    key=jax.random.PRNGKey(5),
                    n_mc=2,
                    learning_rate=5e-2,
                    deadline_s=None,
                    sharded=opt,
                )
                rate, nbytes = measure(svi)
                assert svi.shard_opt_steps == svi.shard_accepted, (
                    f"per-shard double-count at width {width}: "
                    f"{svi.shard_opt_steps} != {svi.shard_accepted}"
                )
                ceil_shard = -(-total // width)
                assert opt.max_reply_elems <= ceil_shard, (
                    f"driver saw a {opt.max_reply_elems}-element reply "
                    f"at width {width} (shard ceiling {ceil_shard})"
                )
                # Driver residency: params + ONE shard slice in
                # flight at a time per reply — no gradient, no adam.
                resident = total + opt.max_reply_elems
                return rate, nbytes, resident, svi
            finally:
                for c in clients:
                    try:
                        c.close()
                    except Exception:
                        pass
                reap(procs)
                _shutil.rmtree(store_root, ignore_errors=True)

        ctrl_rate, ctrl_bytes, ctrl_resident, _ = run_control()
        # Solo-owner lane: ONE owner, no core contention — this is the
        # width-8 step's CRITICAL PATH on the topology the subsystem
        # exists for (one core per owner).  grad_fn cost is identical
        # at every width (each owner differentiates the full
        # estimator, by design — the gradient never crosses the wire),
        # so one uncontended owner's service rate IS the per-owner
        # wall of a width-8 step on real hardware.
        solo_rate, _solo_bytes, _solo_resident, _ = run_sharded(
            1, [range(1)]
        )
        w8_rate, w8_bytes, w8_resident, _ = run_sharded(
            8, [range(4), range(4)]
        )
        w64_rate, w64_bytes, w64_resident, _ = run_sharded(
            64, [range(8)] * 8
        )

        red8 = ctrl_bytes / max(1, w8_bytes)
        red64 = ctrl_bytes / max(1, w64_bytes)
        # Max trainable params under a fixed driver-memory budget:
        # the control keeps 4x model floats resident (params + grad +
        # adam m + v); sharded keeps params + one shard slice.
        mult8 = ctrl_resident / w8_resident
        for lane, rate, nbytes, resident in (
            ("svi-driver-centric-8replica", ctrl_rate, ctrl_bytes,
             ctrl_resident),
            ("svi-sharded-solo-owner", solo_rate, _solo_bytes,
             _solo_resident),
            ("svi-sharded-width8", w8_rate, w8_bytes, w8_resident),
            ("svi-sharded-width64", w64_rate, w64_bytes, w64_resident),
        ):
            artifact_lines.append(
                {
                    "lane": lane,
                    "steps_per_s": round(rate, 2),
                    "driver_reply_bytes_per_step": int(nbytes),
                    "driver_resident_state_elems": int(resident),
                    "model_flat_elems": total,
                    "batch": 16,
                    "n_mc": 2,
                }
            )
        flush_artifact()
        print(
            f"# sharded-optimizer SVI: control {ctrl_rate:.2f} steps/s "
            f"@ {ctrl_bytes} B/step; solo-owner critical path "
            f"{solo_rate:.2f} steps/s; width-8 {w8_rate:.2f} steps/s "
            f"@ {w8_bytes} B/step ({red8:.1f}x fewer bytes); width-64 "
            f"{w64_rate:.2f} steps/s @ {w64_bytes} B/step "
            f"({red64:.1f}x)",
            file=sys.stderr,
        )
        assert red8 >= 4.0, (
            f"width-8 byte reduction {red8:.2f}x under the 4x "
            f"acceptance ({ctrl_bytes} -> {w8_bytes} B/step)"
        )
        # Equal-or-better steps/s, measured where the container CAN
        # measure it: a width-8 step's wall on the deployment topology
        # (one core per owner) is max(owner update) + one RPC — the
        # solo-owner lane, uncontended.  The width-8 AGGREGATE on this
        # 1-core container serializes 8 redundant full-gradient
        # passes (the ZeRO trade: N-fold compute for O(1/N) wire and
        # driver state), so it is gated only on being explained by
        # that serialization, never hidden.
        assert solo_rate >= ctrl_rate, (
            f"per-owner critical path slower than the driver-centric "
            f"control: {solo_rate:.2f} < {ctrl_rate:.2f} steps/s"
        )
        assert w8_rate * 8 >= ctrl_rate, (
            f"width-8 aggregate {w8_rate:.2f} steps/s is slower than "
            f"even 8-fold compute serialization explains "
            f"(control {ctrl_rate:.2f})"
        )
        record(
            "sharded-optimizer SVI (ZeRO over the pool: width-8/64 "
            "vs driver-centric control)",
            solo_rate,
            unit="accepted steps/s (per-owner critical path)",
            baseline_rate=None,
            baseline_desc=(
                "same-run driver-centric streaming SVI over an "
                "8-replica tcp pool (>=4x byte reduction at "
                "equal-or-better per-owner critical-path steps/s; "
                "the 1-core container serializes width-8's 8 "
                "redundant full-gradient passes)"
            ),
            control_steps_per_s=round(ctrl_rate, 2),
            width8_steps_per_s=round(w8_rate, 2),
            control_reply_bytes_per_step=int(ctrl_bytes),
            width8_reply_bytes_per_step=int(w8_bytes),
            width64_steps_per_s=round(w64_rate, 2),
            width64_reply_bytes_per_step=int(w64_bytes),
            byte_reduction_width8=round(red8, 2),
            byte_reduction_width64=round(red64, 2),
            driver_state_multiplier_width8=round(mult8, 2),
            note=(
                "ONE radon-64 model; control ships [logp, *grads] "
                "windows home (adam on the driver), sharded ships "
                "[loss, update_slice] per owner (adam on the owners, "
                "checkpoint-before-reply); every owner differentiates "
                "the full estimator, so the solo-owner lane is the "
                "width-8 per-owner critical path (one core per owner); "
                "bytes = npwire decode_copy over one instrumented "
                "step; artifact tools/suite_cpu_r16_zero.jsonl"
            ),
        )

    guard("sharded-optimizer SVI", _c21)

    # 22. Zero-syscall ring vs the shm doorbell (ISSUE 18): the SAME
    # Gaussian-linreg node contract as config 15, served over (a) the
    # shm arena + TCP doorbell (the round-9 lane, re-run in THIS
    # container as the control) and (b) the seqlock submission/
    # completion rings embedded in the same arenas — descriptor
    # hand-off through shared memory, the doorbell kept only for
    # attach + fallback.  Both lanes move zero payload bytes
    # steady-state (pinned arrays); what the ring removes is the
    # per-frame SOCKET hop: two syscalls per descriptor each way on
    # the doorbell vs a seqlock read (plus an amortized futex
    # park/wake at window edges) on the ring.  HONEST 1-CORE FRAMING
    # (the config-15 0.63x precedent): this container has one core,
    # so a lock-step round trip is context-switch bound on BOTH lanes
    # and the ring's spin-hit regime — where the peer's commit lands
    # while the consumer is still spinning, ~10-15 us round trips,
    # zero syscalls — needs a genuinely-parallel 2-core colocated
    # pair.  The acceptance is therefore parity-shaped: ring >= 0.7x
    # the doorbell on the windowed production-width lane, plus the
    # measurable half of the zero-syscall claim: the DRIVER's
    # descriptor-path syscalls/eval (futex shim counters; strace is
    # absent in this container) amortized below 2/eval windowed,
    # corroborated by the process's voluntary-context-switch delta
    # (ru_nvcsw).  Artifact: tools/suite_cpu_r18_ring.jsonl.
    def _c22():
        import multiprocessing as mp
        import resource as _resource
        import shutil
        import socket as _socket
        import subprocess as sp
        import time as _time

        from pytensor_federated_tpu.service import TcpArraysClient
        from pytensor_federated_tpu.service.ring import (
            RingArraysClient,
            futex_available,
            reset_syscall_counts,
            syscall_counts,
        )
        from pytensor_federated_tpu.service.shm import ShmArraysClient

        artifact_lines = []
        artifact_path = "tools/suite_cpu_r18_ring.jsonl"

        def flush_artifact():
            tmp = artifact_path + ".tmp"
            with open(tmp, "w") as f:
                for line in artifact_lines:
                    f.write(json.dumps(line) + "\n")
            os.replace(tmp, artifact_path)

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        rng = np.random.default_rng(22)
        shapes = {"n64": 64, "n16k": 16384}
        args_by_shape = {}
        for name, n in shapes.items():
            x = rng.normal(size=n)
            y = 0.7 + 1.9 * x + rng.normal(size=n)
            args_by_shape[name] = (
                np.asarray(np.float64(0.7)),
                np.asarray(np.float64(1.9)),
                np.asarray(np.float64(0.5)),
                x,
                y,
            )

        def rate_lane(client, args, seconds=1.5, window=256, n_reqs=512):
            reqs = [args] * n_reqs
            client.evaluate_many(reqs, window=window, batch=True)  # warm
            t0 = _time.perf_counter()
            done = 0
            while _time.perf_counter() - t0 < seconds:
                client.evaluate_many(reqs, window=window, batch=True)
                done += n_reqs
            return done / (_time.perf_counter() - t0)

        def p50_lockstep(client, args, n=300):
            lat = []
            client.evaluate(*args)  # warm
            for _ in range(n):
                t0 = _time.perf_counter()
                client.evaluate(*args)
                lat.append(_time.perf_counter() - t0)
            return float(np.percentile(lat, 50))

        def run_lane(transport, client_cls):
            ctx = mp.get_context("spawn")
            port = free_port()
            proc = ctx.Process(
                target=_bench_serve_shm_node,
                args=(port, True, transport),
                daemon=True,
            )
            proc.start()
            out = {"lane": transport}
            vals = {}
            try:
                client = client_cls(
                    "127.0.0.1", port,
                    connect_timeout_s=2.0, connect_retries=60,
                    connect_backoff_s=0.25,
                )
                deadline = _time.time() + 60
                while True:
                    try:
                        client.ping()
                        break
                    except (ConnectionError, OSError):
                        if _time.time() > deadline or not proc.is_alive():
                            raise
                        _time.sleep(0.25)
                if transport == "ring" and client._com_ring is None:
                    raise RuntimeError(
                        "ring lane fell back to the doorbell"
                    )
                for name, args in args_by_shape.items():
                    vals[name] = [
                        np.asarray(v) for v in client.evaluate(*args)
                    ]
                    reset_syscall_counts()
                    ru0 = _resource.getrusage(
                        _resource.RUSAGE_SELF
                    ).ru_nvcsw
                    t0 = _time.perf_counter()
                    rate = rate_lane(client, args)
                    n_evals = max(
                        1, int(rate * (_time.perf_counter() - t0))
                    )
                    ru1 = _resource.getrusage(
                        _resource.RUSAGE_SELF
                    ).ru_nvcsw
                    shim = dict(syscall_counts())
                    out[f"{name}_rps"] = round(rate, 1)
                    out[f"{name}_descriptor_sys_per_eval"] = round(
                        (shim["futex_wait"] + shim["futex_wake"]
                         + shim["fallback_poll"]) / n_evals,
                        4,
                    )
                    out[f"{name}_nvcsw_per_eval"] = round(
                        (ru1 - ru0) / n_evals, 4
                    )
                out["p50_lockstep_us"] = round(
                    p50_lockstep(client, args_by_shape["n64"]) * 1e6, 1
                )
                client.close()
            finally:
                proc.terminate()
                proc.join(timeout=10)
            return out, vals

        ring_out, ring_vals = run_lane("ring", RingArraysClient)
        shm_out, shm_vals = run_lane("shm", ShmArraysClient)

        # -- cpp-tcp-batched control (the byte-wire champion) ---------
        # Same container, same workload: the honest "did a byte wire
        # already beat both shared-memory lanes?" control the config-15
        # precedent demands.  Its failure must not cost the ring/shm
        # records (round-3 lesson), so the lane is best-effort.
        cpp_out = None
        cpp_vals = {}
        native = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "native"
        )
        binary = os.path.join(native, "cpp_node")
        try:
            if shutil.which("make") and shutil.which("g++"):
                sp.run(
                    ["make", "-C", native],
                    check=True, capture_output=True,
                )
        except Exception:
            pass
        if os.path.exists(binary):
            cport = free_port()
            cproc = sp.Popen(
                [binary, str(cport)], stdout=sp.PIPE,
                stderr=sp.STDOUT, text=True,
            )
            tclient = None
            try:
                line = cproc.stdout.readline()
                if "listening" not in line:
                    raise RuntimeError(f"cpp_node: {line!r}")
                tclient = TcpArraysClient("127.0.0.1", cport)
                out = {"lane": "cpp-tcp-batched"}
                for name, args in args_by_shape.items():
                    cpp_vals[name] = [
                        np.asarray(v) for v in tclient.evaluate(*args)
                    ]
                    ru0 = _resource.getrusage(
                        _resource.RUSAGE_SELF
                    ).ru_nvcsw
                    t0 = _time.perf_counter()
                    rate = rate_lane(tclient, args)
                    n_evals = max(
                        1, int(rate * (_time.perf_counter() - t0))
                    )
                    ru1 = _resource.getrusage(
                        _resource.RUSAGE_SELF
                    ).ru_nvcsw
                    out[f"{name}_rps"] = round(rate, 1)
                    out[f"{name}_nvcsw_per_eval"] = round(
                        (ru1 - ru0) / n_evals, 4
                    )
                out["p50_lockstep_us"] = round(
                    p50_lockstep(tclient, args_by_shape["n64"]) * 1e6, 1
                )
                cpp_out = out
            except Exception:
                import traceback

                traceback.print_exc(file=sys.stderr)
                print(
                    "# cpp-tcp-batched control failed; "
                    "ring/shm lanes kept",
                    file=sys.stderr,
                )
                cpp_out = None
            finally:
                if tclient is not None:
                    tclient.close()
                cproc.kill()
                cproc.wait()

        # Equality gate FIRST: every lane computed the same numbers.
        for name in shapes:
            for a, b in zip(ring_vals[name], shm_vals[name]):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6
                )
            for a, b in zip(ring_vals[name], cpp_vals.get(name, ())):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6
                )

        method = {
            "lane": "method",
            "cores": os.cpu_count(),
            "futex_available": bool(futex_available()),
            "note": (
                "descriptor_sys_per_eval counts the DRIVER process's "
                "ring shim calls (futex_wait + futex_wake + "
                "fallback_poll) per completed eval — strace is absent "
                "in this container, so kernel entries are counted at "
                "the shim that makes them, corroborated by the "
                "driver's ru_nvcsw (voluntary context switches) "
                "delta; the shm doorbell's syscalls are socket "
                "send/recv, visible only in nvcsw_per_eval; 1-core "
                "container — the ring's spin-hit zero-syscall regime "
                "requires a 2-core colocated pair, so the acceptance "
                "is parity + amortized descriptor syscalls, not the "
                "2-core latency target (docs/performance.md)"
            ),
        }
        artifact_lines[:] = [method, ring_out, shm_out]
        if cpp_out is not None:
            artifact_lines.append(cpp_out)
        flush_artifact()

        for out in filter(None, (ring_out, shm_out, cpp_out)):
            print(
                f"# colocated lane {out['lane']}: "
                f"n64 {out['n64_rps']:,.1f} rps, "
                f"n16k {out['n16k_rps']:,.1f} rps, "
                f"p50 lock-step {out['p50_lockstep_us']} us",
                file=sys.stderr,
            )

        ratio = round(ring_out["n16k_rps"] / shm_out["n16k_rps"], 2)
        record(
            "zero-syscall ring vs shm-doorbell (colocated lane)",
            ring_out["n16k_rps"],
            unit="round-trips/s",
            baseline_rate=shm_out["n16k_rps"],
            baseline_desc=(
                "shm doorbell lane re-run in this container on the "
                "same workload — the round-9 zero-copy control (the "
                "cpp-tcp-batched byte wire is re-run alongside as the "
                "second honest control); acceptance: ring >= 0.7x "
                "windowed (1-core parity) and windowed descriptor "
                "syscalls/eval < 2"
            ),
            ring_rps=ring_out["n16k_rps"],
            shm_rps=shm_out["n16k_rps"],
            ring_vs_shm=ratio,
            ring_small_rps=ring_out["n64_rps"],
            shm_small_rps=shm_out["n64_rps"],
            ring_p50_lockstep_us=ring_out["p50_lockstep_us"],
            shm_p50_lockstep_us=shm_out["p50_lockstep_us"],
            ring_descriptor_sys_per_eval=ring_out[
                "n16k_descriptor_sys_per_eval"
            ],
            ring_nvcsw_per_eval=ring_out["n16k_nvcsw_per_eval"],
            shm_nvcsw_per_eval=shm_out["n16k_nvcsw_per_eval"],
            cpp_tcp_batched_rps=(
                None if cpp_out is None else cpp_out["n16k_rps"]
            ),
            cpp_tcp_batched_small_rps=(
                None if cpp_out is None else cpp_out["n64_rps"]
            ),
            cpp_tcp_batched_p50_lockstep_us=(
                None if cpp_out is None
                else cpp_out["p50_lockstep_us"]
            ),
            futex_available=bool(futex_available()),
            note=(
                "same linreg node contract both lanes, equal results "
                "gated at rtol 1e-6; both lanes pin payloads (zero "
                "payload bytes steady-state) — the delta under test "
                "is descriptor transport: socket frames (doorbell) vs "
                "seqlock records + amortized futex park/wake (ring); "
                "1-core container, so lock-step p50 is context-switch "
                "bound on both lanes and the ring's ~10-15 us "
                "spin-hit regime is out of reach — parity acceptance, "
                "config-15 honest-control precedent; the "
                "cpp-tcp-batched byte wire (re-ships + re-decodes "
                "every payload byte per call) is the second control; "
                "artifact tools/suite_cpu_r18_ring.jsonl"
            ),
        )
        assert ring_out["n16k_rps"] >= 0.7 * shm_out["n16k_rps"], (
            f"ring windowed rate {ring_out['n16k_rps']} < 0.7x the "
            f"doorbell control {shm_out['n16k_rps']}"
        )
        assert ring_out["n16k_descriptor_sys_per_eval"] < 2.0, (
            "windowed descriptor path failed to amortize syscalls: "
            f"{ring_out['n16k_descriptor_sys_per_eval']}/eval"
        )

    guard("zero-syscall ring vs shm-doorbell", _c22)

    # 23. blocked Cholesky over the pool (ISSUE 19): the distributed
    # right-looking factorization at widths 2/4/8 vs the single-process
    # numpy/LAPACK control, equality-gated, with MEASURED per-step wire
    # bytes proving the O(panel) steady-state claim (the matrix ships
    # once at distribution; every subsequent step moves only the panel
    # column), plus the GP-posterior dispatch lane.
    def _c23():
        import multiprocessing as mp
        import socket as _socket
        import time as _time

        from pytensor_federated_tpu.linalg import (
            BlockedCholesky,
            BlockLayout,
        )
        from pytensor_federated_tpu.linalg.blocks import (
            LINALG_OPCODES,
            decode_op_header,
        )
        from pytensor_federated_tpu.service.tcp import TcpArraysClient

        artifact_lines = []
        artifact_path = "tools/suite_cpu_r19_linalg.jsonl"

        def flush_artifact():
            tmp = artifact_path + ".tmp"
            with open(tmp, "w") as f:
                for line in artifact_lines:
                    f.write(json.dumps(line) + "\n")
            os.replace(tmp, artifact_path)

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        n, b = 512, 64
        lay = BlockLayout(n, n, b, b)
        g = lay.grid_rows
        tile_bytes = b * b * 8
        panel0_bytes = (g - 1) * tile_bytes
        rng = np.random.default_rng(23)
        a_mat = rng.normal(size=(n, n))
        a_mat = a_mat @ a_mat.T / n + np.eye(n)
        flops = n**3 / 3.0

        # Control: single-process LAPACK, best of 3.
        ref = np.linalg.cholesky(a_mat)
        ctrl_s = float("inf")
        for _ in range(3):
            t0 = _time.perf_counter()
            np.linalg.cholesky(a_mat)
            ctrl_s = min(ctrl_s, _time.perf_counter() - t0)
        ctrl_gflops = flops / ctrl_s / 1e9

        class CountingClient:
            """Payload-byte ledger keyed by (opcode, step) — the
            numbers behind the O(panel) acceptance.  Counts array
            bytes, the transport-independent payload measure."""

            def __init__(self, port):
                self.inner = TcpArraysClient("127.0.0.1", port)
                self.by_op = {}

            def evaluate(self, *arrays):
                opcode, step, _ = decode_op_header(
                    np.asarray(arrays[0])
                )
                out = self.inner.evaluate(*arrays)
                nbytes = sum(
                    np.asarray(x).nbytes for x in arrays
                ) + sum(np.asarray(x).nbytes for x in out)
                key = (opcode, step)
                self.by_op[key] = self.by_op.get(key, 0) + nbytes
                return out

            def close(self):
                self.inner.close()

        put_op = LINALG_OPCODES["PUT"]

        def run_width(width):
            ctx = mp.get_context("spawn")
            ports = [free_port() for _ in range(width)]
            procs = [
                ctx.Process(
                    target=_bench_serve_linalg_node,
                    args=(p, n, b),
                    daemon=True,
                )
                for p in ports
            ]
            for proc in procs:
                proc.start()
            clients = []
            try:
                deadline = _time.time() + 90
                for p in ports:
                    while True:
                        try:
                            with _socket.create_connection(
                                ("127.0.0.1", p), timeout=1.0
                            ):
                                break
                        except OSError:
                            if _time.time() > deadline:
                                raise
                            _time.sleep(0.2)
                clients = [CountingClient(p) for p in ports]
                chol = BlockedCholesky(lay, clients)
                # Warm once (node-side jit/import settle), then the
                # timed runs re-distribute + re-factor — each run is a
                # FULL factorization, distribution included.
                l_first = chol.factor(a_mat)
                for c in clients:
                    c.by_op.clear()
                best_s = float("inf")
                for _ in range(3):
                    t0 = _time.perf_counter()
                    chol.factor(a_mat)
                    best_s = min(best_s, _time.perf_counter() - t0)
                merged = {}
                for c in clients:
                    for k, v in c.by_op.items():
                        merged[k] = merged.get(k, 0) + v
                runs = 3
                dist_bytes = sum(
                    v for (op, _), v in merged.items() if op == put_op
                ) // runs
                step_bytes = [
                    sum(
                        v
                        for (op, s), v in merged.items()
                        if op != put_op and s == k
                    )
                    // runs
                    for k in range(g)
                ]
                out = {
                    "lane": f"pool-w{width}",
                    "width": width,
                    "wall_s": round(best_s, 4),
                    "gflops": round(flops / best_s / 1e9, 3),
                    "vs_control": round(ctrl_s / best_s, 4),
                    "distribution_bytes": dist_bytes,
                    "steady_step_bytes_max": max(step_bytes),
                    "steady_step_bytes": step_bytes,
                    "restores": chol.restores,
                }
                return out, l_first
            finally:
                for c in clients:
                    try:
                        c.close()
                    except Exception:
                        pass
                for proc in procs:
                    proc.terminate()
                for proc in procs:
                    proc.join(timeout=10)

        lanes = []
        for width in (2, 4, 8):
            out, l_w = run_width(width)
            # Equality gate FIRST: the distributed factor IS the
            # LAPACK factor (same f64 kernels tile-by-tile).
            np.testing.assert_allclose(l_w, ref, atol=1e-8)
            lanes.append(out)
            print(
                f"# blocked cholesky w{width}: {out['gflops']} GFLOP/s "
                f"({out['vs_control']}x control), step bytes max "
                f"{out['steady_step_bytes_max']:,} "
                f"(panel0 {panel0_bytes:,})",
                file=sys.stderr,
            )

        # The GP-posterior dispatch lane: models/gp.py routes concrete
        # covariances >= _BLOCKED_CHOL_MIN through linalg.cholesky
        # (LocalBlockClient).  Equality-gate the two dispatch paths on
        # the same covariance.
        from pytensor_federated_tpu.models import gp as gp_mod

        # Lengthscale 0.5 + 1e-4 jitter keeps the covariance
        # f32-factorizable: the jnp control runs at JAX's default f32
        # (the blocked route is f64 numpy), so the gate tolerance is
        # the repo's cross-dtype convention (test_gp.py), not f64.
        ng = 384
        xs = np.linspace(0.0, 8.0, ng)
        cov = np.exp(
            -0.5 * ((xs[:, None] - xs[None, :]) / 0.5) ** 2
        ) + 1e-4 * np.eye(ng)
        cov = cov.astype(np.float64)
        t0 = _time.perf_counter()
        l_blocked = np.asarray(
            gp_mod._posterior_chol(cov, 1e-4, None, block=128)
        )
        gp_blocked_s = _time.perf_counter() - t0
        saved = gp_mod._BLOCKED_CHOL_MIN
        gp_mod._BLOCKED_CHOL_MIN = 10**9
        try:
            t0 = _time.perf_counter()
            l_jnp = np.asarray(
                gp_mod._posterior_chol(cov, 1e-4, None, block=128)
            )
            gp_jnp_s = _time.perf_counter() - t0
        finally:
            gp_mod._BLOCKED_CHOL_MIN = saved
        np.testing.assert_allclose(l_blocked, l_jnp, rtol=2e-3,
                                   atol=1e-4)
        gp_lane = {
            "lane": "gp-posterior-dispatch",
            "n": ng,
            "blocked_ms": round(gp_blocked_s * 1e3, 2),
            "jnp_ms": round(gp_jnp_s * 1e3, 2),
        }

        method = {
            "lane": "method",
            "cores": os.cpu_count(),
            "n": n,
            "block": b,
            "grid": g,
            "control_gflops": round(ctrl_gflops, 3),
            "panel0_bytes": panel0_bytes,
            "matrix_lower_bytes": (
                sum(1 for _ in lay.lower_coords()) * tile_bytes
            ),
            "note": (
                "payload array bytes counted at the driver's client "
                "seam, bucketed by (opcode, step); 1-core container — "
                "every replica process shares the core, so GFLOP/s "
                "cannot scale with width (the config-21 serialization "
                "precedent) and the acceptance is equality + O(panel) "
                "steady wire bytes, not speedup: per-step bytes are "
                "bounded by (width+2) panel columns while a "
                "re-ship-everything protocol would move the O(n^2) "
                "matrix every step"
            ),
        }
        artifact_lines[:] = [method] + lanes + [gp_lane]
        flush_artifact()

        w2 = lanes[0]
        record(
            "blocked Cholesky over the pool (512x512, 64-tile grid)",
            w2["gflops"],
            unit="GFLOP/s",
            baseline_rate=ctrl_gflops,
            baseline_desc=(
                "single-process numpy/LAPACK cholesky on the same "
                "matrix, best of 3 — acceptance: factors equal at "
                "atol 1e-8 at every width, steady per-step wire "
                "bytes <= (width+2) panel columns (O(panel), never "
                "O(matrix)), distribution ships the matrix once"
            ),
            flops_per_eval=None,
            control_gflops=round(ctrl_gflops, 3),
            w2_gflops=w2["gflops"],
            w4_gflops=lanes[1]["gflops"],
            w8_gflops=lanes[2]["gflops"],
            w2_step_bytes_max=w2["steady_step_bytes_max"],
            w8_step_bytes_max=lanes[2]["steady_step_bytes_max"],
            panel0_bytes=panel0_bytes,
            gp_blocked_ms=gp_lane["blocked_ms"],
            gp_jnp_ms=gp_lane["jnp_ms"],
            note=method["note"],
        )
        matrix_bytes = n * n * 8
        for out in lanes:
            width = out["width"]
            bound = (width + 2) * panel0_bytes
            assert out["steady_step_bytes_max"] <= bound, (
                f"w{width}: steady step moved "
                f"{out['steady_step_bytes_max']:,} bytes > the "
                f"O(panel) bound {bound:,}"
            )
            assert out["distribution_bytes"] <= 1.5 * matrix_bytes, (
                f"w{width}: distribution re-shipped the matrix "
                f"({out['distribution_bytes']:,} bytes)"
            )
            assert out["restores"] == 0, (
                f"w{width}: {out['restores']} restores in a "
                "fault-free run"
            )

    guard("blocked Cholesky over the pool", _c23)

    if results:
        print(
            "# wrote "
            + ("BENCH_SUITE.json" if only is None
               else "BENCH_SUITE.partial.json")
            + f" ({len(results)} configs)",
            file=sys.stderr,
        )
    elif only is not None and not failures:
        # A filter that matched nothing is a usage error (exit 2); an
        # all-configs-failed run is NOT — it must fall through to the
        # failures report below with exit 1 (the round-3 outage lesson:
        # the failure list is the diagnostic worth preserving).
        print(
            f"# NO configs matched --only {only!r}: nothing written",
            file=sys.stderr,
        )
        return 2
    if failures:
        print(
            f"# {len(failures)} config(s) FAILED: {failures}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
