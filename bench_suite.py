"""Benchmark suite: every BASELINE.json config, one JSON line each.

``bench.py`` is the driver-facing single-metric benchmark (the 8-shard
flagship); this suite covers the full config list for the record:

1. single-node linear regression (the collapsed demo pair);
2. 8-shard federated linear regression, psum-aggregated logp+grad;
3. hierarchical radon GLM, one shard per county group;
4. Lotka-Volterra ODE param estimation, [theta] -> [LL, dLL] per shard;
5. 64-shard federated logistic regression + a full NUTS posterior.

Plus one net-new long-context config for the record (no reference or
BASELINE analog): T=4096 LGSSM logp+grad via the O(log T)
parallel-in-time Kalman filter.

Each config measures sequential dependent logp+grad evals/s (the NUTS
consumption pattern, chained in one lax.scan, like bench.py); config 5
also reports end-to-end NUTS samples/s. Run: ``python bench_suite.py``.
"""

import json
import sys
import time

from bench import NORTH_STAR, make_chained, measure_rate, preflight


def _rate(fn_flat, flat0):
    # Same two-stage sizing as the driver metric (bench.measure_rate),
    # with lighter floors/targets so five configs stay quick.  One
    # compile per config (dynamic trip count serves all three stages).
    r, n, _wall = measure_rate(
        make_chained(fn_flat),
        flat0,
        n_cal=500,
        floor=2_000,
        mid_wall=0.3,
        target_wall=1.0,
    )
    return r, n


def _flat_fn(logp_fn, params):
    """Flat-vector value_and_grad of ``logp_fn`` at ``params``."""
    import jax
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(params)

    def fn(x):
        return jax.value_and_grad(lambda v: logp_fn(unravel(v)))(x)

    return fn, flat0


def _flat(model):
    return _flat_fn(model.logp, model.init_params())


def main():
    preflight()
    import jax
    import numpy as np

    from pytensor_federated_tpu.models.glm import (
        HierarchicalRadonGLM,
        generate_radon_data,
    )
    from pytensor_federated_tpu.models.linear import (
        FederatedLinearRegression,
        generate_node_data,
    )
    from pytensor_federated_tpu.models.logistic import (
        FederatedLogisticRegression,
        generate_logistic_data,
    )
    from pytensor_federated_tpu.models.ode import make_lv_model

    results = []

    def record(config, value, unit="evals/s", baseline=True, **extra):
        line = {
            "config": config,
            "value": round(value, 1),
            "unit": unit,
            # The 50k north star is an evals/s target for the federated
            # shard configs; other units (and the net-new long-context
            # config, whose per-eval work is a whole T-step filter) have
            # no baseline to compare against.
            "vs_baseline": (
                round(value / NORTH_STAR, 3)
                if unit == "evals/s" and baseline
                else None
            ),
            "backend": jax.default_backend(),
            **extra,
        }
        results.append(line)
        print(json.dumps(line))

    # 1. single-node linear regression (demo pair collapsed; one shard).
    data1, _ = generate_node_data(1, n_obs=64, seed=11)
    fn, x0 = _flat(FederatedLinearRegression(data1))
    r, n = _rate(fn, x0)
    record("single-node linear regression (demo pair)", r, n=n)

    # 2. 8-shard federated linear regression (the bench.py flagship).
    data8, _ = generate_node_data(8, n_obs=64, seed=123)
    fn, x0 = _flat(FederatedLinearRegression(data8))
    r, n = _rate(fn, x0)
    record("8-shard federated linear regression (psum logp+grad)", r, n=n)

    # 3. hierarchical radon GLM, one shard per county group.
    datag, _ = generate_radon_data(16, seed=12)
    fn, x0 = _flat(HierarchicalRadonGLM(datag))
    r, n = _rate(fn, x0)
    record("hierarchical radon GLM (16 county shards)", r, n=n)

    # 4. Lotka-Volterra ODE: [theta] -> [LL, dLL] per shard.
    lv, _ = make_lv_model(8)
    fn, x0 = _flat(lv)
    r, n = _rate(fn, x0)
    record("Lotka-Volterra ODE param estimation (8 shards)", r, n=n)

    # 5. 64-shard federated logistic regression; evals/s + NUTS samples/s.
    datal, _ = generate_logistic_data(n_shards=64, n_obs=64, n_features=8)
    model5 = FederatedLogisticRegression(datal)
    fn, x0 = _flat(model5)
    r, n = _rate(fn, x0)
    record("64-shard federated logistic regression (logp+grad)", r, n=n)

    # 6. Long-context LGSSM: O(log T) parallel-in-time Kalman filter.
    from pytensor_federated_tpu.models.statespace import (
        generate_lgssm_data,
        kalman_logp_parallel,
    )

    y_ss, p_ss = generate_lgssm_data(T=4096)
    fn_ss, flat_ss = _flat_fn(lambda p: kalman_logp_parallel(p, y_ss), p_ss)
    r, n = _rate(fn_ss, flat_ss)
    record(
        "LGSSM T=4096 logp+grad (parallel-in-time Kalman)",
        r,
        baseline=False,
        n=n,
    )

    from pytensor_federated_tpu.samplers import sample

    t0 = time.perf_counter()
    res = sample(
        model5.logp,
        model5.init_params(),
        key=jax.random.PRNGKey(0),
        num_warmup=200,
        num_samples=200,
        num_chains=4,
        jitter=0.1,
    )
    jax.block_until_ready(res.samples)
    wall = time.perf_counter() - t0
    n_draws = 4 * 200
    rhat = float(np.asarray(res.summary()["rhat"]["w"]).max())
    record(
        "64-shard logistic: full NUTS posterior",
        n_draws / wall,
        unit="samples/s",
        wall_s=round(wall, 2),
        note="includes warmup+compile",
        max_rhat=round(rhat, 4),
    )

    # Persist all measurements BEFORE any convergence assertion — a
    # flaky chain must not discard minutes of completed configs.
    with open("BENCH_SUITE.json", "w") as f:
        json.dump(results, f, indent=1)
    print(f"# wrote BENCH_SUITE.json ({len(results)} configs)", file=sys.stderr)
    assert rhat < 1.2, f"NUTS did not converge: max rhat {rhat}"


if __name__ == "__main__":
    main()
