// Standalone C++ federated worker node.
//
// Proves the framework's cross-language federation boundary: the
// reference's README states the node "model implementation could be
// C++, while MCMC/optimization run in Python" (reference:
// README.md:34-35) but ships no native node; this is that node, built
// on the framework's npwire format (service/npwire.py docstring defines
// the layout) over a plain TCP length-prefixed transport
// (service/tcp.py is the Python peer).
//
// Protocol, little-endian throughout:
//   frame:   u32 payload_len, then payload
//   payload: "NPW1" ver(u8) flags(u8) uuid(16B) n_arrays(u32)
//            [flags&1: err_len(u32) + utf8]
//            [flags&2: trace_id(16B), telemetry correlation — read and
//             dropped here; replies never carry it]
//            [flags&16: deadline_s(f64), the request's remaining
//             deadline budget in relative seconds — enforced at
//             admission: an expired budget is answered with the
//             in-band "deadline exceeded" classification, never
//             computed]
//            [flags&32: tenant_len(u16) + utf8, the gateway tier's
//             per-tenant identity — metered at the gateway; a node
//             validates the framing and drops the id]  then per array:
//            dtype_len(u16) dtype_str ndim(u8) shape(u64*ndim)
//            data_len(u64) raw bytes
//            [flags&4 TAIL: spans_len(u32) + JSON — node-side span
//             trees piggybacked on replies (telemetry/reunion.py).
//             A native node keeps no spans, so replies never carry
//             the block; on requests it is validated and dropped,
//             keeping the decoder symmetric with the Python codec]
//   batch:   flags&8 reinterprets the count as n_items and the body as
//            item_len(u32) + item_bytes per item, each a complete
//            payload as above (service/npwire.py encode_batch).  The
//            reply is a batch frame of per-item replies in order —
//            one syscall per pipelined window instead of per call,
//            with error isolation per item.  A ZERO-item batch is the
//            client's capability probe; the empty batch reply echoed
//            here is the "yes".
//
// Compute contract (stateless, mirrors the linear-model blackbox of the
// Python demos): inputs [intercept(), slope(), sigma(), x(n), y(n)] as
// float64; outputs [logp(), dlogp/dintercept(), dlogp/dslope()].
//
// Build: make -C native   (-> native/cpp_node)
// Run:   ./cpp_node <port> [<port> ...] [--fault-plan <spec-or-file>]
//
// Fault injection (the cross-language slice of the chaos subsystem,
// pytensor_federated_tpu/faultinject — FaultPlan.native_spec() emits
// this format): comma-separated rules, each anchored to the nth frame
// this process serves (process-wide counter, batch frames count once):
//   delay:<nth>:<ms>        sleep <ms> before sending the nth reply
//   disconnect:<nth>        close the connection instead of replying
//   truncate:<nth>:<pct>    send the length prefix plus only <pct>% of
//                           the nth reply's bytes, then close — the
//                           mid-frame kill (peer reads a short frame)
// The spec is taken literally, or — if it names a readable file — read
// from that file.  A malformed spec exits 2 loudly: a chaos run whose
// plan silently failed to parse would test nothing.
//
// One listener thread per port (the in-process analog of the
// reference's one-process-per-port worker pool,
// reference: demo_node.py:98-108) and one thread per accepted
// connection, so concurrent clients are served concurrently; each
// connection handles a stream of evaluate frames (the lock-step
// request/reply pattern of the reference's bidirectional stream,
// reference: service.py:150-158).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cmath>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <system_error>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[4] = {'N', 'P', 'W', '1'};
constexpr uint8_t kVersion = 1;
constexpr uint8_t kFlagError = 1;
constexpr uint8_t kFlagTrace = 2;
constexpr uint8_t kFlagSpans = 4;
constexpr uint8_t kFlagBatch = 8;
constexpr uint8_t kFlagDeadline = 16;
constexpr uint8_t kFlagTenant = 32;
constexpr uint8_t kFlagPartition = 64;
constexpr uint8_t kFlagVersion = 128;
// Every known flag bit, mirrored from service/wire_registry.py (the
// declared source; graftlint's wire-registry rule cross-checks this
// file).  Decoders reject any bit outside the mask: an unknown flag
// means blocks this build cannot place, and skipping them would be
// silent mis-parsing of everything after (loud-failure contract).
// ISSUE 16 (kFlagVersion) saturated the byte; the header's version
// field is the remaining escape hatch for layout changes.
constexpr uint8_t kKnownFlags = kFlagError | kFlagTrace | kFlagSpans |
                                kFlagBatch | kFlagDeadline | kFlagTenant |
                                kFlagPartition | kFlagVersion;
// flags byte offset in the payload: magic(4) + version(1)
constexpr size_t kFlagsOff = 5;

struct Array {
  std::string dtype;
  std::vector<uint64_t> shape;
  std::vector<uint8_t> data;

  size_t nelem() const {
    size_t n = 1;
    for (uint64_t s : shape) n *= static_cast<size_t>(s);
    return n;
  }
};

// Gradient-partition index block (flag 64) — layout declared in
// service/wire_registry.py PARTITION_STRUCT; routing/partition.py
// owns the head/tail slice rule this node implements in serve_plain.
struct Partition {
  uint32_t index = 0;
  uint32_t count = 0;
  uint64_t offset = 0;
  uint64_t length = 0;
  uint64_t total = 0;
};

struct Message {
  uint8_t uuid[16];
  std::string error;  // empty = no error
  std::vector<Array> arrays;
  // Remaining deadline budget (flag 16), relative seconds off the
  // wire.  has_deadline=false = unbounded (the pre-deadline wire).
  bool has_deadline = false;
  double deadline_s = 0.0;
  // Partition request/echo (flag 64).  has_partition=false = the
  // pre-partition wire (byte-identical replies).
  bool has_partition = false;
  Partition partition;
  // Step-version stamp (flag 128) — the sharded-optimizer lane
  // (optim/sharded.py).  This node holds no optimizer state, so a
  // versioned request is refused loudly in-band (serve_plain); the
  // block is still framing-validated here so the refusal names the
  // right problem, never a mis-parse.
  bool has_version = false;
  uint64_t step_version = 0;
};

// ---- low-level IO -------------------------------------------------------

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r == 0) return false;  // clean EOF
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// ---- npwire codec -------------------------------------------------------

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), n_(n) {}

  bool bytes(void* out, size_t k) {
    if (off_ + k > n_) return false;
    std::memcpy(out, p_ + off_, k);
    off_ += k;
    return true;
  }
  template <typename T>
  bool le(T* out) {  // all wire ints are little-endian; assume LE host
    return bytes(out, sizeof(T));
  }
  size_t remaining() const { return n_ - off_; }
  // Bounds-check BEFORE copying: assigning from a raw cursor with an
  // attacker-controlled length and checking afterwards would be a
  // heap overread, so no unchecked cursor accessor exists.
  bool str(std::string* out, size_t k) {
    if (off_ + k > n_) return false;
    out->assign(reinterpret_cast<const char*>(p_ + off_), k);
    off_ += k;
    return true;
  }

 private:
  const uint8_t* p_;
  size_t n_;
  size_t off_ = 0;
};

bool decode(const std::vector<uint8_t>& buf, Message* msg, std::string* why) {
  Reader r(buf.data(), buf.size());
  char magic[4];
  uint8_t ver = 0, flags = 0;
  uint32_t n_arrays = 0;
  if (!r.bytes(magic, 4) || std::memcmp(magic, kMagic, 4) != 0) {
    *why = "bad magic";
    return false;
  }
  if (!r.le(&ver) || ver != kVersion) {
    *why = "unsupported version";
    return false;
  }
  if (!r.le(&flags) || !r.bytes(msg->uuid, 16) || !r.le(&n_arrays)) {
    *why = "truncated header";
    return false;
  }
  if (flags & ~kKnownFlags) {
    *why = "unknown flag bits (version-skewed peer?)";
    return false;
  }
  if (flags & kFlagError) {
    uint32_t elen = 0;
    if (!r.le(&elen) || !r.str(&msg->error, elen)) {
      *why = "truncated error block";
      return false;
    }
  }
  if (flags & kFlagTrace) {
    uint8_t trace_id[16];
    if (!r.bytes(trace_id, 16)) {
      *why = "truncated trace block";
      return false;
    }
    // Telemetry correlation id — a Python driver's span tree key.  A
    // native node keeps no spans, so the id is consumed and dropped.
  }
  if (flags & kFlagDeadline) {
    // Remaining deadline budget, f64 relative seconds (the sender
    // computed "time left" at encode; clocks never cross the wire).
    if (!r.le(&msg->deadline_s)) {
      *why = "truncated deadline block";
      return false;
    }
    msg->has_deadline = true;
  }
  if (flags & kFlagTenant) {
    // Gateway-tier tenant id (u16-length utf8) — metering happens at
    // the gateway, so a node validates the framing and drops the id.
    uint16_t tlen = 0;
    std::string tenant;
    if (!r.le(&tlen) || !r.str(&tenant, tlen)) {
      *why = "truncated tenant block";
      return false;
    }
  }
  if (flags & kFlagPartition) {
    // Gradient-partition block: index(u32) count(u32) offset(u64)
    // length(u64) total(u64).  A request carrying it asks for the
    // head/tail SLICED reply (serve_plain applies the rule); invalid
    // geometry is rejected here, loudly, before any compute.
    Partition& p = msg->partition;
    if (!r.le(&p.index) || !r.le(&p.count) || !r.le(&p.offset) ||
        !r.le(&p.length) || !r.le(&p.total)) {
      *why = "truncated partition block";
      return false;
    }
    // Overflow-safe geometry check: `offset + length > total` wraps
    // for hostile u64 values and would admit a block that then reads
    // as a zero-filled slice (silent wrong data) or drives a huge
    // resize — subtract instead of add.
    if (p.count == 0 || p.index >= p.count || p.offset > p.total ||
        p.length > p.total - p.offset) {
      *why = "invalid partition block";
      return false;
    }
    msg->has_partition = true;
  }
  if (flags & kFlagVersion) {
    // Step-version stamp: one u64 after the partition block
    // (wire_registry.VERSION_STRUCT).  Zero is a meaningful stamp.
    if (!r.le(&msg->step_version)) {
      *why = "truncated version block";
      return false;
    }
    msg->has_version = true;
  }
  // Each array needs >= 11 bytes of headers (2 dtype-len + 1 ndim +
  // 8 data-len), so any frame can hold at most remaining/11 arrays.
  if (n_arrays > r.remaining() / 11) {
    *why = "array count exceeds payload";
    return false;
  }
  // Grow incrementally rather than resize(n_arrays) up front: Array is
  // ~80 bytes of bookkeeping vs the 11-byte wire minimum, so an
  // up-front resize would let a fully-sent 256 MiB frame allocate ~7x
  // its own size before the first per-array read fails.  Incremental
  // growth keeps memory proportional to bytes actually decoded.
  msg->arrays.reserve(std::min<size_t>(n_arrays, 4096));
  for (uint32_t ai = 0; ai < n_arrays; ++ai) {
    msg->arrays.emplace_back();
    auto& a = msg->arrays.back();
    uint16_t dtlen = 0;
    uint8_t ndim = 0;
    uint64_t dlen = 0;
    if (!r.le(&dtlen) || !r.str(&a.dtype, dtlen)) {
      *why = "truncated dtype";
      return false;
    }
    if (!r.le(&ndim)) {
      *why = "truncated dtype/ndim";
      return false;
    }
    a.shape.resize(ndim);
    for (auto& s : a.shape)
      if (!r.le(&s)) {
        *why = "truncated shape";
        return false;
      }
    if (!r.le(&dlen)) {
      *why = "truncated data length";
      return false;
    }
    if (dlen > r.remaining()) {  // reject before the resize allocates
      *why = "truncated data";
      return false;
    }
    a.data.resize(static_cast<size_t>(dlen));
    if (!r.bytes(a.data.data(), a.data.size())) {
      *why = "truncated data";
      return false;
    }
  }
  if (flags & kFlagSpans) {
    // Telemetry sidecar (JSON span trees, tail block).  A native node
    // has no span store, so the block is framing-validated and
    // dropped — same posture as the trace id above.
    uint32_t slen = 0;
    if (!r.le(&slen) || slen > r.remaining()) {
      *why = "truncated spans block";
      return false;
    }
    std::string spans_json;
    if (!r.str(&spans_json, slen)) {
      *why = "truncated spans block";
      return false;
    }
  }
  return true;
}

void put(std::vector<uint8_t>* out, const void* p, size_t n) {
  const auto* b = static_cast<const uint8_t*>(p);
  out->insert(out->end(), b, b + n);
}
template <typename T>
void put_le(std::vector<uint8_t>* out, T v) {
  put(out, &v, sizeof(T));
}

std::vector<uint8_t> encode(const Message& msg) {
  std::vector<uint8_t> out;
  put(&out, kMagic, 4);
  put_le<uint8_t>(&out, kVersion);
  uint8_t flags = msg.error.empty() ? 0 : kFlagError;
  if (msg.has_partition) flags |= kFlagPartition;
  put_le<uint8_t>(&out, flags);
  put(&out, msg.uuid, 16);
  put_le<uint32_t>(&out, static_cast<uint32_t>(msg.arrays.size()));
  if (!msg.error.empty()) {
    put_le<uint32_t>(&out, static_cast<uint32_t>(msg.error.size()));
    put(&out, msg.error.data(), msg.error.size());
  }
  if (msg.has_partition) {
    put_le<uint32_t>(&out, msg.partition.index);
    put_le<uint32_t>(&out, msg.partition.count);
    put_le<uint64_t>(&out, msg.partition.offset);
    put_le<uint64_t>(&out, msg.partition.length);
    put_le<uint64_t>(&out, msg.partition.total);
  }
  for (const auto& a : msg.arrays) {
    put_le<uint16_t>(&out, static_cast<uint16_t>(a.dtype.size()));
    put(&out, a.dtype.data(), a.dtype.size());
    put_le<uint8_t>(&out, static_cast<uint8_t>(a.shape.size()));
    for (uint64_t s : a.shape) put_le<uint64_t>(&out, s);
    put_le<uint64_t>(&out, static_cast<uint64_t>(a.data.size()));
    put(&out, a.data.data(), a.data.size());
  }
  return out;
}

// ---- batch frames (flag 8) ----------------------------------------------

Message compute(const Message& in);  // fwd decl (model below)
bool is_f8(const Array& a);          // fwd decl (model below)

// The head/tail slice rule (routing/partition.py): reply array 0 (the
// logp head) rides whole; arrays 1.. are the TAIL, flat-concatenated
// and sliced to the requested element range.  The requester's `total`
// must equal the actual flat tail size — a driver/node shape
// disagreement becomes an in-band error, never a mis-sliced gradient.
void apply_partition(const Partition& p, Message* reply) {
  if (!reply->error.empty()) return;  // error replies carry no slice
  if (reply->arrays.empty()) {
    reply->error = "partition requested but the reply has no head";
    return;
  }
  uint64_t total = 0;
  for (size_t i = 1; i < reply->arrays.size(); ++i) {
    if (!is_f8(reply->arrays[i])) {
      // Name the offending slot and its dtype, like the python rule:
      // the C++ node serves <f8 tails only, so any other dtype is the
      // mismatch by definition.
      std::ostringstream oss;
      oss << "partitioned tail arrays must share one dtype, got "
          << "reply[" << i << "]=" << reply->arrays[i].dtype
          << " (this node serves <f8 tails)";
      reply->error = oss.str();
      return;
    }
    total += reply->arrays[i].nelem();
  }
  if (p.total != total) {
    std::ostringstream oss;
    oss << "partition total " << p.total << " != reply tail size "
        << total << " (driver/node shape disagreement)";
    reply->error = oss.str();
    return;
  }
  Array slice;
  slice.dtype = "<f8";
  slice.shape = {p.length};
  slice.data.resize(static_cast<size_t>(p.length) * 8);
  uint64_t pos = 0;  // element cursor over the flat tail
  uint64_t written = 0;
  for (size_t i = 1; i < reply->arrays.size(); ++i) {
    const Array& a = reply->arrays[i];
    const uint64_t n = a.nelem();
    const uint64_t lo = std::max<uint64_t>(pos, p.offset);
    const uint64_t hi = std::min<uint64_t>(pos + n, p.offset + p.length);
    if (lo < hi) {
      std::memcpy(slice.data.data() + (lo - p.offset) * 8,
                  a.data.data() + (lo - pos) * 8, (hi - lo) * 8);
      written += hi - lo;
    }
    pos += n;
  }
  (void)written;
  Message out;
  std::memcpy(out.uuid, reply->uuid, 16);
  out.arrays.push_back(reply->arrays[0]);
  out.arrays.push_back(std::move(slice));
  out.has_partition = true;
  out.partition = p;
  *reply = std::move(out);
}

// One plain payload -> one reply payload (shared by the lock-step loop
// and the per-item path inside a batch frame).
std::vector<uint8_t> serve_plain(const std::vector<uint8_t>& buf) {
  Message in, reply;
  std::string why;
  if (decode(buf, &in, &why)) {
    if (in.has_deadline && in.deadline_s <= 0.0) {
      // Admission enforcement (service/deadline.py vocabulary): an
      // already-expired request is answered, never computed — the
      // Python client maps this marker to its DeadlineExceeded class.
      std::memcpy(reply.uuid, in.uuid, 16);
      reply.error = "deadline exceeded: budget spent before admission";
    } else if (in.has_version) {
      // The sharded-optimizer lane (flag 128) needs node-owned
      // optimizer state; this node has none.  Loud and in-band so a
      // mis-negotiated driver fails over instead of decoding a reply
      // that silently never applied its update.
      std::memcpy(reply.uuid, in.uuid, 16);
      reply.error =
          "versioned sharded-optimizer updates are not supported by "
          "the native node";
    } else {
      reply = compute(in);
      if (in.has_partition) apply_partition(in.partition, &reply);
    }
  } else {
    std::memset(reply.uuid, 0, 16);
    reply.error = "decode failed: " + why;
  }
  return encode(reply);
}

// Outer-level batch failure: a batch frame whose own framing is broken
// answers a zero-item batch reply carrying the error block (layout
// mirrors npwire.encode_batch with error=...).
std::vector<uint8_t> batch_error_reply(const std::string& err) {
  std::vector<uint8_t> out;
  put(&out, kMagic, 4);
  put_le<uint8_t>(&out, kVersion);
  put_le<uint8_t>(&out, static_cast<uint8_t>(kFlagBatch | kFlagError));
  uint8_t zero[16] = {0};
  put(&out, zero, 16);
  put_le<uint32_t>(&out, 0);  // n_items
  put_le<uint32_t>(&out, static_cast<uint32_t>(err.size()));
  put(&out, err.data(), err.size());
  return out;
}

// A batch frame (flag 8): K nested complete payloads behind one outer
// header.  Each item decodes and computes independently — one poisoned
// item yields an error reply in ITS slot only.  Zero items = the
// capability probe; the empty batch reply is the affirmative answer.
std::vector<uint8_t> serve_batch(const std::vector<uint8_t>& buf) {
  Reader r(buf.data(), buf.size());
  char magic[4];
  uint8_t ver = 0, flags = 0;
  uint8_t uuid[16];
  uint32_t n_items = 0;
  if (!r.bytes(magic, 4) || std::memcmp(magic, kMagic, 4) != 0 ||
      !r.le(&ver) || ver != kVersion || !r.le(&flags) ||
      !r.bytes(uuid, 16) || !r.le(&n_items))
    return batch_error_reply("decode failed: truncated batch header");
  if (flags & ~kKnownFlags)
    return batch_error_reply(
        "decode failed: unknown flag bits (version-skewed peer?)");
  if (flags & kFlagError) {
    uint32_t elen = 0;
    std::string e;
    if (!r.le(&elen) || !r.str(&e, elen))
      return batch_error_reply("decode failed: truncated error block");
  }
  if (flags & kFlagTrace) {
    uint8_t trace_id[16];
    if (!r.bytes(trace_id, 16))
      return batch_error_reply("decode failed: truncated trace block");
  }
  if (flags & kFlagDeadline) {
    double deadline_s = 0.0;
    if (!r.le(&deadline_s))
      return batch_error_reply("decode failed: truncated deadline block");
    if (deadline_s <= 0.0)
      // The outer budget covers the whole window: expired at admission
      // means no item is computed (the in-band deadline classification
      // the Python client maps to DeadlineExceeded).
      return batch_error_reply(
          "deadline exceeded: budget spent before admission");
  }
  if (flags & kFlagTenant) {
    // Framing-validated and dropped, same posture as plain frames.
    uint16_t tlen = 0;
    std::string tenant;
    if (!r.le(&tlen) || !r.str(&tenant, tlen))
      return batch_error_reply("decode failed: truncated tenant block");
  }
  if (flags & kFlagPartition) {
    // An OUTER partition block asks for a REDUCE window (sum the
    // items' replies, answer partition-indexed slices —
    // routing/partition.py).  The native node serves sliced PLAIN
    // frames but not reduce windows; the refusal is loud and in-band
    // so a driver that mis-negotiated fails over instead of decoding
    // garbage.
    Partition p;
    if (!r.le(&p.index) || !r.le(&p.count) || !r.le(&p.offset) ||
        !r.le(&p.length) || !r.le(&p.total))
      return batch_error_reply("decode failed: truncated partition block");
    return batch_error_reply(
        "partition reduce windows are not supported by the native node");
  }
  if (flags & kFlagVersion) {
    // Outer version stamp on a batch frame = the sharded-optimizer
    // lane; same refusal posture as reduce windows above.
    uint64_t step_version = 0;
    if (!r.le(&step_version))
      return batch_error_reply("decode failed: truncated version block");
    return batch_error_reply(
        "versioned sharded-optimizer updates are not supported by the "
        "native node");
  }
  // Each item needs >= 4 bytes (its length prefix), so any frame holds
  // at most remaining/4 items — reject hostile counts before looping.
  if (n_items > r.remaining() / 4)
    return batch_error_reply("decode failed: item count exceeds payload");
  std::vector<std::vector<uint8_t>> replies;
  replies.reserve(std::min<size_t>(n_items, 4096));
  for (uint32_t i = 0; i < n_items; ++i) {
    uint32_t ilen = 0;
    if (!r.le(&ilen) || ilen > r.remaining())
      return batch_error_reply("decode failed: truncated batch item");
    std::vector<uint8_t> item(ilen);
    if (!r.bytes(item.data(), item.size()))
      return batch_error_reply("decode failed: truncated batch item");
    replies.push_back(serve_plain(item));
  }
  if (flags & kFlagSpans) {  // validated and dropped, like plain frames
    uint32_t slen = 0;
    std::string spans_json;
    if (!r.le(&slen) || slen > r.remaining() ||
        !r.str(&spans_json, slen))
      return batch_error_reply("decode failed: truncated spans block");
  }
  std::vector<uint8_t> out;
  put(&out, kMagic, 4);
  put_le<uint8_t>(&out, kVersion);
  put_le<uint8_t>(&out, kFlagBatch);
  put(&out, uuid, 16);
  put_le<uint32_t>(&out, static_cast<uint32_t>(replies.size()));
  for (const auto& rp : replies) {
    put_le<uint32_t>(&out, static_cast<uint32_t>(rp.size()));
    put(&out, rp.data(), rp.size());
  }
  return out;
}

Array scalar_f8(double v) {
  Array a;
  a.dtype = "<f8";
  a.data.resize(8);
  std::memcpy(a.data.data(), &v, 8);
  return a;
}

// ---- the model: Gaussian linear-regression logp + grad ------------------

bool is_f8(const Array& a) { return a.dtype == "<f8" || a.dtype == "float64"; }

const double* f8(const Array& a) {
  return reinterpret_cast<const double*>(a.data.data());
}

Message compute(const Message& in) {
  Message out;
  std::memcpy(out.uuid, in.uuid, 16);
  if (in.arrays.size() != 5) {
    out.error = "expected 5 inputs: intercept, slope, sigma, x, y";
    return out;
  }
  for (const auto& a : in.arrays)
    if (!is_f8(a)) {
      out.error = "all inputs must be float64 (<f8), got " + a.dtype;
      return out;
    }
  const Array &ai = in.arrays[0], &as = in.arrays[1], &asig = in.arrays[2],
              &ax = in.arrays[3], &ay = in.arrays[4];
  if (ai.nelem() != 1 || as.nelem() != 1 || asig.nelem() != 1) {
    out.error = "intercept/slope/sigma must be scalars";
    return out;
  }
  if (ax.nelem() != ay.nelem()) {
    out.error = "x and y must have equal length";
    return out;
  }
  const double a = f8(ai)[0], b = f8(as)[0], sigma = f8(asig)[0];
  const double* x = f8(ax);
  const double* y = f8(ay);
  const size_t n = ax.nelem();
  if (sigma <= 0.0) {
    out.error = "sigma must be positive";
    return out;
  }
  const double inv_var = 1.0 / (sigma * sigma);
  const double log_norm = -std::log(sigma) - 0.5 * std::log(2.0 * M_PI);
  double logp = 0.0, g_a = 0.0, g_b = 0.0;
  for (size_t i = 0; i < n; ++i) {
    const double resid = y[i] - (a + b * x[i]);
    logp += -0.5 * resid * resid * inv_var + log_norm;
    const double w = resid * inv_var;
    g_a += w;
    g_b += w * x[i];
  }
  out.arrays.push_back(scalar_f8(logp));
  out.arrays.push_back(scalar_f8(g_a));
  out.arrays.push_back(scalar_f8(g_b));
  return out;
}

// ---- fault injection (--fault-plan) -------------------------------------

struct FaultRule {
  enum Kind { kDelay, kDisconnect, kTruncate } kind;
  uint64_t nth;    // 1-based frame number this rule fires on
  uint64_t param;  // delay: milliseconds; truncate: percent kept
};

std::vector<FaultRule> g_fault_rules;
std::atomic<uint64_t> g_frames{0};

const FaultRule* fault_for(uint64_t frame_no) {
  for (const auto& r : g_fault_rules)
    if (r.nth == frame_no) return &r;
  return nullptr;
}

// "delay:2:50,disconnect:4,truncate:6:50" (or a file holding it) ->
// g_fault_rules; false on any malformed entry.
bool parse_fault_plan(const std::string& arg) {
  std::string spec = arg;
  std::ifstream f(arg);
  if (f.good()) {
    std::stringstream ss;
    ss << f.rdbuf();
    spec = ss.str();
  }
  std::stringstream entries(spec);
  std::string entry;
  while (std::getline(entries, entry, ',')) {
    // Trim whitespace/newlines a file-sourced spec may carry.
    while (!entry.empty() && std::isspace(entry.back())) entry.pop_back();
    while (!entry.empty() && std::isspace(entry.front())) entry.erase(0, 1);
    if (entry.empty()) continue;
    FaultRule r{};
    unsigned long long nth = 0, param = 0;
    if (std::sscanf(entry.c_str(), "delay:%llu:%llu", &nth, &param) == 2) {
      r.kind = FaultRule::kDelay;
    } else if (std::sscanf(entry.c_str(), "disconnect:%llu", &nth) == 1) {
      r.kind = FaultRule::kDisconnect;
    } else if (std::sscanf(entry.c_str(), "truncate:%llu:%llu", &nth,
                           &param) == 2) {
      r.kind = FaultRule::kTruncate;
      if (param > 100) return false;
    } else {
      return false;
    }
    if (nth == 0) return false;  // 1-based, like the Python plan
    r.nth = nth;
    r.param = param;
    g_fault_rules.push_back(r);
  }
  return true;
}

// ---- server loop --------------------------------------------------------

// Upper bound on one frame's payload.  Big enough for any realistic
// array batch, small enough that a hostile 0xFFFFFFFF length prefix
// cannot drive a 4 GiB allocation per connection thread.
constexpr uint32_t kMaxFrameBytes = 256u * 1024 * 1024;

void serve_connection(int fd) try {
  for (;;) {
    uint32_t len = 0;
    if (!read_exact(fd, &len, 4)) return;  // peer closed
    if (len > kMaxFrameBytes) return;      // hostile length prefix
    std::vector<uint8_t> buf(len);
    if (!read_exact(fd, buf.data(), len)) return;
    const FaultRule* fault =
        g_fault_rules.empty() ? nullptr : fault_for(++g_frames);
    if (fault && fault->kind == FaultRule::kDisconnect) {
      std::fprintf(stderr, "faultinject[disconnect] frame %llu\n",
                   static_cast<unsigned long long>(fault->nth));
      return;  // close without replying — the peer sees a dead socket
    }
    // Batch frames (flag 8) take the per-item path; everything else is
    // the classic lock-step single evaluate.
    std::vector<uint8_t> payload =
        (buf.size() > kFlagsOff && (buf[kFlagsOff] & kFlagBatch))
            ? serve_batch(buf)
            : serve_plain(buf);
    uint32_t plen = static_cast<uint32_t>(payload.size());
    if (fault && fault->kind == FaultRule::kDelay)
      ::usleep(static_cast<useconds_t>(fault->param) * 1000);
    if (fault && fault->kind == FaultRule::kTruncate) {
      // Mid-frame kill: the prefix promises plen bytes, fewer arrive,
      // then the connection closes — the peer's framed read fails
      // loudly ("peer closed mid-frame"), never a silent short frame.
      size_t keep = payload.size() * fault->param / 100;
      if (payload.size() > 1)
        keep = std::min(std::max<size_t>(keep, 1), payload.size() - 1);
      std::fprintf(stderr, "faultinject[truncate] frame %llu (%zu/%zu)\n",
                   static_cast<unsigned long long>(fault->nth), keep,
                   payload.size());
      write_exact(fd, &plen, 4);
      write_exact(fd, payload.data(), keep);
      return;
    }
    if (!write_exact(fd, &plen, 4) ||
        !write_exact(fd, payload.data(), payload.size()))
      return;
  }
} catch (const std::exception& e) {
  // A bad_alloc (or anything else) from one connection's decode or
  // compute must close that connection, not std::terminate the whole
  // multi-port process from a detached thread.
  std::fprintf(stderr, "connection dropped: %s\n", e.what());
}

int listen_on(int port) {
  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) {
    std::perror("socket");
    return -1;
  }
  int one = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    std::perror("bind");
    return -1;
  }
  if (::listen(srv, 64) < 0) {
    std::perror("listen");
    return -1;
  }
  return srv;
}

void accept_loop(int srv) {
  int one = 1;
  for (;;) {
    int fd = ::accept(srv, nullptr, nullptr);
    if (fd < 0) {
      // Transient conditions (reset-in-backlog, fd/thread pressure)
      // must not kill the listener: the port would keep accepting TCP
      // connections from its backlog while serving no frames, hanging
      // clients silently.
      if (errno == EINTR || errno == ECONNABORTED) continue;
      if (errno == EMFILE || errno == ENFILE || errno == EAGAIN) {
        std::perror("accept (transient, retrying)");
        ::usleep(10 * 1000);
        continue;
      }
      // Anything else is fatal: exit loudly (pre-pool behavior) so a
      // supervisor notices, instead of degrading one port silently.
      std::perror("accept");
      std::exit(1);
    }
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    try {
      std::thread([fd]() {
        serve_connection(fd);
        ::close(fd);
      }).detach();
    } catch (const std::system_error&) {
      // Thread limit hit: serve this connection inline (serial but
      // correct) rather than aborting the whole process.
      serve_connection(fd);
      ::close(fd);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  std::vector<int> ports;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "--fault-plan") == 0) {
      if (i + 1 >= argc || !parse_fault_plan(argv[++i])) {
        std::fprintf(stderr, "bad --fault-plan spec\n");
        return 2;
      }
      continue;
    }
    ports.push_back(std::atoi(argv[i]));
  }
  if (ports.empty()) {
    std::fprintf(stderr,
                 "usage: %s <port> [<port> ...] [--fault-plan <spec>]\n",
                 argv[0]);
    return 2;
  }
  ::signal(SIGPIPE, SIG_IGN);

  std::vector<int> socks;
  for (int port : ports) {
    int srv = listen_on(port);
    if (srv < 0) return 1;
    socks.push_back(srv);
  }
  // Readiness lines on stdout — the Python test waits for the first.
  for (int port : ports)
    std::printf("cpp_node listening on 127.0.0.1:%d\n", port);
  std::fflush(stdout);

  std::vector<std::thread> listeners;
  for (int srv : socks) listeners.emplace_back(accept_loop, srv);
  for (auto& t : listeners) t.join();
  return 0;
}
