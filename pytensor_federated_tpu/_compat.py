"""Version-portability shims for the jax API surface.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export, and its replication-checking kwarg
was renamed ``check_rep`` -> ``check_vma`` when the varying-manual-axes
system landed; this package must import cleanly and run on both sides
of those moves (the CPU test container and the TPU capture container
have carried different jax versions).  Import from here — one fallback,
not one per module.

Exports:

- ``shard_map``: top-level-or-experimental, always accepting the modern
  ``check_vma=`` spelling (translated to ``check_rep=`` on older jax).
- ``mark_varying_supported``: True when the running jax has the
  ``pvary``/``pcast`` primitives that :func:`parallel.mesh.mark_varying`
  rides; on older jax the vma system does not exist and marking is an
  identity (the check_rep machinery handles replicated operands itself).
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - jax-version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:  # pragma: no cover - jax-version-dependent

    def shard_map(*args, **kwargs):
        """``shard_map`` accepting ``check_vma=`` on pre-vma jax.

        ``check_vma`` maps onto the old ``check_rep``, and when the
        caller says nothing the OLD checker is disabled: without
        ``pvary`` there is no way to annotate loop carries that become
        device-varying (ring/ulysses accumulators, user ODE scans), so
        the pre-vma replication tracker rejects valid programs with
        "Scan carry ... mismatched replication types".  Numerical
        parity under the disabled checker is pinned by the golden-model
        gradient tests (test_sharded, test_federated_primitives,
        test_statespace, ...)."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        else:
            kwargs.setdefault("check_rep", False)
        return _shard_map(*args, **kwargs)


def _probe_mark_varying() -> bool:
    from jax import lax

    return hasattr(lax, "pcast") or hasattr(lax, "pvary")


mark_varying_supported = _probe_mark_varying()

try:  # graduated out of jax.experimental
    from jax import enable_x64
except (ImportError, AttributeError):  # pragma: no cover
    from jax.experimental import enable_x64

__all__ = ["shard_map", "mark_varying_supported", "enable_x64"]
