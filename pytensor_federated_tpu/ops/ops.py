"""Graph-integration ops: embed compute functions into differentiable graphs.

TPU-native re-design of the reference's wrapper ops
(reference: pytensor_federated/wrapper_ops.py).  The reference wraps its
gRPC clients as PyTensor ``Op`` s so PyMC graphs can call remote
likelihoods; here the "graph" is a JAX trace, so an op is a callable that
is (a) input-coercing, (b) jit-safe, and (c) differentiable with the same
contract as the reference:

- :class:`ArraysToArraysOp` — generic arrays->arrays
  (reference: wrapper_ops.py:14-33).
- :class:`LogpOp` — scalar log-potential (reference: wrapper_ops.py:44-69).
- :class:`LogpGradOp` — returns ``(logp, grads)`` and participates in
  autodiff exactly like the reference's symbolic ``.grad()``: the VJP of
  ``logp`` w.r.t. input ``i`` is ``g_logp * grads[i]``, using the
  *forward-pass-supplied* gradients instead of differentiating through the
  compute function (reference: wrapper_ops.py:119-132).  Like the
  reference, gradients w.r.t. the grad outputs are rejected — no
  second-order autodiff through the federated boundary
  (reference: wrapper_ops.py:123-125).

The reference needs separate ``Async*`` variants of each op because its
executor is synchronous while transport is asyncio
(reference: wrapper_ops.py:36-41, 72-81, 135-146).  XLA dispatch is
already asynchronous — every op here *is* the async variant — so the
``Async*`` names are provided as aliases for API parity.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..signatures import Array, ComputeFn, LogpFn, LogpGradFn, check_scalar


class ArraysToArraysOp:
    """Wrap an arrays->arrays function with input coercion.

    Parity: reference wrapper_ops.py:14-33 — inputs are coerced with
    ``as_tensor`` (here ``jnp.asarray``; fixes the reference's "issue #24"
    raw-int regression by construction, reference: test_wrapper_ops.py:284-289).
    """

    def __init__(self, fn: ComputeFn, *, jit: bool = False):
        self.fn = jax.jit(fn) if jit else fn

    def __call__(self, *inputs) -> Sequence[Array]:
        args = tuple(jnp.asarray(x) for x in inputs)
        return list(self.fn(*args))


class LogpOp:
    """Inputs -> scalar log-potential (reference: wrapper_ops.py:44-69)."""

    def __init__(self, logp_fn: LogpFn):
        self.logp_fn = logp_fn

    def __call__(self, *inputs) -> Array:
        args = tuple(jnp.asarray(x) for x in inputs)
        return check_scalar(jnp.asarray(self.logp_fn(*args)), "logp")


def _make_logp_grad_call(logp_grad_fn: LogpGradFn) -> Callable:
    """Build the custom-VJP core shared by LogpGradOp instances."""

    @jax.custom_vjp
    def call(*inputs):
        logp, grads = logp_grad_fn(*inputs)
        logp = check_scalar(jnp.asarray(logp), "logp")
        grads = tuple(jnp.asarray(g) for g in grads)
        if len(grads) != len(inputs):
            raise ValueError(
                f"logp_grad_fn returned {len(grads)} grads for "
                f"{len(inputs)} inputs"
            )
        return (logp, grads)

    def fwd(*primals):
        # symbolic_zeros=True wraps each primal in CustomVJPPrimal.
        out = call(*(p.value for p in primals))
        _, grads = out
        return out, grads

    def bwd(residual_grads, cotangents):
        g_logp, g_grads = cotangents
        # Reject connected gradients w.r.t. the grad outputs — the same
        # "no second-order autodiff through the federated boundary"
        # contract as the reference (wrapper_ops.py:123-125) and this
        # repo's bridge op (bridge/pytensor_ops.py).  With
        # ``symbolic_zeros=True`` an output that nothing differentiates
        # arrives as a SymbolicZero, so a *connected* cotangent on a
        # grad output is detectable at trace time and fails loudly here
        # instead of silently contributing zero to a Hessian.
        SymbolicZero = jax.custom_derivatives.SymbolicZero
        if any(not isinstance(g, SymbolicZero) for g in g_grads):
            raise NotImplementedError(
                "gradients with respect to LogpGradOp's grad outputs are "
                "not supported: the federated boundary is first-order "
                "only (nodes supply logp and first grads; second-order "
                "information never crosses the wire). Use the grads "
                "output as data (lax.stop_gradient) if that is intended."
            )
        if isinstance(g_logp, SymbolicZero):
            return tuple(jnp.zeros_like(g) for g in residual_grads)
        return tuple(
            jnp.asarray(g_logp, dtype=jnp.result_type(g)) * g
            for g in residual_grads
        )

    call.defvjp(fwd, bwd, symbolic_zeros=True)
    return call


class LogpGradOp:
    """Inputs -> ``(logp, grads)`` with forward-supplied VJP.

    Parity: reference wrapper_ops.py:84-132.  The reference's ``.grad()``
    re-applies the op on the same inputs and relies on CSE to dedup the
    second apply (reference: wrapper_ops.py:126-131); here the forward
    pass already returns the grads, the VJP closes over them as
    residuals, and XLA's common-subexpression elimination plays the CSE
    role inside one jitted program.
    """

    def __init__(self, logp_grad_fn: LogpGradFn):
        self.logp_grad_fn = logp_grad_fn
        self._call = _make_logp_grad_call(logp_grad_fn)

    def __call__(self, *inputs):
        args = tuple(jnp.asarray(x) for x in inputs)
        logp, grads = self._call(*args)
        return logp, grads

    def logp(self, *inputs) -> Array:
        """Scalar-only view — differentiable via the forward-supplied VJP."""
        return self(*inputs)[0]


def from_logp_fn(logp_fn: LogpFn) -> LogpGradOp:
    """LogpGradOp whose gradients come from autodiff of ``logp_fn``.

    TPU-native convenience with no reference analog (reference nodes must
    supply gradients, reference: signatures.py:26-33).
    """
    from ..wrappers import logp_grad_from_logp

    return LogpGradOp(logp_grad_from_logp(logp_fn))


# API-parity aliases: on XLA every op dispatches asynchronously already
# (reference needs distinct Async* classes: wrapper_ops.py:36-41, 72-81,
# 135-146).
AsyncArraysToArraysOp = ArraysToArraysOp
AsyncLogpOp = LogpOp
AsyncLogpGradOp = LogpGradOp
