"""Blackbox / host escape hatch: non-JAX compute inside a JAX graph.

The reference's whole premise is that the node function is a *blackbox*
to the driver — "the model implementation could be C++, while MCMC/
optimization run in Python" (reference: README.md:34-35): any callable
behind the wire contract works, at the price of a network round-trip per
evaluation.  The TPU-native design keeps that capability as an explicit
*off-hot-path* door: a host callback (``jax.pure_callback``) whose output
signature is declared up front, wrapped so it is differentiable under the
same forward-supplied-gradient contract as :class:`..ops.ops.LogpGradOp`.

Use cases preserved from the reference: wrapping a legacy C/C++/Fortran
likelihood, or bridging to a *true* cross-trust-domain federated node via
:mod:`pytensor_federated_tpu.service` (the host RPC client plugs in here
as the ``host_fn``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..signatures import Array, ArraysSpec
from .ops import LogpGradOp


def blackbox_compute(
    host_fn: Callable[..., Sequence[np.ndarray]],
    out_spec: ArraysSpec,
    *,
    vmap_method: str = "sequential",
) -> Callable[..., list[Array]]:
    """Wrap a host (non-JAX) arrays->arrays function for use under jit.

    ``out_spec`` declares the static output signature — the analog of the
    reference's wire schema (reference: protobufs/service.proto:6-19):
    the driver must know output shapes to build the graph, exactly as
    PyTensor ops declare output types (reference: wrapper_ops.py:97-105).

    The callback runs on the host; XLA treats it as opaque.  This is the
    one deliberately slow path in the framework (SURVEY §7 step 6).
    """
    out_spec = tuple(out_spec)

    def fn(*inputs) -> list[Array]:
        args = tuple(jnp.asarray(x) for x in inputs)
        flat_out = jax.pure_callback(
            lambda *a: tuple(
                np.asarray(o, dtype=s.dtype)
                for o, s in zip(host_fn(*a), out_spec)
            ),
            out_spec,
            *args,
            vmap_method=vmap_method,
        )
        return list(flat_out)

    return fn


def blackbox_logp_grad(
    host_logp_grad: Callable[..., tuple],
    in_spec: ArraysSpec,
    *,
    logp_dtype=jnp.float32,
) -> LogpGradOp:
    """Differentiable blackbox logp+grad op backed by a host callable.

    ``host_logp_grad(*arrays) -> (logp, [grads])`` with NumPy semantics —
    the exact node contract of the reference
    (reference: signatures.py:26-33) — becomes a :class:`LogpGradOp`
    whose VJP uses the host-supplied gradients
    (reference: wrapper_ops.py:119-132).  ``in_spec`` fixes each input's
    shape/dtype so grad output signatures are static.
    """
    in_spec = tuple(in_spec)
    out_spec = (jax.ShapeDtypeStruct((), jnp.dtype(logp_dtype)),) + in_spec

    def host_flat(*arrays):
        logp, grads = host_logp_grad(*(np.asarray(a) for a in arrays))
        return [np.asarray(logp)] + [np.asarray(g) for g in grads]

    flat = blackbox_compute(host_flat, out_spec)

    def logp_grad_fn(*inputs):
        out = flat(*inputs)
        return out[0], tuple(out[1:])

    return LogpGradOp(logp_grad_fn)
