"""Parallel fan-out of independent evaluations.

TPU-native re-design of the reference's concurrency engine
(reference: pytensor_federated/op_async.py).  The reference needs three
pieces of machinery to overlap N independent remote calls:

- ``AsyncOp`` bridging a sync executor to coroutines (op_async.py:16-34),
- ``ParallelAsyncOp`` fusing N applies into one ``asyncio.gather``
  (op_async.py:68-132),
- the ``fuse_asyncs`` graph rewrite that finds independent applies and
  fuses them automatically at compile time (op_async.py:135-234).

On TPU, the first and third collapse: everything traced into one ``jit``
is scheduled by XLA, which already overlaps independent subgraphs (and
runs them as one fused SPMD program — better than latency-hiding).
:func:`fuse` documents/implements that equivalence for on-device fns.

What does NOT collapse is fan-out over *host/blackbox* functions (the true
federated case): XLA host callbacks execute serially per program, so
overlapping N slow remote nodes needs an explicit gather — that is
:class:`ParallelLogpGrad` / :func:`parallel_host_call`, which batch N host
calls into ONE callback whose host side runs a thread pool.  Wall time is
max(node latencies), not the sum — the same guarantee the reference
proves by timing (reference: test_op_async.py:98-105, 180-194).
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..fanout_exec import MemberExecutorPool
from ..signatures import Array, ArraysSpec


def fuse(fns: Sequence[Callable], *, jit: bool = True) -> Callable:
    """Fuse N independent on-device functions into one program.

    ``fuse([f, g])(args_f, args_g) -> [f(*args_f), g(*args_g)]``.

    Parity note: this is the explicit form of the reference's
    ``fuse_asyncs`` rewrite (reference: op_async.py:216-234) — under XLA
    the fusion is *automatic* for anything traced together; calling
    :func:`fuse` simply guarantees the N evaluations compile into a
    single executable so independent shard computations overlap.
    """

    def fused(*args_per_fn):
        if len(args_per_fn) != len(fns):
            raise ValueError(
                f"expected {len(fns)} argument tuples, got {len(args_per_fn)}"
            )
        return [f(*a) for f, a in zip(fns, args_per_fn)]

    return jax.jit(fused) if jit else fused


def parallel_host_call(
    host_fns: Sequence[Callable[..., Sequence[np.ndarray]]],
    out_specs: Sequence[ArraysSpec],
) -> Callable[..., List[List[Array]]]:
    """Evaluate N host functions concurrently inside ONE callback.

    The direct :class:`ParallelAsyncOp` analog (reference:
    op_async.py:68-132): inputs are passed per-child and sliced back out
    per-child, and the host side runs every child in a thread pool —
    ``asyncio.gather`` becomes ``ThreadPoolExecutor.map``.  Returns a
    jittable ``fn(args_0, args_1, ...) -> [outputs_0, outputs_1, ...]``
    where each ``args_i`` is a tuple of arrays for child ``i``.
    """
    host_fns = list(host_fns)
    out_specs = [tuple(s) for s in out_specs]
    flat_spec = tuple(s for spec in out_specs for s in spec)
    n_out = [len(s) for s in out_specs]
    # One PERSISTENT single-thread executor PER CHILD: node i always runs
    # on its own long-lived thread, so thread-keyed client state (event
    # loop, cached gRPC stream) maps 1:1 to nodes.  A shared pool would
    # run node i on a different thread each call (N x N connections); a
    # fresh pool per call would recycle thread idents, handing a new
    # thread a cached channel bound to a dead thread's event loop.
    # MemberExecutorPool adds lazy creation + GC finalization, so a
    # dropped callable cannot leak its threads for the process lifetime
    # (the round-2 advisor finding on fusion.py applied here too).
    pool = MemberExecutorPool(len(host_fns), name="pft-fanout")

    def close():
        pool.shutdown()

    def fn(*args_per_child) -> List[List[Array]]:
        if len(args_per_child) != len(host_fns):
            raise ValueError(
                f"expected {len(host_fns)} argument tuples, "
                f"got {len(args_per_child)}"
            )
        arities = [len(a) for a in args_per_child]
        flat_in = [jnp.asarray(x) for a in args_per_child for x in a]

        def host(*flat_arrays):
            # Slice the concatenated inputs per child apply — same move
            # as ParallelAsyncOp.perform (reference: op_async.py:115-124).
            chunks, i = [], 0
            for k in arities:
                chunks.append(flat_arrays[i : i + k])
                i += k
            futures = [
                pool.submit(i, lambda f=f, c=c: list(f(*c)))
                for i, (f, c) in enumerate(zip(host_fns, chunks))
            ]
            results = [fut.result() for fut in futures]
            flat = [
                np.asarray(o, dtype=s.dtype)
                for outs, spec in zip(results, out_specs)
                for o, s in zip(outs, spec)
            ]
            return tuple(flat)

        # sequential vmap: a batched caller (e.g. vmap over MCMC chains)
        # replays the fan-out per batch element — remote nodes see a
        # stream of requests, matching the lock-step wire protocol.
        flat_out = jax.pure_callback(
            host, flat_spec, *flat_in, vmap_method="sequential"
        )
        out, i = [], 0
        for k in n_out:
            out.append(list(flat_out[i : i + k]))
            i += k
        return out

    fn.close = close
    return fn


class ParallelLogpGrad:
    """N blackbox logp+grad nodes evaluated concurrently and differentiably.

    The fused op the reference's rewrite produces for its federated hot
    path: one apply that fans out to every node and gathers
    ``(logp_i, grads_i)`` (reference: op_async.py:107-132 +
    wrapper_ops.py:135-146).  The VJP applies the forward-supplied
    per-node gradients (``g_logp_i * grads_i``), matching
    reference wrapper_ops.py:119-132; second-order autodiff through the
    boundary is unsupported, as in the reference (wrapper_ops.py:123-125).

    ``in_specs[i]`` fixes the input signature of node ``i`` so the
    callback's output signature is static.
    """

    def __init__(
        self,
        host_logp_grads: Sequence[Callable[..., tuple]],
        in_specs: Sequence[ArraysSpec],
        *,
        logp_dtype=jnp.float32,
    ):
        if len(host_logp_grads) != len(in_specs):
            raise ValueError("need one in_spec per node")
        self.n_nodes = len(host_logp_grads)
        self.in_specs = [tuple(s) for s in in_specs]
        scalar = jax.ShapeDtypeStruct((), jnp.dtype(logp_dtype))
        out_specs = [(scalar,) + spec for spec in self.in_specs]

        def flat_node(i):
            fn = host_logp_grads[i]

            def host(*arrays):
                logp, grads = fn(*(np.asarray(a) for a in arrays))
                return [np.asarray(logp)] + [np.asarray(g) for g in grads]

            return host

        fanout = parallel_host_call(
            [flat_node(i) for i in range(self.n_nodes)], out_specs
        )
        self._fanout = fanout
        arities = [len(s) for s in self.in_specs]

        @jax.custom_vjp
        def call(*flat_inputs):
            args_per_child, i = [], 0
            for k in arities:
                args_per_child.append(tuple(flat_inputs[i : i + k]))
                i += k
            outs = fanout(*args_per_child)
            logps = tuple(o[0] for o in outs)
            grads = tuple(tuple(o[1:]) for o in outs)
            return logps, grads

        def fwd(*flat_inputs):
            out = call(*flat_inputs)
            return out, out[1]

        def bwd(residual_grads, cotangents):
            g_logps, _g_grads = cotangents
            flat = []
            for g_logp, grads in zip(g_logps, residual_grads):
                for g in grads:
                    flat.append(jnp.asarray(g_logp, dtype=jnp.result_type(g)) * g)
            return tuple(flat)

        call.defvjp(fwd, bwd)
        self._call = call

    def __call__(self, inputs_per_node: Sequence[Tuple]) -> List[Tuple]:
        """``[(args of node i)] -> [(logp_i, grads_i)]``, one fused fan-out."""
        if len(inputs_per_node) != self.n_nodes:
            raise ValueError(
                f"expected inputs for {self.n_nodes} nodes, "
                f"got {len(inputs_per_node)}"
            )
        flat = [jnp.asarray(x) for args in inputs_per_node for x in args]
        logps, grads = self._call(*flat)
        return list(zip(logps, grads))

    def total_logp(self, inputs_per_node: Sequence[Tuple]) -> Array:
        """Sum of node logps — the sum-of-potentials reduction the
        reference expresses in-graph (reference: demo_model.py:34-36)."""
        results = self(inputs_per_node)
        return jnp.sum(jnp.stack([lp for lp, _ in results]))

    def close(self) -> None:
        """Shut down the per-node executor threads.  Mirrors the
        reference client's stream teardown in ``__del__``
        (reference: service.py:355-365)."""
        self._fanout.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
