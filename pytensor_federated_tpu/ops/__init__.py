"""Graph-integration ops (reference L4/L5 analog)."""

from .blackbox import blackbox_compute, blackbox_logp_grad
from .fanout import ParallelLogpGrad, fuse, parallel_host_call
from .pallas_kernels import linreg_logp_grad_fn, linreg_reductions
from .ops import (
    ArraysToArraysOp,
    AsyncArraysToArraysOp,
    AsyncLogpGradOp,
    AsyncLogpOp,
    LogpGradOp,
    LogpOp,
    from_logp_fn,
)

__all__ = [
    "ArraysToArraysOp",
    "AsyncArraysToArraysOp",
    "AsyncLogpGradOp",
    "AsyncLogpOp",
    "LogpGradOp",
    "LogpOp",
    "ParallelLogpGrad",
    "blackbox_compute",
    "blackbox_logp_grad",
    "from_logp_fn",
    "fuse",
    "linreg_logp_grad_fn",
    "linreg_reductions",
    "parallel_host_call",
]
