"""Pallas TPU kernels for the hot federated-likelihood ops.

The reference's per-node hot path is a compiled PyTensor C function
evaluating a Gaussian linear-regression logp and its gradients
(reference: demo_node.py:30-43 builds the graph; demo_node.py:39-42
compiles ``[logp, dlogp/dintercept, dlogp/dslope]``).  Here the same
computation is a hand-written Pallas kernel that makes ONE fused pass
over each shard's ``(x, y, mask)`` block and produces the log-likelihood
*and* every sufficient gradient reduction simultaneously:

    ll_i        = sum_n m (-0.5 z^2 - log_sigma - 0.5 log 2pi)
    gmu_i       = sum_n m r / sigma^2          (d ll / d(intercept+offset_i))
    gx_i        = sum_n m r x / sigma^2        (d ll / d slope, per shard)
    gz_i        = sum_n m (z^2 - 1)            (d ll / d log_sigma, per shard)

with ``r = y - mu``, ``z = r / sigma``.  ``jax.value_and_grad`` on the
plain-JAX likelihood stages a forward pass plus a transposed backward
pass; this kernel reads the data exactly once and keeps every reduction
in VMEM, so the bytes moved from HBM are halved — the op is
bandwidth-bound, which makes that the ceiling that matters
(see /opt/skills/guides/pallas_guide.md, "HBM bandwidth").

Everything is wired up as a ``jax.custom_vjp`` so the kernel drops into
``jax.value_and_grad`` / NUTS unchanged.  On non-TPU backends the kernel
runs in Pallas interpreter mode, so CPU tests exercise the identical
code path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import LOG_2PI

# Lane layout of the per-shard reduction tile (see _linreg_kernel).
_LANE_LL, _LANE_GMU, _LANE_GX, _LANE_GZ = 0, 1, 2, 3
_N_LANES = 128  # one float32 register lane row


def _interpret_default() -> bool:
    """Interpreter mode unless compiled Mosaic is explicitly requested.

    Compiled Pallas needs a direct Mosaic-capable TPU runtime; tunneled
    single-chip dev environments (PJRT proxy plugins) may accept XLA
    programs but wedge on Mosaic payloads, so the compiled path is
    opt-in via ``PFTPU_PALLAS_COMPILED=1`` rather than keyed off
    ``jax.default_backend()``.
    """
    import os

    if os.environ.get("PFTPU_PALLAS_COMPILED") == "1":
        return False
    return True


def probe_compiled_mosaic(timeout_s: float = 180.0) -> bool:
    """Check whether this runtime executes compiled Mosaic kernels.

    Tunneled/PJRT-proxy single-chip environments can *hang* (not raise)
    on Mosaic payloads, so the probe runs a tiny compiled kernel in a
    subprocess under a wall-clock timeout (see ``utils.probe_backend``).
    Run it BEFORE this process initializes jax — single-host TPU
    runtimes are exclusive per process.  Returns True only on a clean
    numerically-correct run.
    """
    from ..utils import probe_backend

    return probe_backend(try_mosaic=True, timeout_s=timeout_s)[1]


def _linreg_kernel(scal_ref, off_ref, x_ref, y_ref, m_ref, out_ref):
    """One (BS, BN) block: fused logp + gradient reductions.

    ``scal_ref`` (SMEM): ``[intercept, slope, log_sigma]``.
    ``off_ref``: per-shard intercept offsets, block ``(BS, 1)``.
    ``x/y/m_ref``: data blocks ``(BS, BN)``.
    ``out_ref``: ``(BS, 128)`` accumulator tile; lanes 0..3 hold
    ``ll, gmu, gx, gz`` (lane layout keeps the store a single aligned
    (8,128) vector write instead of four sub-lane scatters).
    """
    j = pl.program_id(1)

    intercept = scal_ref[0]
    slope = scal_ref[1]
    log_sigma = scal_ref[2]
    inv_s2 = jnp.exp(-2.0 * log_sigma)

    x = x_ref[:]
    y = y_ref[:]
    m = m_ref[:]
    mu = (intercept + off_ref[:]) + slope * x  # off broadcasts (BS,1)->(BS,BN)
    r = y - mu
    z2 = r * r * inv_s2

    ll = jnp.sum(m * (-0.5 * z2 - log_sigma - 0.5 * LOG_2PI), axis=1)
    gmu = jnp.sum(m * r, axis=1) * inv_s2
    gx = jnp.sum(m * r * x, axis=1) * inv_s2
    gz = jnp.sum(m * (z2 - 1.0), axis=1)

    lane = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 1)
    tile = (
        jnp.where(lane == _LANE_LL, ll[:, None], 0.0)
        + jnp.where(lane == _LANE_GMU, gmu[:, None], 0.0)
        + jnp.where(lane == _LANE_GX, gx[:, None], 0.0)
        + jnp.where(lane == _LANE_GZ, gz[:, None], 0.0)
    )

    @pl.when(j == 0)
    def _():
        out_ref[:] = tile

    @pl.when(j != 0)
    def _():
        out_ref[:] = out_ref[:] + tile


def _pad_axis(a: jax.Array, axis: int, to_multiple: int) -> jax.Array:
    size = a.shape[axis]
    pad = (-size) % to_multiple
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


def linreg_reductions(
    scalars: jax.Array,
    offsets: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    *,
    block_shards: int = 8,
    block_obs: int = 512,
    interpret: bool | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-shard ``(ll, gmu, gx, gz)`` reductions, one fused data pass.

    Resolves ``interpret=None`` from the environment *outside* jit so the
    jit cache keys on the resolved value (an env change between calls
    must not be masked by a stale cached trace).
    """
    if interpret is None:
        interpret = _interpret_default()
    return _linreg_reductions_jit(
        scalars,
        offsets,
        x,
        y,
        mask,
        block_shards=block_shards,
        block_obs=block_obs,
        interpret=bool(interpret),
    )


@functools.partial(
    jax.jit, static_argnames=("block_shards", "block_obs", "interpret")
)
def _linreg_reductions_jit(
    scalars: jax.Array,
    offsets: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    *,
    block_shards: int,
    block_obs: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-shard ``(ll, gmu, gx, gz)`` reductions, one fused data pass.

    ``scalars = [intercept, slope, log_sigma]``; ``offsets``: ``(S,)``;
    ``x, y, mask``: ``(S, N)`` float32.  Returns four ``(S,)`` vectors.
    Shards/observations are zero-padded to the block grid; padded rows
    and columns carry ``mask == 0`` so they contribute nothing.
    """
    S, N = x.shape

    bs = min(block_shards, max(S, 1))
    bn = min(block_obs, max(N, 1))
    x = _pad_axis(_pad_axis(x, 0, bs), 1, bn)
    y = _pad_axis(_pad_axis(y, 0, bs), 1, bn)
    mask = _pad_axis(_pad_axis(mask, 0, bs), 1, bn)
    offs = _pad_axis(offsets[:, None], 0, bs)
    Sp, Np = x.shape

    grid = (Sp // bs, Np // bn)
    out = pl.pallas_call(
        _linreg_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((3,), lambda i, j: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bs, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bs, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bs, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bs, bn), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((bs, _N_LANES), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, _N_LANES), jnp.float32),
        interpret=interpret,
    )(scalars, offs, x, y, mask)

    out = out[:S]
    return (
        out[:, _LANE_LL],
        out[:, _LANE_GMU],
        out[:, _LANE_GX],
        out[:, _LANE_GZ],
    )


def linreg_logp_grad_fn(x, y, mask, *, interpret: bool | None = None):
    """Build ``logp_and_grad(params) -> (logp, grads)`` on the kernel.

    ``params`` pytree matches
    :class:`..models.linear.FederatedLinearRegression`:
    ``{intercept, slope, log_sigma, offsets}``.  The returned function is
    differentiable (``jax.custom_vjp``): the VJP replays the reductions
    already produced by the single forward pass, so ``value_and_grad``
    costs ONE data pass total.  Second-order autodiff through the kernel
    is unsupported — same boundary contract as the reference's
    ``LogpGradOp.grad`` (reference: wrapper_ops.py:123-125).
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)

    def reductions(params):
        scalars = jnp.stack(
            [params["intercept"], params["slope"], params["log_sigma"]]
        ).astype(jnp.float32)
        return linreg_reductions(
            scalars, params["offsets"].astype(jnp.float32), x, y, mask,
            interpret=interpret,
        )

    @jax.custom_vjp
    def data_logp(params):
        ll, _, _, _ = reductions(params)
        return jnp.sum(ll)

    def fwd(params):
        ll, gmu, gx, gz = reductions(params)
        grads = {
            "intercept": jnp.sum(gmu),
            "slope": jnp.sum(gx),
            "log_sigma": jnp.sum(gz),
            "offsets": gmu.astype(params["offsets"].dtype),
        }
        return jnp.sum(ll), grads

    def bwd(grads, g):
        return (jax.tree_util.tree_map(lambda t: g * t, grads),)

    data_logp.defvjp(fwd, bwd)

    def logp_and_grad(params):
        return jax.value_and_grad(data_logp)(params)

    logp_and_grad.data_logp = data_logp
    return logp_and_grad
