"""Adapters between modeling signatures and the flat arrays contract.

TPU-native re-design of the reference's server-side adapters
(reference: pytensor_federated/common.py:12-49).  The reference wraps a
``LogpFunc`` / ``LogpGradFunc`` into the flat ``ComputeFunc`` convention
with *runtime* shape checks; here the same contracts are validated at
trace time (static XLA shapes) and the wrapped functions stay jittable.

A TPU-native extra: :func:`logp_grad_from_logp` derives the gradient with
``jax.value_and_grad`` instead of requiring the node author to hand-derive
it (the reference's nodes compile a separate dlogp graph,
reference: demo_node.py:39-42).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .signatures import Array, ComputeFn, LogpFn, LogpGradFn, check_scalar


def wrap_logp_fn(logp_fn: LogpFn) -> ComputeFn:
    """Adapt a logp function to the ``arrays -> [arrays]`` contract.

    Parity: reference common.py:12-23 (``wrap_logp_func``) — output is a
    single scalar array; non-scalar logp is rejected (at trace time here).
    """

    def compute_fn(*inputs: Array) -> Sequence[Array]:
        logp = logp_fn(*inputs)
        return [check_scalar(jnp.asarray(logp), "logp")]

    return compute_fn


def wrap_logp_grad_fn(logp_grad_fn: LogpGradFn) -> ComputeFn:
    """Adapt a logp-and-grad function to ``arrays -> [logp, *grads]``.

    Parity: reference common.py:26-49 (``wrap_logp_grad_func``) — exactly
    one gradient per input, each with its input's shape; scalar logp.
    """

    def compute_fn(*inputs: Array) -> Sequence[Array]:
        logp, grads = logp_grad_fn(*inputs)
        logp = check_scalar(jnp.asarray(logp), "logp")
        grads = tuple(jnp.asarray(g) for g in grads)
        if len(grads) != len(inputs):
            raise ValueError(
                f"Expected one gradient per input ({len(inputs)}), "
                f"got {len(grads)}."
            )
        for i, (g, x) in enumerate(zip(grads, inputs)):
            xs = jnp.shape(jnp.asarray(x))
            if jnp.shape(g) != xs:
                raise ValueError(
                    f"Gradient {i} has shape {jnp.shape(g)}, "
                    f"expected input shape {xs}."
                )
        return [logp, *grads]

    return compute_fn


def logp_grad_from_logp(logp_fn: LogpFn) -> LogpGradFn:
    """Derive a ``LogpGradFn`` from a logp function via autodiff.

    TPU-native addition with no reference equivalent: the reference's
    nodes must supply gradients explicitly (reference: signatures.py:26-33);
    on the JAX path they come for free and fuse into one XLA program.
    """

    def logp_grad_fn(*inputs: Array):
        args = tuple(jnp.asarray(x) for x in inputs)
        logp, grads = jax.value_and_grad(
            lambda *a: check_scalar(logp_fn(*a), "logp"),
            argnums=tuple(range(len(args))),
        )(*args)
        return logp, tuple(grads)

    return logp_grad_fn
