"""Trace reunion: merge driver-side and node-side span trees per call.

PR 1 put a 16-byte trace id on the wire so both halves of one RPC time
themselves under the same key — but the node's half stayed stranded in
the node process's ring buffer.  This module is the driver-side meeting
point: node span trees travel driver-ward two ways —

- **piggybacked** on the reply of the very call they describe (npwire
  spans flag / npproto field 16; the transports ingest them
  automatically, service/client.py + service/tcp.py), and
- **pulled** via the enriched GetLoad lane
  (:func:`..service.client.get_node_traces`), for spans whose reply
  never arrived — the forensics case.

Ingested trees land in a bounded per-trace store; :func:`merged` (one
trace) and :func:`merge_all` (everything, for incident bundles) line
them up against the driver's own completed root spans
(:func:`.spans.recent_traces`) by trace id, turning "the call took
9 ms" into the end-to-end decomposition — driver encode → call → node
decode/queue/compute/encode → driver decode — with no clock-sync
assumption beyond per-process monotonic durations.

Thread-safe; bounded BOTH ways (``PFTPU_REUNION_CAP`` trace ids,
default 128, oldest evicted; at most ``_BUCKET_CAP`` trees per trace,
duplicates dropped by content) because this is always-on plumbing, not
a profiler — in particular the GetLoad pull lane re-delivers the same
node trees on every poll, and re-ingesting them must be a no-op.
"""

from __future__ import annotations

import json as _json
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from . import spans as _spans

__all__ = ["ingest", "remote_traces", "merged", "merge_all", "clear"]

_CAP = int(os.environ.get("PFTPU_REUNION_CAP", "128"))
#: Max distinct trees retained per trace id (a trace is one logical
#: call: a handful of trees from retries/multiple nodes, never hundreds).
_BUCKET_CAP = 32
# trace_id hex -> list of remote span trees (dicts, .spans.Span.to_dict
# shape).  OrderedDict for cheap oldest-first eviction.
_remote: "OrderedDict[str, List[dict]]" = OrderedDict()
# trace_id hex -> canonical-JSON keys of the trees already stored (the
# pull lane re-delivers identical trees every poll; see module docstring).
_seen_keys: Dict[str, set] = {}
_lock = threading.Lock()


def ingest(trees: Sequence[dict], *, source: str = "node") -> int:
    """Store remote span trees, keyed by their ``trace_id``; returns how
    many NEW trees were kept.  Trees without a trace id (or malformed
    entries) are dropped silently — an instrumentation lane must never
    make the RPC that carried it fail — and a tree already stored for
    its trace (byte-identical content, e.g. a GetLoad re-poll) is
    deduplicated.  ``source`` annotates each tree."""
    if not _spans.enabled():
        return 0
    kept = 0
    with _lock:
        for tree in trees:
            if not isinstance(tree, dict):
                continue
            tid = tree.get("trace_id")
            if not isinstance(tid, str) or not tid:
                continue
            try:
                key = _json.dumps(tree, sort_keys=True, default=str)
            except (TypeError, ValueError):
                continue  # unserializable sidecar: drop, never raise
            tree = dict(tree)
            tree.setdefault("source", source)
            bucket = _remote.get(tid)
            if bucket is None:
                while len(_remote) >= _CAP:
                    old_tid, _ = _remote.popitem(last=False)
                    _seen_keys.pop(old_tid, None)
                _remote[tid] = bucket = []
                _seen_keys[tid] = set()
            else:
                _remote.move_to_end(tid)
            keys = _seen_keys[tid]
            if key in keys or len(bucket) >= _BUCKET_CAP:
                continue
            keys.add(key)
            bucket.append(tree)
            kept += 1
    return kept


def remote_traces(trace_id: Optional[str] = None) -> List[dict]:
    """Remote trees for one trace id (hex), or every stored tree."""
    with _lock:
        if trace_id is not None:
            return list(_remote.get(trace_id, ()))
        return [t for bucket in _remote.values() for t in bucket]


def merged(trace_id: str) -> dict:
    """One trace's reunion: ``{"trace_id", "driver": [...trees...],
    "remote": [...trees...]}`` — driver side from the local completed-
    root ring, remote side from the ingest store."""
    driver = [
        t for t in _spans.recent_traces() if t.get("trace_id") == trace_id
    ]
    return {
        "trace_id": trace_id,
        "driver": driver,
        "remote": remote_traces(trace_id),
    }


def merge_all() -> List[dict]:
    """Every trace id seen on either side, merged — the incident-bundle
    payload.  Ordered oldest-first by first appearance."""
    ids: "OrderedDict[str, None]" = OrderedDict()
    for t in _spans.recent_traces():
        tid = t.get("trace_id")
        if tid:
            ids.setdefault(tid, None)
    with _lock:
        for tid in _remote:
            ids.setdefault(tid, None)
    return [merged(tid) for tid in ids]


def clear() -> None:
    """Drop the remote-tree store (test isolation)."""
    with _lock:
        _remote.clear()
        _seen_keys.clear()
