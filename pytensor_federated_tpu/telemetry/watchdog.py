"""Armed-deadline hang watchdog + self-contained incident bundles.

The live-chip failure modes are SILENT (CLAUDE.md): a wedged PJRT
plugin blocks the first device query forever, compiled Mosaic can wedge
the tunnel relay, an HTTP/2 batch window can deadlock, and a psum whose
participant died just hangs until XLA aborts the process.  Every one of
those used to cost a capture window and a round of hand forensics with
``faulthandler.dump_traceback_later``.  This module makes the forensics
automatic:

- :func:`armed` — a context manager wrapping a known wedge point with a
  deadline.  If the body has not exited when the deadline passes, a
  monitor thread writes an **incident bundle** (below) and keeps going;
  the hang itself is untouched — safely interrupting a wedged PJRT call
  is not possible, but a silent hang becomes an artifact.
- :func:`write_incident_bundle` — one self-contained JSON file:
  all-thread tracebacks (the ``faulthandler.dump_traceback_later``
  readout, taken via ``sys._current_frames`` so it lands in structured
  JSON instead of stderr), the flight-recorder tail
  (:mod:`.flightrec`), the metrics+traces snapshot (:mod:`.export`),
  and the driver↔node merged call trees (:mod:`.reunion`).
  ``tools/incident_report.py`` renders a bundle as a markdown
  postmortem.

Arm points wired in this package (each env-tunable, ``0`` disables):

====================================  ==============================  =======
wedge point                           env knob                        default
====================================  ==============================  =======
gRPC/TCP pipelined batch windows      ``PFTPU_WATCHDOG_RPC_S``        300 s
backend/Pallas liveness probe         (probe timeout + margin)        —
elastic sampling segment (psum        ``PFTPU_WATCHDOG_SAMPLE_S``     off
rendezvous wedge)
bench measurement phase               ``PFTPU_WATCHDOG_BENCH_S``      off
====================================  ==============================  =======

One daemon monitor thread for the whole process, started lazily on the
first arm; arming costs a heap push + condition notify, disarming a
lazy-delete flag — invisible next to the ms-scale operations being
guarded.  The watchdog never arms while telemetry is disabled.
Bundle writes are rate-limited per arm-point name
(``PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S``, default 60): a deadline set
below a workload's legitimate wall, re-armed every batch, must not
fill the disk — throttled fires are still flight-recorded.
"""

from __future__ import annotations

import heapq
import itertools
import json
import logging
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from . import spans as _spans

__all__ = [
    "armed",
    "write_incident_bundle",
    "incident_dir",
    "last_incident_path",
    "rpc_timeout_s",
    "env_timeout_s",
    "thread_dump",
]

_log = logging.getLogger(__name__)


def incident_dir() -> str:
    """Where bundles land: ``$PFTPU_INCIDENT_DIR`` or
    ``<tmp>/pftpu-incidents`` (created on demand)."""
    path = os.environ.get("PFTPU_INCIDENT_DIR") or os.path.join(
        tempfile.gettempdir(), "pftpu-incidents"
    )
    os.makedirs(path, exist_ok=True)
    return path


def env_timeout_s(var: str, default: float) -> float:
    """THE env-knob parser for every watchdog deadline: float seconds,
    garbage or empty degrades to ``default`` (a misspelt knob must
    never crash the operation it guards — bench.py's one-JSON-line
    invariant depends on it)."""
    try:
        return float(os.environ.get(var, "") or default)
    except ValueError:
        return default


def rpc_timeout_s() -> float:
    """The batch-window arm deadline (``PFTPU_WATCHDOG_RPC_S``,
    default 300; ``0`` disables)."""
    return env_timeout_s("PFTPU_WATCHDOG_RPC_S", 300.0)


def thread_dump() -> List[dict]:
    """All-thread tracebacks as structured data — the
    ``faulthandler.dump_traceback_later`` readout, JSON-friendly."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(
            {
                "thread_id": ident,
                "name": names.get(ident, "?"),
                "stack": [
                    line.rstrip("\n")
                    for line in traceback.format_stack(frame)
                ],
            }
        )
    return out


_last_incident: Optional[str] = None
_last_lock = threading.Lock()
_bundle_seq = itertools.count(1)


def last_incident_path() -> Optional[str]:
    """Path of the most recent bundle this process wrote, or ``None``."""
    with _last_lock:
        return _last_incident


def write_incident_bundle(
    reason: str,
    *,
    attrs: Optional[Dict[str, Any]] = None,
    dir: Optional[str] = None,  # noqa: A002 - CLI-ish keyword
    flightrec_tail: int = 256,
) -> str:
    """Write one self-contained incident bundle; returns its path.

    Contents (one JSON object): ``reason``, ``ts``, ``pid``/``argv``,
    caller ``attrs``, ``threads`` (all-thread tracebacks),
    ``flightrec`` (last ``flightrec_tail`` events), ``telemetry``
    (metrics + recent span trees, :func:`.export.snapshot`), and
    ``trace_reunion`` (driver-side and node-side span trees merged per
    trace id, :func:`.reunion.merge_all`).  When a fault-injection plan
    is installed (:mod:`..faultinject`), a ``fault_plan`` section
    embeds its id, rules, and live fire counters, so a chaos-triggered
    bundle is self-describing — *what chaos did* sits next to *how the
    system reacted*.  When a :class:`~.collector.FleetCollector` is
    live, a ``fleet`` section embeds the latest sweep's staleness
    record and the clock-aligned cross-process incident timeline
    (:func:`.collector.bundle_sections`).  Everything is read
    best-effort: a half-wedged
    process must still get SOME bundle out, so each section degrades to
    an ``"error"`` string instead of aborting the write.
    """
    from . import export as _export
    from . import flightrec as _flightrec
    from . import reunion as _reunion

    def _fault_plan():
        from ..faultinject import runtime as _fi_runtime

        return _fi_runtime.snapshot()

    def _fleet():
        # The clock-aligned fleet picture, when a FleetCollector is
        # live in this process (None keeps single-process bundles
        # clean — same contract as fault_plan).
        from . import collector as _collector

        return _collector.bundle_sections()

    bundle: dict = {
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "attrs": dict(attrs or {}),
    }
    for key, build in (
        ("threads", thread_dump),
        ("flightrec", lambda: _flightrec.events(flightrec_tail)),
        ("telemetry", _export.snapshot),
        ("trace_reunion", _reunion.merge_all),
        ("fault_plan", _fault_plan),
        ("fleet", _fleet),
    ):
        try:
            value = build()
        except Exception as e:  # best-effort: never lose the bundle
            value = {"error": f"{type(e).__name__}: {e}"}
        if key in ("fault_plan", "fleet") and value is None:
            continue  # nothing live on that lane: keep bundles clean
        bundle[key] = value

    slug = "".join(c if c.isalnum() or c in "-_" else "-" for c in reason)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    # Per-process sequence number: two bundles in the same SECOND (e.g.
    # concurrent batch windows expiring together) must not clobber
    # each other.
    path = os.path.join(
        dir or incident_dir(),
        f"incident-{stamp}-{slug}-{os.getpid()}-{next(_bundle_seq)}.json",
    )
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(bundle, fh, default=str)
    global _last_incident
    with _last_lock:
        _last_incident = path
    _flightrec.record("incident.bundle", reason=reason, path=path)
    _log.warning("incident bundle written: %s (%s)", path, reason)
    return path


# -- the monitor ------------------------------------------------------------


class _Armed:
    """One armed deadline; also the context manager token."""

    __slots__ = ("name", "deadline", "attrs", "active", "fired", "bundle")

    def __init__(self, name: str, deadline: float, attrs: dict):
        self.name = name
        self.deadline = deadline
        self.attrs = attrs
        self.active = True  # lazy delete: disarm flips this
        self.fired = False
        self.bundle: Optional[str] = None

    def __enter__(self) -> "_Armed":
        return self

    def __exit__(self, *exc) -> None:
        disarm(self)


class _NoopArmed:
    __slots__ = ()
    name = None
    fired = False
    bundle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopArmed()

_mon_lock = threading.Lock()
_mon_cond = threading.Condition(_mon_lock)
_heap: List[tuple] = []  # (deadline, seq, _Armed)
_heap_seq = itertools.count()
_mon_thread: Optional[threading.Thread] = None
# name -> monotonic time of that arm point's last bundle write.  A
# repeatedly-firing arm point (a deadline set below a workload's
# legitimate wall, re-armed per batch) must not fill the disk with
# near-identical bundles or bury a real incident: within the gap the
# fire is still flight-recorded, only the bundle write is suppressed.
_last_bundle_at: Dict[str, float] = {}


def _bundle_gap_s() -> float:
    return env_timeout_s("PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S", 60.0)


def _monitor() -> None:
    with _mon_cond:
        while True:
            while _heap and (
                not _heap[0][2].active or _heap[0][0] <= time.monotonic()
            ):
                _, _, entry = heapq.heappop(_heap)
                if not entry.active:
                    continue  # lazily-deleted disarm
                entry.active = False
                entry.fired = True
                # Release the lock while writing: the bundle dump is
                # slow I/O and arm/disarm must not stall behind it.
                _mon_cond.release()
                try:
                    from . import flightrec as _flightrec

                    now = time.monotonic()
                    last = _last_bundle_at.get(entry.name)
                    throttled = (
                        last is not None and now - last < _bundle_gap_s()
                    )
                    if not throttled:
                        entry.bundle = write_incident_bundle(
                            f"watchdog:{entry.name}", attrs=entry.attrs
                        )
                        # Timestamp only a SUCCESSFUL write: a failed
                        # write (disk full, unwritable dir) must not
                        # throttle the next fire into writing nothing.
                        _last_bundle_at[entry.name] = now
                    _flightrec.record(
                        "watchdog.fired",
                        name=entry.name,
                        bundle=entry.bundle,
                        throttled=throttled,
                        attrs=dict(entry.attrs),
                    )
                    _log.warning(
                        "watchdog %r fired after its deadline — %s (the "
                        "wedged operation is still wedged; this thread "
                        "only reports)",
                        entry.name,
                        f"incident bundle at {entry.bundle}"
                        if entry.bundle
                        else "bundle write throttled "
                        "(PFTPU_WATCHDOG_MIN_BUNDLE_GAP_S)",
                    )
                except Exception:
                    _log.exception("watchdog bundle write failed")
                finally:
                    _mon_cond.acquire()
            if _heap:
                _mon_cond.wait(max(0.0, _heap[0][0] - time.monotonic()))
            else:
                _mon_cond.wait()


def arm(name: str, timeout_s: float, **attrs: Any):
    """Arm a deadline ``timeout_s`` from now; returns a token for
    :func:`disarm` (also a context manager).  ``timeout_s <= 0`` or
    telemetry disabled returns a shared no-op token."""
    if timeout_s is None or timeout_s <= 0 or not _spans.enabled():
        return _NOOP
    entry = _Armed(name, time.monotonic() + timeout_s, attrs)
    global _mon_thread
    with _mon_cond:
        if _mon_thread is None or not _mon_thread.is_alive():
            _mon_thread = threading.Thread(
                target=_monitor, name="pftpu-watchdog", daemon=True
            )
            _mon_thread.start()
        heapq.heappush(_heap, (entry.deadline, next(_heap_seq), entry))
        _mon_cond.notify()
    return entry


def disarm(token) -> None:
    """Cancel an armed deadline (idempotent; no-op token accepted)."""
    if isinstance(token, _Armed):
        token.active = False  # lazy delete; monitor skips it


def armed(name: str, timeout_s: Optional[float] = None, **attrs: Any):
    """Context manager form: ``with watchdog.armed("tcp.batch", 300):``.
    ``timeout_s=None`` uses the RPC default (:func:`rpc_timeout_s`);
    the yielded token's ``.fired``/``.bundle`` report what happened."""
    if timeout_s is None:
        timeout_s = rpc_timeout_s()
    return arm(name, timeout_s, **attrs)
