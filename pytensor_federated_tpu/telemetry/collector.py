"""Fleet collector: cross-process telemetry harvest, merge, timeline.

PRs 1-2 made one *process* observable; this module makes the FLEET
observable.  A :class:`FleetCollector` harvests full telemetry
snapshots from every replica over the existing lanes — the enriched
GetLoad request payload ``b"telemetry"`` on the npwire lane (declared
in :data:`..service.wire_registry.GETLOAD_PAYLOADS`, mirroring the
PR-2 ``b"traces"`` pull), or HTTP ``GET /snapshot`` against a
:class:`.export.MetricsExporter` for nodes without a GetLoad lane —
and merges them into one fleet view:

- **counters** are summed across replicas per label set,
- **histograms** merge bucket-wise (the shared fixed bucket ladder was
  designed for exactly this; mismatched ladders raise
  :class:`FleetMergeError` — loud, never a silently wrong quantile),
- **gauges** are kept per-replica under a ``replica`` label (summing
  instantaneous values across processes is meaningless).

A replica that dies mid-scrape is marked STALE — listed in
:attr:`FleetSnapshot.stale`, counted in
``pftpu_collector_replicas_stale``, flight-recorded as
``collector.replica_stale`` — and its numbers are EXCLUDED from the
merged view: a fleet aggregate is either complete or loudly partial,
never silently partial.

Clock alignment: every scrape estimates the replica's wall-clock
offset Cristian-style — the node stamps its clock into the snapshot
(``ts``, :func:`.export.snapshot`), the driver brackets the scrape
with its own clock, and the offset is taken against the RTT midpoint
(error bounded by ±RTT/2; on the loopback lanes this is tens of
microseconds, far below the millisecond-scale events being ordered).
:func:`FleetSnapshot.timeline` applies the offsets to every replica's
flight-record tail and interleaves them with the driver's own events
into ONE ordered incident timeline — embedded in incident bundles
(:func:`.watchdog.write_incident_bundle` pulls it from every live
collector via :func:`bundle_sections`) and rendered by
``tools/incident_report.py``.

The collector rides a replica pool when given one
(:class:`~..routing.pool.NodePool` — the live replica registry is
re-read every sweep, so replicas added/removed/failed-over mid-run
are followed), or a static target list otherwise.  ``start()`` runs
the sweep on a background daemon thread at ``interval_s``; each
snapshot is handed to the registered ``observers`` — the
:class:`.slo.BurnRateEngine` is the canonical one, making this the
signal bus a future autoscaler consumes (ROADMAP item 1).

Docs: docs/observability.md "Fleet plane".
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
import weakref
from collections import deque
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from . import flightrec as _flightrec
from . import metrics as _metrics

__all__ = [
    "FleetCollector",
    "FleetSnapshot",
    "ReplicaScrape",
    "FleetMergeError",
    "merge_metric_snapshots",
    "merged_quantile",
    "fleet_timeline",
    "bundle_sections",
    "LOCAL_REPLICA",
]

_log = logging.getLogger(__name__)

#: The pseudo-replica address of the collector's own process (the
#: driver): its registry and flight record join the fleet view with a
#: clock offset of exactly zero.
LOCAL_REPLICA = "driver"

_SCRAPES = _metrics.counter(
    "pftpu_collector_scrapes_total",
    "Fleet-collector replica scrapes, by outcome",
    ("outcome",),
)
_SCRAPE_S = _metrics.histogram(
    "pftpu_collector_scrape_seconds",
    "Per-replica fleet-collector scrape round-trip latency",
)
_STALE = _metrics.gauge(
    "pftpu_collector_replicas_stale",
    "Replicas whose last fleet scrape failed (stale in the fleet view)",
)
_CLOCK_OFFSET = _metrics.gauge(
    "pftpu_collector_clock_offset_seconds",
    "Estimated replica wall-clock offset vs this driver (Cristian-style,"
    " RTT-midpoint)",
    ("replica",),
)


class FleetMergeError(RuntimeError):
    """Per-replica snapshots disagree in a way a merge must not paper
    over: same family name with different instrument types, or
    histograms with different bucket ladders."""


# -- merge ------------------------------------------------------------------


def _merge_histogram_children(
    children: Dict[Tuple[Tuple[str, str], ...], dict],
    labels: Dict[str, str],
    child: Mapping[str, Any],
    name: str,
    replica: str,
) -> None:
    key = tuple(sorted(labels.items()))
    buckets = dict(child.get("buckets") or {})
    agg = children.get(key)
    if agg is None:
        children[key] = {
            "labels": dict(labels),
            "count": int(child.get("count", 0)),
            "sum": float(child.get("sum", 0.0)),
            "buckets": {str(k): int(v) for k, v in buckets.items()},
        }
        return
    if set(agg["buckets"]) != set(str(k) for k in buckets):
        raise FleetMergeError(
            f"histogram {name!r}: replica {replica} uses bucket ladder "
            f"{sorted(buckets)} but the fleet ladder is "
            f"{sorted(agg['buckets'])} — refusing a bucket-wise merge "
            "of incompatible ladders"
        )
    agg["count"] += int(child.get("count", 0))
    agg["sum"] += float(child.get("sum", 0.0))
    for bound, n in buckets.items():
        agg["buckets"][str(bound)] += int(n)


def merge_metric_snapshots(
    per_replica: Mapping[str, Mapping[str, Any]],
) -> Dict[str, Any]:
    """Merge per-replica ``metrics.snapshot()`` maps into one fleet
    map, same shape as a single-registry snapshot.

    Merge semantics (module docstring): counters summed per label set,
    histograms merged bucket-wise (count/sum/bucket counts added;
    exemplars are per-process and dropped), gauges kept per replica
    under an added ``replica`` label.  A gauge that ALREADY carries a
    ``replica`` label (a scraped driver's pool gauges) keeps it and
    the scrape source goes under ``source`` instead — two processes'
    views of the same pool stay distinguishable.

    Raises :class:`FleetMergeError` on type or bucket-ladder conflicts
    — the merge is exact or it is refused; it never averages its way
    past a disagreement.  The merge is pure (inputs untouched), so the
    property test can compare it bit-for-bit against observing the
    union in one registry.
    """
    merged: Dict[str, Any] = {}
    # name -> (kind, help, children-accumulator)
    hist_children: Dict[str, Dict[Tuple[Tuple[str, str], ...], dict]] = {}
    counter_children: Dict[str, Dict[Tuple[Tuple[str, str], ...], dict]] = {}
    for replica in sorted(per_replica):
        snap = per_replica[replica]
        if not isinstance(snap, Mapping):
            raise FleetMergeError(
                f"replica {replica}: metrics snapshot is "
                f"{type(snap).__name__}, not a mapping"
            )
        for name, entry in snap.items():
            kind = entry.get("type", "untyped")
            known = merged.get(name)
            if known is None:
                merged[name] = {
                    "type": kind,
                    "help": entry.get("help", ""),
                    "children": [],
                }
            elif known["type"] != kind:
                raise FleetMergeError(
                    f"metric {name!r}: replica {replica} reports type "
                    f"{kind!r} but the fleet view already holds "
                    f"{known['type']!r}"
                )
            for child in entry.get("children", ()):
                labels = dict(child.get("labels") or {})
                if kind == "histogram":
                    _merge_histogram_children(
                        hist_children.setdefault(name, {}),
                        labels, child, name, replica,
                    )
                elif kind == "counter":
                    key = tuple(sorted(labels.items()))
                    acc = counter_children.setdefault(name, {})
                    agg = acc.get(key)
                    if agg is None:
                        acc[key] = {
                            "labels": labels,
                            "value": float(child.get("value", 0.0)),
                        }
                    else:
                        agg["value"] += float(child.get("value", 0.0))
                else:  # gauge (and anything untyped): per-replica
                    if "replica" in labels:
                        labels = {**labels, "source": replica}
                    else:
                        labels = {**labels, "replica": replica}
                    merged[name]["children"].append(
                        {"labels": labels, "value": child.get("value")}
                    )
    for name, acc in counter_children.items():
        merged[name]["children"].extend(
            acc[k] for k in sorted(acc)
        )
    for name, acc in hist_children.items():
        merged[name]["children"].extend(
            acc[k] for k in sorted(acc)
        )
    return merged


def merged_quantile(
    family: Optional[Mapping[str, Any]], q: float
) -> float:
    """Quantile estimate over ALL children of one merged histogram
    family (upper bucket bound containing the q-th observation — the
    same estimate :meth:`..telemetry.metrics.Histogram.approx_quantile`
    makes in-process).  ``nan`` for an absent/empty family."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    buckets: Dict[float, int] = {}
    total = 0
    for child in (family or {}).get("children", ()):
        for bound, n in (child.get("buckets") or {}).items():
            b = float(bound)
            buckets[b] = buckets.get(b, 0) + int(n)
        total += int(child.get("count", 0))
    if total == 0:
        return float("nan")
    rank = q * total
    seen = 0
    for bound in sorted(buckets):
        seen += buckets[bound]
        if seen >= rank and buckets[bound]:
            return bound
    return float("inf")


# -- scrape results ---------------------------------------------------------


class ReplicaScrape:
    """One replica's scrape outcome (fresh or stale)."""

    __slots__ = (
        "address", "lane", "ok", "error", "ts", "rtt_s",
        "clock_offset_s", "metrics", "traces", "flightrec", "load",
    )

    def __init__(self, address: str, lane: str):
        self.address = address
        self.lane = lane
        self.ok = False
        self.error: Optional[str] = None
        self.ts: Optional[float] = None
        self.rtt_s: Optional[float] = None
        self.clock_offset_s: Optional[float] = None
        self.metrics: Optional[dict] = None
        self.traces: List[dict] = []
        self.flightrec: List[dict] = []
        self.load: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "address": self.address,
            "lane": self.lane,
            "ok": self.ok,
            "error": self.error,
            "ts": self.ts,
            "rtt_s": self.rtt_s,
            "clock_offset_s": self.clock_offset_s,
            "metrics": self.metrics,
            "traces": self.traces,
            "flightrec": self.flightrec,
            "load": self.load,
        }


class FleetSnapshot:
    """One sweep's fleet view: per-replica scrapes + the merged
    registry + the loud-staleness record."""

    __slots__ = ("ts", "replicas", "merged", "stale", "unscraped")

    def __init__(
        self,
        ts: float,
        replicas: Dict[str, ReplicaScrape],
        merged: dict,
        stale: List[str],
        unscraped: List[str],
    ):
        self.ts = ts
        self.replicas = replicas
        self.merged = merged
        self.stale = stale
        self.unscraped = unscraped

    @property
    def complete(self) -> bool:
        """True when every registered replica answered this sweep."""
        return not self.stale and not self.unscraped

    def timeline(self, *, tail: Optional[int] = None) -> List[dict]:
        """The clock-aligned fleet timeline (:func:`fleet_timeline`)."""
        return fleet_timeline(self, tail=tail)

    def to_dict(self) -> dict:
        return {
            "ts": self.ts,
            "complete": self.complete,
            "stale": list(self.stale),
            "unscraped": list(self.unscraped),
            "merged": self.merged,
            "replicas": {
                a: r.to_dict() for a, r in self.replicas.items()
            },
        }


def fleet_timeline(
    snapshot: FleetSnapshot, *, tail: Optional[int] = None
) -> List[dict]:
    """Interleave every replica's flight-record tail into one ordered
    incident timeline.

    Each event gains ``replica`` (who recorded it) and ``ts_fleet``
    (its timestamp shifted onto the DRIVER's clock by the replica's
    estimated offset — alignment error is bounded by ±RTT/2 of the
    scrape that estimated it).  Events from the driver's own record
    (:data:`LOCAL_REPLICA`) carry offset zero by construction.
    ``tail`` keeps only the newest ``tail`` events after the merge.
    """
    out: List[dict] = []
    for addr, scrape in snapshot.replicas.items():
        offset = scrape.clock_offset_s or 0.0
        for ev in scrape.flightrec:
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            out.append(
                {**ev, "replica": addr, "ts_fleet": ts - offset}
            )
    out.sort(key=lambda e: e["ts_fleet"])
    if tail is not None:
        out = out[-tail:]
    return out


# -- the collector ----------------------------------------------------------

TargetSpec = Union[str, Tuple[str, int]]


def _as_addr(target: TargetSpec) -> Tuple[str, int]:
    if isinstance(target, str):
        host, _, port = target.rpartition(":")
        return host or "127.0.0.1", int(port)
    host, port = target
    return str(host), int(port)


def _scrape_http(host: str, port: int, timeout_s: float) -> dict:
    url = f"http://{host}:{port}/snapshot"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = resp.read()
    payload = json.loads(body)
    if not isinstance(payload, dict) or "metrics" not in payload:
        raise ValueError(f"{url} returned no metrics map")
    return payload


# Live started collectors, so incident bundles can embed the fleet
# picture without anyone threading a handle through the call stack.
_active: "weakref.WeakSet[FleetCollector]" = weakref.WeakSet()


class FleetCollector:
    """Harvest + merge the fleet's telemetry (module docstring).

    ``targets``: ``host:port`` strings or ``(host, port)`` pairs
    scraped over the GetLoad ``b"telemetry"`` lane.  ``http_targets``:
    the same shapes scraped over ``GET /snapshot`` (the fallback lane
    for nodes that expose a :class:`.export.MetricsExporter` instead
    of a gRPC GetLoad — TCP/shm template nodes), OR a mapping
    ``{serving_addr: exporter_target}`` — the exporter is scraped but
    the result is recorded under the replica's SERVING address, which
    is how a tcp/shm pool replica (whose exporter is necessarily a
    different socket) joins the fleet view under its own name instead
    of being listed unscraped.  ``pool``: a
    :class:`~..routing.pool.NodePool` whose live registry is re-read
    every sweep — grpc replicas ride the GetLoad lane; replicas of
    other transports are reported in :attr:`FleetSnapshot.unscraped`
    unless the mapping form of ``http_targets`` names them (the
    TCP/shm protocols have no telemetry reply lane).  The alias
    registry is LIVE: :meth:`add_http_target` /
    :meth:`remove_http_target` register and drop exporter mappings at
    runtime (the gateway autoscaler calls them as replicas spawn and
    drain), and with a ``pool`` attached each sweep garbage-collects
    aliases whose serving address has left the pool registry — a
    departed replica must never linger as a stale scrape target
    (ISSUE 12).  ``include_local`` folds this
    process's own registry and flight record in as the
    :data:`LOCAL_REPLICA` pseudo-replica (offset zero) so driver-side
    client/pool families and node families meet in one view.

    ``observers``: callables receiving each :class:`FleetSnapshot`
    (the SLO engine's ``observe``); an observer raising is logged and
    never stops the sweep.  ``start()``/``stop()`` run the sweep on a
    background daemon thread at ``interval_s`` (the pool-probe
    cadence posture); ``scrape_once()`` is the synchronous sweep.
    """

    def __init__(
        self,
        targets: Sequence[TargetSpec] = (),
        *,
        http_targets: Union[
            Sequence[TargetSpec], Mapping[str, TargetSpec]
        ] = (),
        pool: Optional[Any] = None,
        interval_s: float = 2.0,
        timeout_s: float = 2.0,
        include_local: bool = True,
        flightrec_tail: int = 128,
        history: int = 64,
        observers: Iterable[Callable[["FleetSnapshot"], Any]] = (),
    ):
        self._targets = [_as_addr(t) for t in targets]
        if isinstance(http_targets, Mapping):
            self._http_targets: List[Tuple[str, int]] = []
            self._http_aliases = {
                str(addr): _as_addr(t)
                for addr, t in http_targets.items()
            }
        else:
            self._http_targets = [_as_addr(t) for t in http_targets]
            self._http_aliases = {}
        self.pool = pool
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.include_local = bool(include_local)
        self.flightrec_tail = int(flightrec_tail)
        self.observers: List[Callable[["FleetSnapshot"], Any]] = list(
            observers
        )
        self.history: Deque[FleetSnapshot] = deque(maxlen=int(history))
        self._lock = threading.Lock()
        # Aliases registered at RUNTIME (add_http_target) follow pool
        # membership and are GC'd when their replica departs;
        # constructor-passed aliases are static configuration and are
        # never GC'd (they may name non-pool exporters).
        self._dynamic_aliases: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Addresses whose clock-offset gauge child this collector set
        # last sweep — so replicas that die or leave the pool get
        # their child REMOVED instead of exporting a stale offset
        # forever (and churn can't grow the label set without bound).
        self._offset_replicas: set = set()

    # -- target registry --------------------------------------------------

    def add_http_target(
        self, record_as: str, target: TargetSpec
    ) -> None:
        """Register (or re-point) an exporter alias at runtime: the
        exporter at ``target`` is scraped and recorded under the
        replica's serving address ``record_as`` — the hook the gateway
        autoscaler calls when it spawns a tcp/shm replica, so the
        fleet view follows scale-up without a collector restart."""
        with self._lock:
            self._http_aliases[str(record_as)] = _as_addr(target)
            self._dynamic_aliases.add(str(record_as))

    def remove_http_target(self, record_as: str) -> None:
        """Drop an exporter alias (idempotent) — scale-down's half of
        :meth:`add_http_target`: a drained replica stops being scraped
        on the next sweep instead of lingering as a stale target."""
        with self._lock:
            self._http_aliases.pop(str(record_as), None)
            self._dynamic_aliases.discard(str(record_as))

    def _sweep_targets(
        self,
    ) -> Tuple[List[Tuple[str, int, str, str]], List[str]]:
        """-> ([(host, port, lane, record_as)], [unscrapable pool
        addresses]).  ``record_as`` is the fleet-view address the
        scrape lands under — the scraped socket itself except for
        ``http_targets`` aliases, where a replica's exporter is
        scraped but recorded under its serving address."""
        seen: set = set()
        out: List[Tuple[str, int, str, str]] = []
        unscraped: List[str] = []
        for host, port in self._targets:
            if f"{host}:{port}" not in seen:
                seen.add(f"{host}:{port}")
                out.append((host, port, "grpc", f"{host}:{port}"))
        for host, port in self._http_targets:
            if f"{host}:{port}" not in seen:
                seen.add(f"{host}:{port}")
                out.append((host, port, "http", f"{host}:{port}"))
        with self._lock:
            aliases = dict(self._http_aliases)
            dynamic = set(self._dynamic_aliases)
        if self.pool is not None and dynamic:
            # Runtime-registered aliases (add_http_target) follow the
            # live pool registry: a DYNAMIC alias whose serving
            # address has left the pool is a departed autoscaled
            # replica — GC it so churn can neither scrape ghosts nor
            # grow the alias map without bound.  Static (constructor)
            # aliases are configuration and are never GC'd.  The
            # membership re-check and the pop happen under ONE lock
            # hold (with the registry re-read inside it), so a
            # replica re-spawned on the same address — whose
            # add_replica happens-before its add_http_target — can
            # never have its fresh registration collected: either the
            # re-read sees the replica, or the registration lands
            # after the pop and survives.
            live = {r.address for r in self.pool.replicas}
            for record_as in list(aliases):
                if record_as not in dynamic or record_as in live:
                    continue
                removed = False
                with self._lock:
                    if record_as in self._dynamic_aliases and (
                        record_as
                        not in {r.address for r in self.pool.replicas}
                    ):
                        self._http_aliases.pop(record_as, None)
                        self._dynamic_aliases.discard(record_as)
                        removed = True
                if removed:
                    del aliases[record_as]
                    _flightrec.record(
                        "collector.target_gc", replica=record_as
                    )
        for record_as, (host, port) in aliases.items():
            if record_as not in seen:
                seen.add(record_as)
                out.append((host, port, "http", record_as))
        if self.pool is not None:
            for replica in self.pool.replicas:
                if replica.address in seen:
                    continue
                seen.add(replica.address)
                if replica.transport == "grpc":
                    out.append(
                        (
                            replica.host, replica.port, "grpc",
                            replica.address,
                        )
                    )
                else:
                    # No telemetry reply lane on the tcp/shm wire: the
                    # replica is VISIBLY absent from the fleet view,
                    # not silently missing (map its exporter in
                    # http_targets={addr: (host, port)} to include it
                    # under this serving address).
                    unscraped.append(replica.address)
        return out, unscraped

    # -- scraping ---------------------------------------------------------

    def _ingest(
        self,
        scrape: ReplicaScrape,
        telemetry: dict,
        load: Optional[dict],
        t0_wall: float,
        t1_wall: float,
        rtt_s: float,
    ) -> None:
        scrape.rtt_s = rtt_s
        scrape.ok = True
        scrape.load = load
        scrape.metrics = telemetry.get("metrics") or {}
        traces = telemetry.get("traces")
        scrape.traces = traces if isinstance(traces, list) else []
        events = telemetry.get("flightrec")
        scrape.flightrec = events if isinstance(events, list) else []
        node_ts = telemetry.get("ts")
        if isinstance(node_ts, (int, float)):
            scrape.ts = float(node_ts)
            # Cristian: the node stamped its clock somewhere inside
            # [t0, t1] of our request; the midpoint is the minimum-
            # error estimate, off by at most ±RTT/2.
            scrape.clock_offset_s = scrape.ts - (t0_wall + t1_wall) / 2.0

    async def _scrape_one_async(
        self, host: str, port: int, lane: str, record_as: str
    ) -> ReplicaScrape:
        """One replica scrape (grpc GetLoad lane inline on the sweep
        loop; http lane handed to the executor so a slow exporter
        cannot serialize the sweep).  Never raises: a dead replica
        returns ``ok=False`` with the error string — the loud-stale
        verdict, not an exception tearing down the sweep."""
        import asyncio

        scrape = ReplicaScrape(record_as, lane)
        t0_wall = time.time()
        t0 = time.perf_counter()
        try:
            if lane == "http":
                loop = asyncio.get_running_loop()
                telemetry: Optional[dict] = await asyncio.wait_for(
                    loop.run_in_executor(
                        None, _scrape_http, host, port, self.timeout_s
                    ),
                    timeout=self.timeout_s + 1.0,
                )
                load = None
            else:
                from ..service.client import get_node_telemetry_async

                load = await get_node_telemetry_async(
                    host, port, timeout=self.timeout_s
                )
                telemetry = None if load is None else load["telemetry"]
                if load is not None:
                    # The telemetry payload already lands on the
                    # scrape's own fields; keeping it inside .load too
                    # would hold (and serialize) every replica's full
                    # snapshot twice across the whole history ring.
                    load = {
                        k: v for k, v in load.items() if k != "telemetry"
                    }
            if telemetry is None:
                raise ConnectionError(
                    "no telemetry reply (unreachable, npproto-wire, or "
                    "pre-telemetry node)"
                )
        except Exception as e:
            scrape.error = f"{type(e).__name__}: {e}"
            return scrape
        self._ingest(
            scrape, telemetry, load,
            t0_wall, time.time(), time.perf_counter() - t0,
        )
        return scrape

    def _local_scrape(self) -> ReplicaScrape:
        from . import export as _export

        scrape = ReplicaScrape(LOCAL_REPLICA, "local")
        snap = _export.snapshot()
        scrape.ok = True
        scrape.ts = snap["ts"]
        scrape.rtt_s = 0.0
        scrape.clock_offset_s = 0.0
        scrape.metrics = snap["metrics"]
        scrape.traces = snap["traces"]
        scrape.flightrec = _flightrec.events(self.flightrec_tail)
        return scrape

    def scrape_once(self) -> FleetSnapshot:
        """One concurrent sweep over the live target registry; returns
        the fleet snapshot (also appended to :attr:`history` and
        handed to every observer).  Dead replicas are marked stale —
        loudly — and excluded from the merged view; the sweep itself
        is bounded by ``timeout_s`` per replica and never hangs on a
        dying peer."""
        targets, unscraped = self._sweep_targets()
        t0 = time.perf_counter()
        replicas: Dict[str, ReplicaScrape] = {}
        if targets:
            import asyncio

            from ..utils import get_event_loop

            async def sweep() -> List[ReplicaScrape]:
                return list(
                    await asyncio.gather(
                        *(
                            self._scrape_one_async(
                                host, port, lane, record_as
                            )
                            for host, port, lane, record_as in targets
                        )
                    )
                )

            # One cached loop per calling thread (the repo's grpc.aio
            # convention — channels are loop-bound, and a fresh loop
            # per sweep thrashes the shared poller; same posture as
            # NodePool.probe_once).
            for scrape in get_event_loop().run_until_complete(sweep()):
                replicas[scrape.address] = scrape
        if self.include_local:
            replicas[LOCAL_REPLICA] = self._local_scrape()
        stale = sorted(
            a for a, s in replicas.items() if not s.ok
        )
        for addr in stale:
            _SCRAPES.labels(outcome="error").inc()
            _flightrec.record(
                "collector.replica_stale",
                replica=addr,
                error=replicas[addr].error,
            )
        offset_addrs: set = set()
        for addr, scrape in replicas.items():
            if not scrape.ok:
                continue
            if scrape.lane != "local":
                _SCRAPES.labels(outcome="ok").inc()
                if scrape.rtt_s is not None:
                    _SCRAPE_S.observe(scrape.rtt_s)
            if scrape.clock_offset_s is not None:
                _CLOCK_OFFSET.labels(replica=addr).set(
                    scrape.clock_offset_s
                )
                offset_addrs.add(addr)
        for addr in self._offset_replicas - offset_addrs:
            _CLOCK_OFFSET.remove(replica=addr)
        self._offset_replicas = offset_addrs
        _STALE.set(len(stale))
        merged = merge_metric_snapshots(
            {a: s.metrics for a, s in replicas.items() if s.ok}
        )
        snapshot = FleetSnapshot(
            ts=time.time(),
            replicas=replicas,
            merged=merged,
            stale=stale,
            unscraped=sorted(unscraped),
        )
        _flightrec.record(
            "collector.scrape",
            n_ok=len(replicas) - len(stale),
            n_stale=len(stale),
            n_unscraped=len(unscraped),
            wall_s=round(time.perf_counter() - t0, 6),
        )
        with self._lock:
            self.history.append(snapshot)
        for observer in self.observers:
            try:
                observer(snapshot)
            except Exception:
                _log.exception("fleet-snapshot observer failed")
        return snapshot

    def latest(self) -> Optional[FleetSnapshot]:
        """The newest snapshot, or ``None`` before the first sweep."""
        with self._lock:
            return self.history[-1] if self.history else None

    # -- background sweep -------------------------------------------------

    def start(self) -> "FleetCollector":
        """Start the background sweep loop (idempotent); returns self."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name="pftpu-fleet-collector",
                daemon=True,
            )
            self._thread.start()
        _active.add(self)
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # one bad sweep must never kill the loop
                _log.exception("fleet scrape sweep failed")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self.timeout_s + 5.0)
            self._thread = None
        _active.discard(self)

    def __enter__(self) -> "FleetCollector":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def bundle_sections(*, timeline_tail: int = 256) -> Optional[list]:
    """The fleet picture for an incident bundle: a LIST with one entry
    per live collector — the latest snapshot's staleness record plus
    the clock-aligned timeline tail.  ``None`` when no collector is
    running (ordinary single-process bundles stay clean) — mirror of
    the fault_plan section's contract in
    :func:`.watchdog.write_incident_bundle`.  Always a list, even for
    a lone collector, so bundle consumers never shape-switch."""
    sections = []
    for collector in list(_active):
        snapshot = collector.latest()
        if snapshot is None:
            continue
        sections.append(
            {
                "ts": snapshot.ts,
                "complete": snapshot.complete,
                "stale": snapshot.stale,
                "unscraped": snapshot.unscraped,
                "replicas": {
                    a: {
                        "ok": s.ok,
                        "error": s.error,
                        "rtt_s": s.rtt_s,
                        "clock_offset_s": s.clock_offset_s,
                    }
                    for a, s in snapshot.replicas.items()
                },
                "timeline": snapshot.timeline(tail=timeline_tail),
            }
        )
    return sections or None
