"""Declarative SLOs + a multi-window burn-rate engine over fleet
snapshots.

An :class:`Slo` declares what the fleet owes its callers — a goodput
floor, a p99 latency line, a shed-fraction budget — and a
:class:`BurnRateEngine` turns successive
:class:`~.collector.FleetSnapshot` sweeps into the classic
multi-window **burn rate**: how fast the fleet is consuming its error
budget, per look-back window.  Burn 1.0 = consuming budget exactly as
fast as the SLO allows; > 1 = on course to blow it (page); the short
window catches a cliff in seconds while the long window rides out
blips — the standard SRE alerting shape, computed here from the SAME
merged registries the rest of the fleet plane uses.

Objective semantics (per window, from counter/histogram DELTAS):

- ``p99_s``: budget = 1% of calls may exceed the line (that is what
  p99 *means*); burn = (fraction of the window's observations above
  the line) / 0.01, read bucket-wise from the latency histogram — so
  the line should sit on a bucket boundary of the shared ladder
  (:data:`~.metrics.DEFAULT_LATENCY_BUCKETS`) or it is rounded DOWN to
  one (conservative: the straddling bucket's calls all count against
  the budget).
- ``shed_frac_max``: burn = (shed fraction of the window) / budget.
- ``goodput_min``: a floor, not a ratio of bad events — burn =
  floor / observed goodput (capped; an idle fleet with zero traffic
  reports no goodput burn rather than a false page).

Window burn = max over declared objectives; engine burn = max over
windows.  Deltas are computed PER REPLICA between the two snapshots
bounding each window and only for replicas fresh in both — a replica
dying mid-window (or a counter reset on restart) can therefore never
produce a negative delta or a torn aggregate; it simply stops
contributing, while the collector's staleness marking keeps its death
loud.

Every ``observe()`` updates ``pftpu_slo_burn_rate`` (gauge, per
window) and flight-records ``slo.burn`` whenever any window burns
above 1 — the signal bus a future autoscaler consumes (ROADMAP
item 1).  Docs: docs/observability.md "Fleet plane".
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
from collections import deque
from typing import Any, Deque, Dict, Mapping, Optional, Sequence, Tuple

from . import flightrec as _flightrec
from . import metrics as _metrics

__all__ = ["Slo", "BurnRateEngine"]

_BURN = _metrics.gauge(
    "pftpu_slo_burn_rate",
    "SLO error-budget burn rate (max over objectives), per window",
    ("window",),
)

#: Burn values are capped here: a zero-goodput window against a floor
#: objective is "infinitely" bad, but an actual inf poisons JSON
#: artifacts and chart axes alike.
_BURN_CAP = 1000.0

_EVALUATE_METHODS = (
    "evaluate",
    "evaluate_stream",
    "evaluate_batch",
    "evaluate_reduce",
)


@dataclasses.dataclass(frozen=True)
class Slo:
    """One service-level objective set (module docstring).

    ``latency_metric`` defaults to the driver-observed per-attempt
    histogram (``pftpu_client_call_seconds`` — end-to-end, the number
    callers feel; the collector's ``include_local`` pseudo-replica is
    what brings it into the fleet view).  ``requests_metric`` /
    ``sheds_metric`` / ``errors_metric`` default to the node-side
    families every serving lane shares, so goodput and shed fractions
    aggregate across the whole fleet regardless of transport."""

    name: str = "default"
    goodput_min: Optional[float] = None
    p99_s: Optional[float] = None
    shed_frac_max: Optional[float] = None
    latency_metric: str = "pftpu_client_call_seconds"
    requests_metric: str = "pftpu_server_requests_total"
    sheds_metric: str = "pftpu_admission_shed_total"
    errors_metric: str = "pftpu_server_errors_total"
    #: The partition lane's shard-granular counters (ISSUE 13) —
    #: ``{outcome=ok|error}`` items served into reduce windows and
    #: reassemblies.  They refine the error clamp below: shard errors
    #: are request-granular evidence, so a replica that answered few
    #: (or zero) FRAMES but refused shards must not fold to zero
    #: errors and read healthy.
    partition_metric: str = "pftpu_partition_shards_total"

    def __post_init__(self) -> None:
        if (
            self.goodput_min is None
            and self.p99_s is None
            and self.shed_frac_max is None
        ):
            raise ValueError(
                "an Slo needs at least one objective (goodput_min, "
                "p99_s, or shed_frac_max)"
            )


# One replica's extracted sample: counters + a flattened histogram.
_Hist = Tuple[int, Dict[float, int]]  # (count, {bound: n})


def _counter_total(
    metrics_map: Mapping[str, Any],
    name: str,
    label: Optional[str] = None,
    allowed: Optional[Sequence[str]] = None,
) -> float:
    fam = metrics_map.get(name) or {}
    total = 0.0
    for child in fam.get("children", ()):
        if label is not None and allowed is not None:
            if (child.get("labels") or {}).get(label) not in allowed:
                continue
        v = child.get("value")
        if isinstance(v, (int, float)):
            total += v
    return total


def _hist_flat(metrics_map: Mapping[str, Any], name: str) -> _Hist:
    fam = metrics_map.get(name) or {}
    count = 0
    buckets: Dict[float, int] = {}
    for child in fam.get("children", ()):
        count += int(child.get("count", 0))
        for bound, n in (child.get("buckets") or {}).items():
            b = float(bound)
            buckets[b] = buckets.get(b, 0) + int(n)
    return count, buckets


def _hist_delta(new: _Hist, old: _Hist) -> _Hist:
    count = new[0] - old[0]
    if count < 0:  # reset: the restarted process's whole history counts
        return new
    buckets = {
        b: max(0, n - old[1].get(b, 0)) for b, n in new[1].items()
    }
    return count, buckets


def _frac_over(hist: _Hist, threshold_s: float) -> Optional[float]:
    """Fraction of the histogram's observations above ``threshold_s``,
    bucket-wise.  A threshold sitting exactly on a bucket bound counts
    that bucket as good; a threshold INSIDE a bucket counts the whole
    straddling bucket against the budget (conservative — borderline
    calls can only hurt, never help).  Observations beyond the last
    bound are the count minus the bucket sum."""
    count, buckets = hist
    if count <= 0:
        return None
    bounds = sorted(buckets)
    idx = bisect.bisect_left(bounds, threshold_s)
    if idx < len(bounds) and bounds[idx] == threshold_s:
        idx += 1
    good = sum(buckets[b] for b in bounds[:idx])
    return max(0, count - good) / count


class BurnRateEngine:
    """Fold successive fleet snapshots into per-window burn rates
    (module docstring).  Thread-safe; wire it to a collector as an
    observer — ``FleetCollector(..., observers=[engine.observe])`` —
    or call :meth:`observe` by hand between sweeps."""

    def __init__(
        self,
        slo: Slo,
        *,
        windows_s: Sequence[float] = (60.0, 300.0),
        max_samples: int = 512,
    ):
        if not windows_s:
            raise ValueError("need at least one look-back window")
        self.slo = slo
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self._samples: Deque[dict] = deque(maxlen=int(max_samples))
        self._lock = threading.Lock()
        self._last_report: Optional[dict] = None

    # -- sampling ---------------------------------------------------------

    def _extract(self, snapshot: Any) -> dict:
        """Per-FRESH-replica counter/histogram values of one sweep."""
        per_replica: Dict[str, dict] = {}
        for addr, scrape in snapshot.replicas.items():
            if not scrape.ok or scrape.metrics is None:
                continue
            m = scrape.metrics
            per_replica[addr] = {
                "requests": _counter_total(
                    m, self.slo.requests_metric,
                    "method", _EVALUATE_METHODS,
                ),
                "errors": _counter_total(m, self.slo.errors_metric),
                "sheds": _counter_total(m, self.slo.sheds_metric),
                "shards": _counter_total(m, self.slo.partition_metric),
                "shard_errors": _counter_total(
                    m, self.slo.partition_metric, "outcome", ("error",)
                ),
                "latency": _hist_flat(m, self.slo.latency_metric),
            }
        return {"ts": snapshot.ts, "replicas": per_replica}

    def observe(self, snapshot: Any) -> dict:
        """Ingest one :class:`~.collector.FleetSnapshot`; returns the
        current burn report (also kept as :meth:`report`)."""
        sample = self._extract(snapshot)
        with self._lock:
            self._samples.append(sample)
            report = self._compute(sample)
            self._last_report = report
        burn = report["burn_rate"]
        for window, rec in report["windows"].items():
            wburn = rec.get("burn_rate")
            _BURN.labels(window=window).set(
                wburn if wburn is not None else 0.0
            )
        if burn is not None and burn > 1.0:
            _flightrec.record(
                "slo.burn",
                slo=self.slo.name,
                burn_rate=round(burn, 3),
                windows={
                    w: round(rec["burn_rate"], 3)
                    for w, rec in report["windows"].items()
                    if rec.get("burn_rate") is not None
                },
            )
        return report

    def report(self) -> Optional[dict]:
        """The most recent burn report, or ``None`` before the first
        :meth:`observe`."""
        with self._lock:
            return self._last_report

    # -- burn math --------------------------------------------------------

    def _window_delta(
        self, newest: dict, window_s: float
    ) -> Optional[dict]:
        """Aggregate per-replica deltas between the newest sample and
        the oldest one inside the window; ``None`` until two samples
        span it."""
        horizon = newest["ts"] - window_s
        oldest = None
        for sample in self._samples:
            if sample is newest:
                continue
            if sample["ts"] >= horizon:
                oldest = sample
                break
        if oldest is None or newest["ts"] <= oldest["ts"]:
            return None
        elapsed = newest["ts"] - oldest["ts"]
        requests = errors = sheds = 0.0
        shards = shard_errors = 0.0
        latency: _Hist = (0, {})

        def cdelta(new_v: float, old_v: float) -> float:
            # Counter-reset rule (same as the histogram path): a value
            # below its baseline means the process restarted, and its
            # whole new history is the window's increase.
            d = new_v - old_v
            return new_v if d < 0 else d

        for addr, new in newest["replicas"].items():
            old = oldest["replicas"].get(addr)
            if old is None:
                continue  # appeared mid-window: no baseline yet
            req_d = cdelta(new["requests"], old["requests"])
            requests += req_d
            # Partition lane (ISSUE 13): shard items are
            # request-granular — clamp per-shard ERROR deltas at
            # per-shard REQUEST deltas, mirroring the frame-level
            # underflow clamp below at shard granularity (a shard
            # cannot fail more than once for goodput purposes).
            shard_d = cdelta(
                new.get("shards", 0.0), old.get("shards", 0.0)
            )
            shard_err_d = min(
                cdelta(
                    new.get("shard_errors", 0.0),
                    old.get("shard_errors", 0.0),
                ),
                shard_d,
            )
            shards += shard_d
            shard_errors += shard_err_d
            # Errors count per ITEM on the batch lanes while requests
            # count frames — clamp per replica (a frame cannot fail
            # more than once for goodput purposes) so a corrupt batch
            # window can never underflow the fleet's goodput into a
            # false all-bad page.  The ceiling includes the SHARD
            # error delta: a replica that answered zero (or few)
            # counted frames while refusing partition shards used to
            # fold its errors to zero and read HEALTHY — shard errors
            # are request-granular evidence and keep it in the
            # goodput's bad column.
            errors += min(
                cdelta(new["errors"], old["errors"]),
                req_d + shard_err_d,
            )
            sheds += cdelta(new["sheds"], old["sheds"])
            d = _hist_delta(new["latency"], old["latency"])
            merged_buckets = dict(latency[1])
            for b, n in d[1].items():
                merged_buckets[b] = merged_buckets.get(b, 0) + n
            latency = (latency[0] + d[0], merged_buckets)
        return {
            "elapsed_s": elapsed,
            "requests": requests,
            "errors": errors,
            "sheds": sheds,
            "shards": shards,
            "shard_errors": shard_errors,
            "latency": latency,
        }

    def _compute(self, newest: dict) -> dict:
        windows: Dict[str, dict] = {}
        overall: Optional[float] = None
        for window_s in self.windows_s:
            key = f"{window_s:g}s"
            delta = self._window_delta(newest, window_s)
            if delta is None:
                windows[key] = {"burn_rate": None}
                continue
            objectives: Dict[str, float] = {}
            goodput = (
                max(
                    0.0,
                    delta["requests"] - delta["errors"] - delta["sheds"],
                )
                / delta["elapsed_s"]
            )
            if (
                self.slo.goodput_min is not None
                and delta["requests"] > 0
            ):
                objectives["goodput"] = min(
                    _BURN_CAP, self.slo.goodput_min / max(goodput, 1e-9)
                )
            if self.slo.p99_s is not None:
                frac_bad = _frac_over(delta["latency"], self.slo.p99_s)
                if frac_bad is not None:
                    objectives["p99"] = min(
                        _BURN_CAP, frac_bad / 0.01
                    )
            if (
                self.slo.shed_frac_max is not None
                and delta["requests"] > 0
            ):
                frac_shed = delta["sheds"] / max(delta["requests"], 1.0)
                objectives["shed"] = min(
                    _BURN_CAP, frac_shed / self.slo.shed_frac_max
                )
            burn = max(objectives.values()) if objectives else None
            windows[key] = {
                "burn_rate": burn,
                "objectives": objectives,
                "goodput_rps": goodput,
                "requests": delta["requests"],
                "sheds": delta["sheds"],
                "errors": delta["errors"],
                "shards": delta.get("shards", 0.0),
                "shard_errors": delta.get("shard_errors", 0.0),
                "elapsed_s": delta["elapsed_s"],
            }
            if burn is not None:
                overall = burn if overall is None else max(overall, burn)
        return {
            "ts": newest["ts"],
            "slo": self.slo.name,
            "burn_rate": overall,
            "violating": bool(overall is not None and overall > 1.0),
            "windows": windows,
        }
