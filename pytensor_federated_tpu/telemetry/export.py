"""Exposition lane: HTTP /metrics endpoint, snapshot API, JSONL dump.

Three ways out of the process, cheapest first:

- :func:`snapshot` — one dict with every metric family plus the recent
  span trees; what a driver polls in-process.
- :func:`dump_jsonl` — append that snapshot as one JSON line to a file;
  what a live-TPU capture session logs between configs
  (tools/metrics_dump.py wraps it as a CLI).
- :class:`MetricsExporter` — an opt-in ``ThreadingHTTPServer`` on a
  daemon thread serving ``GET /metrics`` (classic Prometheus text
  format 0.0.4), ``GET /snapshot`` and ``GET /traces`` (JSON).  Opt-in
  and loopback-bound by default: a federated node's telemetry can leak
  workload shape, so exposing it beyond the host is an explicit
  deployment decision (same posture as
  :class:`~..parallel.multihost.HeartbeatServer`).

The exporter is plain ``http.server`` — no new dependencies — and
serves reads only; nothing here can mutate the registry.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import flightrec as _flightrec
from . import metrics as _metrics
from . import spans as _spans

__all__ = ["MetricsExporter", "start_exporter", "snapshot", "dump_jsonl"]

_log = logging.getLogger(__name__)

#: Content type of the classic text exposition format.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def snapshot(*, traces: int = 16) -> dict:
    """Full telemetry state: metric families + the last ``traces``
    completed span trees + whether recording is on.  ``ts`` is this
    process's wall clock at snapshot build — the anchor a remote
    scraper (:mod:`.collector`) uses for Cristian-style clock-offset
    estimation."""
    return {
        "ts": time.time(),
        "enabled": _spans.enabled(),
        "metrics": _metrics.snapshot(),
        "traces": _spans.recent_traces(traces),
    }


def dump_jsonl(path: str, *, traces: int = 16) -> dict:
    """Append one timestamped snapshot line to ``path``; returns the
    record.  Append-mode so a polling loop (one line per capture
    window) builds a time series the same way
    tools/suite_cpu_*.jsonl does."""
    rec = {"ts": time.time(), **snapshot(traces=traces)}
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(rec) + "\n")
    return rec


class _Handler(BaseHTTPRequestHandler):
    # Populated per-server via the factory in MetricsExporter.
    registry: Optional[_metrics.Registry] = None

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = _metrics.render_prometheus(self.registry).encode("utf-8")
            ctype = PROMETHEUS_CONTENT_TYPE
        elif path == "/snapshot":
            # The flight-record tail rides along so HTTP-lane replicas
            # (TCP/shm template nodes) contribute events to the fleet
            # timeline exactly like the GetLoad b"telemetry" lane —
            # same composition as server.py's get_load reply.
            # default=str: span/flightrec attrs are free-form (numpy
            # scalars included) — degrade to strings rather than fail
            # the scrape, the same posture as server.py's get_load
            # reply and the watchdog's bundle writer.
            body = json.dumps(
                {**snapshot(), "flightrec": _flightrec.events(128)},
                default=str,
            ).encode("utf-8")
            ctype = "application/json"
        elif path == "/traces":
            body = json.dumps(
                _spans.recent_traces(), default=str
            ).encode("utf-8")
            ctype = "application/json"
        else:
            self.send_error(404, "try /metrics, /snapshot or /traces")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        _log.debug("exporter: " + fmt, *args)


class MetricsExporter:
    """Serve the registry over HTTP from a daemon thread.

    ``port=0`` binds an ephemeral port (read it back via ``.port``).
    Loopback by default; pass ``host="0.0.0.0"`` only when the scrape
    path genuinely crosses hosts.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        registry: Optional[_metrics.Registry] = None,
    ):
        handler = type(
            "_BoundHandler",
            (_Handler,),
            {"registry": registry or _metrics.REGISTRY},
        )
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pftpu-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        _log.info("telemetry exporter on %s:%d", host, self.port)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_exporter(
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    registry: Optional[_metrics.Registry] = None,
) -> MetricsExporter:
    """Start an HTTP exposition endpoint; returns the running exporter
    (``.port`` for the bound port, ``.close()`` to stop)."""
    return MetricsExporter(host, port, registry=registry)
