"""Process-global metrics: counters, gauges, fixed-bucket histograms.

The quantitative half of the telemetry subsystem (spans are the
structural half, :mod:`.spans`).  Deliberately dependency-free — no
prometheus_client, no OpenTelemetry SDK: the container cannot grow new
dependencies, and the subset needed here (three instrument kinds, one
registry, one text renderer) is small enough to own outright, the same
way :mod:`..service.npproto_codec` owns its proto3 subset.

Concurrency model: every instrument family holds one ``threading.Lock``
guarding child creation AND value updates.  Python-level locks cost
~100 ns uncontended — invisible next to the millisecond-scale RPC and
compute paths being measured — and make multi-field updates
(histogram count+sum+bucket) atomic across threads; asyncio callers
are single-threaded per loop and inherit the same safety.  When
telemetry is disabled (:func:`~.spans.set_enabled`), every mutator
returns before touching the lock, so the disabled cost is one global
load + one branch (the bench gate in bench.py measures it).

Naming follows Prometheus conventions: ``pftpu_`` prefix, base-unit
``_seconds``/``_bytes`` suffixes, counters ending ``_total``.  The
text renderer emits classic exposition format 0.0.4 (``# HELP``,
``# TYPE``, cumulative ``_bucket{le=...}`` histograms) — scrapeable by
an unmodified Prometheus and validated by the golden-file test.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from . import spans as _spans

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "render_prometheus",
    "snapshot",
    "DEFAULT_LATENCY_BUCKETS",
]

# Fixed latency buckets (seconds): 100 us .. 10 s in a 1-2.5-5 ladder.
# Fixed (not adaptive) so node and driver histograms aggregate across
# processes by simple bucket-wise summation — the property Prometheus
# histograms are built around.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2,
    1e-1, 2.5e-1, 5e-1,
    1.0, 2.5, 5.0, 10.0,
)

# Small-integer buckets (counts: fanout widths, pipeline window depths).
DEFAULT_COUNT_BUCKETS: Tuple[float, ...] = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
)


def _validate_name(name: str) -> None:
    # Prometheus metric/label names: [a-zA-Z_:][a-zA-Z0-9_:]*
    if not name or not all(c.isalnum() or c in "_:" for c in name) or (
        name[0].isdigit()
    ):
        raise ValueError(f"invalid metric/label name {name!r}")


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    """Exposition-format number: integers render bare (no trailing .0),
    +Inf/-Inf/NaN in their spec spellings."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class _Family:
    """Shared machinery: a named instrument with 0+ label dimensions;
    children are materialized per label-value tuple on first use."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
    ):
        _validate_name(name)
        for ln in labelnames:
            _validate_name(ln)
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # Pre-materialize the single unlabeled child so the
            # no-label fast path never takes the creation branch.
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kv: str):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}"
            )
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def _default_child(self):
        return self._children[()]

    def remove(self, **kv: str) -> None:
        """Drop one label-set's child (no-op when absent).  For gauges
        whose label values name transient identities — replicas, peers
        — so a long-running process with churn does not grow the label
        set without bound or keep exporting values for hosts that no
        longer exist."""
        if not self.labelnames:
            raise ValueError(
                f"{self.name}: remove() is for labeled families — the "
                "unlabeled default child is permanent"
            )
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}"
            )
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """``(suffixed_name, labels, value)`` rows for rendering."""
        out = []
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            labels = dict(zip(self.labelnames, key))
            out.extend(child._samples(self.name, labels))  # type: ignore
        return out

    def _reset(self) -> None:
        with self._lock:
            for child in self._children.values():
                child._reset_values()  # type: ignore[attr-defined]


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _spans.enabled():
            return
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _samples(self, name, labels):
        return [(name, labels, self._value)]

    def _reset_values(self):
        self._value = 0.0


class Counter(_Family):
    """Monotonic counter family; name should end ``_total``."""

    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    # Unlabeled convenience mutators forward to the single child.
    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        if not _spans.enabled():
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _spans.enabled():
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _samples(self, name, labels):
        return [(name, labels, self._value)]

    def _reset_values(self):
        self._value = 0.0


class Gauge(_Family):
    """Set/inc/dec instantaneous value (in-flight RPCs, widths)."""

    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_buckets", "_sum", "_count",
                 "_exemplar")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds
        self._buckets = [0] * (len(bounds) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0
        # Last (value, trace_id_hex) observed under an active trace —
        # the exemplar that lets a human jump from "p99 spiked" to one
        # concrete correlated span tree (exposed via snapshot(), not
        # the classic text format, which predates exemplars).
        self._exemplar: Optional[Tuple[float, str]] = None

    def observe(self, value: float) -> None:
        if not _spans.enabled():
            return
        idx = bisect.bisect_left(self._bounds, value)
        tid = _spans.current_trace_id()
        with self._lock:
            self._buckets[idx] += 1
            self._sum += value
            self._count += 1
            if tid is not None:
                self._exemplar = (value, tid.hex())

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def approx_quantile(self, q: float) -> float:
        """Quantile estimate from the cumulative buckets (upper bound
        of the bucket containing the q-th observation) — the same
        estimate ``histogram_quantile`` makes server-side, computed
        here so GetLoad can fold a latency summary into its reply
        without shipping raw buckets."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            if total == 0:
                return float("nan")
            rank = q * total
            seen = 0
            for i, n in enumerate(self._buckets):
                seen += n
                if seen >= rank and n:
                    return (
                        self._bounds[i]
                        if i < len(self._bounds)
                        else float("inf")
                    )
        return float("inf")

    def _samples(self, name, labels):
        with self._lock:
            buckets = list(self._buckets)
            s, c = self._sum, self._count
        out = []
        cum = 0
        for bound, n in zip(self._bounds, buckets):
            cum += n
            out.append(
                (name + "_bucket", {**labels, "le": _format_value(bound)},
                 float(cum))
            )
        out.append((name + "_bucket", {**labels, "le": "+Inf"}, float(c)))
        out.append((name + "_sum", labels, s))
        out.append((name + "_count", labels, float(c)))
        return out

    def _reset_values(self):
        with self._lock:
            self._buckets = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._exemplar = None


class Histogram(_Family):
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        *,
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ):
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(f"buckets must be sorted unique, got {buckets}")
        if bounds and math.isinf(bounds[-1]):
            bounds = bounds[:-1]  # +Inf is implicit
        self._bounds = bounds
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._bounds)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def approx_quantile(self, q: float) -> float:
        return self._default_child().approx_quantile(q)


class Registry:
    """Name -> instrument family map; the process-global one is
    :data:`REGISTRY`.  Get-or-create semantics so every instrumented
    module can declare its instruments at import time in any order;
    re-declaring with a DIFFERENT type/labelset/buckets raises (two
    call sites disagreeing about a metric is a bug, not a merge)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, labelnames, **kwargs)
                self._families[name] = fam
                return fam
        if type(fam) is not cls or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{type(fam).__name__}{fam.labelnames}, cannot re-register "
                f"as {cls.__name__}{tuple(labelnames)}"
            )
        if (
            isinstance(fam, Histogram)
            and "buckets" in kwargs
            and fam._bounds != tuple(float(b) for b in kwargs["buckets"])
        ):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{fam._bounds}"
            )
        return fam

    def counter(self, name, help, labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help, labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name, help, labelnames=(), *, buckets=DEFAULT_LATENCY_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def families(self) -> List[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Zero every instrument's VALUES, keeping registrations — the
        test-isolation hook.  Instruments are module-level singletons in
        the instrumented code, so dropping registrations would orphan
        the references those modules already hold."""
        for fam in self.families():
            fam._reset()


#: The process-global registry every instrumented module records into.
REGISTRY = Registry()


def counter(name: str, help: str, labelnames: Sequence[str] = ()) -> Counter:
    """Get-or-create a counter in the global registry."""
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str, labelnames: Sequence[str] = ()) -> Gauge:
    """Get-or-create a gauge in the global registry."""
    return REGISTRY.gauge(name, help, labelnames)


def histogram(
    name: str,
    help: str,
    labelnames: Sequence[str] = (),
    *,
    buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
) -> Histogram:
    """Get-or-create a fixed-bucket histogram in the global registry."""
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render_prometheus(registry: Optional[Registry] = None) -> str:
    """Classic Prometheus exposition text (format 0.0.4).

    Deterministic: families alphabetical, children in insertion order,
    labels in declaration order — so a fixed sequence of observations
    renders byte-identically (the golden-file test depends on it).
    """
    registry = registry or REGISTRY
    lines: List[str] = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for name, labels, value in fam.samples():
            if labels:
                body = ",".join(
                    f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in labels.items()
                )
                lines.append(f"{name}{{{body}}} {_format_value(value)}")
            else:
                lines.append(f"{name} {_format_value(value)}")
    return "\n".join(lines) + "\n" if lines else ""


def snapshot(registry: Optional[Registry] = None) -> dict:
    """JSON-friendly dump of every family: values, histogram buckets,
    and exemplars (trace-id-bearing observations classic text format
    cannot carry)."""
    registry = registry or REGISTRY
    out: dict = {}
    for fam in registry.families():
        entry: dict = {"type": fam.kind, "help": fam.help}
        children = []
        with fam._lock:
            items = list(fam._children.items())
        for key, child in items:
            labels = dict(zip(fam.labelnames, key))
            if isinstance(child, _HistogramChild):
                with child._lock:
                    rec = {
                        "labels": labels,
                        "count": child._count,
                        "sum": child._sum,
                        "buckets": dict(
                            zip(
                                (_format_value(b) for b in child._bounds),
                                child._buckets,
                            )
                        ),
                    }
                    if child._exemplar is not None:
                        rec["exemplar"] = {
                            "value": child._exemplar[0],
                            "trace_id": child._exemplar[1],
                        }
            else:
                rec = {"labels": labels, "value": child.value}  # type: ignore
            children.append(rec)
        entry["children"] = children
        out[fam.name] = entry
    return out
