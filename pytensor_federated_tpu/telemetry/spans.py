"""Contextvar-propagated span trees with wire-portable trace ids.

The structural half of the telemetry subsystem (:mod:`.metrics` is the
quantitative half).  A *span* is one named, monotonic-clock-timed stage
(``span("encode")``, ``span("compute")``); nesting via
``contextvars.ContextVar`` builds a tree per logical operation, and a
16-byte *trace id* — minted at the driver, carried inside the request
payload (npwire flag block / npproto field 15, see
:mod:`..service.npwire` / :mod:`..service.npproto_codec`) — stitches
the driver-side tree to the node-side tree of the same call.  That is
the piece the round-3 live-chip incidents were missing: when a rate
looks wrong, the per-stage decomposition (wire encode, queue wait,
compute, decode) says *where* the time went, per correlated call.

Completed ROOT spans land in a bounded ring buffer
(:func:`recent_traces`) — the exemplar store.  Bounded because this is
always-on instrumentation, not a profiler: the last N traces answer
"what did a slow call look like", full traces belong to the JSONL dump
(:func:`~.export.dump_jsonl`).

Cost model: one module-global bool gates everything.  Disabled,
``span()`` returns a shared no-op context manager — no allocation, no
clock read, no contextvar write (bench.py's telemetry-overhead gate
measures this path); enabled, a span costs two ``perf_counter`` reads,
one small object, and two contextvar ops.

ContextVars propagate into ``asyncio`` tasks automatically and into
thread pools only via ``contextvars.copy_context()`` — the fanout
executor does exactly that (:mod:`..fanout_exec`) so member spans
parent correctly across threads.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
import uuid as uuid_mod
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "Span",
    "span",
    "trace",
    "enabled",
    "set_enabled",
    "new_trace_id",
    "current_trace_id",
    "current_span",
    "trace_context",
    "recent_traces",
    "clear_traces",
    "set_trace_capacity",
]

# One global bool, read on every telemetry operation (spans AND metric
# mutators in .metrics).  Plain attribute, not a ContextVar: the off
# switch must cost a single LOAD_GLOBAL, and enable/disable is a
# process-level deployment decision, not a per-task one.
_ENABLED = os.environ.get("PFTPU_TELEMETRY", "1") != "0"

_current_span: contextvars.ContextVar[Optional["Span"]] = (
    contextvars.ContextVar("pftpu_current_span", default=None)
)
_current_trace: contextvars.ContextVar[Optional[bytes]] = (
    contextvars.ContextVar("pftpu_current_trace", default=None)
)

_TRACE_CAP = 64
_recent: Deque["Span"] = deque(maxlen=_TRACE_CAP)
_recent_lock = threading.Lock()
_span_counter = itertools.count(1)

# Flight-recorder hooks (set once by .flightrec at its import): called
# with the Span on enter/exit of every ACTIVE span.  Plain module
# globals checked with one load — spans must stay importable without
# flightrec (no cycle), and the disabled path must not pay a registry.
_on_span_open = None
_on_span_close = None


def _set_span_hooks(on_open, on_close) -> None:
    """Install the span open/close listeners (flightrec's registration
    point; ``None`` uninstalls)."""
    global _on_span_open, _on_span_close
    _on_span_open = on_open
    _on_span_close = on_close


def enabled() -> bool:
    """Whether telemetry (spans AND metrics) is recording."""
    return _ENABLED


def set_enabled(value: bool) -> bool:
    """Flip recording on/off process-wide; returns the previous state.
    Env default: ``PFTPU_TELEMETRY=0`` starts disabled."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(value)
    return prev


def new_trace_id() -> bytes:
    """Mint a 16-byte trace id (uuid4 bytes — same width as the wire's
    correlation uuid, so both ride the payload at fixed cost)."""
    return uuid_mod.uuid4().bytes


def current_trace_id() -> Optional[bytes]:
    """The trace id of the innermost active trace, or ``None``."""
    return _current_trace.get()


def current_span() -> Optional["Span"]:
    """The innermost active span, or ``None``."""
    return _current_span.get()


class Span:
    """One timed stage.  Built by :func:`span`; read via
    :meth:`to_dict`/:func:`recent_traces`."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs",
        "t_start", "duration", "error", "children",
        "_tok_span", "_tok_trace",
    )

    def __init__(self, name: str, trace_id: bytes, parent: Optional["Span"],
                 attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_span_counter)
        self.parent_id = parent.span_id if parent is not None else None
        self.attrs = attrs
        self.t_start = 0.0
        self.duration = 0.0
        self.error: Optional[str] = None
        self.children: List["Span"] = []
        self._tok_span = None
        self._tok_trace = None

    def to_dict(self) -> dict:
        """JSON-friendly tree (trace ids as hex)."""
        d: dict = {
            "name": self.name,
            "trace_id": self.trace_id.hex(),
            "span_id": self.span_id,
            "duration_s": self.duration,
        }
        if self.parent_id is not None:
            d["parent_id"] = self.parent_id
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error is not None:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class _ActiveSpan:
    """Context manager driving one :class:`Span`'s lifetime."""

    __slots__ = ("_span",)

    def __init__(self, s: Span):
        self._span = s

    @property
    def span(self) -> Span:
        return self._span

    def set_attr(self, key: str, value: Any) -> None:
        self._span.attrs[key] = value

    def __enter__(self) -> "_ActiveSpan":
        s = self._span
        s._tok_span = _current_span.set(s)
        if _current_trace.get() != s.trace_id:
            s._tok_trace = _current_trace.set(s.trace_id)
        if _on_span_open is not None:
            _on_span_open(s)
        s.t_start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        s.duration = time.perf_counter() - s.t_start
        if exc is not None:
            s.error = f"{exc_type.__name__}: {exc}"
        _current_span.reset(s._tok_span)
        if s._tok_trace is not None:
            _current_trace.reset(s._tok_trace)
        parent = _current_span.get()
        if parent is not None and parent.trace_id == s.trace_id:
            parent.children.append(s)
        else:
            with _recent_lock:
                _recent.append(s)
        if _on_span_close is not None:
            _on_span_close(s)
        return False  # never swallow


class _NoopSpan:
    """Shared disabled-path context manager: no allocation per call."""

    __slots__ = ()

    @property
    def span(self) -> None:
        return None

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()


def span(name: str, **attrs: Any):
    """Open a span named ``name`` under the current trace.

    With no active trace, a fresh trace id is minted — every root span
    starts a trace, so driver code needs no explicit setup.  Attributes
    are free-form JSON-friendly annotations (``span("fanout",
    width=8)``).  Returns a context manager whose ``.span`` is the live
    :class:`Span` (``None`` when telemetry is disabled).
    """
    if not _ENABLED:
        return _NOOP
    trace_id = _current_trace.get()
    if trace_id is None:
        trace_id = new_trace_id()
    return _ActiveSpan(Span(name, trace_id, _current_span.get(), attrs))


# Root-span alias: reads as "begin a traced operation" at call sites.
trace = span


class _TraceContext:
    """Adopt an externally-supplied trace id (see :func:`trace_context`)."""

    __slots__ = ("_trace_id", "_tok")

    def __init__(self, trace_id: Optional[bytes]):
        self._trace_id = trace_id
        self._tok = None

    def __enter__(self):
        if self._trace_id is not None:
            self._tok = _current_trace.set(self._trace_id)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._tok is not None:
            _current_trace.reset(self._tok)
        return False


def trace_context(trace_id: Optional[bytes]):
    """Bind an existing trace id to the current context — the NODE side
    of correlation: the server decodes the driver's trace id off the
    wire and runs its spans under it, so both halves share one id.
    ``None`` (no id on the wire, or telemetry disabled) is a no-op.
    """
    if not _ENABLED:
        return _NOOP
    return _TraceContext(trace_id)


def recent_traces(n: Optional[int] = None) -> List[dict]:
    """The last ``n`` (default: all retained) completed root spans as
    dict trees, oldest first."""
    with _recent_lock:
        items = list(_recent)
    if n is not None:
        items = items[-n:]
    return [s.to_dict() for s in items]


def clear_traces() -> None:
    """Drop the retained root spans (test isolation)."""
    with _recent_lock:
        _recent.clear()


def set_trace_capacity(n: int) -> None:
    """Resize the root-span ring buffer (keeps the newest entries)."""
    global _recent, _TRACE_CAP
    if n < 1:
        raise ValueError(f"capacity must be >= 1, got {n}")
    with _recent_lock:
        _TRACE_CAP = int(n)
        _recent = deque(_recent, maxlen=_TRACE_CAP)
