"""Telemetry: spans, metrics, and a Prometheus-style exposition lane.

The system's cost structure is a pipeline of invisible stages — wire
encode, queue wait, compute, decode, fanout overlap — and this package
makes them always-on observable (the per-stage accounting DrJAX
arXiv:2403.07128 and the TPU scaling study arXiv:2112.09017 lean on to
find where MapReduce-style fanout loses hardware efficiency):

- :mod:`.spans` — contextvar-propagated span trees with 16-byte trace
  ids that ride the wire, correlating driver-side and node-side timing
  of the same RPC.
- :mod:`.metrics` — thread/asyncio-safe counters, gauges and
  fixed-bucket histograms in a process-global registry, rendered in
  classic Prometheus text format.
- :mod:`.export` — opt-in HTTP exposition endpoint + snapshot()/JSONL
  dump for pull-based collection.

Dependency-free, and near-zero cost when disabled
(``PFTPU_TELEMETRY=0`` or :func:`set_enabled`; bench.py's overhead
gate measures the disabled path).  Metric names are catalogued in
docs/observability.md.
"""

from .export import MetricsExporter, dump_jsonl, snapshot, start_exporter
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Registry,
    counter,
    gauge,
    histogram,
    render_prometheus,
)
from .spans import (
    Span,
    clear_traces,
    current_span,
    current_trace_id,
    enabled,
    new_trace_id,
    recent_traces,
    set_enabled,
    span,
    trace_context,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsExporter",
    "REGISTRY",
    "Registry",
    "Span",
    "clear_traces",
    "counter",
    "current_span",
    "current_trace_id",
    "dump_jsonl",
    "enabled",
    "gauge",
    "histogram",
    "new_trace_id",
    "recent_traces",
    "render_prometheus",
    "set_enabled",
    "snapshot",
    "span",
    "start_exporter",
    "trace_context",
]
