"""Telemetry: spans, metrics, and a Prometheus-style exposition lane.

The system's cost structure is a pipeline of invisible stages — wire
encode, queue wait, compute, decode, fanout overlap — and this package
makes them always-on observable (the per-stage accounting DrJAX
arXiv:2403.07128 and the TPU scaling study arXiv:2112.09017 lean on to
find where MapReduce-style fanout loses hardware efficiency):

- :mod:`.spans` — contextvar-propagated span trees with 16-byte trace
  ids that ride the wire, correlating driver-side and node-side timing
  of the same RPC.
- :mod:`.metrics` — thread/asyncio-safe counters, gauges and
  fixed-bucket histograms in a process-global registry, rendered in
  classic Prometheus text format.
- :mod:`.export` — opt-in HTTP exposition endpoint + snapshot()/JSONL
  dump for pull-based collection.
- :mod:`.flightrec` — always-on black-box flight recorder: a bounded
  ring of structured events (span open/close, RPC retries/drops,
  integrity-gate verdicts, heartbeat/remesh decisions) dumpable on
  demand, at exit, on signal, and on crash.
- :mod:`.watchdog` — armed-deadline hang watchdog over the known wedge
  points (PJRT init, batch windows, psum rendezvous); an expired
  deadline writes a self-contained incident bundle instead of leaving
  a silent hang.
- :mod:`.reunion` — driver-side merge of node span trees (piggybacked
  on replies / pulled via GetLoad) with local spans, per trace id.
- :mod:`.collector` — the FLEET plane: harvest every replica's
  snapshot over the GetLoad ``b"telemetry"`` / HTTP ``/snapshot``
  lanes, merge (counters summed, histograms bucket-wise, gauges
  per-replica) with loud staleness marking, estimate per-replica
  clock offsets, and interleave all flight records into one ordered
  incident timeline.
- :mod:`.critpath` — critical-path analysis over reunion-merged span
  trees: per-stage p50/p99 decomposition of end-to-end latency,
  dominant-stage counts, fanout straggler diagnosis.
- :mod:`.slo` — declarative SLOs + a multi-window burn-rate engine
  over successive fleet snapshots (the autoscaler's signal bus).

Dependency-free, and near-zero cost when disabled
(``PFTPU_TELEMETRY=0`` or :func:`set_enabled`; bench.py's overhead
gate measures the disabled path).  Metric names and the flight-record
event taxonomy are catalogued in docs/observability.md.
"""

from . import collector, critpath, flightrec, reunion, slo, watchdog
from .collector import FleetCollector, FleetSnapshot
from .export import MetricsExporter, dump_jsonl, snapshot, start_exporter
from .slo import BurnRateEngine, Slo
from .watchdog import write_incident_bundle
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    Registry,
    counter,
    gauge,
    histogram,
    render_prometheus,
)
from .spans import (
    Span,
    clear_traces,
    current_span,
    current_trace_id,
    enabled,
    new_trace_id,
    recent_traces,
    set_enabled,
    span,
    trace_context,
)

__all__ = [
    "BurnRateEngine",
    "Counter",
    "FleetCollector",
    "FleetSnapshot",
    "Gauge",
    "Histogram",
    "MetricsExporter",
    "REGISTRY",
    "Registry",
    "Slo",
    "Span",
    "clear_traces",
    "collector",
    "counter",
    "critpath",
    "current_span",
    "current_trace_id",
    "dump_jsonl",
    "enabled",
    "flightrec",
    "gauge",
    "histogram",
    "new_trace_id",
    "recent_traces",
    "render_prometheus",
    "reunion",
    "set_enabled",
    "slo",
    "snapshot",
    "span",
    "start_exporter",
    "trace_context",
    "watchdog",
    "write_incident_bundle",
]
