"""Critical-path analysis over reunion-merged span trees.

The spans subsystem records WHERE time went per call; this module
turns a population of reunion-merged traces
(:func:`.reunion.merge_all`) into the answer an operator actually
needs: *which stage owns the latency*.  "p99 is 9 ms" becomes "6 ms
of it is queue wait on replica :50052".

Per trace, the end-to-end driver wall (the driver root span:
``rpc.evaluate`` / ``pool.evaluate`` / the ``evaluate_many`` twins) is
attributed to named stages:

==================  ========================================================
stage               source
==================  ========================================================
``driver_encode``   driver-side ``encode`` spans
``driver_decode``   driver-side ``decode`` spans
``driver_overhead`` driver root minus its direct children (retry loops,
                    pool pick/hedge bookkeeping between attempts)
``wire``            driver ``call``/``pool.attempt``/``pool.window`` span
                    minus the matched node tree's total — bytes in flight
                    plus transport stack both ways
``node_decode``     node ``decode_s`` attr (decode happens before the node
                    span opens; every lane stamps it as an attribute)
``node_queue``      node ``compute`` span's ``queue_wait_s`` attr
                    (thread-executor / micro-batcher coalescing queue)
``node_compute``    node ``compute`` span minus its queue wait
``node_encode``     node ``encode`` spans
==================  ========================================================

plus ``unattributed`` (whatever the spans did not cover — the report's
``coverage_frac`` is the attributed fraction, the honesty metric the
suite's fleet config gates at ≥ 90%).  When the node half of a trace
never arrived (reply lost, node dead before the GetLoad pull), the
whole call interval inside ``call`` stays in ``wire`` — visible, not
invented.

The report aggregates per-stage p50/p99/total, counts the DOMINANT
stage per trace, splits the decomposition per replica (the
``replica`` attr the pool stamps on its attempt spans), and runs a
fanout straggler diagnosis over ``fanout`` spans (width, straggler
gap, the member index that lost the race).  Pure functions over
dicts; no clock-sync assumption beyond per-process monotonic
durations (only DURATIONS are compared, never cross-process
timestamps — the fleet timeline in :mod:`.collector` owns wall-clock
alignment).

Docs: docs/observability.md "Fleet plane".
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

from . import reunion as _reunion

__all__ = [
    "STAGES",
    "analyze",
    "analyze_recent",
    "decompose_trace",
    "format_report",
]

#: Stage names, in pipeline order (the report renders them this way).
STAGES = (
    "driver_encode",
    "wire",
    "node_decode",
    "node_queue",
    "node_compute",
    "node_encode",
    "driver_decode",
    "driver_overhead",
)

_DRIVER_ROOTS = {
    "rpc.evaluate",
    "rpc.evaluate_many",
    "pool.evaluate",
    "pool.evaluate_many",
}
_CALL_SPANS = {"call", "pool.attempt", "pool.window"}
_NODE_ROOTS = {"node.evaluate", "node.evaluate_batch"}


def _walk(tree: Mapping[str, Any]) -> Iterable[Mapping[str, Any]]:
    yield tree
    for child in tree.get("children", ()):
        yield from _walk(child)


def _dur(span: Optional[Mapping[str, Any]]) -> float:
    if span is None:
        return 0.0
    d = span.get("duration_s")
    return float(d) if isinstance(d, (int, float)) else 0.0


def _attr(span: Mapping[str, Any], key: str) -> Optional[float]:
    v = (span.get("attrs") or {}).get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _find_driver_root(
    trees: Sequence[Mapping[str, Any]],
) -> Optional[Mapping[str, Any]]:
    for tree in trees:
        if tree.get("name") in _DRIVER_ROOTS:
            return tree
    return None


def _node_total(tree: Mapping[str, Any]) -> float:
    """One node tree's whole served interval: span duration plus the
    pre-span decode the lanes stamp as ``decode_s``."""
    return _dur(tree) + (_attr(tree, "decode_s") or 0.0)


def decompose_trace(merged: Mapping[str, Any]) -> Optional[dict]:
    """Attribute ONE reunion-merged trace's driver wall to stages.

    ``merged`` is the :func:`.reunion.merged` shape (``driver`` +
    ``remote`` tree lists).  Returns ``None`` when the trace has no
    recognizable driver root (a node-only trace — e.g. pulled from a
    node whose driver ring already evicted its half).  The result maps
    every :data:`STAGES` name to seconds, plus ``wall_s``,
    ``unattributed_s``, ``coverage_frac``, ``dominant`` and
    ``replicas`` (per-replica attempt walls, from the pool's span
    attrs).
    """
    driver_root = _find_driver_root(merged.get("driver") or [])
    if driver_root is None:
        return None
    remote = [
        t
        for t in (merged.get("remote") or [])
        if t.get("name") in _NODE_ROOTS
    ]
    stages: Dict[str, float] = {s: 0.0 for s in STAGES}
    wall = _dur(driver_root)

    # Driver side: encode/decode anywhere under the root; the direct-
    # child gap is pool/retry bookkeeping.
    direct = driver_root.get("children", ())
    stages["driver_overhead"] = max(
        0.0, wall - sum(_dur(c) for c in direct)
    )
    # The wire interval is the INNERMOST call-ish span of each chain:
    # a pool.attempt wraps rpc.evaluate whose own `call` child is the
    # actual socket interval — counting the wrapper too would fold the
    # driver-side encode/decode it contains into "wire" twice.
    call_spans = _call_spans_of(driver_root)
    innermost = [
        span
        for span in call_spans
        if not any(
            d.get("name") in _CALL_SPANS for d in _descendants(span)
        )
    ]
    call_wall = sum(_dur(span) for span in innermost)
    replicas: Dict[str, float] = {}
    for span in call_spans:
        replica = (span.get("attrs") or {}).get("replica")
        if isinstance(replica, str):
            replicas[replica] = replicas.get(replica, 0.0) + _dur(span)
    for span in _walk(driver_root):
        name = span.get("name")
        if name == "encode":
            stages["driver_encode"] += _dur(span)
        elif name == "decode":
            stages["driver_decode"] += _dur(span)

    # Node side: every remote tree for this trace (retries/hedges can
    # contribute several) — their intervals all sit inside call_wall.
    node_total = 0.0
    for tree in remote:
        node_total += _node_total(tree)
        stages["node_decode"] += _attr(tree, "decode_s") or 0.0
        for span in _walk(tree):
            name = span.get("name")
            if name == "compute":
                queue = _attr(span, "queue_wait_s") or 0.0
                stages["node_queue"] += queue
                stages["node_compute"] += max(0.0, _dur(span) - queue)
            elif name == "encode":
                stages["node_encode"] += _dur(span)
    stages["wire"] = max(0.0, call_wall - node_total)
    # Node span time not in decode/queue/compute/encode (asarray
    # copies, span bookkeeping) stays unattributed — honesty over
    # completeness.
    attributed = sum(stages.values())
    unattributed = max(0.0, wall - attributed)
    dominant = max(stages, key=lambda s: stages[s]) if wall > 0 else None
    return {
        **stages,
        "wall_s": wall,
        "unattributed_s": unattributed,
        "coverage_frac": (
            min(1.0, attributed / wall) if wall > 0 else 0.0
        ),
        "dominant": dominant,
        "replicas": replicas,
        "trace_id": merged.get("trace_id"),
    }


def _call_spans_of(root: Mapping[str, Any]) -> List[Mapping[str, Any]]:
    return [s for s in _walk(root) if s.get("name") in _CALL_SPANS]


def _descendants(span: Mapping[str, Any]) -> List[Mapping[str, Any]]:
    out: List[Mapping[str, Any]] = []
    for child in span.get("children", ()):
        out.extend(_walk(child))
    return out


def _quantile(values: List[float], q: float) -> float:
    if not values:
        return float("nan")
    values = sorted(values)
    idx = max(0, min(len(values) - 1, int(math.ceil(q * len(values))) - 1))
    return values[idx]


def _fanout_diagnosis(
    driver_trees: Sequence[Mapping[str, Any]],
) -> Optional[dict]:
    """Straggler picture over every ``fanout`` span in the driver
    trees: gap quantiles and which member index loses most often."""
    gaps: List[float] = []
    widths: List[float] = []
    slowest: Dict[str, int] = {}
    for tree in driver_trees:
        for span in _walk(tree):
            if span.get("name") != "fanout":
                continue
            gap = _attr(span, "straggler_gap_s")
            if gap is not None:
                gaps.append(gap)
            width = _attr(span, "width")
            if width is not None:
                widths.append(width)
            members = [
                c
                for c in span.get("children", ())
                if c.get("name") == "fanout.member"
            ]
            if members:
                worst = max(members, key=_dur)
                idx = (worst.get("attrs") or {}).get("idx")
                slowest[str(idx)] = slowest.get(str(idx), 0) + 1
    if not gaps and not slowest:
        return None
    return {
        "n_fanouts": max(len(gaps), sum(slowest.values())),
        "straggler_gap_p50_s": _quantile(gaps, 0.5),
        "straggler_gap_p99_s": _quantile(gaps, 0.99),
        "mean_width": (
            sum(widths) / len(widths) if widths else float("nan")
        ),
        "slowest_member_counts": slowest,
    }


def analyze(
    merged_traces: Sequence[Mapping[str, Any]],
) -> dict:
    """Aggregate the per-trace decomposition over a trace population.

    Returns the critical-path report: per-stage ``p50_s``/``p99_s``/
    ``total_s``/``frac`` (fraction of total attributed wall),
    ``dominant_stage`` counts, overall ``coverage_frac`` (attributed
    wall / driver wall — the ≥ 0.9 acceptance line), per-replica
    attempt walls, and the fanout straggler diagnosis.  Traces without
    a driver root are counted in ``n_skipped`` rather than silently
    dropped.
    """
    per_stage: Dict[str, List[float]] = {s: [] for s in STAGES}
    per_stage["unattributed"] = []
    dominant: Dict[str, int] = {}
    replicas: Dict[str, float] = {}
    wall_total = attributed_total = 0.0
    walls: List[float] = []
    n_skipped = 0
    driver_trees: List[Mapping[str, Any]] = []
    for merged in merged_traces:
        driver_trees.extend(merged.get("driver") or [])
        rec = decompose_trace(merged)
        if rec is None:
            n_skipped += 1
            continue
        walls.append(rec["wall_s"])
        wall_total += rec["wall_s"]
        attributed_total += rec["wall_s"] - rec["unattributed_s"]
        for stage in STAGES:
            per_stage[stage].append(rec[stage])
        per_stage["unattributed"].append(rec["unattributed_s"])
        if rec["dominant"] is not None:
            dominant[rec["dominant"]] = (
                dominant.get(rec["dominant"], 0) + 1
            )
        for addr, wall in rec["replicas"].items():
            replicas[addr] = replicas.get(addr, 0.0) + wall
    stages_report = {}
    for stage, values in per_stage.items():
        total = sum(values)
        stages_report[stage] = {
            "p50_s": _quantile(values, 0.5),
            "p99_s": _quantile(values, 0.99),
            "total_s": total,
            "frac": total / wall_total if wall_total > 0 else 0.0,
        }
    return {
        "n_traces": len(walls),
        "n_skipped": n_skipped,
        "wall_total_s": wall_total,
        "wall_p50_s": _quantile(walls, 0.5),
        "wall_p99_s": _quantile(walls, 0.99),
        "coverage_frac": (
            attributed_total / wall_total if wall_total > 0 else 0.0
        ),
        "stages": stages_report,
        "dominant_stage": dominant,
        "replica_wall_s": {
            a: replicas[a] for a in sorted(replicas)
        },
        "fanout": _fanout_diagnosis(driver_trees),
    }


def analyze_recent() -> dict:
    """The report over everything currently in the reunion store +
    the driver's completed-root ring (:func:`.reunion.merge_all`)."""
    return analyze(_reunion.merge_all())


def _fmt_s(v: float) -> str:
    if isinstance(v, float) and math.isnan(v):
        return "-"
    if v >= 1.0:
        return f"{v:.3f} s"
    return f"{v * 1e3:.3f} ms"


def format_report(report: Mapping[str, Any]) -> str:
    """Render one :func:`analyze` report as an aligned text table —
    what ``tools/metrics_dump.py --fleet`` and the tutorial print."""
    rows = [("stage", "p50", "p99", "total", "share", "dominant#")]
    dominant = report.get("dominant_stage") or {}
    for stage in (*STAGES, "unattributed"):
        rec = (report.get("stages") or {}).get(stage)
        if rec is None:
            continue
        rows.append(
            (
                stage,
                _fmt_s(rec["p50_s"]),
                _fmt_s(rec["p99_s"]),
                _fmt_s(rec["total_s"]),
                f"{100.0 * rec['frac']:.1f}%",
                str(dominant.get(stage, "")),
            )
        )
    widths = [
        max(len(r[i]) for r in rows) for i in range(len(rows[0]))
    ]
    out = [
        "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        for row in rows
    ]
    out.append(
        f"traces: {report.get('n_traces', 0)} "
        f"(skipped {report.get('n_skipped', 0)}), wall p50 "
        f"{_fmt_s(report.get('wall_p50_s', float('nan')))} / p99 "
        f"{_fmt_s(report.get('wall_p99_s', float('nan')))}, coverage "
        f"{100.0 * report.get('coverage_frac', 0.0):.1f}%"
    )
    replica_wall = report.get("replica_wall_s") or {}
    if replica_wall:
        out.append(
            "attempt wall by replica: "
            + ", ".join(
                f"{a}={_fmt_s(w)}" for a, w in replica_wall.items()
            )
        )
    fanout = report.get("fanout")
    if fanout:
        out.append(
            f"fanouts: {fanout['n_fanouts']}, straggler gap p50 "
            f"{_fmt_s(fanout['straggler_gap_p50_s'])} / p99 "
            f"{_fmt_s(fanout['straggler_gap_p99_s'])}"
        )
    return "\n".join(out) + "\n"
