"""Black-box flight recorder: a bounded ring of structured events.

The incident half of the telemetry subsystem.  Spans and metrics
(:mod:`.spans`, :mod:`.metrics`) answer "how fast is the steady
state"; the flight recorder answers the question the live-chip
history keeps asking — *what happened in the seconds before this
process wedged/crashed/returned-without-executing* (CLAUDE.md
round-3 findings).  It records a small structured event for each
noteworthy state transition:

========================  ====================================================
kind                      emitted by
========================  ====================================================
``span.open``/``span.close``  every telemetry span (hooked from :mod:`.spans`)
``rpc.retry``/``rpc.drop``    driver transports (service/client.py, tcp.py)
``rpc.error``                 in-band server error replies at the driver
``server.error``              node-side decode/compute failures (server.py)
``fanout.member_error``       a fused-fanout member raising (fanout_exec.py)
``fanout.member_retry``       a transient member failure re-run via a pool
``pool.breaker_*``            replica breaker transitions (routing/pool.py)
``pool.failover``             a call/window tail moving onto another replica
``pool.hedge``                a hedged request firing at a second replica
``pool.probe_failed``         a background replica probe failing
``pool.replica_added``/``_removed``  live pool registry changes
``sampler.pool_recovered``    the elastic pool-recovery tier (elastic.py)
``mesh.peer_dead``            a heartbeat death verdict (parallel/multihost.py)
``mesh.remesh``               mesh rebuilt after failure (parallel/multihost.py)
``sampler.run``               one sample() run settling (samplers/mcmc.py)
``sampler.segment_failed``    an elastic segment raising (samplers/elastic.py)
``sampler.recovered``         elastic recovery about to resume
``bench.integrity``           measure_rate verdicts, pass or refusal (bench.py)
``probe.backend``             subprocess backend-liveness probe verdicts (utils)
``watchdog.fired``            an armed deadline expiring (:mod:`.watchdog`)
``incident.bundle``           an incident bundle hitting disk
``fault.<kind>``              every injected fault (faultinject.runtime) with
                              plan id / rule / point / trace id
``fault.plan_*``              fault-plan install/uninstall lifecycle
``server.drain_*``            graceful-drain lifecycle (server.py)
========================  ====================================================

plus anything user code passes to :func:`record`.

Always-on, near-zero when idle: events are only born when something
*happens* (an RPC, a failure, a span), and each costs one small dict
plus a lock-guarded deque append.  When telemetry is disabled —
``PFTPU_TELEMETRY=0`` / ``spans.set_enabled(False)`` — or the recorder
itself is off (:func:`set_enabled`), :func:`record` returns after one
branch (bench.py's overhead gate measures both the micro cost and the
driver-metric delta every run).

Eviction contract: the ring holds the newest ``capacity`` events —
EXCEPT that the ``span.open`` event of every still-open span is held
aside (pinned) until that span closes, so a dump taken mid-operation
always shows how the operation *started*.  Ancestors of an open span
are themselves still open (a parent span cannot exit before its
children), so an open span's whole ancestry survives eviction — the
property tests/test_flightrec.py pins down.  On close, the open event
rejoins the ring (original sequence number) followed by the close
event; :func:`events` merges ring + pinned in sequence order.

Four ways out of the process:

- :func:`events` / :func:`dump_jsonl` — on demand.
- :func:`install_handlers` — ``atexit`` (dump at interpreter exit),
  ``SIGUSR2`` (dump on signal, the classic black-box "read it out
  while it hangs" path — safe because the handler only reads state
  under a lock no signal-interrupted frame can hold while *in* the
  handler... see the function docstring for the precise story), and a
  chained ``sys.excepthook`` that writes a full incident bundle
  (:func:`.watchdog.write_incident_bundle`) on an uncaught exception.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from . import spans as _spans

__all__ = [
    "record",
    "events",
    "clear",
    "enabled",
    "set_enabled",
    "set_capacity",
    "capacity",
    "dump_jsonl",
    "install_handlers",
]

_CAP = int(os.environ.get("PFTPU_FLIGHTREC_CAP", "512"))
_ring: Deque[dict] = deque(maxlen=_CAP)
# span_id -> its span.open event, held OUT of the ring until the span
# closes (the eviction contract in the module docstring).
_pinned: Dict[int, dict] = {}
_lock = threading.Lock()
_seq = itertools.count(1)

# The recorder's own switch, layered under the process-wide telemetry
# switch: effective recording = spans.enabled() AND _ENABLED.  Separate
# so bench.py can isolate the recorder's cost with telemetry still on.
_ENABLED = os.environ.get("PFTPU_FLIGHTREC", "1") != "0"


def enabled() -> bool:
    """Whether the flight recorder is recording (requires telemetry on)."""
    return _ENABLED and _spans.enabled()


def set_enabled(value: bool) -> bool:
    """Flip the recorder on/off (telemetry master switch still applies);
    returns the previous recorder state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(value)
    return prev


def capacity() -> int:
    return _CAP


def set_capacity(n: int) -> None:
    """Resize the event ring (keeps the newest; pinned events unaffected)."""
    global _ring, _CAP
    if n < 1:
        raise ValueError(f"capacity must be >= 1, got {n}")
    with _lock:
        _CAP = int(n)
        _ring = deque(_ring, maxlen=_CAP)


def _event(kind: str, attrs: Dict[str, Any]) -> dict:
    # Caller attrs FIRST, reserved keys last: the record's ordering and
    # identity (seq/ts/kind, plus the ambient trace id) must win over a
    # caller that happens to pass an attr named like them — a forged
    # "seq" would corrupt the sort the eviction contract relies on.
    ev: dict = dict(attrs) if attrs else {}
    ev["seq"] = next(_seq)
    ev["ts"] = time.time()
    ev["kind"] = kind
    tid = _spans.current_trace_id()
    if tid is not None:
        ev["trace_id"] = tid.hex()
    return ev


def record(kind: str, **attrs: Any) -> None:
    """Append one structured event (JSON-friendly ``attrs``) to the
    ring.  The active trace id (if any) is stamped on automatically so
    incident events correlate with span trees.  No-op while disabled."""
    if not (_ENABLED and _spans.enabled()):
        return
    ev = _event(kind, attrs)
    with _lock:
        _ring.append(ev)


# -- span hooks (installed into .spans at import time, bottom of file) ------


def _on_span_open(span) -> None:
    if not _ENABLED:  # spans.enabled() already true or the span is a no-op
        return
    ev = _event(
        "span.open",
        {"name": span.name, "span_id": span.span_id},
    )
    ev["trace_id"] = span.trace_id.hex()  # the span's id, not the ambient one
    with _lock:
        _pinned[span.span_id] = ev


def _on_span_close(span) -> None:
    if not _ENABLED:
        # Still unpin: the open event may have been pinned while the
        # recorder was ON — leaving it would report a closed span as
        # open forever (and leak one dict per such span).
        with _lock:
            _pinned.pop(span.span_id, None)
        return
    close = _event(
        "span.close",
        {
            "name": span.name,
            "span_id": span.span_id,
            "duration_s": span.duration,
        },
    )
    close["trace_id"] = span.trace_id.hex()
    if span.error is not None:
        close["error"] = span.error
    with _lock:
        open_ev = _pinned.pop(span.span_id, None)
        if open_ev is not None:
            # Rejoins with its ORIGINAL seq: events() sorts, so the
            # record reads in true temporal order even though the ring
            # receives it late.
            _ring.append(open_ev)
        _ring.append(close)


def events(n: Optional[int] = None) -> List[dict]:
    """The retained flight record, oldest first: ring events plus the
    pinned ``span.open`` events of still-open spans, merged in sequence
    order.  ``n`` keeps only the newest ``n`` RING events — pinned
    opens are always included regardless of age (the eviction contract:
    a tail-trimmed incident dump must still show how the still-running
    operation started), so the result may slightly exceed ``n``."""
    with _lock:
        ring = list(_ring)
        pinned = list(_pinned.values())
    if n is not None:
        ring = ring[-n:]
    items = sorted(ring + pinned, key=lambda e: e["seq"])
    return items


def clear() -> None:
    """Drop all retained events, pinned included (test isolation)."""
    with _lock:
        _ring.clear()
        _pinned.clear()


def dump_jsonl(path: str, *, n: Optional[int] = None) -> int:
    """Append the flight record to ``path``, one JSON line per event;
    returns the number of lines written."""
    evs = events(n)
    with open(path, "a", encoding="utf-8") as fh:
        for ev in evs:
            # default=str: attrs are free-form (numpy scalars included)
            # and every dump lane — atexit and SIGUSR2 especially —
            # must degrade, never lose the record to a TypeError.
            fh.write(json.dumps(ev, default=str) + "\n")
    return len(evs)


# -- exit / signal / crash handlers -----------------------------------------

_handlers_installed = False
_installed_path: Optional[str] = None
_prev_excepthook = None


def install_handlers(
    path: Optional[str] = None,
    *,
    on_exit: bool = True,
    signum: Optional[int] = None,
    on_crash: bool = True,
) -> str:
    """Install the black-box readout handlers; returns the dump path.

    - ``on_exit``: an ``atexit`` hook appends the flight record to
      ``path`` (default ``$PFTPU_FLIGHTREC_DUMP`` or
      ``<incident dir>/flightrec-<pid>.jsonl``) if any events exist.
    - ``signum`` (default ``SIGUSR2``; pass ``0`` to skip): a signal
      handler that appends the record on demand — the "the process is
      hung, read the black box" path.  The handler itself only SPAWNS
      a short-lived thread that does the locked read + file I/O:
      CPython runs Python signal handlers on the main thread between
      bytecodes, so a handler that took the (non-reentrant) internal
      lock directly would deadlock whenever the signal lands while the
      main thread is inside one of this module's ``with _lock:``
      sections — the suspended frame holds the lock the handler would
      wait on.  A thread blocks safely instead: the main thread
      resumes, finishes its append, releases, and the dump proceeds.
    - ``on_crash``: chains ``sys.excepthook`` so an uncaught exception
      writes a full incident bundle
      (:func:`.watchdog.write_incident_bundle`, reason ``"crash"``)
      before the normal traceback prints.

    Idempotent: a second call changes nothing and returns the path the
    FIRST call installed — the returned path is always where dumps
    actually land (a repeat caller's different ``path`` argument is
    ignored, not silently half-honored).
    """
    import atexit
    import signal as _signal
    import sys

    global _handlers_installed, _installed_path, _prev_excepthook

    if _handlers_installed:
        return _installed_path  # type: ignore[return-value]

    if path is None:
        path = os.environ.get("PFTPU_FLIGHTREC_DUMP")
    if path is None:
        from .watchdog import incident_dir

        path = os.path.join(incident_dir(), f"flightrec-{os.getpid()}.jsonl")

    def _dump(*_a):
        try:
            if events(1):
                dump_jsonl(path)
        except OSError:
            pass  # a dying process must not die harder over its dump

    def _dump_on_signal(*_a):
        # Never touch _lock from the handler frame itself (docstring:
        # the interrupted main-thread frame may HOLD it); hand the
        # locked read to a thread that can block and proceed.
        threading.Thread(
            target=_dump, name="pftpu-flightrec-dump", daemon=True
        ).start()

    if on_exit:
        atexit.register(_dump)
    if signum is None:
        signum = getattr(_signal, "SIGUSR2", 0)
    if signum:
        try:
            _signal.signal(signum, _dump_on_signal)
        except (ValueError, OSError):
            pass  # non-main thread / unsupported platform: skip the lane
    if on_crash:
        _prev_excepthook = sys.excepthook

        def _crash_hook(exc_type, exc, tb):
            try:
                from .watchdog import write_incident_bundle

                write_incident_bundle(
                    "crash",
                    attrs={
                        "exc_type": exc_type.__name__,
                        "exc": str(exc)[:500],
                    },
                )
            except Exception:
                pass
            (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = _crash_hook
    _handlers_installed = True
    _installed_path = path
    return path


# Register the span hooks exactly once, at import time: the flight
# recorder is always-on (module docstring), and its own _ENABLED flag
# is the cheap opt-out the hooks check first.
_spans._set_span_hooks(_on_span_open, _on_span_close)
