"""Small utilities.

Maps the reference's utility layer (reference: pytensor_federated/utils.py).
``argmin_none_or_func`` keeps the exact contract of the reference's load
balancer helper (reference: utils.py:13-34).  The event-loop machinery
(reference: utils.py:37-61, ``get_useful_event_loop`` + nest_asyncio) exists
only because the reference bridges a *synchronous* graph executor into
async gRPC calls; the TPU hot path has no event loop at all — XLA dispatch
is already asynchronous — so that helper survives only for the optional
host-federation transport (:mod:`pytensor_federated_tpu.service`).
"""

from __future__ import annotations

import asyncio
import math
import os
import threading
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

# Shared Gaussian constant — single definition for every model/kernel.
LOG_2PI = math.log(2.0 * math.pi)


def probe_backend(
    *, try_mosaic: bool = False, timeout_s: float = 180.0
) -> tuple[bool, bool]:
    """Probe the default jax backend in a subprocess: ``(live, mosaic_ok)``.

    One child process, run BEFORE the caller initializes jax itself:
    single-host TPU runtimes are exclusive per process, so a child
    spawned after the parent holds the chip could never attach and a
    healthy runtime would mis-probe as dead.  The child prints ``LIVE``
    after a tiny on-device op and — only with ``try_mosaic`` —
    ``MOSAIC_OK`` after running a compiled Pallas kernel.  A hang (the
    tunneled-relay wedge mode) is cut off by the timeout, and the
    partial output still distinguishes dead-backend from
    wedged-on-Mosaic.  The child imports this package, so PYTHONPATH is
    set explicitly (the repo may not be pip-installed).
    """
    import subprocess
    import sys

    code = (
        "import jax, jax.numpy as jnp\n"
        "jax.devices()\n"
        "assert float(jnp.ones(()).sum()) == 1.0\n"
        "print('LIVE', flush=True)\n"
    )
    if try_mosaic:
        code += (
            "import numpy as np\n"
            "from pytensor_federated_tpu.ops.pallas_kernels import"
            " linreg_reductions\n"
            "S, N = 8, 64\n"
            "x = jnp.ones((S, N)); y = 2.0 * jnp.ones((S, N))\n"
            "m = jnp.ones((S, N))\n"
            "sc = jnp.zeros((3,), jnp.float32)\n"
            "off = jnp.zeros((S,), jnp.float32)\n"
            "ll, gmu, gx, gz = linreg_reductions("
            "sc, off, x, y, m, interpret=False)\n"
            "assert np.allclose(np.asarray(gmu), 2.0 * N), np.asarray(gmu)\n"
            "print('MOSAIC_OK', flush=True)\n"
        )
    from .telemetry import flightrec as _flightrec

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    timed_out = False
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            env=env,
        )
        out = (res.stdout or b"").decode("utf-8", "replace")
        if res.returncode != 0:
            print(
                "# backend probe failed:\n"
                + (res.stderr or b"").decode("utf-8", "replace")[-2000:],
                file=sys.stderr,
            )
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode("utf-8", "replace")
        timed_out = True
        print(f"# backend probe timed out after {timeout_s}s", file=sys.stderr)
    except OSError as e:
        print(f"# backend probe could not run: {e}", file=sys.stderr)
        _flightrec.record("probe.backend", verdict="unrunnable", error=str(e))
        return False, False
    live, mosaic_ok = "LIVE" in out, "MOSAIC_OK" in out
    # Probe verdicts are the canonical pre-incident breadcrumb for a
    # wedged PJRT tunnel: a DEAD verdict's timestamp bounds when the
    # wedge happened (flight-record taxonomy: probe.backend).
    _flightrec.record(
        "probe.backend",
        verdict="live" if live else ("timeout" if timed_out else "dead"),
        try_mosaic=try_mosaic,
        mosaic_ok=mosaic_ok,
        timeout_s=timeout_s,
    )
    return live, mosaic_ok


def ensure_live_backend(
    *, try_mosaic: Optional[bool] = None, timeout_s: float = 150.0
) -> bool:
    """Preflight the default backend; fall back to CPU if it is wedged.

    Call BEFORE this process initializes jax.  Returns whether compiled
    Mosaic may be used for Pallas kernels.  Behavior:

    - ``JAX_PLATFORMS=cpu`` (the documented CPU dry-run env): force the
      CPU backend directly — probing a CPU child reports LIVE
      regardless of TPU state, so it would cost a subprocess jax import
      to learn nothing.
    - Tunneled runtimes (``PALLAS_AXON_POOL_IPS`` set): probe liveness
      in a subprocess under a timeout (a wedged relay blocks PJRT
      client init forever); the Mosaic attempt itself can wedge the
      relay for later processes, so there it defaults to opt-in via
      ``PFTPU_PALLAS_COMPILED=1``.
    - Direct runtimes: probe, attempting Mosaic by default.

    On a dead backend, prints a diagnostic to stderr and restricts this
    process to CPU so the caller still runs instead of hanging.
    """
    import sys

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        force_cpu_backend()
        return False
    tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    if try_mosaic is None:
        try_mosaic = (not tunneled) or (
            os.environ.get("PFTPU_PALLAS_COMPILED") == "1"
        )
    if not tunneled and not try_mosaic:
        # Nothing to learn: only tunneled runtimes wedge at client
        # init, and the caller doesn't want the Mosaic answer — skip
        # the subprocess jax bring-up entirely.
        return False
    live, mosaic_ok = probe_backend(try_mosaic=try_mosaic, timeout_s=timeout_s)
    if not live:
        print("# backend unresponsive -> CPU fallback", file=sys.stderr)
        force_cpu_backend()
        return False
    # Live tunneled chip: warm-start future programs from the disk
    # cache (and keep them runnable through a remote-compile outage).
    if tunneled:
        enable_compilation_cache()
    return mosaic_ok


def enable_compilation_cache(path: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (default:
    ``$PFTPU_CACHE_DIR`` or ``<repo>/.jax_cache``).

    Two wins on the tunneled TPU (round 3): a warm cache turns the
    20-40 s remote compile per program shape into a disk read on
    re-capture, and — because the axon remote-compile service can die
    mid-session while the data plane stays up — cached programs keep
    benches runnable through a compile-service outage.  Harmless where
    the backend does not support executable serialization (cache
    misses just compile as before).  Call before the first jit.
    """
    import jax

    if path is None:
        path = os.environ.get("PFTPU_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything that took real compile time; the default
        # min_entry_size filter would skip the small-but-remote
        # programs that dominate tunnel wall time.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - config names are versioned
        pass


def force_cpu_backend(plugin: str = "axon") -> None:
    """Restrict this process to the CPU backend without dialing ``plugin``.

    Tunneled single-chip environments pre-register a PJRT plugin whose
    client *init* dials a relay — and a wedged relay blocks forever, so
    merely enumerating devices can hang the process.  CPU-only work
    (tests, virtual-mesh dry runs, fallback benchmarking) must therefore
    both restrict ``jax_platforms`` AND drop the plugin's backend
    factory before the first device query.  Call before any jax API
    that initializes backends; no-op (beyond the platform restriction)
    if the plugin isn't registered.  The factory pop uses a private
    jax API, so it is best-effort — a jax upgrade degrades to the
    platform restriction alone rather than an ImportError.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop(plugin, None)
    except Exception:
        pass


def argmin_none_or_func(
    items: Sequence[Optional[T]], func: Callable[[T], float]
) -> Optional[int]:
    """Index of the item minimizing ``func``, ignoring ``None`` entries.

    Returns ``None`` if every item is ``None``.  Exact behavioral parity
    with reference utils.py:13-34 (used by load balancing to pick the
    least-loaded healthy server; ``None`` marks an unresponsive one).
    """
    best_i: Optional[int] = None
    best_v: Optional[float] = None
    for i, item in enumerate(items):
        if item is None:
            continue
        v = func(item)
        if best_v is None or v < best_v:
            best_i, best_v = i, v
    return best_i


_thread_loops = threading.local()


def get_event_loop() -> asyncio.AbstractEventLoop:
    """Return THIS thread's event loop (create and cache if necessary).

    Slimmed-down analog of reference utils.py:37-61.  The reference needs
    ``nest_asyncio`` because PyTensor's sync executor re-enters a running
    loop; our executor is XLA, so re-entrancy never happens on the compute
    path and this helper only serves the host transport's sync wrappers.

    The cache is thread-local and stable across calls (the policy-based
    lookup this replaces warns on 3.12+ and raises in non-main threads,
    which previously made this helper mint a fresh loop per call
    there).  Loop identity still matters for connection reuse — an aio
    channel is bound to the loop it was created on — so the service
    connection cache keys on (client, process, thread, loop)
    (service/client.py: _conn_key); this helper only guarantees the
    sync wrappers a stable private loop per thread.
    """
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        pass
    loop = getattr(_thread_loops, "loop", None)
    if loop is None or loop.is_closed():
        loop = asyncio.new_event_loop()
        # Deliberately NOT asyncio.set_event_loop: this loop is private
        # to the sync wrappers; installing it in the policy slot would
        # clobber a loop the application registered for its own use.
        _thread_loops.loop = loop
    return loop
