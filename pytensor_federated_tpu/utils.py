"""Small utilities.

Maps the reference's utility layer (reference: pytensor_federated/utils.py).
``argmin_none_or_func`` keeps the exact contract of the reference's load
balancer helper (reference: utils.py:13-34).  The event-loop machinery
(reference: utils.py:37-61, ``get_useful_event_loop`` + nest_asyncio) exists
only because the reference bridges a *synchronous* graph executor into
async gRPC calls; the TPU hot path has no event loop at all — XLA dispatch
is already asynchronous — so that helper survives only for the optional
host-federation transport (:mod:`pytensor_federated_tpu.service`).
"""

from __future__ import annotations

import asyncio
import math
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

# Shared Gaussian constant — single definition for every model/kernel.
LOG_2PI = math.log(2.0 * math.pi)


def force_cpu_backend(plugin: str = "axon") -> None:
    """Restrict this process to the CPU backend without dialing ``plugin``.

    Tunneled single-chip environments pre-register a PJRT plugin whose
    client *init* dials a relay — and a wedged relay blocks forever, so
    merely enumerating devices can hang the process.  CPU-only work
    (tests, virtual-mesh dry runs, fallback benchmarking) must therefore
    both restrict ``jax_platforms`` AND drop the plugin's backend
    factory before the first device query.  Call before any jax API
    that initializes backends; no-op (beyond the platform restriction)
    if the plugin isn't registered.  The factory pop uses a private
    jax API, so it is best-effort — a jax upgrade degrades to the
    platform restriction alone rather than an ImportError.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop(plugin, None)
    except Exception:
        pass


def argmin_none_or_func(
    items: Sequence[Optional[T]], func: Callable[[T], float]
) -> Optional[int]:
    """Index of the item minimizing ``func``, ignoring ``None`` entries.

    Returns ``None`` if every item is ``None``.  Exact behavioral parity
    with reference utils.py:13-34 (used by load balancing to pick the
    least-loaded healthy server; ``None`` marks an unresponsive one).
    """
    best_i: Optional[int] = None
    best_v: Optional[float] = None
    for i, item in enumerate(items):
        if item is None:
            continue
        v = func(item)
        if best_v is None or v < best_v:
            best_i, best_v = i, v
    return best_i


def get_event_loop() -> asyncio.AbstractEventLoop:
    """Return a usable asyncio event loop (create one if necessary).

    Slimmed-down analog of reference utils.py:37-61.  The reference needs
    ``nest_asyncio`` because PyTensor's sync executor re-enters a running
    loop; our executor is XLA, so re-entrancy never happens on the compute
    path and this helper only serves the host transport's sync wrappers.
    """
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        pass
    try:
        loop = asyncio.get_event_loop_policy().get_event_loop()
        if loop.is_closed():
            raise RuntimeError
        return loop
    except RuntimeError:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        return loop
