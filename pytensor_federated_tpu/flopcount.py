"""FLOP accounting for the benchmarks: exact per-eval counts + MFU.

The reference publishes no performance numbers at all (BASELINE.md), so
round 1 reported raw evals/s — which says nothing about how much of the
accelerator each eval actually uses (a few-kFLOP eval at 100k/s is
launch-bound, not compute-bound).  This module adds the two numbers that
make evals/s interpretable:

- ``flops_per_eval``: the FLOP count of the *actual compiled
  executable*, read from XLA's own cost model
  (``Compiled.cost_analysis()["flops"]``) rather than a hand-derived
  formula.  Hand counts drift from what the compiler really emits
  (fusion, algebraic simplification, rematerialization); XLA's count is
  exact for the HLO that runs.  Lowering happens on the CPU backend —
  the FLOP count is a property of the program, not the device, and CPU
  compiles are instant (a TPU lowering would cost a 20-40 s remote
  compile per config, CLAUDE.md).
- ``mfu``: model FLOP utilization = achieved FLOP/s over the chip's
  peak.  Peak comes from a device-kind table for TPUs (bf16 dense
  peak, the standard MFU convention — e.g. the PaLM paper's appendix B
  and jax-ml.github.io/scaling-book) and from a *measured* dense-matmul
  roofline on CPU, where no meaningful vendor peak exists.  The basis
  is always recorded alongside the number (``mfu_basis``) so an MFU is
  never quoted without saying what "peak" meant.

Sanity guarantee: tests/test_flopcount.py cross-checks the XLA count
against closed-form analytic counts for programs simple enough to count
by hand (dense matmul, linear-regression logp+grad).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "xla_flops_per_eval",
    "peak_flops",
    "mfu",
    "measured_matmul_peak",
    "TPU_BF16_PEAK_FLOPS",
]


# Dense bf16 peak FLOP/s by PJRT device_kind substring.  Sources are the
# public TPU system specs (cloud.google.com/tpu/docs/system-architecture);
# matching is by substring because device_kind strings vary across PJRT
# plugin versions ("TPU v5 lite", "TPU v5e", ...).
TPU_BF16_PEAK_FLOPS = {
    "v6": 918e12,  # Trillium / v6e
    "v5p": 459e12,
    "v5": 197e12,  # v5e / "v5 lite" (after the v5p check)
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def xla_flops_per_eval(fn, *args) -> Optional[float]:
    """Exact FLOP count of one ``fn(*args)`` call, from XLA's cost model.

    Lowers and compiles ``fn`` for the CPU backend (fast, never dials
    the TPU tunnel) and reads ``cost_analysis()["flops"]``.  Returns
    None if the cost model is unavailable in this runtime rather than
    guessing.  Note XLA counts a fused multiply-add as 2 FLOPs and
    reports transcendentals (exp/log/erf) separately — this is the
    matmul-convention count that MFU is defined over.
    """
    try:
        cpu = jax.devices("cpu")[0]
        # Lower on abstract shapes: jax.default_device only steers
        # UNcommitted arrays, so a TPU-committed arg would drag .compile()
        # onto the tunnel (20-40s remote compile) during a live capture —
        # and device_put'ing it to CPU would pull its bytes through the
        # tunnel instead.  ShapeDtypeStruct gives the identical count
        # with zero data movement and zero tunnel contact.
        args = jax.tree_util.tree_map(
            lambda a: (
                jax.ShapeDtypeStruct(a.shape, a.dtype)
                if isinstance(a, (jax.Array, np.ndarray))
                else a
            ),
            args,
        )
        with jax.default_device(cpu):
            compiled = jax.jit(fn).lower(*args).compile()
            ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jaxlibs wrap in a list
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        if flops is None or flops < 0:
            return None
        return float(flops)
    except Exception:  # pragma: no cover - runtime-dependent
        return None


_MEASURED_PEAK_CACHE: dict = {}


def measured_matmul_peak(backend: Optional[str] = None, n: int = 1536) -> float:
    """Practical dense-matmul roofline of ``backend`` in FLOP/s.

    Times ``n x n @ n x n`` (f32 on CPU, bf16 on TPU — each backend's
    native MXU/FMA format) and returns the best of a few repeats.  This
    is what "peak" means on hosts where no vendor dense-peak number is
    defensible; cached per backend per process.
    """
    backend = backend or jax.default_backend()
    key = (backend, n)
    if key in _MEASURED_PEAK_CACHE:
        return _MEASURED_PEAK_CACHE[key]
    dtype = jnp.bfloat16 if backend == "tpu" else jnp.float32
    dev = jax.devices(backend)[0]
    with jax.default_device(dev):
        a = jnp.ones((n, n), dtype)
        mm = jax.jit(lambda a: a @ a)
        jax.block_until_ready(mm(a))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(mm(a))
            best = min(best, time.perf_counter() - t0)
    peak = 2.0 * n**3 / best
    _MEASURED_PEAK_CACHE[key] = peak
    return peak


def peak_flops(backend: Optional[str] = None) -> tuple[float, str]:
    """``(peak_flops, basis_string)`` for ``backend``.

    TPU: vendor bf16 dense peak looked up by device_kind (the standard
    MFU denominator).  Anything else: measured dense f32 matmul
    roofline, explicitly labelled as such.
    """
    backend = backend or jax.default_backend()
    if backend == "tpu":
        devices = jax.devices("tpu")
        kind = devices[0].device_kind
        norm = kind.lower().replace(" ", "").replace("lite", "")
        for sub, peak in TPU_BF16_PEAK_FLOPS.items():
            if sub in norm:
                # flops_per_eval counts the WHOLE program's work, so on
                # a multi-chip run the denominator must be the peak of
                # every chip the program can use — a single-chip peak
                # would overstate MFU by n_devices.
                total = peak * len(devices)
                return total, (
                    f"{len(devices)}x {kind} bf16 dense peak "
                    f"{total:.3g} FLOP/s"
                )
        # Unknown TPU generation: fall through to the measured roofline.
    peak = measured_matmul_peak(backend)
    return peak, (
        f"measured dense-matmul roofline on {backend} ({peak:.3g} FLOP/s)"
    )


def mfu(
    flops_per_eval: Optional[float],
    evals_per_sec: float,
    backend: Optional[str] = None,
) -> dict[str, Any]:
    """Benchmark-record fields: achieved FLOP/s and model FLOP
    utilization, plus the basis string that defines "peak".

    Returns ``{"flops_per_eval", "flops_per_sec", "mfu", "mfu_basis"}``
    with Nones when the FLOP count is unavailable — a record must say
    "unknown" rather than omit the field (VERDICT round 1: unlabelled
    evals/s are unfalsifiable).
    """
    if flops_per_eval is None:
        return {
            "flops_per_eval": None,
            "flops_per_sec": None,
            "mfu": None,
            "mfu_basis": "flop count unavailable",
        }
    peak, basis = peak_flops(backend)
    achieved = flops_per_eval * evals_per_sec
    return {
        "flops_per_eval": round(flops_per_eval),
        "flops_per_sec": round(achieved),
        "mfu": round(achieved / peak, 6),
        "mfu_basis": basis,
    }
