"""Minimal distribution objects for the effect-handler front end.

Each distribution is a frozen value object with ``log_prob`` (the
elementwise log-density — sites sum it themselves, so masking and
plate scaling compose outside) and ``sample`` (a reparameterized or
direct draw; prior-predictive discovery and ``seed``-handled traces
use it).  The logp kernels REUSE the closed-form expressions the
model zoo already ships (``models/linear._normal_logpdf`` is the one
Gaussian kernel in the repo — NumPyro-style distribution objects wrap
it rather than fork it), so a PPL model and its hand-written twin
cannot drift numerically.

Everything is batched/elementwise: parameters broadcast against the
value exactly like ``jnp`` arithmetic, and there is no event-shape
machinery — the :class:`~.handlers.plate` owns independence
structure, which is all the ``fed`` compiler needs.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..models.linear import _normal_logpdf

__all__ = [
    "Bernoulli",
    "Distribution",
    "Exponential",
    "HalfNormal",
    "HalfNormalLog",
    "Normal",
]

_LOG_HALF_NORMAL_CONST = 0.5 * math.log(2.0 / math.pi)


@dataclasses.dataclass(frozen=True)
class Distribution:
    """Base class: elementwise ``log_prob`` + ``sample``."""

    def log_prob(self, value: Any) -> jax.Array:
        raise NotImplementedError

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        raise NotImplementedError

    def shape(self) -> Tuple[int, ...]:
        """Broadcast shape of the parameters (the per-draw shape)."""
        leaves = [
            jnp.shape(getattr(self, f.name))
            for f in dataclasses.fields(self)
        ]
        out: Tuple[int, ...] = ()
        for s in leaves:
            out = jnp.broadcast_shapes(out, s)
        return out


@dataclasses.dataclass(frozen=True)
class Normal(Distribution):
    """Gaussian — wraps the repo's one ``_normal_logpdf`` kernel."""

    loc: Any = 0.0
    scale: Any = 1.0

    def log_prob(self, value: Any) -> jax.Array:
        return _normal_logpdf(value, self.loc, self.scale)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.shape()
        return self.loc + self.scale * jax.random.normal(key, shape)


@dataclasses.dataclass(frozen=True)
class HalfNormal(Distribution):
    """Half-Gaussian on ``x > 0`` (support is NOT checked — samplers
    that need an unconstrained parameterization should use
    :class:`HalfNormalLog` instead)."""

    scale: Any = 1.0

    def log_prob(self, value: Any) -> jax.Array:
        z = value / self.scale
        return (
            -0.5 * z * z - jnp.log(self.scale) + _LOG_HALF_NORMAL_CONST
        )

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.shape()
        return jnp.abs(self.scale * jax.random.normal(key, shape))


@dataclasses.dataclass(frozen=True)
class HalfNormalLog(Distribution):
    """The law of ``log(X)`` for ``X ~ HalfNormal(scale)`` — the
    repo's standard unconstrained scale prior (``-0.5 exp(2u)/s^2 + u``
    plus constants: the HalfNormal log-density at ``exp(u)`` with the
    log-transform Jacobian, exactly the ``hierbase.py`` /
    ``models/glm.py`` ``log_tau`` term).  Sampling NUTS/SVI over this
    value needs no bijector machinery."""

    scale: Any = 1.0

    def log_prob(self, value: Any) -> jax.Array:
        x = jnp.exp(value) / self.scale
        return (
            -0.5 * x * x
            + value
            - jnp.log(self.scale)
            + _LOG_HALF_NORMAL_CONST
        )

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.shape()
        draw = jnp.abs(self.scale * jax.random.normal(key, shape))
        return jnp.log(draw + jnp.finfo(jnp.float32).tiny)


@dataclasses.dataclass(frozen=True)
class Exponential(Distribution):
    """Exponential(rate) on ``x > 0`` (support not checked)."""

    rate: Any = 1.0

    def log_prob(self, value: Any) -> jax.Array:
        return jnp.log(self.rate) - self.rate * value

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.shape()
        return jax.random.exponential(key, shape) / self.rate


@dataclasses.dataclass(frozen=True)
class Bernoulli(Distribution):
    """Bernoulli over {0, 1} parameterized by logits — the stable
    ``y*eta - log(1 + e^eta)`` kernel (``models/logistic.py``)."""

    logits: Any = 0.0

    def log_prob(self, value: Any) -> jax.Array:
        return value * self.logits - jnp.logaddexp(0.0, self.logits)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = tuple(sample_shape) + self.shape()
        return jax.random.bernoulli(
            key, jax.nn.sigmoid(self.logits), shape
        ).astype(jnp.float32)
