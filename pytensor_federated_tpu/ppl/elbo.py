"""The shared ELBO core every VI lane optimizes through.

Before ISSUE 15 the repo carried three hand-rolled copies of the same
two pieces — the Gaussian entropy constant and the
jit(``lax.scan``) Adam loop — in ``samplers/advi.py`` (mean-field and
full-rank) and ``samplers/flows.py`` (RealNVP).  They now live here
once, and the ``ppl`` SVI lanes (:mod:`.svi`) optimize through the
same functions, so an ELBO bug cannot exist in one family and not
another.

Everything is behavior-preserving by construction: :func:`scan_vi`
is byte-for-byte the loop the samplers ran (same optimizer-update
order, same ``jax.random.split(key, num_steps)`` stream, same jit
boundary), and :func:`gaussian_entropy` is the same closed form —
the samplers' seeded regression tests run unmodified.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from ..utils import LOG_2PI

try:
    import optax

    _HAS_OPTAX = True
except ModuleNotFoundError:  # pragma: no cover
    _HAS_OPTAX = False

__all__ = [
    "gaussian_entropy",
    "meanfield_draws",
    "meanfield_neg_elbo",
    "scan_vi",
]


def gaussian_entropy(dim: int, log_sd_sum: Any = 0.0) -> jax.Array:
    """Closed-form entropy of a ``dim``-dimensional Gaussian with
    ``Σ log σ_i = log_sd_sum``: ``log_sd_sum + dim/2 (1 + log 2π)``.
    With ``log_sd_sum=0`` this is the standard-normal base entropy
    (the flow lane's constant)."""
    return log_sd_sum + 0.5 * dim * (1.0 + LOG_2PI)


def scan_vi(
    neg_elbo: Callable[[Any, jax.Array], jax.Array],
    var0: Any,
    *,
    key: jax.Array,
    num_steps: int,
    optimizer: Any,
) -> Tuple[Any, jax.Array]:
    """The whole VI optimization as one jitted ``lax.scan``:
    ``(final_var_params, elbo_trace)``.  ``neg_elbo(var, key)`` is any
    estimator (mean-field, full-rank, flow, federated minibatch); one
    step is ``value_and_grad`` → optimizer update, and the carried
    trace is ``-loss`` per step."""
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("scan_vi requires optax")

    def run(k: jax.Array) -> Tuple[Any, jax.Array]:
        opt0 = optimizer.init(var0)

        def step(
            carry: Tuple[Any, Any], kk: jax.Array
        ) -> Tuple[Tuple[Any, Any], jax.Array]:
            var, opt_state = carry
            loss, g = jax.value_and_grad(neg_elbo)(var, kk)
            updates, opt_state = optimizer.update(g, opt_state)
            var = optax.apply_updates(var, updates)
            return (var, opt_state), -loss

        (var, _), elbos = jax.lax.scan(
            step, (var0, opt0), jax.random.split(k, num_steps)
        )
        return var, elbos

    return jax.jit(run)(key)


def meanfield_draws(
    mu: jax.Array, log_sd: jax.Array, key: jax.Array, n_mc: int
) -> jax.Array:
    """``n_mc`` reparameterized draws from ``N(mu, diag(exp(log_sd)²))``
    — shape ``(n_mc, dim)``."""
    eps = jax.random.normal(key, (n_mc,) + mu.shape, mu.dtype)
    return mu[None, :] + jnp.exp(log_sd)[None, :] * eps


def meanfield_neg_elbo(
    e_logp_fn: Callable[[jax.Array, jax.Array], jax.Array],
    dim: int,
    *,
    n_mc: int,
    split_keys: bool,
) -> Callable[[Tuple[jax.Array, jax.Array], jax.Array], jax.Array]:
    """Build the mean-field negative-ELBO estimator over a flat
    parameter vector: MC expectation of ``e_logp_fn(x_draws, key)``
    plus the closed-form Gaussian entropy.

    ``split_keys=False`` reuses one key for both the draws and the
    logp (the non-stochastic lane's RNG stream, which seeded tests
    pin); ``split_keys=True`` splits it (the doubly stochastic /
    minibatch lane)."""

    def neg_elbo(
        var: Tuple[jax.Array, jax.Array], key: jax.Array
    ) -> jax.Array:
        mu, log_sd = var
        if split_keys:
            k_eps, k_mb = jax.random.split(key)
        else:
            k_eps, k_mb = key, key
        x = meanfield_draws(mu, log_sd, k_eps, n_mc)
        return -(
            e_logp_fn(x, k_mb)
            + gaussian_entropy(dim, jnp.sum(log_sd))
        )

    return neg_elbo
