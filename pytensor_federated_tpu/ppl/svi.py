"""Stochastic variational inference on compiled ``ppl`` programs —
batch mode and STREAMING mode.

Batch mode (:func:`svi_fit`) is mean-field SVI through the shared
ELBO core (:mod:`.elbo`): the whole optimization is one jitted
``lax.scan``, with an optional unbiased minibatch estimator
(``compiled.logp_minibatch``) per step — doubly stochastic VI over
federated shards.

Streaming mode (:class:`StreamingSVI`) is the scenario the exact
NUTS/tempering lane cannot serve (ISSUE 15): optimizer state lives on
the driver, per-shard likelihood+gradient work rides the replica pool
— typically THROUGH the PR-12 gateway (``PoolPlacement`` over a
``TcpArraysClient`` dialed at the front door, per-tenant quotas and
all) — and minibatches arrive as live traffic instead of a schedule.
Every step runs under the PR-10 deadline regime:

- a batch whose windows exceed the step budget is SHED
  (``DeadlineExceeded`` — the gateway/node classification arrives
  in-band) and the optimizer does NOT step;
- a batch denied by the gateway's tenant quota is shed as overload;
- transient transport/compute failures skip the batch loudly;
- a batch is applied at most once — the optimizer's own step counter
  is the proof (``opt_steps == accepted``, the chaos ``--lane
  streaming`` invariant), so shed work can never double-count.

Convergence telemetry rides the PR-11 plane:
``pftpu_svi_batches_total{outcome}``, ``pftpu_svi_elbo``, and
``svi.step`` / ``svi.shed`` flight events.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from ..service import deadline as _deadline
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from .compiler import CompiledModel
from .elbo import gaussian_entropy, meanfield_draws, meanfield_neg_elbo, scan_vi
from .handlers import PPLError

try:
    import optax

    _HAS_OPTAX = True
except ModuleNotFoundError:  # pragma: no cover
    _HAS_OPTAX = False

__all__ = ["StreamingSVI", "SVIResult", "svi_fit"]

SVI_BATCHES = _metrics.counter(
    "pftpu_svi_batches_total",
    "Streaming-SVI minibatch outcomes",
    labelnames=("outcome",),
)
SVI_ELBO = _metrics.gauge(
    "pftpu_svi_elbo", "Latest streaming-SVI ELBO estimate"
)


class SVIResult(NamedTuple):
    """Mean-field fit in user pytree structure (the
    :class:`~..samplers.advi.ADVIResult` contract)."""

    mean: Any
    sd: Any
    elbo_trace: jax.Array
    flat_mean: jax.Array
    flat_log_sd: jax.Array

    def sample(self, key: jax.Array, n: int, unravel: Callable[[jax.Array], Any]) -> Any:
        eps = jax.random.normal(
            key, (n, self.flat_mean.shape[0]), self.flat_mean.dtype
        )
        flat = (
            self.flat_mean[None, :]
            + jnp.exp(self.flat_log_sd)[None, :] * eps
        )
        return jax.vmap(unravel)(flat)


def svi_fit(
    compiled: CompiledModel,
    *,
    key: jax.Array,
    num_steps: int = 1000,
    n_mc: int = 8,
    learning_rate: float = 1e-2,
    init_log_sd: float = -2.0,
    minibatch: bool = False,
    batch_size: Optional[int] = None,
    init_params: Optional[Any] = None,
) -> Tuple[SVIResult, Callable[[jax.Array], Any]]:
    """Batch mean-field SVI on a compiled model; returns ``(result,
    unravel)``.  ``minibatch=True`` estimates each step's logp on a
    random shard subsample (``compiled.logp_minibatch`` — unbiased by
    the plate scaling), so per-step cost drops with the batch while
    the ELBO gradient stays unbiased.  Best with ``placement=None``
    (the scan jits end to end); pool placements should prefer
    :class:`StreamingSVI`."""
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("svi_fit requires optax")
    init = init_params if init_params is not None else compiled.init_params()
    flat0, unravel = ravel_pytree(init)
    dim = int(flat0.shape[0])
    dtype = flat0.dtype

    if minibatch:

        def e_logp_fn(x: jax.Array, k: jax.Array) -> jax.Array:
            keys = jax.random.split(k, x.shape[0])
            vals = jax.vmap(
                lambda xi, ki: compiled.logp_minibatch(
                    unravel(xi), ki, batch_size=batch_size
                )
            )(x, keys)
            return jnp.mean(vals)

    else:
        batch_logp = jax.vmap(lambda xi: compiled.logp(unravel(xi)))

        def e_logp_fn(x: jax.Array, k: jax.Array) -> jax.Array:
            return jnp.mean(batch_logp(x))

    neg_elbo = meanfield_neg_elbo(
        e_logp_fn, dim, n_mc=n_mc, split_keys=minibatch
    )
    var0 = (flat0, jnp.full((dim,), init_log_sd, dtype))
    (mu, log_sd), elbos = scan_vi(
        neg_elbo,
        var0,
        key=key,
        num_steps=num_steps,
        optimizer=optax.adam(learning_rate),
    )
    result = SVIResult(
        mean=unravel(mu),
        sd=unravel(jnp.exp(log_sd)),
        elbo_trace=elbos,
        flat_mean=mu,
        flat_log_sd=log_sd,
    )
    return result, unravel


def _classify_skip(exc: BaseException) -> Optional[str]:
    """Map a step failure to its shed/skip outcome, or None when the
    exception is a programming error that must propagate (the loud
    posture: only CLASSIFIED failures are absorbed).

    Pool windows execute inside ``jax.pure_callback`` under
    ``value_and_grad``, which re-raises host failures as an XLA
    runtime error whose MESSAGE carries the original traceback — so
    classification also matches the in-band deadline/overload strings,
    not just the exception types."""
    text = str(exc)
    if isinstance(exc, PPLError) or "PPLError" in text:
        # A model/contract bug is deterministic: propagate even when
        # the callback layer erased the type (the traceback text
        # still names it) — retrying/skipping forever would be silent.
        return None
    if isinstance(
        exc, _deadline.DeadlineExceeded
    ) or _deadline.is_deadline_error(text):
        return "shed_deadline"
    try:
        from ..gateway.fairness import is_overload_error

        if is_overload_error(text):
            return "shed_overload"
    except ImportError:  # pragma: no cover - gateway always ships
        pass
    if isinstance(exc, (RuntimeError, ValueError, ConnectionError, OSError)):
        return "failed"
    return None


class StreamingSVI:
    """Mean-field SVI whose minibatches arrive as live traffic.

    ``compiled`` is a :class:`~.compiler.CompiledModel`, typically
    with a ``PoolPlacement(TcpArraysClient(gateway_host, gateway_port,
    tenant=...), tag="svi")`` so likelihood windows ride the gateway.
    Each arriving batch is a 1-D array of shard indices (the federated
    minibatch: data never leaves the nodes, only indices and
    parameters travel).  Call :meth:`step` per batch; outcomes are
    ``"accepted"``, ``"shed_deadline"``, ``"shed_overload"``, or
    ``"failed"``.

    Accounting contract (chaos ``--lane streaming`` proves it under
    flapping replicas, a hog tenant, and deadline sheds):

    - ``opt_steps`` (read from the optimizer state itself) ==
      ``accepted`` — a shed batch can NEVER have stepped the
      optimizer, and no batch steps it twice;
    - ``offered == accepted + sum(skipped.values())`` — every batch
      is accounted exactly once;
    - unclassified exceptions propagate (nothing is silently eaten).
    """

    def __init__(
        self,
        compiled: CompiledModel,
        *,
        key: jax.Array,
        learning_rate: float = 5e-2,
        n_mc: int = 2,
        init_log_sd: float = -2.0,
        deadline_s: Optional[float] = None,
        init_params: Optional[Any] = None,
    ) -> None:
        if not _HAS_OPTAX:
            raise ModuleNotFoundError("StreamingSVI requires optax")
        self.compiled = compiled
        self.deadline_s = deadline_s
        self.n_mc = int(n_mc)
        init = (
            init_params if init_params is not None
            else compiled.init_params()
        )
        flat0, self._unravel = ravel_pytree(init)
        self.dim = int(flat0.shape[0])
        self._dtype = flat0.dtype
        self.mu = flat0
        self.log_sd = jnp.full((self.dim,), init_log_sd, self._dtype)
        self._opt = optax.adam(learning_rate)
        self._opt_state = self._opt.init((self.mu, self.log_sd))
        self._key = key
        self.offered = 0
        self.accepted = 0
        self.skipped: Dict[str, int] = {}
        self.elbo_trace: List[float] = []

    # -- accounting ----------------------------------------------------

    @property
    def opt_steps(self) -> int:
        """The optimizer's OWN step counter (optax adam carries one) —
        the ground truth the accepted-batch count is checked against."""
        counts = [
            int(np.asarray(c))
            for c in jax.tree_util.tree_leaves(self._opt_state)
            if jnp.ndim(c) == 0 and jnp.issubdtype(
                jnp.result_type(c), jnp.integer
            )
        ]
        return max(counts) if counts else 0

    # -- the ELBO estimator --------------------------------------------

    def _neg_elbo(
        self,
        var: Tuple[jax.Array, jax.Array],
        key: jax.Array,
        idx: jax.Array,
    ) -> jax.Array:
        mu, log_sd = var
        x = meanfield_draws(mu, log_sd, key, self.n_mc)
        # Python-mean over the MC draws: each draw is one pool window
        # (vmap over a pool-placed program would serialize anyway via
        # the callback's sequential vmap rule).
        terms = [
            self.compiled.logp_indices(self._unravel(x[i]), idx)
            for i in range(self.n_mc)
        ]
        e_logp = sum(terms[1:], terms[0]) / float(self.n_mc)
        return -(e_logp + gaussian_entropy(self.dim, jnp.sum(log_sd)))

    def step(self, batch_idx: Any) -> str:
        """Consume one arriving minibatch (1-D shard-index array).
        Applies at most ONE optimizer update; returns the outcome."""
        self.offered += 1
        self._key, sub = jax.random.split(self._key)
        idx = jnp.asarray(batch_idx, jnp.int32)
        try:
            with _deadline.deadline_scope(self.deadline_s):
                loss, grads = jax.value_and_grad(self._neg_elbo)(
                    (self.mu, self.log_sd), sub, idx
                )
                # Materialize before touching optimizer state: a pool
                # failure must surface HERE, with zero state mutated.
                loss = jax.block_until_ready(loss)
                grads = jax.block_until_ready(grads)
        except Exception as exc:  # noqa: BLE001 - classified below
            outcome = _classify_skip(exc)
            if outcome is None:
                raise
            self.skipped[outcome] = self.skipped.get(outcome, 0) + 1
            SVI_BATCHES.labels(outcome=outcome).inc()
            _flightrec.record(
                "svi.shed",
                outcome=outcome,
                offered=self.offered,
                error=f"{type(exc).__name__}: {str(exc)[:120]}",
            )
            return outcome
        updates, self._opt_state = self._opt.update(
            grads, self._opt_state
        )
        self.mu, self.log_sd = optax.apply_updates(
            (self.mu, self.log_sd), updates
        )
        self.accepted += 1
        elbo = float(-loss)
        self.elbo_trace.append(elbo)
        SVI_BATCHES.labels(outcome="accepted").inc()
        SVI_ELBO.set(elbo)
        _flightrec.record(
            "svi.step",
            step=self.accepted,
            elbo=round(elbo, 3),
            batch=int(idx.shape[0]),
        )
        return "accepted"

    def consume(self, batches: Any) -> Dict[str, int]:
        """Drain an iterable of index batches through :meth:`step`;
        returns the outcome tally."""
        tally: Dict[str, int] = {}
        for batch in batches:
            outcome = self.step(batch)
            tally[outcome] = tally.get(outcome, 0) + 1
        return tally

    def result(self) -> Tuple[SVIResult, Callable[[jax.Array], Any]]:
        """The fit so far, in the :func:`svi_fit` result shape."""
        res = SVIResult(
            mean=self._unravel(self.mu),
            sd=self._unravel(jnp.exp(self.log_sd)),
            elbo_trace=jnp.asarray(self.elbo_trace),
            flat_mean=self.mu,
            flat_log_sd=self.log_sd,
        )
        return res, self._unravel
