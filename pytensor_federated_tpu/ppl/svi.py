"""Stochastic variational inference on compiled ``ppl`` programs —
batch mode and STREAMING mode.

Batch mode (:func:`svi_fit`) is mean-field SVI through the shared
ELBO core (:mod:`.elbo`): the whole optimization is one jitted
``lax.scan``, with an optional unbiased minibatch estimator
(``compiled.logp_minibatch``) per step — doubly stochastic VI over
federated shards.

Streaming mode (:class:`StreamingSVI`) is the scenario the exact
NUTS/tempering lane cannot serve (ISSUE 15): optimizer state lives on
the driver, per-shard likelihood+gradient work rides the replica pool
— typically THROUGH the PR-12 gateway (``PoolPlacement`` over a
``TcpArraysClient`` dialed at the front door, per-tenant quotas and
all) — and minibatches arrive as live traffic instead of a schedule.
Every step runs under the PR-10 deadline regime:

- a batch whose windows exceed the step budget is SHED
  (``DeadlineExceeded`` — the gateway/node classification arrives
  in-band) and the optimizer does NOT step;
- a batch denied by the gateway's tenant quota is shed as overload;
- transient transport/compute failures skip the batch loudly;
- a batch is applied at most once — the optimizer's own step counter
  is the proof (``opt_steps == accepted``, the chaos ``--lane
  streaming`` invariant), so shed work can never double-count.

Convergence telemetry rides the PR-11 plane:
``pftpu_svi_batches_total{outcome}``, ``pftpu_svi_elbo``, and
``svi.step`` / ``svi.shed`` flight events.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from ..service import deadline as _deadline
from ..service.npwire import WireError as _WireError
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from .compiler import CompiledModel
from .elbo import gaussian_entropy, meanfield_draws, meanfield_neg_elbo, scan_vi
from .handlers import PPLError

try:
    import optax

    _HAS_OPTAX = True
except ModuleNotFoundError:  # pragma: no cover
    _HAS_OPTAX = False

__all__ = [
    "StreamingSVI",
    "SVIResult",
    "make_meanfield_neg_elbo",
    "make_sharded_update_compute",
    "svi_fit",
]

SVI_BATCHES = _metrics.counter(
    "pftpu_svi_batches_total",
    "Streaming-SVI minibatch outcomes",
    labelnames=("outcome",),
)
SVI_ELBO = _metrics.gauge(
    "pftpu_svi_elbo", "Latest streaming-SVI ELBO estimate"
)


class SVIResult(NamedTuple):
    """Mean-field fit in user pytree structure (the
    :class:`~..samplers.advi.ADVIResult` contract)."""

    mean: Any
    sd: Any
    elbo_trace: jax.Array
    flat_mean: jax.Array
    flat_log_sd: jax.Array

    def sample(self, key: jax.Array, n: int, unravel: Callable[[jax.Array], Any]) -> Any:
        eps = jax.random.normal(
            key, (n, self.flat_mean.shape[0]), self.flat_mean.dtype
        )
        flat = (
            self.flat_mean[None, :]
            + jnp.exp(self.flat_log_sd)[None, :] * eps
        )
        return jax.vmap(unravel)(flat)


def svi_fit(
    compiled: CompiledModel,
    *,
    key: jax.Array,
    num_steps: int = 1000,
    n_mc: int = 8,
    learning_rate: float = 1e-2,
    init_log_sd: float = -2.0,
    minibatch: bool = False,
    batch_size: Optional[int] = None,
    init_params: Optional[Any] = None,
) -> Tuple[SVIResult, Callable[[jax.Array], Any]]:
    """Batch mean-field SVI on a compiled model; returns ``(result,
    unravel)``.  ``minibatch=True`` estimates each step's logp on a
    random shard subsample (``compiled.logp_minibatch`` — unbiased by
    the plate scaling), so per-step cost drops with the batch while
    the ELBO gradient stays unbiased.  Best with ``placement=None``
    (the scan jits end to end); pool placements should prefer
    :class:`StreamingSVI`."""
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("svi_fit requires optax")
    init = init_params if init_params is not None else compiled.init_params()
    flat0, unravel = ravel_pytree(init)
    dim = int(flat0.shape[0])
    dtype = flat0.dtype

    if minibatch:

        def e_logp_fn(x: jax.Array, k: jax.Array) -> jax.Array:
            keys = jax.random.split(k, x.shape[0])
            vals = jax.vmap(
                lambda xi, ki: compiled.logp_minibatch(
                    unravel(xi), ki, batch_size=batch_size
                )
            )(x, keys)
            return jnp.mean(vals)

    else:
        batch_logp = jax.vmap(lambda xi: compiled.logp(unravel(xi)))

        def e_logp_fn(x: jax.Array, k: jax.Array) -> jax.Array:
            return jnp.mean(batch_logp(x))

    neg_elbo = meanfield_neg_elbo(
        e_logp_fn, dim, n_mc=n_mc, split_keys=minibatch
    )
    var0 = (flat0, jnp.full((dim,), init_log_sd, dtype))
    (mu, log_sd), elbos = scan_vi(
        neg_elbo,
        var0,
        key=key,
        num_steps=num_steps,
        optimizer=optax.adam(learning_rate),
    )
    result = SVIResult(
        mean=unravel(mu),
        sd=unravel(jnp.exp(log_sd)),
        elbo_trace=elbos,
        flat_mean=mu,
        flat_log_sd=log_sd,
    )
    return result, unravel


def make_meanfield_neg_elbo(
    compiled: CompiledModel,
    unravel: Callable[[jax.Array], Any],
    dim: int,
    n_mc: int,
) -> Callable[..., jax.Array]:
    """The ONE streaming neg-ELBO estimator, shared by the
    driver-centric lane (:meth:`StreamingSVI._neg_elbo`) and the
    sharded-optimizer node compute
    (:func:`make_sharded_update_compute`) — the two lanes
    differentiate the SAME function with the same RNG stream, which is
    why their parameter trajectories are bit-identical on CPU
    (property-tested in tests/test_optim.py)."""

    def neg_elbo(
        var: Tuple[jax.Array, jax.Array],
        key: jax.Array,
        idx: jax.Array,
    ) -> jax.Array:
        mu, log_sd = var
        x = meanfield_draws(mu, log_sd, key, n_mc)
        # Python-mean over the MC draws: each draw is one pool window
        # (vmap over a pool-placed program would serialize anyway via
        # the callback's sequential vmap rule).
        terms = [
            compiled.logp_indices(unravel(x[i]), idx)
            for i in range(n_mc)
        ]
        e_logp = sum(terms[1:], terms[0]) / float(n_mc)
        return -(e_logp + gaussian_entropy(dim, jnp.sum(log_sd)))

    return neg_elbo


def make_sharded_update_compute(
    compiled: CompiledModel,
    store: Any,
    *,
    learning_rate: float = 5e-2,
    n_mc: int = 2,
    init_params: Optional[Any] = None,
) -> Callable[..., list]:
    """The OWNER-replica compute of a sharded streaming-SVI group
    (ISSUE 16): wraps :func:`~..optim.sharded.make_update_compute`
    around this model's neg-ELBO gradient.  Requests carry
    ``[mu, log_sd, rng_key, idx]`` (the driver's step inputs, params
    broadcast whole so the PR-9 pin cache absorbs them); the node
    differentiates the same estimator the driver lane uses, slices its
    owned shard of the flat ``concat(mu, log_sd)`` vector, applies
    ``optax.adam(learning_rate)`` on the slice, and checkpoints into
    ``store`` (a :class:`~..optim.state.ShardStore`) before replying.

    Every owner of one group must be built with the SAME
    ``learning_rate``/``n_mc``/``init_params`` — the shard version
    protocol catches drift in TIME, not in hyperparameters."""
    if not _HAS_OPTAX:
        raise ModuleNotFoundError(
            "make_sharded_update_compute requires optax"
        )
    from ..optim.sharded import make_update_compute

    init = (
        init_params if init_params is not None else compiled.init_params()
    )
    flat0, unravel = ravel_pytree(init)
    dim = int(flat0.shape[0])
    neg_elbo = make_meanfield_neg_elbo(compiled, unravel, dim, int(n_mc))

    # Deliberately NOT jitted: XLA fusion changes rounding at the ULP
    # level on CPU (measured: 3.7e-9 drift on the radon example), and
    # the owner must stay BIT-identical to the driver lane's eager
    # value_and_grad — the subsystem's exactness contract
    # (tests/test_optim.py).  The eager retrace is per-call dispatch
    # overhead both lanes pay equally.
    def grad_fn(
        mu: np.ndarray,
        log_sd: np.ndarray,
        key_data: np.ndarray,
        idx: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        loss, (g_mu, g_log_sd) = jax.value_and_grad(neg_elbo)(
            (jnp.asarray(mu), jnp.asarray(log_sd)),
            jnp.asarray(key_data),
            jnp.asarray(idx, jnp.int32),
        )
        return np.asarray(loss), np.concatenate(
            [np.asarray(g_mu).ravel(), np.asarray(g_log_sd).ravel()]
        )

    def params_of(arrays: Any) -> np.ndarray:
        return np.concatenate(
            [np.asarray(arrays[0]).ravel(), np.asarray(arrays[1]).ravel()]
        )

    return make_update_compute(
        grad_fn,
        optax.adam(learning_rate),
        store,
        params_of=params_of,
    )


def _classify_skip(exc: BaseException) -> Optional[str]:
    """Map a step failure to its shed/skip outcome, or None when the
    exception is a programming error that must propagate (the loud
    posture: only CLASSIFIED failures are absorbed).

    Pool windows execute inside ``jax.pure_callback`` under
    ``value_and_grad``, which re-raises host failures as an XLA
    runtime error whose MESSAGE carries the original traceback — so
    classification also matches the in-band deadline/overload strings,
    not just the exception types."""
    text = str(exc)
    if isinstance(exc, PPLError) or "PPLError" in text:
        # A model/contract bug is deterministic: propagate even when
        # the callback layer erased the type (the traceback text
        # still names it) — retrying/skipping forever would be silent.
        return None
    if isinstance(
        exc, _deadline.DeadlineExceeded
    ) or _deadline.is_deadline_error(text):
        return "shed_deadline"
    try:
        from ..gateway.fairness import is_overload_error

        if is_overload_error(text):
            return "shed_overload"
    except ImportError:  # pragma: no cover - gateway always ships
        pass
    if isinstance(exc, (RuntimeError, ValueError, ConnectionError, OSError)):
        return "failed"
    return None


class StreamingSVI:
    """Mean-field SVI whose minibatches arrive as live traffic.

    ``compiled`` is a :class:`~.compiler.CompiledModel`, typically
    with a ``PoolPlacement(TcpArraysClient(gateway_host, gateway_port,
    tenant=...), tag="svi")`` so likelihood windows ride the gateway.
    Each arriving batch is a 1-D array of shard indices (the federated
    minibatch: data never leaves the nodes, only indices and
    parameters travel).  Call :meth:`step` per batch; outcomes are
    ``"accepted"``, ``"shed_deadline"``, ``"shed_overload"``, or
    ``"failed"``.

    Accounting contract (chaos ``--lane streaming`` proves it under
    flapping replicas, a hog tenant, and deadline sheds):

    - ``opt_steps`` (read from the optimizer state itself) ==
      ``accepted`` — a shed batch can NEVER have stepped the
      optimizer, and no batch steps it twice;
    - ``offered == accepted + sum(skipped.values())`` — every batch
      is accounted exactly once;
    - unclassified exceptions propagate (nothing is silently eaten).

    **Sharded mode** (ISSUE 16): pass ``sharded=`` a
    :class:`~..optim.sharded.ShardedOptimizer` whose owner replicas
    run :func:`make_sharded_update_compute` for this model.  Optimizer
    state then lives ON the owners (``O(model/N)`` each — the driver
    holds no adam state and never sees a gradient), each step
    dispatches one versioned update per shard, and the accounting
    contract becomes PER SHARD: ``shard_opt_steps[k] ==
    shard_accepted[k]`` for every shard, under chaos (the ``--lane
    zero`` invariant — a killed owner's shard restores from its
    checkpoint or refuses loudly, never double-steps).
    ``minibatch_mode="shared"`` sends every owner the same index batch
    (trajectories bit-identical to driver-centric mode);
    ``"split"`` gives each owner a disjoint slice of the batch (same
    total compute, per-shard estimators stay unbiased).
    """

    def __init__(
        self,
        compiled: CompiledModel,
        *,
        key: jax.Array,
        learning_rate: float = 5e-2,
        n_mc: int = 2,
        init_log_sd: float = -2.0,
        deadline_s: Optional[float] = None,
        init_params: Optional[Any] = None,
        sharded: Optional[Any] = None,
        minibatch_mode: str = "shared",
    ) -> None:
        if not _HAS_OPTAX:
            raise ModuleNotFoundError("StreamingSVI requires optax")
        self.compiled = compiled
        self.deadline_s = deadline_s
        self.n_mc = int(n_mc)
        init = (
            init_params if init_params is not None
            else compiled.init_params()
        )
        flat0, self._unravel = ravel_pytree(init)
        self.dim = int(flat0.shape[0])
        self._dtype = flat0.dtype
        self.mu = flat0
        self.log_sd = jnp.full((self.dim,), init_log_sd, self._dtype)
        self._neg_elbo_fn = make_meanfield_neg_elbo(
            compiled, self._unravel, self.dim, self.n_mc
        )
        if minibatch_mode not in ("shared", "split"):
            raise ValueError(
                f"minibatch_mode must be 'shared' or 'split', got "
                f"{minibatch_mode!r}"
            )
        self.minibatch_mode = minibatch_mode
        self._sharded = sharded
        if sharded is not None:
            if sharded.total != 2 * self.dim:
                raise ValueError(
                    f"sharded optimizer covers {sharded.total} elements "
                    f"but this model's flat (mu, log_sd) vector has "
                    f"{2 * self.dim}"
                )
            # No driver-side optimizer: adam state lives on the owners.
            self._opt = None
            self._opt_state = None
            self.shard_accepted: List[int] = [0] * sharded.count
        else:
            self._opt = optax.adam(learning_rate)
            self._opt_state = self._opt.init((self.mu, self.log_sd))
            self.shard_accepted = []
        self._key = key
        self.offered = 0
        self.accepted = 0
        self.skipped: Dict[str, int] = {}
        self.elbo_trace: List[float] = []

    # -- accounting ----------------------------------------------------

    @property
    def opt_steps(self) -> int:
        """The optimizer's OWN step counter — the ground truth the
        accepted-batch count is checked against.  Driver-centric mode
        reads optax adam's count; sharded mode reads the MINIMUM shard
        version (the steps completed on EVERY shard — per-shard truth
        is :attr:`shard_opt_steps`)."""
        if self._sharded is not None:
            return min(self._sharded.versions)
        counts = [
            int(np.asarray(c))
            for c in jax.tree_util.tree_leaves(self._opt_state)
            if jnp.ndim(c) == 0 and jnp.issubdtype(
                jnp.result_type(c), jnp.integer
            )
        ]
        return max(counts) if counts else 0

    @property
    def shard_opt_steps(self) -> List[int]:
        """Sharded mode: each shard's step version — the OWNER-side
        adam step counter (the version IS the count).  The per-shard
        invariant is ``shard_opt_steps[k] == shard_accepted[k]``."""
        if self._sharded is None:
            raise RuntimeError("shard_opt_steps needs sharded mode")
        return list(self._sharded.versions)

    # -- the ELBO estimator --------------------------------------------

    def _neg_elbo(
        self,
        var: Tuple[jax.Array, jax.Array],
        key: jax.Array,
        idx: jax.Array,
    ) -> jax.Array:
        # Delegates to the shared estimator so the driver-centric lane
        # and the sharded owner compute differentiate the SAME function
        # (the bit-identical-trajectory precondition).
        return self._neg_elbo_fn(var, key, idx)

    def step(self, batch_idx: Any) -> str:
        """Consume one arriving minibatch (1-D shard-index array).
        Applies at most ONE optimizer update; returns the outcome."""
        self.offered += 1
        self._key, sub = jax.random.split(self._key)
        idx = jnp.asarray(batch_idx, jnp.int32)
        if self._sharded is not None:
            return self._step_sharded(sub, idx)
        try:
            with _deadline.deadline_scope(self.deadline_s):
                loss, grads = jax.value_and_grad(self._neg_elbo)(
                    (self.mu, self.log_sd), sub, idx
                )
                # Materialize before touching optimizer state: a pool
                # failure must surface HERE, with zero state mutated.
                loss = jax.block_until_ready(loss)
                grads = jax.block_until_ready(grads)
        except Exception as exc:  # noqa: BLE001 - classified below
            outcome = _classify_skip(exc)
            if outcome is None:
                raise
            self.skipped[outcome] = self.skipped.get(outcome, 0) + 1
            SVI_BATCHES.labels(outcome=outcome).inc()
            _flightrec.record(
                "svi.shed",
                outcome=outcome,
                offered=self.offered,
                error=f"{type(exc).__name__}: {str(exc)[:120]}",
            )
            return outcome
        updates, self._opt_state = self._opt.update(
            grads, self._opt_state
        )
        self.mu, self.log_sd = optax.apply_updates(
            (self.mu, self.log_sd), updates
        )
        self.accepted += 1
        elbo = float(-loss)
        self.elbo_trace.append(elbo)
        SVI_BATCHES.labels(outcome="accepted").inc()
        SVI_ELBO.set(elbo)
        _flightrec.record(
            "svi.step",
            step=self.accepted,
            elbo=round(elbo, 3),
            batch=int(idx.shape[0]),
        )
        return "accepted"

    def _step_sharded(self, sub: jax.Array, idx: jax.Array) -> str:
        """One sharded-optimizer step (ISSUE 16): dispatch a versioned
        update to every owner, fold the returned slices into the
        driver's parameter copy.  A failed shard sheds only ITSELF —
        its version (and so its accepted count) does not move, which is
        exactly the per-shard ``opt_steps == accepted`` invariant; the
        BATCH counts accepted only when every shard accepted."""
        opt = self._sharded
        mu_np = np.asarray(self.mu)
        log_sd_np = np.asarray(self.log_sd)
        key_np = np.asarray(sub)
        idx_np = np.asarray(idx, np.int32)
        if self.minibatch_mode == "shared":
            arrays_for: Any = [mu_np, log_sd_np, key_np, idx_np]
        else:
            slices = np.array_split(idx_np, opt.count)

            def arrays_for(
                k: int, part: Any, _s: List[np.ndarray] = slices
            ) -> List[np.ndarray]:
                return [mu_np, log_sd_np, key_np, _s[k]]

        try:
            with _deadline.deadline_scope(self.deadline_s):
                results = opt.step(arrays_for)
        except Exception as exc:  # noqa: BLE001 - classified below
            # A raise out of ShardedOptimizer.step is version
            # divergence or a protocol/geometry violation (per-shard
            # transport failures come back as ShardResults) — that is
            # corruption, never a sheddable batch: propagate.
            if isinstance(exc, _WireError):
                raise
            outcome = _classify_skip(exc)
            if outcome is None:
                raise
            self.skipped[outcome] = self.skipped.get(outcome, 0) + 1
            SVI_BATCHES.labels(outcome=outcome).inc()
            _flightrec.record(
                "svi.shed",
                outcome=outcome,
                offered=self.offered,
                error=f"{type(exc).__name__}: {str(exc)[:120]}",
            )
            return outcome
        flat = np.concatenate([mu_np.ravel(), log_sd_np.ravel()])
        new_flat, accepted_shards = opt.apply(flat, results)
        for k in accepted_shards:
            self.shard_accepted[k] += 1
        self.mu = jnp.asarray(new_flat[: self.dim], self._dtype)
        self.log_sd = jnp.asarray(new_flat[self.dim :], self._dtype)
        failures = [r for r in results if not r.accepted]
        if failures:
            first = next(
                (r.error for r in failures if r.error is not None), None
            )
            outcome = (
                _classify_skip(first) if first is not None else "failed"
            )
            if outcome is None:
                raise first  # unclassified: the loud posture
            self.skipped[outcome] = self.skipped.get(outcome, 0) + 1
            SVI_BATCHES.labels(outcome=outcome).inc()
            _flightrec.record(
                "svi.shed",
                outcome=outcome,
                offered=self.offered,
                shards_failed=[r.index for r in failures],
                error=f"{type(first).__name__}: {str(first)[:120]}"
                if first is not None
                else "",
            )
            return outcome
        self.accepted += 1
        losses = [r.loss for r in results if r.loss is not None]
        if losses:
            elbo = float(-np.mean(losses))
            self.elbo_trace.append(elbo)
            SVI_ELBO.set(elbo)
        SVI_BATCHES.labels(outcome="accepted").inc()
        _flightrec.record(
            "svi.step",
            step=self.accepted,
            elbo=round(self.elbo_trace[-1], 3) if self.elbo_trace else None,
            batch=int(idx_np.shape[0]),
            sharded=True,
        )
        return "accepted"

    def consume(self, batches: Any) -> Dict[str, int]:
        """Drain an iterable of index batches through :meth:`step`;
        returns the outcome tally."""
        tally: Dict[str, int] = {}
        for batch in batches:
            outcome = self.step(batch)
            tally[outcome] = tally.get(outcome, 0) + 1
        return tally

    def result(self) -> Tuple[SVIResult, Callable[[jax.Array], Any]]:
        """The fit so far, in the :func:`svi_fit` result shape."""
        res = SVIResult(
            mean=self._unravel(self.mu),
            sd=self._unravel(jnp.exp(self.log_sd)),
            elbo_trace=jnp.asarray(self.elbo_trace),
            flat_mean=self.mu,
            flat_log_sd=self.log_sd,
        )
        return res, self._unravel
