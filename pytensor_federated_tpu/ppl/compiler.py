"""Compile an effectful model to a placement-lowered ``fed.program``.

The DrJAX correspondence (PAPERS.md): a model's outermost
:class:`~.handlers.plate` IS the federated shard axis, so the plate's
likelihood (and its plate-local latent priors) lowers to
``fed_sum(fed_map(per_shard, ...))`` — the canonical
broadcast→map→sum round every placement in :mod:`..fed` already
executes — while the global prior stays a driver-side term.  One
model definition therefore runs on mesh devices, RPC pools, or a mix,
and the SAME per-shard function deploys to nodes
(:meth:`CompiledModel.node_compute`), so driver and node cannot
drift.

Mechanics: the compiler never inspects model source.  It re-RUNS the
model under handlers —

- discovery: ``trace(seed(model))`` finds the sites, the plates, and
  the parameter shapes;
- per-shard: ``force_subsample({plate: [sid]}, scale=False)`` +
  ``substitute(params)`` evaluates exactly one shard's plate-scoped
  terms (``sid`` is a traced shard id riding ``fed_map`` as an
  integer data leaf; parameters broadcast whole and the plate gathers
  the shard's rows — the ``jnp.take(..., sid)`` idiom of
  ``models/hierbase.py``, which keeps every inexact mapped operand
  broadcast-derived so the PR-13 reduced-window lowering stays
  eligible);
- prior: the same with the plate pinned to one shard, summing only
  the NON-plate sites.

The subsample lane (:meth:`CompiledModel.logp_indices` /
:meth:`CompiledModel.logp_minibatch`) maps ``fed_map`` over an index
batch instead of ``arange(n_shards)`` and scales the plate terms by
``size/batch`` — the unbiased minibatch estimator streaming SVI
consumes (E[scaled minibatch logp] == full-data logp, property-tested
in tests/test_ppl.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import tree_util

from ..fed.lowering import canonical_round, program as fed_program
from ..fed.placements import MeshPlacement, Placement, make_node_compute
from ..fed.primitives import fed_broadcast, fed_map, fed_sum
from .handlers import (
    Message,
    PPLError,
    force_subsample,
    seed,
    substitute,
    trace,
)

__all__ = ["CompiledModel", "compile", "log_density"]

Params = Dict[str, Any]


def site_log_prob(site: Message) -> jax.Array:
    """One site's total log-density term: masked, scaled, summed."""
    lp = site["dist"].log_prob(site["value"])
    if site["mask"] is not None:
        lp = lp * site["mask"]
    return site["scale"] * jnp.sum(lp)


def log_density(
    model: Callable[..., Any],
    model_args: Tuple[Any, ...],
    params: Params,
) -> jax.Array:
    """Direct (non-federated) log-density of ``model`` at ``params``:
    run the model under ``substitute`` and sum every sample site's
    term.  The reference evaluation the compiled lanes are checked
    against; a latent missing from ``params`` is a loud
    :class:`~.handlers.PPLError`."""
    tr = trace(substitute(model, data=dict(params))).get_trace(*model_args)
    total = jnp.zeros(())
    for site in tr.values():
        if site["type"] == "sample":
            total = total + site_log_prob(site)
    return total


def _in_plate(site: Message, plate_name: str) -> bool:
    return any(f.name == plate_name for f in site["plates"])


class CompiledModel:
    """One effectful model, every lane (see module docstring).

    Surfaces:

    - :meth:`logp` / :meth:`logp_and_grad` — full-data log density
      under the placement (``jax.grad`` works through all lanes).
    - :meth:`logp_indices` / :meth:`logp_minibatch` — the unbiased
      scaled estimator over a shard-index batch (the SVI lanes).
    - :meth:`node_compute` — the per-shard ``[logp, *grads]`` compute
      a pool replica deploys (``service.run_node`` /
      ``serve_tcp_once``), built from the same per-shard function the
      driver maps.
    - :attr:`fed_model` / :meth:`fed_batch_model` — the placement-free
      primitive-level programs (flat parameter leaves), which is what
      the ``fed-placement`` lint fixtures trace.
    - :meth:`init_params` / :meth:`sample_prior` — parameter pytrees
      shaped for the samplers.
    """

    def __init__(
        self,
        model: Callable[..., Any],
        model_args: Tuple[Any, ...] = (),
        *,
        placement: Optional[Placement] = None,
        plate: Optional[str] = None,
        batch_size: Optional[int] = None,
        fuse: bool = True,
    ) -> None:
        self.model = model
        self.model_args = tuple(model_args)
        self.placement = placement
        self._fuse = fuse

        # -- discovery pass 1: sites and plates ------------------------
        tr = trace(seed(model, rng_key=jax.random.PRNGKey(0))).get_trace(
            *self.model_args
        )
        outer: Dict[str, int] = {}
        for site in tr.values():
            if site["plates"]:
                frame = site["plates"][0]
                outer[frame.name] = frame.size
        if plate is None:
            if len(outer) != 1:
                raise PPLError(
                    "compile() needs exactly one outermost plate to map "
                    f"onto shards; found {sorted(outer) or 'none'} — "
                    "pass plate=<name> to pick one"
                )
            plate = next(iter(outer))
        if plate not in outer:
            raise PPLError(
                f"plate {plate!r} not found in the model (outermost "
                f"plates: {sorted(outer)})"
            )
        self.plate_name: str = plate
        self.plate_size: int = outer[plate]
        self.n_shards: int = self.plate_size

        # -- discovery pass 2: full-size parameter template ------------
        # Forcing every plate to its full index set makes plate-local
        # latents draw at FULL size even when the author declared
        # subsample_size (the template must cover every shard's rows).
        full = {
            name: jnp.arange(size) for name, size in outer.items()
        }
        tracer = trace(seed(model, rng_key=jax.random.PRNGKey(0)))
        with force_subsample(indices=full, scale=False):
            full_trace = tracer.get_trace(*self.model_args)
        self.local_sites: List[str] = []
        self.global_sites: List[str] = []
        template: Params = {}
        batch_default: Optional[int] = None
        for site in full_trace.values():
            if site["type"] != "sample":
                continue
            if site["observed"]:
                continue
            name = site["name"]
            template[name] = jnp.zeros_like(site["value"])
            if _in_plate(site, self.plate_name):
                self.local_sites.append(name)
            else:
                self.global_sites.append(name)
        for site in tr.values():
            for frame in site["plates"]:
                if (
                    frame.name == self.plate_name
                    and frame.effective < frame.size
                ):
                    batch_default = frame.effective
        self._template = template
        self._treedef = tree_util.tree_structure(template)
        self.batch_size = batch_size or batch_default
        if not template:
            raise PPLError("model has no latent sample sites")

        if isinstance(placement, MeshPlacement):
            axis_size = placement.mesh.shape[placement.axis]
            if self.n_shards % axis_size != 0:
                raise PPLError(
                    f"plate {plate!r} has {self.n_shards} shards, not "
                    f"divisible by mesh axis {placement.axis!r} of size "
                    f"{axis_size}"
                )

        sids = jnp.arange(self.n_shards, dtype=jnp.int32)
        self._round = canonical_round(
            self._flat_per_shard, sids, self.n_shards
        )
        self._program = fed_program(
            self.fed_model, placement=placement, fuse=fuse
        )
        self._batch_programs: Dict[int, Callable[..., Any]] = {}

    # -- parameter plumbing -------------------------------------------

    def init_params(self) -> Params:
        """Zero-initialized parameter pytree (one entry per latent)."""
        return {k: jnp.zeros_like(v) for k, v in self._template.items()}

    def sample_prior(self, key: jax.Array) -> Params:
        """One full-size draw from the prior, shaped like
        :meth:`init_params`."""
        full = {self.plate_name: jnp.arange(self.plate_size)}
        tracer = trace(seed(self.model, rng_key=key))
        with force_subsample(indices=full, scale=False):
            tr = tracer.get_trace(*self.model_args)
        return {name: tr[name]["value"] for name in self._template}

    def _leaves(self, params: Params) -> List[Any]:
        leaves, treedef = tree_util.tree_flatten(params)
        if treedef != self._treedef:
            raise PPLError(
                f"params structure mismatch: expected latent sites "
                f"{sorted(self._template)}, got "
                f"{sorted(params) if isinstance(params, dict) else type(params)}"
            )
        return leaves

    def _unflatten(self, leaves: Tuple[Any, ...]) -> Params:
        return tree_util.tree_unflatten(self._treedef, list(leaves))

    # -- the effectful re-runs ----------------------------------------

    def _site_sum(
        self, params: Params, idx: jax.Array, *, in_plate: bool
    ) -> jax.Array:
        tracer = trace(substitute(self.model, data=dict(params)))
        with force_subsample(
            indices={self.plate_name: idx}, scale=False
        ):
            tr = tracer.get_trace(*self.model_args)
        total = jnp.zeros(())
        for site in tr.values():
            if site["type"] != "sample":
                continue
            if _in_plate(site, self.plate_name) != in_plate:
                continue
            total = total + site_log_prob(site)
        return total

    def _flat_per_shard(self, *args: Any) -> jax.Array:
        """Per-shard plate logp over flat wire operands
        ``(params leaves..., sid)`` — the pool wire contract and the
        ``fed_map`` body, one function."""
        leaves, sid = args[:-1], args[-1]
        params = self._unflatten(leaves)
        idx = jnp.asarray(sid, jnp.int32).reshape((1,))
        return self._site_sum(params, idx, in_plate=True)

    def prior_logp(self, params: Params) -> jax.Array:
        """The driver-side global prior (every non-plate site)."""
        idx = jnp.zeros((1,), jnp.int32)
        return self._site_sum(params, idx, in_plate=False)

    # -- placement-free fed programs (lint fixtures trace these) ------

    def fed_model(self, *leaves: Any) -> jax.Array:
        """Full-data placement-free program over flat parameter
        leaves: ``prior + fed_sum(fed_map(per_shard, shard_ids))``."""
        return self.prior_logp(self._unflatten(leaves)) + self._round(
            *leaves
        )

    def fed_batch_model(self, m: int) -> Callable[..., jax.Array]:
        """The subsample program for batches of ``m`` shard indices:
        ``(*param_leaves, idx) -> prior + (size/m) * fed_sum(...)`` —
        the index batch rides ``fed_map`` as an integer data leaf."""
        m = int(m)
        if not (1 <= m <= self.n_shards):
            raise PPLError(
                f"batch size {m} not in 1..{self.n_shards}"
            )
        scale = self.plate_size / m

        def batch_model(*args: Any) -> jax.Array:
            leaves, idx = args[:-1], args[-1]
            pb = fed_broadcast(tuple(leaves), m)
            lps = fed_map(
                lambda shard: self._flat_per_shard(*shard[0], shard[1]),
                (pb, idx),
            )
            return self.prior_logp(
                self._unflatten(leaves)
            ) + scale * fed_sum(lps)

        return batch_model

    # -- the public evaluation surface --------------------------------

    def logp(self, params: Params) -> jax.Array:
        """Full-data log density under the placement."""
        return self._program(*self._leaves(params))

    def logp_and_grad(self, params: Params) -> Tuple[jax.Array, Params]:
        return jax.value_and_grad(self.logp)(params)

    def logp_indices(self, params: Params, idx: Any) -> jax.Array:
        """Scaled plate logp over an explicit shard-index batch (1-D
        int array): ``prior + (size/len(idx)) * Σ_plate``.  With
        ``idx = arange(n_shards)`` this equals :meth:`logp`."""
        idx = jnp.asarray(idx, jnp.int32)
        if idx.ndim != 1:
            raise PPLError(
                f"idx must be 1-D shard indices, got shape "
                f"{tuple(idx.shape)}"
            )
        m = int(idx.shape[0])
        prog = self._batch_programs.get(m)
        if prog is None:
            prog = self._batch_programs[m] = fed_program(
                self.fed_batch_model(m),
                placement=self.placement,
                fuse=self._fuse,
            )
        return prog(*self._leaves(params), idx)

    def logp_minibatch(
        self,
        params: Params,
        key: jax.Array,
        *,
        batch_size: Optional[int] = None,
    ) -> jax.Array:
        """Unbiased scaled logp over a random minibatch of shards
        (without replacement).  ``batch_size`` defaults to the plate's
        declared ``subsample_size``."""
        m = batch_size or self.batch_size
        if m is None:
            raise PPLError(
                "no batch size: declare subsample_size on the plate or "
                "pass batch_size="
            )
        idx = jax.random.choice(
            key, self.n_shards, (int(m),), replace=False
        )
        return self.logp_indices(params, idx)

    # -- node deployment ----------------------------------------------

    def node_compute(self, *, grads: bool = True) -> Callable[..., list]:
        """Node-side compute matching the wire contract of this
        model's pool-placed ``fed_map``: requests carry
        ``(params leaves..., shard_id)``; replies ``[logp, *grads]``.
        Built from the SAME per-shard function the driver maps."""
        return make_node_compute(self._flat_per_shard, grads=grads)


def compile(
    model: Callable[..., Any],
    model_args: Tuple[Any, ...] = (),
    *,
    placement: Optional[Placement] = None,
    plate: Optional[str] = None,
    batch_size: Optional[int] = None,
    fuse: bool = True,
) -> CompiledModel:
    """Compile an effectful model to a placement-lowered federated
    program — see :class:`CompiledModel`."""
    return CompiledModel(
        model,
        model_args,
        placement=placement,
        plate=plate,
        batch_size=batch_size,
        fuse=fuse,
    )
