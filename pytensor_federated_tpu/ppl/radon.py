"""The hierarchical radon GLM, written ONCE as an effectful model.

The ISSUE-15 endgame demo: the same model the repo ships hand-written
(``models/glm.py`` — the BASELINE "PyMC hierarchical radon GLM"
config) expressed through the effect layer, so ONE definition drives
every execution mode: direct log-density, NUTS, parallel tempering,
batch SVI, and streaming SVI through the gateway (tutorial §24;
bench_suite config 20 measures posterior-quality-vs-wall-clock).

Scales are log-parameterized through :class:`~.distributions.
HalfNormalLog` — the same HalfNormal(1)-with-Jacobian term
``models/glm.py`` writes by hand — so the parameter vector is fully
unconstrained and plugs straight into the samplers.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax.numpy as jnp

from ..models.glm import generate_radon_data
from .distributions import HalfNormalLog, Normal
from .handlers import deterministic, plate, sample, subsample

__all__ = ["make_radon_example", "radon_model"]


def radon_model(floor: Any, log_radon: Any, mask: Any) -> None:
    """Partial-pooling radon GLM over county shards (one county = one
    plate position = one federated shard).  Arguments are the packed
    ``(n_counties, n_obs)`` arrays from
    :func:`~..models.glm.generate_radon_data`."""
    mu_alpha = sample("mu_alpha", Normal(0.0, 10.0))
    log_sigma_alpha = sample("log_sigma_alpha", HalfNormalLog(1.0))
    beta = sample("beta", Normal(0.0, 10.0))
    log_sigma = sample("log_sigma", HalfNormalLog(1.0))
    with plate("county", int(floor.shape[0])) as county:
        alpha_raw = sample("alpha_raw", Normal(0.0, 1.0))
        alpha = deterministic(
            "alpha", mu_alpha + jnp.exp(log_sigma_alpha) * alpha_raw
        )
        f = subsample(floor, county)
        y = subsample(log_radon, county)
        m = subsample(mask, county)
        eta = alpha[:, None] + beta * f
        sample(
            "obs",
            Normal(eta, jnp.exp(log_sigma)),
            obs=y,
            mask=m,
        )


def make_radon_example(
    n_counties: int = 16,
    *,
    mean_obs: int = 24,
    seed: int = 11,
) -> Tuple[Callable[..., None], Tuple[Any, ...], dict]:
    """Synthetic radon data packed for the effectful model: returns
    ``(model, model_args, true_params)`` ready for
    ``ppl.compile(model, model_args, ...)``."""
    data, true = generate_radon_data(
        n_counties, mean_obs=mean_obs, seed=seed
    )
    (floor, y), mask = data.tree()
    return radon_model, (floor, y, mask), true
