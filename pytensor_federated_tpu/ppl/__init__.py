"""``ppl`` — an effect-handler probabilistic front end that compiles
plate-structured models to ``fed.program`` (ISSUE 15).

One model definition, every execution mode (the NumPyro composable-
effects design, PAPERS.md): probabilistic statements —
:func:`sample`, :func:`deterministic`, :class:`plate`,
:func:`subsample` — emit messages through composable handlers
(:class:`trace`, :class:`replay`, :class:`condition`,
:class:`substitute`, :class:`seed`, :class:`block`), and the compiler
(:func:`compile`) maps the outermost plate onto the existing
``fed_map``/``fed_sum`` primitives (the DrJAX plate→MapReduce
correspondence), so the same model runs

- directly (:func:`log_density`),
- under NUTS / tempering (``samplers.sample(compiled.logp, ...)``),
- as batch SVI through the shared ELBO core (:func:`svi_fit` —
  which ``samplers/advi.py`` and ``samplers/flows.py`` now also
  optimize through), and
- as STREAMING SVI over live minibatch traffic through the gateway
  (:class:`StreamingSVI`), under the deadline regime.

Quick shape::

    from pytensor_federated_tpu import fed, ppl
    from pytensor_federated_tpu.ppl.distributions import Normal

    def model(x, y):
        w = ppl.sample("w", Normal(0.0, 1.0))
        with ppl.plate("shards", x.shape[0]) as sh:
            xs, ys = ppl.subsample(x, sh), ppl.subsample(y, sh)
            ppl.sample("obs", Normal(w * xs, 1.0), obs=ys)

    c = ppl.compile(model, (x, y), placement=fed.MeshPlacement(mesh))
    value, grads = c.logp_and_grad(c.init_params())

tutorial §24 walks the radon GLM through all four modes; docs/ppl.md
is the design document.
"""

from . import distributions
from .compiler import CompiledModel, compile, log_density
from .elbo import (
    gaussian_entropy,
    meanfield_draws,
    meanfield_neg_elbo,
    scan_vi,
)
from .handlers import (
    Messenger,
    PPLError,
    block,
    condition,
    deterministic,
    force_subsample,
    plate,
    replay,
    sample,
    seed,
    subsample,
    substitute,
    trace,
)
from .radon import make_radon_example, radon_model
from .svi import StreamingSVI, SVIResult, svi_fit

__all__ = [
    "CompiledModel",
    "Messenger",
    "PPLError",
    "StreamingSVI",
    "SVIResult",
    "block",
    "compile",
    "condition",
    "deterministic",
    "distributions",
    "force_subsample",
    "gaussian_entropy",
    "log_density",
    "make_radon_example",
    "meanfield_draws",
    "meanfield_neg_elbo",
    "plate",
    "radon_model",
    "replay",
    "sample",
    "scan_vi",
    "seed",
    "subsample",
    "substitute",
    "svi_fit",
    "trace",
]
