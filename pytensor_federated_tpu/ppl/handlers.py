"""Composable effect handlers: one model definition, many executions.

NumPyro-style (PAPERS.md: "Composable Effects for Flexible and
Accelerated Probabilistic Programming in NumPyro"): a model is a plain
Python function whose probabilistic statements — :func:`sample`,
:func:`deterministic`, :class:`plate`, :func:`subsample` — emit
*messages* through a stack of handlers instead of executing a fixed
semantics.  Each handler is a context manager on a thread-local stack;
a message travels innermost-to-outermost through
``process_message`` (so the INNERMOST handler that resolves a site's
value wins — the :class:`condition` / :class:`substitute` precedence
contract, pinned in tests/test_ppl.py), gets a default resolution
(draw from the prior if a ``seed`` handler supplied a key; a loud
:class:`PPLError` otherwise), then travels back out through
``postprocess_message`` (where :class:`trace` records).

The same model function therefore drives every execution mode in the
repo: direct log-density evaluation (:func:`~.compiler.log_density`),
prior sampling (``seed`` + ``trace``), NUTS/tempering (via the
compiled logp), and the ``fed``-lowered mesh/pool/mixed programs
(:func:`~.compiler.compile` re-runs the model under
:class:`force_subsample` to extract per-shard likelihoods — the
DrJAX plate→``fed_map`` correspondence).

Handlers run inside JAX traces (``fed_map`` bodies, ``jax.grad``), so
everything here is pure Python bookkeeping over traced values — no
host callbacks, no randomness outside an explicit ``seed``.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .distributions import Distribution

__all__ = [
    "Messenger",
    "PPLError",
    "block",
    "condition",
    "deterministic",
    "force_subsample",
    "plate",
    "replay",
    "sample",
    "seed",
    "subsample",
    "substitute",
    "trace",
]

Message = Dict[str, Any]


class PPLError(RuntimeError):
    """Loud failure of the effect layer: an unhandled site, a missing
    value, a duplicate name, a geometry mismatch.  A RuntimeError
    subclass on purpose — like :class:`~..service.deadline.
    DeadlineExceeded`, every lane already treats RuntimeError as
    deterministic/non-retryable."""


class _Local(threading.local):
    def __init__(self) -> None:
        self.stack: List["Messenger"] = []


_LOCAL = _Local()


def _stack() -> List["Messenger"]:
    return _LOCAL.stack


class Messenger:
    """Base handler: a context manager on the thread-local stack,
    optionally wrapping a model function (``handler(fn)(*args)`` runs
    ``fn`` with the handler active — handlers compose by nesting)."""

    def __init__(self, fn: Optional[Callable[..., Any]] = None) -> None:
        self.fn = fn

    def __enter__(self) -> "Messenger":
        _stack().append(self)
        return self

    def __exit__(self, *exc: object) -> None:
        popped = _stack().pop()
        if popped is not self:  # pragma: no cover - stack discipline bug
            raise PPLError(
                "handler stack corrupted: __exit__ out of order"
            )

    def process_message(self, msg: Message) -> None:
        """Inbound pass, innermost handler first."""

    def postprocess_message(self, msg: Message) -> None:
        """Outbound pass after the value is resolved."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self.fn is None:
            raise PPLError(
                f"{type(self).__name__} wraps no function; use it as a "
                "context manager or pass fn"
            )
        with self:
            return self.fn(*args, **kwargs)


def apply_stack(msg: Message) -> Message:
    """Run one message through the active handler stack (the NumPyro
    protocol): process innermost→outermost, stopping at a
    :class:`block`; default-resolve the value; postprocess back from
    the stop point inward."""
    stack = _stack()
    pointer = 0
    for pointer, handler in enumerate(reversed(stack)):
        handler.process_message(msg)
        if msg.get("stop"):
            break
    if msg["value"] is None and msg["type"] == "sample":
        if msg["rng_key"] is None:
            raise PPLError(
                f"sample site {msg['name']!r} has no value: provide it "
                "via substitute/condition/replay, or wrap the model in "
                "ppl.seed(...) to draw from the prior"
            )
        dist: Distribution = msg["dist"]
        msg["value"] = dist.sample(
            msg["rng_key"], tuple(msg["sample_shape"])
        )
    # Postprocess INNERMOST-first: an inner plate must gather its
    # shard's rows before an outer trace records the site.
    for handler in reversed(stack[len(stack) - pointer - 1 :]):
        handler.postprocess_message(msg)
    return msg


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def sample(
    name: str,
    dist: Distribution,
    *,
    obs: Any = None,
    mask: Any = None,
) -> Any:
    """Declare a random variable.  Returns its value under the active
    handler interpretation (observed data, a substituted parameter, a
    replayed draw, or a fresh prior draw under ``seed``)."""
    if not _stack():
        raise PPLError(
            f"sample({name!r}) outside any handler: wrap the model in "
            "ppl.trace / ppl.seed / ppl.substitute / ... before calling"
        )
    msg: Message = {
        "type": "sample",
        "name": name,
        "dist": dist,
        "value": obs,
        "observed": obs is not None,
        "mask": mask,
        "scale": 1.0,
        "plates": (),
        "rng_key": None,
        "sample_shape": (),
        "stop": False,
    }
    apply_stack(msg)
    return msg["value"]


def deterministic(name: str, value: Any) -> Any:
    """Record a named derived quantity (no log-density contribution);
    returns ``value`` unchanged."""
    if not _stack():
        raise PPLError(
            f"deterministic({name!r}) outside any handler: wrap the "
            "model in ppl.trace / ppl.seed / ... before calling"
        )
    msg: Message = {
        "type": "deterministic",
        "name": name,
        "dist": None,
        "value": value,
        "observed": False,
        "mask": None,
        "scale": 1.0,
        "plates": (),
        "rng_key": None,
        "sample_shape": (),
        "stop": False,
    }
    apply_stack(msg)
    return msg["value"]


@dataclasses.dataclass(frozen=True)
class PlateFrame:
    """One plate's static identity on a site: name, declared (full)
    size, and the effective size this execution ran with."""

    name: str
    size: int
    effective: int


class plate(Messenger):
    """Vectorized independence context over a LEADING axis.

    Sites declared inside carry the frame in ``msg["plates"]`` —
    the :mod:`.compiler` maps the outermost plate onto ``fed_map``
    shards (DrJAX's plate→map correspondence).  ``subsample_size``
    turns the plate into a minibatch plate: under a :class:`seed`
    handler it draws ``subsample_size`` indices without replacement,
    :func:`subsample` gathers plate-scoped data by them, and every
    inside site's log-density is scaled by ``size/subsample_size`` so
    the scaled minibatch logp is an unbiased estimate of the full-data
    logp (property-tested in tests/test_ppl.py).

    A :class:`force_subsample` handler overrides the indices from
    outside the model — the compiler's per-shard and minibatch lanes,
    and the unbiasedness tests, use that seam.
    """

    def __init__(
        self,
        name: str,
        size: int,
        *,
        subsample_size: Optional[int] = None,
    ) -> None:
        super().__init__(None)
        self.name = name
        self.size = int(size)
        if self.size < 1:
            raise PPLError(f"plate {name!r} size must be >= 1")
        self.subsample_size = (
            int(subsample_size) if subsample_size is not None else self.size
        )
        if not (1 <= self.subsample_size <= self.size):
            raise PPLError(
                f"plate {name!r}: subsample_size {self.subsample_size} "
                f"not in 1..{self.size}"
            )
        self._indices: Optional[jax.Array] = None
        self._scale: float = 1.0
        # id()s of arrays subsample() returned under THIS entry —
        # provenance that tells an index-ordered value from a raw
        # full-order one when their shapes coincide (see _resize).
        self._gathered: set = set()

    def __enter__(self) -> "plate":
        super().__enter__()
        forced = _innermost_force(self.name)
        if forced is not None:
            idx = jnp.asarray(forced.indices[self.name])
            if idx.ndim != 1:
                raise PPLError(
                    f"forced indices for plate {self.name!r} must be "
                    f"1-D, got shape {tuple(idx.shape)}"
                )
            self._indices = idx
            n = int(idx.shape[0])
            self._scale = (self.size / n) if forced.scale else 1.0
        elif self.subsample_size < self.size:
            key = _subsample_key(self.name)
            self._indices = jax.random.choice(
                key, self.size, (self.subsample_size,), replace=False
            )
            self._scale = self.size / self.subsample_size
        else:
            self._indices = None
            self._scale = 1.0
        self._gathered = set()
        return self

    @property
    def indices(self) -> jax.Array:
        """The active index set (``arange(size)`` when not
        subsampling)."""
        if self._indices is None:
            return jnp.arange(self.size)
        return self._indices

    @property
    def effective_size(self) -> int:
        if self._indices is None:
            return self.size
        return int(self._indices.shape[0])

    def process_message(self, msg: Message) -> None:
        if msg["type"] not in ("sample", "deterministic"):
            return
        eff = self.effective_size
        msg["plates"] = (
            PlateFrame(self.name, self.size, eff),
        ) + msg["plates"]
        msg["scale"] = msg["scale"] * self._scale
        if (
            msg["type"] == "sample"
            and not msg["observed"]
            and msg["value"] is None
        ):
            msg["sample_shape"] = (eff,) + tuple(msg["sample_shape"])

    def _resize(
        self, name: str, what: str, value: Any, *, observed: bool
    ) -> Any:
        """Bring one plate-scoped array onto this execution's index
        set.  LATENTS carry the FULL plate axis by contract (the
        compiler broadcasts whole parameter arrays to every shard), so
        they are ALWAYS gathered — even when the index set is a
        full-length permutation, where an already-the-right-size check
        would silently pair shard i's latent with shard j's data.
        OBSERVED values/masks are either already index-ordered (the
        model gathered them through subsample()) and pass through at
        the effective size, or condition/obs-attached at the FULL
        size and gathered here; anything else is a loud geometry
        error — a full-size value that merely BROADCAST against
        shard-shaped siblings would silently count the whole plate
        once per shard."""
        eff = self.effective_size
        dim = int(jnp.shape(value)[0])
        if observed and dim == eff:
            # At eff == size an observed value's SHAPE is ambiguous:
            # an already-index-ordered subsample() output and a raw
            # full-order condition/obs attachment look the same.
            # Provenance disambiguates — subsample() registered its
            # outputs with this plate, so registered values pass;
            # anything else under a non-identity concrete index set
            # refuses loudly (silent row misalignment otherwise).
            # Traced full-length indices keep the pass-through: the
            # shipped lanes deliver pre-sliced data there
            # (slice_data=False) or route it through subsample().
            if (
                eff == self.size
                and self._indices is not None
                and id(value) not in self._gathered
            ):
                try:
                    conc = np.asarray(self._indices)
                except Exception:  # tracer: cannot concretize
                    conc = None
                if conc is not None and not np.array_equal(
                    conc, np.arange(self.size)
                ):
                    raise PPLError(
                        f"{what} of observed site {name!r} inside "
                        f"plate {self.name!r} is full-length under a "
                        "permuted/duplicated index set — whether it "
                        "is already index-ordered is ambiguous; route "
                        "it through subsample() or force a strict "
                        "subset of indices"
                    )
            return value
        if dim == self.size:
            return jnp.take(value, self._indices, axis=0)
        expected = (
            f"the effective size {eff} (already sliced) or the full "
            f"plate size {self.size} (gathered by the active indices)"
            if observed
            else f"the full plate size {self.size} (latents are "
            "gathered by the active indices)"
        )
        raise PPLError(
            f"{what} of site {name!r} inside plate {self.name!r} has "
            f"leading dim {dim}; expected {expected}"
        )

    def postprocess_message(self, msg: Message) -> None:
        # Under an index override, values carrying the FULL plate axis
        # are gathered onto this execution's rows: substituted LATENTS
        # by contract (the compiler broadcasts whole parameter arrays
        # to every shard), and condition/obs-attached OBSERVATIONS or
        # masks that bypassed subsample() — anything that matches
        # neither the full nor the effective size refuses loudly
        # (never a silently-broadcast full-data likelihood per shard).
        if (
            self._indices is None
            or msg["type"] != "sample"
            or msg["value"] is None
            or msg["rng_key"] is not None  # fresh draw: already sized
        ):
            return
        if not any(
            f.name == self.name for f in msg["plates"]
        ):  # pragma: no cover - defensive
            return
        value = msg["value"]
        if jnp.ndim(value) < 1:
            if msg["observed"]:
                return  # scalar obs broadcasts like any jnp operand
            raise PPLError(
                f"site {msg['name']!r} inside plate {self.name!r} has "
                "a scalar value; plate-scoped latents must carry the "
                "plate axis leading"
            )
        msg["value"] = self._resize(
            msg["name"], "value", value, observed=msg["observed"]
        )
        if msg["mask"] is not None and jnp.ndim(msg["mask"]) >= 1:
            msg["mask"] = self._resize(
                msg["name"], "mask", msg["mask"], observed=True
            )


def subsample(data: Any, frame: Optional[plate] = None) -> Any:
    """Gather plate-scoped data by the active plate's index set
    (identity when the plate is not subsampling).  ``frame`` defaults
    to the innermost active plate.  Under a :class:`force_subsample`
    with ``slice_data=False`` this is the identity — the compiler's
    streaming lane delivers pre-sliced shard data."""
    pl = frame
    if pl is None:
        for handler in reversed(_stack()):
            if isinstance(handler, plate):
                pl = handler
                break
    if pl is None:
        raise PPLError("subsample() outside any active plate")
    if pl._indices is None:
        return data
    forced = _innermost_force(pl.name)
    if forced is not None and not forced.slice_data:
        # Pre-sliced by the caller (the streaming lane): identity,
        # but still REGISTERED — these leaves are index-ordered.
        for leaf in jax.tree_util.tree_leaves(data):
            pl._gathered.add(id(leaf))
        return data
    idx = pl._indices
    out = jax.tree_util.tree_map(
        lambda leaf: jnp.take(leaf, idx, axis=0), data
    )
    for leaf in jax.tree_util.tree_leaves(out):
        pl._gathered.add(id(leaf))
    return out


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------


class trace(Messenger):
    """Record every site into an ordered dict (model execution order).
    Duplicate site names are a loud :class:`PPLError`."""

    def __init__(self, fn: Optional[Callable[..., Any]] = None) -> None:
        super().__init__(fn)
        self._trace: "collections.OrderedDict[str, Message]" = (
            collections.OrderedDict()
        )

    def __enter__(self) -> "trace":
        super().__enter__()
        self._trace = collections.OrderedDict()
        return self

    def postprocess_message(self, msg: Message) -> None:
        if msg["type"] not in ("sample", "deterministic"):
            return
        name = msg["name"]
        if name in self._trace:
            raise PPLError(f"duplicate site name {name!r} in one trace")
        self._trace[name] = dict(msg)

    def get_trace(
        self, *args: Any, **kwargs: Any
    ) -> "collections.OrderedDict[str, Message]":
        self(*args, **kwargs)
        return self._trace


class replay(Messenger):
    """Reuse the values of a previously recorded trace (sample sites
    only; sites absent from the trace resolve normally)."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        guide_trace: Optional[Dict[str, Message]] = None,
    ) -> None:
        super().__init__(fn)
        self.guide_trace = guide_trace or {}

    def process_message(self, msg: Message) -> None:
        if msg["type"] != "sample" or msg["value"] is not None:
            return
        site = self.guide_trace.get(msg["name"])
        if site is not None:
            msg["value"] = site["value"]


class condition(Messenger):
    """Clamp sites to OBSERVED values: the sites contribute likelihood
    terms and count as data downstream.  The innermost handler that
    resolves a site wins (see :class:`substitute`)."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        data: Optional[Dict[str, Any]] = None,
    ) -> None:
        super().__init__(fn)
        self.data = data or {}

    def process_message(self, msg: Message) -> None:
        if msg["type"] != "sample" or msg["value"] is not None:
            return
        if msg["name"] in self.data:
            msg["value"] = self.data[msg["name"]]
            msg["observed"] = True


class substitute(Messenger):
    """Set site VALUES without marking them observed — parameter
    evaluation (the logp lanes run the model under ``substitute`` with
    the sampler's current position).  Innermost wins: a
    ``substitute`` nested inside a ``condition`` takes the site, and
    vice versa — precedence is purely positional, pinned in
    tests/test_ppl.py."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        data: Optional[Dict[str, Any]] = None,
    ) -> None:
        super().__init__(fn)
        self.data = data or {}

    def process_message(self, msg: Message) -> None:
        if msg["type"] != "sample" or msg["value"] is not None:
            return
        if msg["name"] in self.data:
            msg["value"] = self.data[msg["name"]]


class seed(Messenger):
    """Supply PRNG keys: each unresolved sample site (in execution
    order) consumes one split of the handler's key, so the same key
    yields the same trace — the determinism contract the compiler's
    seeded-trace tests pin.  Subsampling plates also draw their index
    keys here (:func:`_subsample_key`)."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        rng_key: Optional[jax.Array] = None,
    ) -> None:
        super().__init__(fn)
        if rng_key is None:
            raise PPLError("seed(...) requires rng_key")
        self.rng_key = rng_key
        self._key = rng_key

    def __enter__(self) -> "seed":
        super().__enter__()
        self._key = self.rng_key  # reentrant determinism
        return self

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def process_message(self, msg: Message) -> None:
        if (
            msg["type"] == "sample"
            and msg["value"] is None
            and msg["rng_key"] is None
        ):
            msg["rng_key"] = self.next_key()


class block(Messenger):
    """Hide matching sites from handlers OUTSIDE this one (an outer
    ``trace`` never records them; an outer ``substitute`` cannot set
    them).  ``hide`` lists names; ``hide_fn`` is a message predicate;
    with neither, everything is hidden."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        *,
        hide: Optional[List[str]] = None,
        hide_fn: Optional[Callable[[Message], bool]] = None,
    ) -> None:
        super().__init__(fn)
        self.hide = set(hide) if hide is not None else None
        self.hide_fn = hide_fn

    def _hidden(self, msg: Message) -> bool:
        if self.hide_fn is not None:
            return bool(self.hide_fn(msg))
        if self.hide is not None:
            return msg["name"] in self.hide
        return True

    def process_message(self, msg: Message) -> None:
        if self._hidden(msg):
            msg["stop"] = True


class force_subsample(Messenger):
    """Pin plate index sets from OUTSIDE the model — the seam the
    compiler's per-shard/minibatch lanes and the unbiasedness property
    tests drive.

    ``indices`` maps plate name → 1-D index array.  ``scale=True``
    applies the ``size/len(indices)`` minibatch scaling (the unbiased
    estimator); ``scale=False`` leaves terms unscaled (the compiler's
    full-data per-shard evaluation, where every shard contributes its
    exact term once).  ``slice_data=False`` makes :func:`subsample`
    the identity for the forced plates — the streaming lane delivers
    shard data already sliced, while latent parameter arrays still
    arrive full-size and are gathered by the plate."""

    def __init__(
        self,
        fn: Optional[Callable[..., Any]] = None,
        indices: Optional[Dict[str, Any]] = None,
        *,
        scale: bool = True,
        slice_data: bool = True,
    ) -> None:
        super().__init__(fn)
        self.indices = dict(indices or {})
        self.scale = bool(scale)
        self.slice_data = bool(slice_data)


def _innermost_force(plate_name: str) -> Optional[force_subsample]:
    for handler in reversed(_stack()):
        if (
            isinstance(handler, force_subsample)
            and plate_name in handler.indices
        ):
            return handler
    return None


def _subsample_key(plate_name: str) -> jax.Array:
    for handler in reversed(_stack()):
        if isinstance(handler, seed):
            return handler.next_key()
    raise PPLError(
        f"plate {plate_name!r} subsamples but no seed handler is "
        "active: wrap the model in ppl.seed(...) (or force indices "
        "with ppl.force_subsample)"
    )
