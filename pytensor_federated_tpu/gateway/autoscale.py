"""Replica autoscaling for the gateway tier.

Spawn and drain pool replicas from OBSERVED pressure — the ISSUE-11
signals the fleet plane already computes, not guesses: the gateway's
fair-queue depth, the pool's EWMA per-request latency, and the
gateway's shed/denial rate.  Scaling actions ride the machinery the
pool already has:

- **scale-up** registers the new replica only after it answers the
  liveness probe (the zero-item batch frame) — a cold replica never
  receives a traffic share it cannot serve — and from there the
  breaker's half-open ladder owns warm-up: a fresh replica that flaps
  is quarantined after ``failure_threshold`` failures and wins traffic
  back through a SINGLE half-open probe, never a thundering herd
  (:mod:`..routing.breaker`).
- **scale-down** is the PR-5 graceful-drain shape: the replica leaves
  the pool registry FIRST (no new picks; the gateway's in-flight
  upstream window completes on its own connection), then after
  ``drain_grace_s`` the operator's ``stop_replica`` callback reaps the
  process.  A registered collector is told to drop the replica's
  scrape target in the same step — the FleetCollector fix this PR
  ships (departed replicas must not linger as stale targets).

**Hysteresis** so flapping replicas don't thrash the scaler: an action
fires only after ``consecutive`` consecutive over-threshold
observations, scale-up and scale-down have separate thresholds with a
dead band between them, and each action arms a per-direction cooldown.
Decisions and outcomes are loud: ``pftpu_gateway_autoscale_total``
plus ``gateway.autoscale`` flight-recorder points.

``step()`` is the synchronous, clock-injectable decision function
(tests drive it directly); ``start()`` runs it on a daemon thread at
``interval_s``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..routing.pool import NodePool, _tcp_probe
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics

__all__ = ["Autoscaler", "ReplicaHandle"]

_AUTOSCALE = _metrics.counter(
    "pftpu_gateway_autoscale_total",
    "Autoscaler actions, by direction and outcome",
    ("direction", "outcome"),
)
_AUTOSCALE_REPLICAS = _metrics.gauge(
    "pftpu_gateway_autoscaled_replicas",
    "Replicas currently owned (spawned) by the gateway autoscaler",
)

#: (host, port, opaque-handle) — what ``spawn_replica`` returns; the
#: handle travels back into ``stop_replica`` untouched.
ReplicaHandle = Tuple[str, int, Any]


class Autoscaler:
    """Queue-pressure-driven replica scaling over a
    :class:`~..routing.pool.NodePool`.

    ``signals``: a callable returning the gateway's observation dict
    (:meth:`~.server.GatewayServer.signals`: ``queue_depth`` plus
    rolling ``shed``/``denied`` counters).  ``spawn_replica()`` must
    start a node and return ``(host, port, handle)``;
    ``stop_replica(handle)`` reaps it.  ``collector`` (optional): a
    :class:`~..telemetry.collector.FleetCollector` whose http-target
    registry follows spawned/drained replicas (``exporter_of(host,
    port)`` maps a replica to its exporter address when the node
    exposes one)."""

    def __init__(
        self,
        pool: NodePool,
        signals: Callable[[], Dict[str, float]],
        spawn_replica: Callable[[], ReplicaHandle],
        stop_replica: Callable[[Any], None],
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        scale_up_queue_depth: float = 16.0,
        scale_down_queue_depth: float = 2.0,
        scale_up_ewma_s: Optional[float] = None,
        scale_up_shed_rate: Optional[float] = None,
        consecutive: int = 2,
        cooldown_up_s: float = 2.0,
        cooldown_down_s: float = 10.0,
        warmup_timeout_s: float = 20.0,
        drain_grace_s: float = 1.0,
        interval_s: float = 1.0,
        transport: str = "tcp",
        collector: Optional[Any] = None,
        exporter_of: Optional[
            Callable[[str, int], Optional[Tuple[str, int]]]
        ] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if scale_down_queue_depth >= scale_up_queue_depth:
            raise ValueError(
                "need scale_down_queue_depth < scale_up_queue_depth "
                "(the hysteresis dead band), got "
                f"{scale_down_queue_depth} >= {scale_up_queue_depth}"
            )
        self.pool = pool
        self.signals = signals
        self.spawn_replica = spawn_replica
        self.stop_replica = stop_replica
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_queue_depth = float(scale_up_queue_depth)
        self.scale_down_queue_depth = float(scale_down_queue_depth)
        self.scale_up_ewma_s = scale_up_ewma_s
        self.scale_up_shed_rate = scale_up_shed_rate
        self.consecutive = int(consecutive)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.warmup_timeout_s = float(warmup_timeout_s)
        self.drain_grace_s = float(drain_grace_s)
        self.interval_s = float(interval_s)
        self.transport = transport
        self.collector = collector
        self.exporter_of = exporter_of
        self._clock = clock
        #: Replicas THIS scaler spawned (never drains the seed set).
        self.owned: List[ReplicaHandle] = []
        self._hot_streak = 0
        self._cold_streak = 0
        self._cooldown_until = {"up": 0.0, "down": 0.0}
        self._last_shed: Optional[float] = None
        self._last_step_t: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()

    # -- observation ------------------------------------------------------

    def _shed_rate(self, sig: Dict[str, float], now: float) -> float:
        """Sheds+denials per second since the previous step (rolling
        counters differenced against the injectable clock)."""
        total = float(sig.get("shed", 0.0)) + float(sig.get("denied", 0.0))
        if self._last_shed is None or self._last_step_t is None:
            rate = 0.0
        else:
            dt = max(now - self._last_step_t, 1e-6)
            rate = max(0.0, total - self._last_shed) / dt
        self._last_shed = total
        self._last_step_t = now
        return rate

    def _max_ewma_s(self) -> float:
        vals = [
            r.ewma_latency_s
            for r in self.pool.replicas
            if r.ewma_latency_s is not None
        ]
        return max(vals) if vals else 0.0

    def _pressure(self, sig: Dict[str, float], now: float) -> bool:
        if float(sig.get("queue_depth", 0.0)) >= self.scale_up_queue_depth:
            return True
        if (
            self.scale_up_ewma_s is not None
            and self._max_ewma_s() >= self.scale_up_ewma_s
        ):
            return True
        if (
            self.scale_up_shed_rate is not None
            and self._shed_rate(sig, now) >= self.scale_up_shed_rate
        ):
            return True
        return False

    # -- decision ---------------------------------------------------------

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """One observation + (maybe) one action; returns ``"up"``,
        ``"down"``, or ``None``.  Thread-safe against concurrent
        ``start()``-loop steps."""
        with self._lock:
            return self._step_locked(
                self._clock() if now is None else now
            )

    def _step_locked(self, now: float) -> Optional[str]:
        sig = self.signals()
        hot = self._pressure(sig, now)
        depth = float(sig.get("queue_depth", 0.0))
        cold = depth <= self.scale_down_queue_depth and not hot
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._cold_streak = self._cold_streak + 1 if cold else 0
        n = len(self.pool)
        if (
            self._hot_streak >= self.consecutive
            and n < self.max_replicas
            and now >= self._cooldown_until["up"]
        ):
            self._hot_streak = 0
            self._cooldown_until["up"] = now + self.cooldown_up_s
            return "up" if self._scale_up() else None
        if (
            self._cold_streak >= self.consecutive
            and self.owned
            and n > self.min_replicas
            and now >= self._cooldown_until["down"]
        ):
            self._cold_streak = 0
            self._cooldown_until["down"] = now + self.cooldown_down_s
            return "down" if self._scale_down() else None
        return None

    def _scale_up(self) -> bool:
        try:
            host, port, handle = self.spawn_replica()
        except Exception as e:
            _AUTOSCALE.labels(direction="up", outcome="spawn_failed").inc()
            _flightrec.record(
                "gateway.autoscale", direction="up",
                outcome="spawn_failed", error=str(e)[:200],
            )
            return False
        # Warm-up gate: the replica joins the pool only once it answers
        # the liveness probe — before that it has no traffic share at
        # all; after joining, the breaker half-open ladder owns any
        # subsequent flap (module docstring).
        deadline = time.monotonic() + self.warmup_timeout_s
        while time.monotonic() < deadline:
            if _tcp_probe(host, port, timeout=1.0):
                break
            time.sleep(0.05)
        else:
            _AUTOSCALE.labels(
                direction="up", outcome="warmup_timeout"
            ).inc()
            _flightrec.record(
                "gateway.autoscale", direction="up",
                outcome="warmup_timeout", replica=f"{host}:{port}",
            )
            try:
                self.stop_replica(handle)
            except Exception:
                pass
            return False
        self.pool.add_replica(host, port, transport=self.transport)
        self.owned.append((host, port, handle))
        _AUTOSCALE_REPLICAS.set(len(self.owned))
        self._register_scrape(host, port)
        _AUTOSCALE.labels(direction="up", outcome="ok").inc()
        _flightrec.record(
            "gateway.autoscale", direction="up", outcome="ok",
            replica=f"{host}:{port}", pool_size=len(self.pool),
        )
        return True

    def _scale_down(self) -> bool:
        host, port, handle = self.owned.pop()
        # Graceful drain: leave the registry first (no new picks; the
        # gateway finishes any in-flight window on its own upstream
        # connection), linger for the grace period, then reap.
        self.pool.remove_replica(host, port)
        self._unregister_scrape(host, port)
        if self.drain_grace_s > 0:
            time.sleep(self.drain_grace_s)
        try:
            self.stop_replica(handle)
        except Exception as e:
            _AUTOSCALE.labels(
                direction="down", outcome="stop_failed"
            ).inc()
            _flightrec.record(
                "gateway.autoscale", direction="down",
                outcome="stop_failed", replica=f"{host}:{port}",
                error=str(e)[:200],
            )
            _AUTOSCALE_REPLICAS.set(len(self.owned))
            return True  # the replica DID leave the pool
        _AUTOSCALE_REPLICAS.set(len(self.owned))
        _AUTOSCALE.labels(direction="down", outcome="ok").inc()
        _flightrec.record(
            "gateway.autoscale", direction="down", outcome="ok",
            replica=f"{host}:{port}", pool_size=len(self.pool),
        )
        return True

    def _register_scrape(self, host: str, port: int) -> None:
        if self.collector is None or self.exporter_of is None:
            return
        target = self.exporter_of(host, port)
        if target is not None:
            self.collector.add_http_target(f"{host}:{port}", target)

    def _unregister_scrape(self, host: str, port: int) -> None:
        if self.collector is None:
            return
        remove = getattr(self.collector, "remove_http_target", None)
        if remove is not None:
            remove(f"{host}:{port}")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name="pftpu-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.step()
            except Exception as e:
                # One bad step must never kill the loop — but a
                # persistently-failing scaler silently pinning the
                # fleet size would be the quiet failure this repo
                # forbids: every miss is metered and flight-recorded.
                _AUTOSCALE.labels(
                    direction="step", outcome="error"
                ).inc()
                _flightrec.record(
                    "gateway.autoscale", direction="step",
                    outcome="error",
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
            self._stop_evt.wait(self.interval_s)

    def stop(self, *, drain_owned: bool = False) -> None:
        self._stop_evt.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        if drain_owned:
            with self._lock:
                while self.owned:
                    self._scale_down()
