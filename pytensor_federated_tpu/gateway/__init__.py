"""Gateway tier: the front door that multiplexes many clients onto the
replica pool (ISSUE 12).

Three pieces, one package:

- :mod:`.server` — the accept tier: one address speaking the existing
  npwire framing, thousands of downstream connections on one asyncio
  loop, requests coalesced into a few upstream pipelined batch windows
  against a :class:`~..routing.pool.NodePool`.
- :mod:`.fairness` — per-tenant identity (the new wire field, declared
  in :mod:`..service.wire_registry`), token-bucket quotas, and
  deficit-round-robin weighted-fair queueing, so one hog tenant cannot
  starve the rest.
- :mod:`.autoscale` — spawn/drain pool replicas from observed
  queue-depth / EWMA-latency / shed-rate signals, with hysteresis,
  probe-gated warm-up, and graceful drain on the way down.

docs/gateway.md is the architecture document; tutorial §22 drives a
gateway end to end.
"""

from .autoscale import Autoscaler, ReplicaHandle
from .fairness import (
    OVERLOAD_ERROR_PREFIX,
    TenantFairness,
    TokenBucket,
    WeightedFairQueue,
    is_overload_error,
    overload_error,
)
from .server import GatewayServer, GatewayThread, serve_gateway

__all__ = [
    "Autoscaler",
    "GatewayServer",
    "GatewayThread",
    "OVERLOAD_ERROR_PREFIX",
    "ReplicaHandle",
    "TenantFairness",
    "TokenBucket",
    "WeightedFairQueue",
    "is_overload_error",
    "overload_error",
    "serve_gateway",
]
