"""Per-tenant fairness for the gateway tier: quotas + weighted-fair queueing.

The accept tier (:mod:`.server`) holds thousands of downstream
connections on one loop, which means one misbehaving client — a hog
tenant replaying a tight loop — can fill every upstream window and
starve everyone else while each individual request still looks
perfectly legal.  PR 10 built the *server-side* shield (deadlines,
shedding admission, retry budgets); this module is the *front-door*
half, metering by TENANT identity (the new wire field: npwire flag
bit 32 / npproto field 19 / shm doorbell flag bit 8, declared in
:mod:`..service.wire_registry`) instead of by connection:

- :class:`TokenBucket` — per-tenant admission quota (monotonic-clock
  token bucket, the :class:`~..routing.budget.RetryBudget` shape).  A
  tenant past its rate is DENIED loudly: an in-band retryable error
  carrying :data:`OVERLOAD_ERROR_PREFIX` plus the tenant id, a
  ``pftpu_gateway_denials_total{tenant, reason}`` tick, and a
  ``gateway.denied`` flight-recorder point — never silent drops, never
  an unbounded queue.
- :class:`WeightedFairQueue` — deficit round robin (DRR) over
  per-tenant FIFO queues.  Each backlogged tenant is visited once per
  round and accumulates ``weight x quantum`` deficit per visit, so ANY
  active tenant with backlog is served within a bounded number of
  pops: at most ``ceil(1 / quantum_t) x n_active`` pops after it
  becomes head-of-round (property-tested in tests/test_gateway.py) —
  the no-starvation contract a plain shared FIFO cannot make.
- :class:`TenantFairness` — the composition the gateway server drives:
  ``admit()`` at frame arrival (quota + per-tenant backlog bound),
  ``push()``/``pop()`` around the upstream coalescing loop.

Single-owner by design: the gateway's asyncio loop is the only caller
of ``admit``/``push``/``pop`` (no locks on the hot path); the metric
families are process-global like every other ``pftpu_*`` family
(catalog: docs/observability.md).
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Callable, Deque, Dict, Optional, Tuple

from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics

__all__ = [
    "OVERLOAD_ERROR_PREFIX",
    "TokenBucket",
    "WeightedFairQueue",
    "TenantFairness",
    "is_overload_error",
    "overload_error",
]

#: The in-band error classification for gateway denials (quota or
#: backlog).  RETRYABLE on purpose — the caller's work is fine, the
#: front door is momentarily full — which is the opposite posture of
#: the deadline classification (whose budget is gone everywhere at
#: once); clients that understand the marker may back off and re-send.
OVERLOAD_ERROR_PREFIX = "gateway overloaded"


def overload_error(tenant: str, detail: str) -> str:
    """The in-band denial string: classification marker + the tenant
    it applies to (the loudness contract: every denial names its
    tenant, in-band and in telemetry)."""
    return f"{OVERLOAD_ERROR_PREFIX} [tenant {tenant}]: {detail}"


def is_overload_error(error: Optional[str]) -> bool:
    """Whether a reply's in-band error is the gateway-denial
    classification (substring, like ``deadline.is_deadline_error``:
    lanes may wrap it in their own stage prefixes)."""
    return error is not None and OVERLOAD_ERROR_PREFIX in error


# -- gateway metric families (catalog: docs/observability.md) -------------

GATEWAY_REQUESTS = _metrics.counter(
    "pftpu_gateway_requests_total",
    "Requests entering the gateway accept tier, by outcome",
    ("outcome",),
)
GATEWAY_DENIALS = _metrics.counter(
    "pftpu_gateway_denials_total",
    "Requests denied at the gateway front door, by tenant and reason",
    ("tenant", "reason"),
)
GATEWAY_SHED = _metrics.counter(
    "pftpu_gateway_shed_total",
    "Requests shed by the gateway before upstream dispatch, by reason",
    ("reason",),
)
GATEWAY_QUEUE_DEPTH = _metrics.gauge(
    "pftpu_gateway_queue_depth",
    "Requests queued in the gateway's weighted-fair queue, by tenant",
    ("tenant",),
)


class TokenBucket:
    """Monotonic-clock token bucket (the retry-budget shape,
    :mod:`..routing.budget`): ``try_spend`` refills lazily from wall
    time and never blocks.  ``rate_per_s`` tokens accrue per second up
    to ``burst``; a spend past the balance is a denial."""

    def __init__(
        self,
        rate_per_s: float = 100.0,
        burst: float = 200.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError(
                f"need rate_per_s > 0 and burst > 0, got "
                f"{rate_per_s}/{burst}"
            )
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst)
        self._clock: Callable[[], float] = (
            clock if clock is not None else time.monotonic
        )
        self._tokens = self.burst
        self._last = float(self._clock())

    def _refill(self) -> None:
        now = float(self._clock())
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate_per_s
        )
        self._last = now

    def tokens(self) -> float:
        self._refill()
        return self._tokens

    def try_spend(self, cost: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False


class _TenantState:
    """One tenant's DRR bookkeeping: FIFO backlog + deficit counter."""

    __slots__ = ("queue", "deficit", "weight")

    def __init__(self, weight: float) -> None:
        self.queue: Deque[object] = deque()
        self.deficit = 0.0
        self.weight = weight


class WeightedFairQueue:
    """Deficit-round-robin fair queue over per-tenant FIFOs.

    ``pop`` serves the round-robin head tenant while its deficit
    covers one request (cost 1.0), recharging ``weight x quantum`` per
    round-trip through the active ring.  With every weight >=
    ``min_weight`` (enforced), a backlogged tenant is served within
    ``ceil(1 / (min_weight x quantum)) x n_active`` pops — the bounded
    no-starvation property tests/test_gateway.py pins.

    Not thread-safe: owned by the gateway's event loop (module
    docstring)."""

    #: Weights below this are clamped up: a zero weight would make the
    #: DRR ring spin forever without serving (and "present but starved
    #: by configuration" is exactly what this queue exists to forbid).
    MIN_WEIGHT = 0.01

    def __init__(
        self,
        *,
        quantum: float = 1.0,
        default_weight: float = 1.0,
        weights: Optional[Dict[str, float]] = None,
    ) -> None:
        if quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {quantum}")
        self.quantum = float(quantum)
        self.default_weight = max(float(default_weight), self.MIN_WEIGHT)
        self._weights = {
            t: max(float(w), self.MIN_WEIGHT)
            for t, w in (weights or {}).items()
        }
        # Insertion-ordered ring of tenants with backlog; rotation is
        # pop-from-front/push-to-back on the key list.
        self._states: Dict[str, _TenantState] = {}
        self._active: "OrderedDict[str, None]" = OrderedDict()
        self._depth = 0

    def weight_of(self, tenant: str) -> float:
        return self._weights.get(tenant, self.default_weight)

    def set_weight(self, tenant: str, weight: float) -> None:
        w = max(float(weight), self.MIN_WEIGHT)
        self._weights[tenant] = w
        state = self._states.get(tenant)
        if state is not None:
            state.weight = w

    def depth(self, tenant: Optional[str] = None) -> int:
        if tenant is None:
            return self._depth
        state = self._states.get(tenant)
        return 0 if state is None else len(state.queue)

    def active_tenants(self) -> Tuple[str, ...]:
        return tuple(self._active)

    def push(self, tenant: str, item: object) -> None:
        state = self._states.get(tenant)
        if state is None:
            state = self._states[tenant] = _TenantState(
                self.weight_of(tenant)
            )
        state.queue.append(item)
        self._depth += 1
        if tenant not in self._active:
            self._active[tenant] = None
        GATEWAY_QUEUE_DEPTH.labels(tenant=tenant).set(len(state.queue))

    def push_front(self, tenant: str, item: object) -> None:
        """Head re-insert for an item POPPED but not dispatched (the
        window byte-cap hit): preserves the per-tenant FIFO contract —
        a plain ``push`` would reorder it behind its own siblings and
        let continuous traffic defer a large frame forever — and gives
        back the DRR deficit its pop spent (the deferral served
        nobody)."""
        state = self._states.get(tenant)
        if state is None:
            state = self._states[tenant] = _TenantState(
                self.weight_of(tenant)
            )
        state.queue.appendleft(item)
        state.deficit += 1.0
        self._depth += 1
        if tenant not in self._active:
            self._active[tenant] = None
        GATEWAY_QUEUE_DEPTH.labels(tenant=tenant).set(len(state.queue))

    def pop(self) -> Optional[Tuple[str, object]]:
        """Serve one request fairly, or ``None`` when idle.  Bounded
        work per call: each ring pass either serves or adds quantum to
        every visited tenant, so the loop ends within
        ``ceil(1 / (min_weight x quantum))`` passes."""
        if self._depth == 0:
            return None
        while True:
            tenant, _ = next(iter(self._active.items()))
            state = self._states[tenant]
            if not state.queue:
                # A drained tenant leaves the ring, the state map, AND
                # the queue-depth label set — its deficit resets
                # (DRR's anti-burst rule) and its bookkeeping must not
                # accumulate per distinct tenant id forever (the id is
                # attacker-controlled wire input).
                del self._active[tenant]
                del self._states[tenant]
                GATEWAY_QUEUE_DEPTH.remove(tenant=tenant)
                continue
            if state.deficit < 1.0:
                state.deficit += state.weight * self.quantum
                self._active.move_to_end(tenant)
                continue
            state.deficit -= 1.0
            item = state.queue.popleft()
            self._depth -= 1
            GATEWAY_QUEUE_DEPTH.labels(tenant=tenant).set(
                len(state.queue)
            )
            if not state.queue:
                del self._active[tenant]
                del self._states[tenant]
                GATEWAY_QUEUE_DEPTH.remove(tenant=tenant)
            return tenant, item


class TenantFairness:
    """Quota + fair-queue admission, the gateway server's one policy
    object.

    ``quota_rate_per_s``/``quota_burst``: each tenant's token bucket
    (``None`` rate = unmetered, fairness still applies through the
    queue).  ``max_backlog_per_tenant`` bounds one tenant's queued
    requests — a hog tenant faster than its quota fills ITS backlog
    and gets denied, while other tenants' queues stay shallow.
    ``weights`` biases DRR service (a paying tenant can be worth 4x a
    free one); unnamed tenants get ``default_weight``.

    ``max_tenants`` bounds the number of CONCURRENTLY TRACKED tenant
    ids.  The tenant id is attacker-controlled wire input, so without
    a bound a client rotating fresh ids per request would mint itself
    a new full token bucket (and a new metric label child) every call
    — evading the quota entirely and growing state without limit.  At
    the cap, an unseen id first tries to reclaim an IDLE slot (a
    bucket back at full burst loses nothing by eviction — it is
    indistinguishable from a fresh one); failing that, the request is
    denied loudly with ``reason="tenant_cardinality"`` under the
    bounded ``(overflow)`` metric label (the real id still travels in
    the in-band error, where cardinality costs nothing)."""

    #: The metric-label stand-in for ids past the cardinality cap —
    #: raw attacker-chosen ids must never become metric labels.
    OVERFLOW_LABEL = "(overflow)"

    def __init__(
        self,
        *,
        quota_rate_per_s: Optional[float] = None,
        quota_burst: Optional[float] = None,
        max_backlog_per_tenant: int = 256,
        quantum: float = 1.0,
        default_weight: float = 1.0,
        weights: Optional[Dict[str, float]] = None,
        max_tenants: int = 1024,
    ) -> None:
        self.quota_rate_per_s = quota_rate_per_s
        self.quota_burst = (
            float(quota_burst)
            if quota_burst is not None
            else (2.0 * quota_rate_per_s if quota_rate_per_s else 0.0)
        )
        self.max_backlog_per_tenant = int(max_backlog_per_tenant)
        self.queue = WeightedFairQueue(
            quantum=quantum,
            default_weight=default_weight,
            weights=weights,
        )
        self.max_tenants = int(max_tenants)
        self._buckets: Dict[str, TokenBucket] = {}

    def _evict_idle_bucket(self) -> bool:
        """Reclaim one slot from a tenant whose bucket refilled to
        full burst (idle long enough to lose nothing by eviction)."""
        for tenant, bucket in self._buckets.items():
            if (
                bucket.tokens() >= bucket.burst
                and not self.queue.depth(tenant)
            ):
                del self._buckets[tenant]
                return True
        return False

    def bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        if self.quota_rate_per_s is None:
            return None
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = TokenBucket(
                rate_per_s=self.quota_rate_per_s, burst=self.quota_burst
            )
        return bucket

    def _is_tracked(self, tenant: str) -> bool:
        """Whether this tenant already holds fairness state (a quota
        bucket or queued backlog)."""
        return tenant in self._buckets or self.queue.depth(tenant) > 0

    def _n_tracked(self) -> int:
        """Distinct tenants currently holding fairness state.  The
        cardinality cap must count BOTH maps: with quotas disabled no
        buckets ever exist, and a cap keyed on buckets alone would be
        inert — rotating ids would mint unlimited per-tenant backlog
        allowances (total queue memory unbounded)."""
        return len(self._buckets.keys() | self.queue._states.keys())

    def admit(self, tenant: str) -> Optional[str]:
        """Admission verdict for one arriving request: ``None`` admits;
        a string is the in-band denial error (already metered and
        flight-recorded, always naming the tenant)."""
        if (
            not self._is_tracked(tenant)
            and self._n_tracked() >= self.max_tenants
            and not self._evict_idle_bucket()
        ):
            GATEWAY_DENIALS.labels(
                tenant=self.OVERFLOW_LABEL, reason="tenant_cardinality"
            ).inc()
            GATEWAY_REQUESTS.labels(outcome="denied_cardinality").inc()
            _flightrec.record(
                "gateway.denied",
                tenant=self.OVERFLOW_LABEL,
                reason="tenant_cardinality",
            )
            return overload_error(
                tenant,
                f"tenant table full ({self.max_tenants} active "
                "tenants); retry later",
            )
        bucket = self.bucket_for(tenant)
        if bucket is not None and not bucket.try_spend():
            GATEWAY_DENIALS.labels(tenant=tenant, reason="quota").inc()
            GATEWAY_REQUESTS.labels(outcome="denied_quota").inc()
            _flightrec.record(
                "gateway.denied", tenant=tenant, reason="quota"
            )
            return overload_error(
                tenant,
                f"quota exhausted ({self.quota_rate_per_s}/s, "
                f"burst {self.quota_burst:g}); retry later",
            )
        if self.queue.depth(tenant) >= self.max_backlog_per_tenant:
            GATEWAY_DENIALS.labels(tenant=tenant, reason="backlog").inc()
            GATEWAY_REQUESTS.labels(outcome="denied_backlog").inc()
            _flightrec.record(
                "gateway.denied", tenant=tenant, reason="backlog"
            )
            return overload_error(
                tenant,
                f"backlog full ({self.max_backlog_per_tenant} queued); "
                "retry later",
            )
        return None
