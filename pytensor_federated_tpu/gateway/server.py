"""The gateway accept tier: one address, thousands of downstream
connections, a few upstream windows.

This is the front door ROADMAP item 1 calls "what turns a replica pool
into a *service*": downstream clients speak the exact npwire/TCP
framing they already speak to a node (``u32 length + npwire frame``,
:mod:`..service.tcp`), so a :class:`~..service.tcp.TcpArraysClient`
pointed at the gateway works unchanged — including the zero-item
batch-frame capability probe and pipelined ``evaluate_many``.  Behind
the accept loop, the gateway re-multiplexes every connection's
requests into a small number of upstream BATCH-FRAME windows against a
:class:`~..routing.pool.NodePool` — the driver-side twin of the PR-3
MicroBatcher: thousands of downstream sockets, a handful of upstream
syscalls.

Design points (docs/gateway.md is the narrative version):

- **Zero payload decode.**  Requests pass through as opaque npwire
  frames: admission reads only the cheap fixed-offset peeks
  (:func:`~..service.npwire.peek_deadline`,
  :func:`~..service.npwire.peek_tenant`,
  :func:`~..service.npwire.frame_uuid`), and upstream windows nest the
  original frames via :func:`~..service.npwire.encode_batch`.  Replies
  route back by per-item uuid, still encoded.
- **Deadline propagation.**  An arriving frame's remaining budget is
  pinned to an absolute monotonic instant; expired work is shed
  IN-BAND (the :mod:`..service.deadline` classification) at arrival,
  again pre-coalesce when it expires in the queue, and the upstream
  frame is restamped with the window's best remaining budget so node
  admission sees truth, not the client's stale stamp.
- **Per-tenant fairness.**  :mod:`.fairness` meters quotas and orders
  dispatch (DRR); denials are loud in-band errors naming the tenant.
- **Per-connection FIFO replies.**  Downstream clients correlate
  replies by order + uuid (the lock-step npwire contract), so each
  connection has a writer coroutine that emits replies strictly in
  request-arrival order even though upstream windows complete out of
  order.
- **Byte-capped coalescing.**  A window closes at ``frame_items``
  requests or :data:`WINDOW_BYTE_CAP` bytes (the transport stack's
  32 KiB in-flight cap) — whichever comes first; mid-batch upstream
  errors fail only their own window, with one budgeted failover
  attempt through the pool (:meth:`~..routing.pool.NodePool.allow_retry`).

Every wait is bounded (graftlint ``unbounded-wait`` covers this
package): downstream payload reads, upstream round-trips, and reply
futures all sit under ``asyncio.wait_for``; only the idle
next-request header wait is unbounded, exactly like the node's own
frame loop.
"""

from __future__ import annotations

import asyncio
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..faultinject import runtime as _fi
from ..routing.pool import NodePool, Replica
from ..service import deadline as _deadline
from ..service.npwire import (
    WireError,
    decode_batch,
    encode_arrays,
    encode_batch,
    fast_uuid,
    frame_uuid,
    is_batch_frame,
    peek_deadline,
    peek_partition,
    peek_tenant,
)
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from .fairness import (
    GATEWAY_REQUESTS,
    GATEWAY_SHED,
    TenantFairness,
    overload_error,
)

__all__ = ["GatewayServer", "GatewayThread", "serve_gateway"]

#: Upstream window byte cap — the same 32 KiB in-flight bound the
#: pipelined transport clients enforce (service/tcp.py), so a window
#: of coalesced requests can never deadlock a node's socket buffers.
WINDOW_BYTE_CAP = 32 * 1024

#: One per-connection reply-channel entry: (builder resolving to the
#: reply payload, fallback building a well-formed in-band error frame
#: with the request's own uuid/kind should the builder outrun the
#: reply ceiling).
_ReplyEntry = Tuple[Callable[[], Any], Callable[[], bytes]]

_GATEWAY_CONNECTIONS = _metrics.gauge(
    "pftpu_gateway_connections",
    "Downstream connections currently held by the gateway",
)
_GATEWAY_WINDOW_REQS = _metrics.histogram(
    "pftpu_gateway_window_requests",
    "Requests coalesced into one upstream window frame",
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
_GATEWAY_UPSTREAM_S = _metrics.histogram(
    "pftpu_gateway_upstream_seconds",
    "Upstream window round-trip latency",
)
_GATEWAY_QUEUE_WAIT_S = _metrics.histogram(
    "pftpu_gateway_queue_wait_seconds",
    "Time a request spends in the fair queue before dispatch",
)


class _Pending:
    """One downstream request riding the gateway: the still-encoded
    frame, its admission metadata, and the future its reply lands on."""

    __slots__ = (
        "frame", "uuid", "tenant", "deadline_mono", "enq_t", "future",
        "attempts",
    )

    def __init__(
        self,
        frame: bytes,
        uuid: bytes,
        tenant: str,
        deadline_mono: Optional[float],
        future: "asyncio.Future[bytes]",
    ) -> None:
        self.frame = frame
        self.uuid = uuid
        self.tenant = tenant
        self.deadline_mono = deadline_mono
        self.enq_t = time.monotonic()
        self.future = future
        self.attempts = 0

    def remaining_s(self, now: float) -> Optional[float]:
        if self.deadline_mono is None:
            return None
        return self.deadline_mono - now


class _Upstream:
    """One upstream connection: lock-step batch-frame windows against a
    single replica (the npwire FIFO contract — one window in flight per
    connection; parallelism comes from the pool's width)."""

    def __init__(
        self, host: str, port: int, connect_timeout_s: float
    ) -> None:
        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _connect(self) -> None:
        if self._writer is None:
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                timeout=self.connect_timeout_s,
            )

    async def close(self) -> None:
        writer = self._writer
        self._reader = None
        self._writer = None
        if writer is not None:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def window(self, frame: bytes, timeout_s: float) -> bytes:
        """One batch frame out, one batch reply back.  A failure of any
        kind closes the connection (desynchronized by definition) and
        re-raises for the caller's failover logic."""
        async with self._lock:
            try:
                await self._connect()
                assert self._reader is not None
                assert self._writer is not None
                if _fi.active_plan is not None:  # chaos seam
                    frame = await _fi.filter_bytes_async(
                        "gateway.upstream.send", frame,
                        f"{self.host}:{self.port}",
                    )
                self._writer.write(struct.pack("<I", len(frame)) + frame)
                await asyncio.wait_for(
                    self._writer.drain(), timeout=timeout_s
                )
                hdr = await asyncio.wait_for(
                    self._reader.readexactly(4), timeout=timeout_s
                )
                (n,) = struct.unpack("<I", hdr)
                reply = await asyncio.wait_for(
                    self._reader.readexactly(n), timeout=timeout_s
                )
                if _fi.active_plan is not None:  # chaos seam
                    reply = await _fi.filter_bytes_async(
                        "gateway.upstream.recv", reply,
                        f"{self.host}:{self.port}",
                    )
                return reply
            except Exception:
                await self.close()
                raise


class GatewayServer:
    """The front door: accept downstream npwire connections, coalesce
    into upstream pool windows, with per-tenant fairness.

    ``pool``: the upstream :class:`~..routing.pool.NodePool` (tcp/shm
    replicas answer the batch-frame protocol; the gateway speaks raw
    npwire regardless of the replica's registered transport client).
    ``fairness``: a :class:`~.fairness.TenantFairness` (default: no
    quotas, equal weights).  ``default_tenant`` labels frames carrying
    no tenant block.  ``frame_items``/``window_byte_cap`` bound one
    upstream window; ``upstream_timeout_s`` bounds each upstream
    round-trip; ``reply_timeout_s`` is the per-request ceiling after
    which a queued reply future is answered with an in-band error
    (belt-and-suspenders: every path that can resolve it is already
    bounded).

    ``denial_pause_s`` is DENIAL PACING: after a frame from a
    connection is quota/backlog-denied, the accept loop pauses that
    one connection's reads for the interval before taking its next
    frame.  Without it a flooding tenant converts the gateway's own
    denial throughput into a DoS vector — every denied frame still
    costs the loop a peek and a reply, so a deep pipelined flood of
    denials crowds out well-behaved tenants' frames on the shared
    loop.  The pause scales with the number of denials the frame drew
    (a BATCH frame of K denied items pays ~K pauses, capped at
    :data:`MAX_DENIAL_PAUSE_S` — otherwise wrapping the flood in
    batch frames would amortize one pause across hundreds of
    denials), so a denied connection degrades to roughly
    ``1/denial_pause_s`` REQUESTS/s however framed (and kernel TCP
    backpressure stalls its sender), while connections that are never
    denied never pause (bench_suite config 18's hog lane measures
    exactly this).

    ``downstream_frame_timeout_s`` bounds reading ONE frame's payload
    after its length prefix arrives (a peer that goes silent
    mid-frame) — deliberately its own knob: tuning the upstream
    window bound must not silently disconnect slow downstream
    senders."""

    #: Ceiling on one accumulated denial pause — reads must always
    #: make progress so the connection can drain and close.
    MAX_DENIAL_PAUSE_S = 5.0

    def __init__(
        self,
        pool: NodePool,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        fairness: Optional[TenantFairness] = None,
        default_tenant: str = "default",
        frame_items: int = 32,
        window_byte_cap: int = WINDOW_BYTE_CAP,
        upstream_timeout_s: float = 30.0,
        reply_timeout_s: float = 120.0,
        connect_timeout_s: float = 5.0,
        max_dispatch_tasks: int = 8,
        backlog: int = 1024,
        denial_pause_s: float = 0.05,
        downstream_frame_timeout_s: float = 30.0,
    ) -> None:
        self.pool = pool
        self.host = host
        self.port = int(port)
        self.fairness = fairness or TenantFairness()
        self.default_tenant = default_tenant
        self.frame_items = int(frame_items)
        self.window_byte_cap = int(window_byte_cap)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.reply_timeout_s = float(reply_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_dispatch_tasks = int(max_dispatch_tasks)
        self.backlog = int(backlog)
        self.denial_pause_s = float(denial_pause_s)
        self.downstream_frame_timeout_s = float(downstream_frame_timeout_s)
        self._server: Optional[asyncio.AbstractServer] = None
        self._dispatcher: Optional["asyncio.Task[None]"] = None
        self._work = asyncio.Event()
        self._stopping = False
        self._upstreams: Dict[str, _Upstream] = {}
        self._tasks: "set[asyncio.Task[Any]]" = set()
        # Rolling counters the autoscaler differences into rates.
        self.stats: Dict[str, int] = {
            "accepted": 0, "ok": 0, "shed": 0, "denied": 0, "failed": 0,
        }

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> int:
        """Bind and serve; returns the bound port."""
        # A 10k-connection front door must not refuse a connect burst
        # at the kernel's default SYN backlog.
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port,
            backlog=self.backlog,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._dispatcher = asyncio.get_running_loop().create_task(
            self._dispatch_loop()
        )
        _flightrec.record(
            "gateway.started", addr=f"{self.host}:{self.port}",
            replicas=len(self.pool),
        )
        return self.port

    async def stop(self) -> None:
        self._stopping = True
        self._work.set()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._dispatcher is not None:
            self._dispatcher.cancel()
            try:
                await self._dispatcher
            except (asyncio.CancelledError, Exception):
                pass
        for task in list(self._tasks):
            task.cancel()
        for upstream in self._upstreams.values():
            await upstream.close()
        self._upstreams.clear()
        _flightrec.record("gateway.stopped")

    def signals(self) -> Dict[str, float]:
        """The autoscaler's observation surface: queue depth + rolling
        outcome counters (difference across calls for rates)."""
        out: Dict[str, float] = {
            "queue_depth": float(self.fairness.queue.depth()),
        }
        out.update({k: float(v) for k, v in self.stats.items()})
        return out

    # -- downstream: accept + reply ordering ------------------------------

    async def _handle_conn(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        _GATEWAY_CONNECTIONS.inc()
        # FIFO reply channel: entries are (builder, fallback) pairs —
        # the builder resolves to the reply payload in strict
        # request-arrival order; the fallback builds a WELL-FORMED
        # in-band error frame (right uuid, right frame kind) should
        # the builder outrun the reply ceiling.
        replies: "asyncio.Queue[Optional[_ReplyEntry]]" = (
            asyncio.Queue()
        )
        writer_task = asyncio.get_running_loop().create_task(
            self._conn_writer(writer, replies)
        )
        self._tasks.add(writer_task)
        writer_task.add_done_callback(self._tasks.discard)
        try:
            while not self._stopping:
                try:
                    # Idle wait for the NEXT request: unbounded on
                    # purpose, like the node's own frame loop; the
                    # mid-frame payload read below is bounded.
                    hdr = await reader.readexactly(4)
                    (n,) = struct.unpack("<I", hdr)
                    payload = await asyncio.wait_for(
                        reader.readexactly(n),
                        timeout=self.downstream_frame_timeout_s,
                    )
                except (
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                    ConnectionError,
                    OSError,
                ):
                    break
                if _fi.active_plan is not None:  # chaos seam
                    try:
                        payload = await _fi.filter_bytes_async(
                            "gateway.recv", payload
                        )
                    except (ConnectionError, OSError):
                        break
                denied_before = self.stats["denied"]
                await self._ingest(payload, replies)
                pause = self._denial_pause_for(
                    self.stats["denied"] - denied_before
                )
                if pause > 0:
                    # Denial pacing (class docstring): this connection
                    # just drew denials — read its next frame at a
                    # trickle so a flood of denials cannot crowd the
                    # loop; never-denied connections never pause.
                    await asyncio.sleep(pause)
        finally:
            await replies.put(None)  # writer drains then exits
            try:
                await asyncio.wait_for(writer_task, timeout=5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                writer_task.cancel()
            except (ConnectionError, OSError):
                pass
            try:
                writer.close()
            except (ConnectionError, OSError):
                pass
            _GATEWAY_CONNECTIONS.dec()

    async def _conn_writer(
        self,
        writer: asyncio.StreamWriter,
        replies: "asyncio.Queue[Optional[_ReplyEntry]]",
    ) -> None:
        """Emit replies in strict arrival order; each entry's awaitable
        is bounded by ``reply_timeout_s``, and a fired ceiling answers
        with the entry's own fallback frame — the request's real uuid
        and frame kind, so the downstream client reads a correlated
        in-band error instead of desynchronizing on a zeroed one."""
        while True:
            entry = await replies.get()
            if entry is None:
                return
            factory, fallback = entry
            try:
                payload = await asyncio.wait_for(
                    factory(), timeout=self.reply_timeout_s
                )
            except asyncio.TimeoutError:
                GATEWAY_SHED.labels(reason="reply_timeout").inc()
                self.stats["failed"] += 1
                payload = fallback()
            try:
                writer.write(struct.pack("<I", len(payload)) + payload)
                await writer.drain()
            except (ConnectionError, OSError):
                # Downstream left; keep draining entries so pending
                # futures don't leak unobserved-exception warnings.
                continue

    # -- admission --------------------------------------------------------

    def _denial_pause_for(self, denied_delta: int) -> float:
        """The read pause one frame's denials earn: per-denial, so a
        batch frame of K denied items pays ~K pauses instead of
        amortizing one pause across the whole flood (class docstring);
        capped so the connection always keeps draining."""
        if self.denial_pause_s <= 0 or denied_delta <= 0:
            return 0.0
        return min(
            self.denial_pause_s * denied_delta, self.MAX_DENIAL_PAUSE_S
        )

    def _shed_reply(
        self, frame: bytes, *, batch: bool, error: str
    ) -> bytes:
        try:
            uid = frame_uuid(frame)
        except WireError:
            uid = b"\0" * 16
        if batch:
            return encode_batch([], uuid=uid, error=error)
        return encode_arrays([], uuid=uid, error=error)

    async def _ingest(
        self,
        payload: bytes,
        replies: "asyncio.Queue[Optional[_ReplyEntry]]",
    ) -> None:
        """Admit one downstream frame: probe echo, per-item admission
        for batch frames, plain admission otherwise.  Always enqueues
        exactly ONE reply entry, preserving arrival order."""

        def immediate(payload_bytes: bytes) -> "_ReplyEntry":
            async def done() -> bytes:
                return payload_bytes
            return done, lambda: payload_bytes

        if is_batch_frame(payload):
            try:
                items, outer_uuid, _err, _tid, _sp = decode_batch(payload)
            except WireError as e:
                GATEWAY_REQUESTS.labels(outcome="bad_frame").inc()
                await replies.put(immediate(self._shed_reply(
                    payload, batch=True, error=f"decode error: {e}"
                )))
                return
            try:
                reduce_part = peek_partition(payload)
            except WireError:
                reduce_part = None
            if reduce_part is not None:
                # A REDUCE window (outer partition block, ISSUE 13):
                # the gateway coalesces PER ITEM across tenants, which
                # would silently decompose the caller's partial-sum
                # contract — refuse loudly instead (reduce windows
                # ride direct tcp/shm pools or aggregator trees).
                GATEWAY_REQUESTS.labels(outcome="bad_frame").inc()
                await replies.put(immediate(encode_batch(
                    [], uuid=outer_uuid,
                    error=(
                        "partition reduce windows are not served "
                        "through the gateway (dial a tcp/shm pool or "
                        "an aggregator tree directly)"
                    ),
                )))
                return
            if not items:
                # The capability/liveness probe: answer it ourselves —
                # the gateway IS batch-capable by construction.
                await replies.put(immediate(
                    encode_batch([], uuid=outer_uuid)
                ))
                return
            futures = [
                self._admit_item(item) for item in items
            ]

            async def gather_batch() -> bytes:
                parts = await asyncio.gather(*futures)
                return encode_batch(list(parts), uuid=outer_uuid)

            def batch_fallback() -> bytes:
                return encode_batch(
                    [], uuid=outer_uuid,
                    error=overload_error(
                        "*", "gateway reply ceiling exceeded"
                    ),
                )

            await replies.put((gather_batch, batch_fallback))
            return
        fut = self._admit_item(payload)
        try:
            uid = frame_uuid(payload)
        except WireError:
            uid = b"\0" * 16  # fut already resolved with the decode error

        def plain_fallback(uid: bytes = uid) -> bytes:
            return encode_arrays(
                [], uuid=uid,
                error=overload_error(
                    "*", "gateway reply ceiling exceeded"
                ),
            )

        await replies.put(((lambda: fut), plain_fallback))

    def _admit_item(self, frame: bytes) -> "asyncio.Future[bytes]":
        """Admission for ONE request frame -> future of its reply frame
        (resolved immediately for sheds/denials)."""
        loop = asyncio.get_running_loop()
        future: "asyncio.Future[bytes]" = loop.create_future()
        self.stats["accepted"] += 1
        try:
            uid = frame_uuid(frame)
            budget = peek_deadline(frame)
            tenant = peek_tenant(frame) or self.default_tenant
        except WireError as e:
            GATEWAY_REQUESTS.labels(outcome="bad_frame").inc()
            future.set_result(self._shed_reply(
                frame, batch=False, error=f"decode error: {e}"
            ))
            return future
        if budget is not None and budget <= 0.0:
            # Expired before the gateway ever saw it: shed pre-queue.
            GATEWAY_SHED.labels(reason="expired_arrival").inc()
            GATEWAY_REQUESTS.labels(outcome="shed_expired").inc()
            self.stats["shed"] += 1
            _flightrec.record(
                "gateway.shed", reason="expired_arrival", tenant=tenant
            )
            future.set_result(encode_arrays(
                [], uuid=uid,
                error=_deadline.deadline_error(
                    "budget spent before gateway admission"
                ),
            ))
            return future
        denial = self.fairness.admit(tenant)
        if denial is not None:
            self.stats["denied"] += 1
            future.set_result(
                encode_arrays([], uuid=uid, error=denial)
            )
            return future
        GATEWAY_REQUESTS.labels(outcome="admitted").inc()
        deadline_mono = (
            None if budget is None else time.monotonic() + budget
        )
        self.fairness.queue.push(
            tenant, _Pending(frame, uid, tenant, deadline_mono, future)
        )
        self._work.set()
        return future

    # -- upstream dispatch ------------------------------------------------

    async def _dispatch_loop(self) -> None:
        """Drain the fair queue into upstream windows.  One collection
        loop; windows run as concurrent tasks bounded by
        ``max_dispatch_tasks`` (parallelism across replicas)."""
        sem = asyncio.Semaphore(self.max_dispatch_tasks)
        while not self._stopping:
            window = self._collect_window()
            if not window:
                self._work.clear()
                try:
                    # Bounded idle tick so shutdown is never waited on
                    # forever (unbounded-wait posture).
                    await asyncio.wait_for(self._work.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    pass
                continue
            await sem.acquire()
            task = asyncio.get_running_loop().create_task(
                self._run_window(window, sem)
            )
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    def _collect_window(self) -> List[_Pending]:
        """Pop up to ``frame_items``/``window_byte_cap`` of fair-queued
        work, shedding entries whose deadline expired while queued
        (pre-coalesce: expired work must never ride an upstream
        window)."""
        window: List[_Pending] = []
        nbytes = 0
        now = time.monotonic()
        while len(window) < self.frame_items:
            popped = self.fairness.queue.pop()
            if popped is None:
                break
            _tenant, item = popped
            pending = item  # type: ignore[assignment]
            assert isinstance(pending, _Pending)
            remaining = pending.remaining_s(now)
            if remaining is not None and remaining <= 0.0:
                GATEWAY_SHED.labels(reason="expired_queued").inc()
                GATEWAY_REQUESTS.labels(outcome="shed_expired").inc()
                self.stats["shed"] += 1
                _flightrec.record(
                    "gateway.shed", reason="expired_queued",
                    tenant=pending.tenant,
                )
                if not pending.future.done():
                    pending.future.set_result(encode_arrays(
                        [], uuid=pending.uuid,
                        error=_deadline.deadline_error(
                            "budget spent in the gateway queue"
                        ),
                    ))
                continue
            _GATEWAY_QUEUE_WAIT_S.observe(now - pending.enq_t)
            if window and nbytes + len(pending.frame) > self.window_byte_cap:
                # Byte cap reached: the entry leads the NEXT window —
                # head re-insert, so the tenant's own FIFO order holds
                # and a large frame cannot be deferred forever behind
                # its smaller siblings.
                self.fairness.queue.push_front(pending.tenant, pending)
                break
            window.append(pending)
            nbytes += len(pending.frame)
        return window

    def _upstream_for(self, replica: Replica) -> _Upstream:
        upstream = self._upstreams.get(replica.address)
        if upstream is None:
            upstream = self._upstreams[replica.address] = _Upstream(
                replica.host, replica.port, self.connect_timeout_s
            )
        return upstream

    def _window_budget_s(self, window: Sequence[_Pending]) -> Optional[float]:
        """The batch frame's outer deadline stamp: the window's BEST
        remaining budget (min would shed viable work with one expired
        sibling; expired items were already shed pre-coalesce)."""
        now = time.monotonic()
        remains = [
            r for r in (p.remaining_s(now) for p in window) if r is not None
        ]
        if len(remains) < len(window):
            return None  # an unbounded item keeps the window admitted
        return max(remains) if remains else None

    async def _run_window(
        self, window: List[_Pending], sem: asyncio.Semaphore
    ) -> None:
        try:
            await self._run_window_inner(window)
        finally:
            sem.release()
            if self.fairness.queue.depth():
                self._work.set()

    async def _run_window_inner(self, window: List[_Pending]) -> None:
        """Send one coalesced window upstream and route the per-item
        replies home; on transport failure, one budgeted failover
        attempt through the pool, then loud in-band errors."""
        excluded: List[str] = []
        for attempt in range(2):
            picked = self.pool.pick(1, exclude=excluded)
            if not picked:
                self._fail_window(
                    window,
                    overload_error(
                        "*", "no upstream replica available; retry later"
                    ),
                    reason="no_upstream",
                )
                return
            replica = picked[0]
            budget = self._window_budget_s(window)
            outer_uuid = fast_uuid()
            frame = encode_batch(
                [p.frame for p in window],
                uuid=outer_uuid,
                deadline_s=budget,
            )
            _GATEWAY_WINDOW_REQS.observe(len(window))
            timeout = self.upstream_timeout_s
            if budget is not None:
                timeout = min(timeout, budget + 1.0)
            t0 = time.perf_counter()
            try:
                reply = await self._upstream_for(replica).window(
                    frame, timeout
                )
                items, ruid, outer_err, _tid, _sp = decode_batch(reply)
            except (
                WireError,
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                ConnectionError,
                OSError,
            ) as e:
                self.pool.record_result(replica, False)
                _flightrec.record(
                    "gateway.upstream_failed",
                    replica=replica.address,
                    error=f"{type(e).__name__}: {str(e)[:120]}",
                )
                excluded.append(replica.address)
                if attempt == 0 and self.pool.allow_retry(
                    "gateway_failover"
                ):
                    continue
                self._fail_window(
                    window,
                    overload_error(
                        "*",
                        f"upstream {replica.address} failed "
                        f"({type(e).__name__}); retry later",
                    ),
                    reason="upstream_failed",
                )
                return
            latency = time.perf_counter() - t0
            _GATEWAY_UPSTREAM_S.observe(latency)
            self.pool.record_result(
                replica, True, latency_s=latency, n_requests=len(window)
            )
            if outer_err is not None or ruid != outer_uuid:
                # Outer-level failure (node admission shed, decode
                # error): cover the whole window in-band.
                err = outer_err or "upstream reply did not correlate"
                self._fail_window(window, err, reason="upstream_error")
                return
            by_uuid: Dict[bytes, bytes] = {}
            for item in items:
                try:
                    by_uuid[frame_uuid(item)] = item
                except WireError:
                    continue
            for pending in window:
                reply_item = by_uuid.get(pending.uuid)
                if reply_item is None:
                    reply_item = encode_arrays(
                        [], uuid=pending.uuid,
                        error="gateway: upstream reply missing this item",
                    )
                    self.stats["failed"] += 1
                else:
                    self.stats["ok"] += 1
                if not pending.future.done():
                    pending.future.set_result(reply_item)
            return

    def _fail_window(
        self, window: Sequence[_Pending], error: str, *, reason: str
    ) -> None:
        GATEWAY_SHED.labels(reason=reason).inc()
        for pending in window:
            self.stats["failed"] += 1
            if not pending.future.done():
                pending.future.set_result(
                    encode_arrays(
                        [], uuid=pending.uuid, error=error
                    )
                )


class GatewayThread:
    """Run a :class:`GatewayServer` on a dedicated event-loop thread —
    the embedding tests, benchmarks, and the chaos harness use (the
    gateway is asyncio-native; the rest of the harness usually is
    not).  ``start()`` blocks until the port is bound."""

    def __init__(self, pool: NodePool, **kwargs: Any) -> None:
        self.pool = pool
        self.kwargs = kwargs
        self.server: Optional[GatewayServer] = None
        self.port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._error: Optional[BaseException] = None

    def start(self, timeout_s: float = 30.0) -> int:
        self._thread = threading.Thread(
            target=self._run, name="pftpu-gateway", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout_s):
            raise RuntimeError("gateway thread did not come up")
        if self._error is not None:
            raise RuntimeError(
                f"gateway failed to start: {self._error}"
            ) from self._error
        assert self.port is not None
        return self.port

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        self.server = GatewayServer(self.pool, **self.kwargs)

        async def main() -> None:
            try:
                self.port = await self.server.start()  # type: ignore[union-attr]
            except BaseException as e:  # startup failure -> caller
                self._error = e
                raise
            finally:
                self._ready.set()

        try:
            loop.run_until_complete(main())
            loop.run_forever()
        except BaseException:
            pass
        finally:
            try:
                loop.close()
            except Exception:
                pass

    def stop(self, timeout_s: float = 10.0) -> None:
        loop = self._loop
        if loop is None or not loop.is_running():
            return

        async def shutdown() -> None:
            if self.server is not None:
                await self.server.stop()
            asyncio.get_running_loop().stop()

        asyncio.run_coroutine_threadsafe(shutdown(), loop)
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self) -> "GatewayThread":
        self.start()
        return self

    def __exit__(self, *exc: object) -> None:
        self.stop()


def serve_gateway(
    replicas: Sequence[Tuple[str, int]],
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ready_callback: Optional[Callable[[int], None]] = None,
    pool_kwargs: Optional[Dict[str, Any]] = None,
    **kwargs: Any,
) -> None:
    """Blocking gateway entry point for subprocess deployment (the
    chaos harness and bench configs spawn this): builds a TCP
    :class:`~..routing.pool.NodePool` over ``replicas``, starts the
    background probe loop, and serves forever."""
    pool = NodePool(
        list(replicas), transport="tcp", **(pool_kwargs or {})
    )
    pool.start()

    async def main() -> None:
        server = GatewayServer(pool, host=host, port=port, **kwargs)
        bound = await server.start()
        if ready_callback is not None:
            ready_callback(bound)
        # graftlint: disable=unbounded-spin -- sleeping forever IS the idle state of a blocking serve_* entrypoint; the gateway's lanes are deadline-bounded
        while True:
            await asyncio.sleep(3600.0)

    asyncio.run(main())
