"""Checkpoint / resume for long-running sampling jobs.

Net-new durability subsystem — the reference has none (SURVEY §5:
"Checkpoint / resume: none"; its evaluations are stateless and only the
uuid correlation guards pairing, reference: rpc.py:37-50).  On TPU the
expensive artifact is the *chain state* of a long MCMC run (plus its
adaptation results), so this module provides:

- :func:`save_pytree` / :func:`load_pytree` — atomic on-disk snapshots
  of any JAX/numpy pytree (``.npz`` + JSON metadata; write-to-temp +
  ``os.replace`` so a crash mid-write never corrupts the previous
  checkpoint).
- :func:`sample_checkpointed` — the chunked, resumable front door:
  warmup runs once, then sampling proceeds in chunks of
  ``checkpoint_every`` draws, persisting (kernel state, RNG position,
  draws-so-far, adaptation results) after every chunk.  Killing the
  process at any point and calling the same function again resumes from
  the last chunk boundary and produces **bit-identical draws** to an
  uninterrupted run (chunk keys are ``fold_in(key, chunk_index)``, so
  the stream does not depend on where the interruption happened).

Orbax is the right tool for multi-host sharded checkpoints of huge
states; for the sampler-state scale (KBs-MBs, single host) a plain
npz keeps zero non-baked dependencies.  The layout is
orbax-compatible in spirit: one directory per run, one file per step.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_META_KEY = "__pft_metadata__"


def save_pytree(path: str, tree: Any, metadata: Optional[dict] = None) -> None:
    """Atomically snapshot a pytree of arrays (+ JSON metadata) to ``path``.

    Leaves are stored positionally (``leaf_0..leaf_N``); restore with
    :func:`load_pytree` and a structurally identical ``like`` tree.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    payload = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    payload[_META_KEY] = np.frombuffer(
        json.dumps(metadata or {}).encode(), dtype=np.uint8
    )
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_pytree(path: str, like: Any) -> Tuple[Any, dict]:
    """Load a :func:`save_pytree` snapshot into the structure of ``like``.

    Returns ``(tree, metadata)``.  Leaf count must match ``like``;
    dtypes/shapes come from the file.
    """
    with np.load(path) as data:
        metadata = json.loads(bytes(data[_META_KEY].tobytes()).decode())
        leaves, treedef = jax.tree_util.tree_flatten(like)
        n = len(leaves)
        stored = [data[f"leaf_{i}"] for i in range(n)]
        if f"leaf_{n}" in data.files:
            raise ValueError(
                f"checkpoint {path} has more leaves than `like` "
                f"(structure mismatch)"
            )
    return jax.tree_util.tree_unflatten(treedef, stored), metadata


def sample_checkpointed(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    checkpoint_path: str,
    num_warmup: int = 500,
    num_samples: int = 500,
    num_chains: int = 4,
    checkpoint_every: int = 100,
    kernel: str = "nuts",
    max_depth: int = 8,
    target_accept: float = 0.8,
    jitter: float = 1.0,
    logp_and_grad_fn: Optional[Callable] = None,
):
    """Resumable NUTS/HMC sampling with periodic on-disk checkpoints.

    Same posterior contract as :func:`~pytensor_federated_tpu.samplers.sample`
    but the draw loop is chunked: after every ``checkpoint_every`` draws
    the full sampler state is persisted to ``checkpoint_path``.  If that
    file already exists (and its config hash matches), sampling resumes
    after the last completed chunk instead of starting over.  The
    resulting draws are bit-identical to an uninterrupted run.

    Returns a :class:`~pytensor_federated_tpu.samplers.mcmc.SampleResult`.
    """
    from functools import partial

    from .samplers.hmc import HMCState, hmc_step
    from .samplers.mcmc import SampleResult, _warmup
    from .samplers.nuts import nuts_step
    from .samplers.util import flatten_logp

    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dtype = flat_init.dtype
    dim = flat_init.shape[0]

    if logp_and_grad_fn is not None:
        from jax.flatten_util import ravel_pytree

        def lg(x):
            v, g = logp_and_grad_fn(unravel(x))
            return v, ravel_pytree(g)[0]

    else:

        def lg(x):
            return jax.value_and_grad(flat_logp)(x)

    if kernel == "nuts":
        kernel_step = partial(nuts_step, lg, max_depth=max_depth)
    elif kernel == "hmc":
        kernel_step = partial(hmc_step, lg, num_steps=16)
    else:
        raise ValueError(f"unknown kernel {kernel!r} (nuts or hmc)")

    n_chunks = -(-num_samples // checkpoint_every)  # ceil
    config = {
        "num_warmup": num_warmup,
        "num_samples": num_samples,
        "num_chains": num_chains,
        "checkpoint_every": checkpoint_every,
        "kernel": kernel,
        "dim": dim,
    }

    k_jit, k_warm, k_base = jax.random.split(key, 3)

    # ---- state template (for load_pytree structure) ----
    def template():
        return {
            "x": jnp.zeros((num_chains, dim), dtype),
            "logp": jnp.zeros((num_chains,), dtype),
            "grad": jnp.zeros((num_chains, dim), dtype),
            "step_size": jnp.zeros((num_chains,), dtype),
            "inv_mass": jnp.zeros((num_chains, dim), dtype),
            "draws": jnp.zeros(
                (num_chains, n_chunks * checkpoint_every, dim), dtype
            ),
            "accept_prob": jnp.zeros(
                (num_chains, n_chunks * checkpoint_every), dtype
            ),
            "diverging": jnp.zeros(
                (num_chains, n_chunks * checkpoint_every), bool
            ),
        }

    resumed = None
    if os.path.exists(checkpoint_path):
        state, meta = load_pytree(checkpoint_path, template())
        if meta.get("config") == config:
            resumed = (state, int(meta["chunks_done"]))
        # Config mismatch: ignore the stale file and start fresh.

    if resumed is None:
        init_flat = jnp.broadcast_to(flat_init, (num_chains, dim))
        if jitter:
            init_flat = init_flat + jitter * jax.random.normal(
                k_jit, init_flat.shape, dtype
            )

        warm = jax.jit(
            jax.vmap(
                lambda x0, k: _warmup(
                    lg,
                    x0,
                    k,
                    num_warmup=num_warmup,
                    kernel_step=kernel_step,
                    target_accept=target_accept,
                )
            )
        )(init_flat, jax.random.split(k_warm, num_chains))
        state = template()
        state["x"] = warm.state.x
        state["logp"] = warm.state.logp
        state["grad"] = warm.state.grad
        state["step_size"] = warm.step_size
        state["inv_mass"] = warm.inv_mass
        chunks_done = 0
        save_pytree(
            checkpoint_path,
            state,
            {"config": config, "chunks_done": 0},
        )
    else:
        state, chunks_done = resumed

    @jax.jit
    def run_chunk(state, chunk_idx):
        """checkpoint_every draws for all chains; keys derived from
        (base key, chunk index, chain) — interruption-invariant."""

        def one_chain(hmc, step_size, inv_mass, keys):
            def body(s, k):
                s, info = kernel_step(
                    s, k, step_size=step_size, inv_mass=inv_mass
                )
                return s, (s.x, info.accept_prob, info.diverging)

            return jax.lax.scan(body, hmc, keys)

        chunk_key = jax.random.fold_in(k_base, chunk_idx)
        keys = jax.random.split(
            chunk_key, (num_chains, checkpoint_every)
        )
        hmc = HMCState(state["x"], state["logp"], state["grad"])
        hmc, (xs, aps, divs) = jax.vmap(one_chain)(
            hmc, state["step_size"], state["inv_mass"], keys
        )
        lo = chunk_idx * checkpoint_every
        out = dict(state)
        out["x"], out["logp"], out["grad"] = hmc.x, hmc.logp, hmc.grad
        # xs: (chains, chunk, dim) — scan gives (chunk, dim), vmap prepends chains.
        out["draws"] = jax.lax.dynamic_update_slice(
            state["draws"], xs, (0, lo, 0)
        )
        out["accept_prob"] = jax.lax.dynamic_update_slice(
            state["accept_prob"], aps, (0, lo)
        )
        out["diverging"] = jax.lax.dynamic_update_slice(
            state["diverging"], divs, (0, lo)
        )
        return out

    for chunk in range(chunks_done, n_chunks):
        state = jax.device_get(run_chunk(state, chunk))
        save_pytree(
            checkpoint_path,
            state,
            {"config": config, "chunks_done": chunk + 1},
        )

    draws = jnp.asarray(state["draws"])[:, :num_samples]
    samples = jax.vmap(jax.vmap(unravel))(draws)
    return SampleResult(
        samples=samples,
        stats={
            "accept_prob": jnp.asarray(state["accept_prob"])[:, :num_samples],
            "diverging": jnp.asarray(state["diverging"])[:, :num_samples],
        },
        step_size=jnp.asarray(state["step_size"]),
        inv_mass=jnp.asarray(state["inv_mass"]),
    )
