"""Checkpoint / resume for long-running sampling jobs.

Net-new durability subsystem — the reference has none (SURVEY §5:
"Checkpoint / resume: none"; its evaluations are stateless and only the
uuid correlation guards pairing, reference: rpc.py:37-50).  On TPU the
expensive artifact is the *chain state* of a long MCMC run (plus its
adaptation results), so this module provides:

- :func:`save_pytree` / :func:`load_pytree` — atomic on-disk snapshots
  of any JAX/numpy pytree (``.npz`` + JSON metadata; write-to-temp +
  ``os.replace`` so a crash mid-write never corrupts the previous
  checkpoint).
- :func:`sample_checkpointed` — the chunked, resumable front door:
  warmup runs once, then sampling proceeds in chunks of
  ``checkpoint_every`` draws.  After every chunk the small kernel state
  is re-persisted and that chunk's draws are written to their own file
  (``<path>.chunk0000.npz``, ...) — total I/O is O(total draws), not
  O(chunks x total draws).  Killing the process at any point and
  calling the same function again resumes after the last completed
  chunk and produces **bit-identical draws** to an uninterrupted run
  (chunk keys are ``fold_in(key, chunk_index)``, so the stream does not
  depend on where the interruption happened).  A checkpoint whose
  recorded config (including the RNG key and kernel settings) does not
  match the call is ignored and sampling restarts fresh.

Orbax is the right tool for multi-host sharded checkpoints of huge
states; for the sampler-state scale (KBs-MBs, single host) a plain npz
keeps zero non-baked dependencies, with the same one-file-per-step
layout in spirit.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_META_KEY = "__pft_metadata__"


def save_pytree(path: str, tree: Any, metadata: Optional[dict] = None) -> None:
    """Atomically snapshot a pytree of arrays (+ JSON metadata) to ``path``.

    Leaves are stored positionally (``leaf_0..leaf_N``); restore with
    :func:`load_pytree` and a structurally identical ``like`` tree.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    payload = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    payload[_META_KEY] = np.frombuffer(
        json.dumps(metadata or {}).encode(), dtype=np.uint8
    )
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_pytree(path: str, like: Any) -> Tuple[Any, dict]:
    """Load a :func:`save_pytree` snapshot into the structure of ``like``.

    Returns ``(tree, metadata)``.  Raises ``ValueError`` on leaf-count
    mismatch in either direction (structure mismatch); dtypes/shapes
    come from the file.
    """
    with np.load(path) as data:
        metadata = json.loads(bytes(data[_META_KEY].tobytes()).decode())
        leaves, treedef = jax.tree_util.tree_flatten(like)
        n = len(leaves)
        n_stored = sum(1 for f in data.files if f.startswith("leaf_"))
        if n_stored != n:
            raise ValueError(
                f"checkpoint {path} has {n_stored} leaves, `like` has {n} "
                f"(structure mismatch)"
            )
        stored = [data[f"leaf_{i}"] for i in range(n)]
    return jax.tree_util.tree_unflatten(treedef, stored), metadata


def _chunk_path(checkpoint_path: str, i: int) -> str:
    return f"{checkpoint_path}.chunk{i:04d}.npz"


def _key_fingerprint(key: jax.Array) -> list:
    """JSON-serializable identity of a PRNG key (part of the resume
    config: resuming under a different key must restart, not stitch)."""
    return np.asarray(jax.random.key_data(key)).ravel().tolist()


def sample_checkpointed(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    checkpoint_path: str,
    num_warmup: int = 500,
    num_samples: int = 500,
    num_chains: int = 4,
    checkpoint_every: int = 100,
    kernel: str = "nuts",
    max_depth: int = 8,
    num_hmc_steps: int = 16,
    target_accept: float = 0.8,
    jitter: float = 1.0,
    logp_and_grad_fn: Optional[Callable] = None,
    dense_mass: bool = False,
):
    """Resumable NUTS/HMC sampling with periodic on-disk checkpoints.

    Same posterior contract as :func:`~pytensor_federated_tpu.samplers.sample`
    (gradient kernels only — "nuts"/"hmc") but the draw loop is chunked:
    after every ``checkpoint_every`` draws the kernel state is persisted
    to ``checkpoint_path`` and the chunk's draws to a per-chunk sidecar
    file.  If a matching checkpoint exists, sampling resumes after the
    last completed chunk; the result is bit-identical to an
    uninterrupted run.

    Returns a :class:`~pytensor_federated_tpu.samplers.mcmc.SampleResult`.
    """
    from .samplers.hmc import HMCState
    from .samplers.mcmc import (
        SampleResult,
        _warmup,
        make_flat_logp_and_grad,
        make_kernel_step,
    )

    _, flat_init, unravel, lg = make_flat_logp_and_grad(
        logp_fn, init_params, logp_and_grad_fn
    )
    dtype = flat_init.dtype
    dim = flat_init.shape[0]
    kernel_step = make_kernel_step(
        lg, kernel, max_depth=max_depth, num_hmc_steps=num_hmc_steps
    )
    if kernel not in ("nuts", "hmc"):  # pragma: no cover (make_kernel_step raises)
        raise ValueError(kernel)

    n_chunks = -(-num_samples // checkpoint_every)  # ceil
    config = {
        "key": _key_fingerprint(key),
        "num_warmup": num_warmup,
        "num_samples": num_samples,
        "num_chains": num_chains,
        "checkpoint_every": checkpoint_every,
        "kernel": kernel,
        "max_depth": max_depth,
        "num_hmc_steps": num_hmc_steps,
        "target_accept": target_accept,
        "jitter": jitter,
        "dim": dim,
        # Part of the resume identity: a diagonal-mass checkpoint must
        # not be stitched into a dense-mass run (the state shapes and
        # the kernel differ).
        "dense_mass": dense_mass,
    }

    # Config keys added after a release, with the default value older
    # checkpoints implicitly ran with.  A stored config lacking one of
    # these keys is still compatible iff the current run uses the
    # default — otherwise a routine version upgrade would silently
    # discard every pre-existing checkpoint.
    _added_config_defaults = {"dense_mass": False}

    def _config_compatible(stored) -> bool:
        if stored == config:
            return True
        if not isinstance(stored, dict):
            return False
        for k, cur in config.items():
            if k in stored:
                if stored[k] != cur:
                    return False
            elif (
                k not in _added_config_defaults
                or cur != _added_config_defaults[k]
            ):
                return False
        return all(k in config for k in stored)

    k_jit, k_warm, k_base = jax.random.split(key, 3)

    def state_template():
        return {
            "x": jnp.zeros((num_chains, dim), dtype),
            "logp": jnp.zeros((num_chains,), dtype),
            "grad": jnp.zeros((num_chains, dim), dtype),
            "step_size": jnp.zeros((num_chains,), dtype),
            "inv_mass": jnp.zeros(
                (num_chains, dim, dim) if dense_mass else (num_chains, dim),
                dtype,
            ),
        }

    def chunk_template():
        return {
            "draws": jnp.zeros((num_chains, checkpoint_every, dim), dtype),
            "accept_prob": jnp.zeros((num_chains, checkpoint_every), dtype),
            "diverging": jnp.zeros((num_chains, checkpoint_every), bool),
        }

    # ---- resume or fresh start ----
    resumed = None
    if os.path.exists(checkpoint_path):
        try:
            state, meta = load_pytree(checkpoint_path, state_template())
            if _config_compatible(meta.get("config")):
                chunks_done = int(meta["chunks_done"])
                chunks = [
                    load_pytree(_chunk_path(checkpoint_path, i), chunk_template())[0]
                    for i in range(chunks_done)
                ]
                resumed = (state, chunks_done, chunks)
            else:
                logging.getLogger(__name__).warning(
                    "discarding checkpoint %s: stored sampling config does "
                    "not match the current run; restarting from scratch",
                    checkpoint_path,
                )
        except (ValueError, KeyError, OSError):
            # Stale/foreign/partial checkpoint: restart fresh.
            resumed = None

    if resumed is None:
        init_flat = jnp.broadcast_to(flat_init, (num_chains, dim))
        if jitter:
            init_flat = init_flat + jitter * jax.random.normal(
                k_jit, init_flat.shape, dtype
            )
        warm = jax.jit(
            jax.vmap(
                lambda x0, k: _warmup(
                    lg,
                    x0,
                    k,
                    num_warmup=num_warmup,
                    kernel_step=kernel_step,
                    target_accept=target_accept,
                    dense_mass=dense_mass,
                )
            )
        )(init_flat, jax.random.split(k_warm, num_chains))
        state = {
            "x": warm.state.x,
            "logp": warm.state.logp,
            "grad": warm.state.grad,
            "step_size": warm.step_size,
            "inv_mass": warm.inv_mass,
        }
        chunks_done, chunks = 0, []
        save_pytree(
            checkpoint_path, state, {"config": config, "chunks_done": 0}
        )
    else:
        state, chunks_done, chunks = resumed

    @jax.jit
    def run_chunk(state, chunk_idx):
        """checkpoint_every draws for all chains; keys derived from
        (base key, chunk index, chain) — interruption-invariant."""

        def one_chain(hmc, step_size, inv_mass, keys):
            def body(s, k):
                s, info = kernel_step(
                    s, k, step_size=step_size, inv_mass=inv_mass
                )
                return s, (s.x, info.accept_prob, info.diverging)

            return jax.lax.scan(body, hmc, keys)

        chunk_key = jax.random.fold_in(k_base, chunk_idx)
        keys = jax.random.split(chunk_key, (num_chains, checkpoint_every))
        hmc = HMCState(state["x"], state["logp"], state["grad"])
        hmc, (xs, aps, divs) = jax.vmap(one_chain)(
            hmc, state["step_size"], state["inv_mass"], keys
        )
        new_state = dict(state)
        new_state["x"], new_state["logp"], new_state["grad"] = (
            hmc.x,
            hmc.logp,
            hmc.grad,
        )
        # xs: (chains, chunk, dim) — scan yields (chunk, ...), vmap prepends.
        return new_state, {"draws": xs, "accept_prob": aps, "diverging": divs}

    for i in range(chunks_done, n_chunks):
        state, chunk = jax.device_get(run_chunk(state, i))
        save_pytree(_chunk_path(checkpoint_path, i), chunk)
        save_pytree(
            checkpoint_path, state, {"config": config, "chunks_done": i + 1}
        )
        chunks.append(chunk)

    draws = jnp.concatenate([c["draws"] for c in chunks], axis=1)[
        :, :num_samples
    ]
    samples = jax.vmap(jax.vmap(unravel))(draws)
    return SampleResult(
        samples=samples,
        stats={
            "accept_prob": jnp.concatenate(
                [c["accept_prob"] for c in chunks], axis=1
            )[:, :num_samples],
            "diverging": jnp.concatenate(
                [c["diverging"] for c in chunks], axis=1
            )[:, :num_samples],
        },
        step_size=jnp.asarray(state["step_size"]),
        inv_mass=jnp.asarray(state["inv_mass"]),
    )
