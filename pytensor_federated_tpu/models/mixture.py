"""Federated Gaussian mixtures: shared components, per-site weights.

Density estimation across sites whose populations mix the SAME latent
subgroups in DIFFERENT proportions — the canonical cross-site
heterogeneity structure (e.g. patient subtypes with site-specific
case mixes):

    y_ij ~ Σ_k  π_ik  N(mu_k, sigma_k)      (k = 1..K components)
    π_i  = softmax(logits_i)                 per shard i
    mu, sigma shared across shards

Component labels are marginalized (one ``logsumexp`` per observation —
no discrete latents, so NUTS applies directly), and the component
means are ORDERED by construction (``mu_0`` + positive increments,
the models/ordinal.py cutpoint device) which removes label-switching:
every point of the unconstrained state space is one identifiable
mixture.

Priors: ``mu_0 ~ N(0, prior_scale)``, increments LogNormal(0,1) (their
log-Jacobian joins the prior), ``log_sigma_k ~ N(0,1)`` (LogNormal
scales), per-shard weight logits ``~ N(0,1)`` (a proper prior directly
on the unconstrained parameterization, so no transform Jacobian is
involved).

TPU notes: the per-observation work is a ``(n, K)`` broadcast +
``logsumexp`` — pure VPU elementwise/reduction, batched over shards
under vmap/shard_map; no data-dependent control flow.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp
from .linear import _normal_logpdf

__all__ = [
    "FederatedGaussianMixture",
    "generate_mixture_data",
    "mixture_loglik",
]


def generate_mixture_data(
    n_shards: int = 8,
    *,
    n_obs: int = 128,
    mus=(-2.0, 0.5, 3.0),
    sigmas=(0.5, 0.7, 0.6),
    concentration: float = 2.0,
    seed: int = 47,
):
    """Per-shard draws from shared components with Dirichlet per-shard
    weights."""
    rng = np.random.default_rng(seed)
    mus = np.asarray(mus, np.float64)
    sigmas = np.asarray(sigmas, np.float64)
    K = mus.size
    weights = rng.dirichlet(np.full(K, concentration), size=n_shards)
    shards = []
    for i in range(n_shards):
        z = rng.choice(K, size=n_obs, p=weights[i])
        y = (mus[z] + sigmas[z] * rng.normal(size=n_obs)).astype(np.float32)
        shards.append((y,))
    truth = {"mu": mus, "sigma": sigmas, "weights": weights}
    return pack_shards(shards, pad_to_multiple=8), truth


def mixture_loglik(y, log_w, mu, sigma):
    """Marginalized per-observation mixture log-density.

    ``y``: (n,), ``log_w``: (K,) normalized log-weights, ``mu``/
    ``sigma``: (K,).  One (n, K) broadcast + logsumexp."""
    comp = (
        _normal_logpdf(y[:, None], mu[None, :], sigma[None, :])
        + log_w[None, :]
    )
    return jax.scipy.special.logsumexp(comp, axis=1)


@dataclasses.dataclass
class FederatedGaussianMixture:
    """K shared Gaussian components, per-shard mixing weights."""

    data: ShardedData
    n_components: int
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0

    def __post_init__(self):
        (y,), mask = self.data.tree()
        n = y.shape[0]
        shard_ids = jnp.arange(n, dtype=jnp.int32)

        def per_shard_logp(params, shard):
            (y,), mask, sid = shard
            mu, sigma = self._components(params)
            logits = jnp.take(params["weight_logits"], sid, axis=0)
            log_w = jax.nn.log_softmax(logits)
            ll = mixture_loglik(y, log_w, mu, sigma)
            return jnp.sum(ll * mask)

        self.fed = FederatedLogp(
            per_shard_logp, ((y,), mask, shard_ids), mesh=self.mesh
        )
        self.n_shards = n

    @staticmethod
    def _components(params):
        """Ordered means (mu0 + positive increments) and scales."""
        mu0 = params["mu0"]
        incr = jnp.exp(params["log_incr"])
        mu = jnp.concatenate([mu0[None], mu0 + jnp.cumsum(incr)])
        return mu, jnp.exp(params["log_sigma"])

    def prior_logp(self, params: Any) -> jax.Array:
        lp = _normal_logpdf(params["mu0"], 0.0, self.prior_scale)
        # LogNormal(0,1) increments: N(0,1) density on log_incr IS the
        # prior on the unconstrained coordinate (no extra Jacobian).
        lp += jnp.sum(_normal_logpdf(params["log_incr"], 0.0, 1.0))
        lp += jnp.sum(_normal_logpdf(params["log_sigma"], 0.0, 1.0))
        lp += jnp.sum(_normal_logpdf(params["weight_logits"], 0.0, 1.0))
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def weights(self, params: Any) -> jax.Array:
        """Implied per-shard mixing proportions ``(n_shards, K)``."""
        return jax.nn.softmax(params["weight_logits"], axis=-1)

    def pointwise_loglik(self, params: Any) -> jax.Array:
        (y,), mask = self.data.tree()
        mu, sigma = self._components(params)
        log_w = jax.nn.log_softmax(params["weight_logits"], axis=-1)

        def one(y_i, lw_i):
            return mixture_loglik(y_i, lw_i, mu, sigma)

        return jax.vmap(one)(y, log_w) * mask

    def predictive(self, params: Any, key) -> jax.Array:
        """Simulate replicated data (padded slots zeroed)."""
        (y,), mask = self.data.tree()
        mu, sigma = self._components(params)
        k_z, k_e = jax.random.split(key)
        logits = params["weight_logits"]  # (S, K)
        z = jax.random.categorical(
            k_z, logits[:, None, :], axis=-1, shape=y.shape
        )
        eps = jax.random.normal(k_e, y.shape)
        return (jnp.take(mu, z) + jnp.take(sigma, z) * eps) * mask

    def init_params(self) -> Any:
        K = self.n_components
        (y,), mask = self.data.tree()
        spread = float(np.std(np.asarray(y)[np.asarray(mask) > 0]) + 1e-3)
        return {
            "mu0": jnp.asarray(
                float(np.min(np.asarray(y)[np.asarray(mask) > 0]))
            ),
            "log_incr": jnp.full((K - 1,), float(np.log(spread))),
            "log_sigma": jnp.full((K,), float(np.log(0.5 * spread))),
            "weight_logits": jnp.zeros((self.n_shards, K)),
        }

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
