"""Federated Gamma regression (log link) — positive continuous outcomes.

Completes the classical GLM set on the shared hierarchical base
(Gaussian: linear.py/glm.py, Bernoulli: logistic.py, Poisson/NB:
countdata.py, Student-t: robust.py): durations, costs, concentrations —
strictly positive, right-skewed data.

Shape/mean ("alpha/mu") parameterization:

    y_ij ~ Gamma(shape=alpha, rate=alpha / mu_ij),  mu_ij = exp(eta_ij)

so ``E[y] = mu`` and ``Var[y] = mu^2 / alpha`` — the GLM dispersion
form; alpha is shared and log-parameterized (HalfNormal(10) prior).

TPU notes: same hot shape as the siblings (batched ``X @ w`` on the
MXU); the density needs ``log``/``gammaln`` only.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from .hierbase import HierarchicalGLMBase, log_halfnormal_draw

__all__ = [
    "FederatedGammaGLM",
    "gamma_logpdf",
    "generate_gamma_data",
]


def generate_gamma_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    tau: float = 0.3,
    alpha: float = 3.0,
    seed: int = 29,
):
    """Per-shard positive outcomes with log-link mean structure."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0.0, 0.4, size=n_features)
    b0_true = 0.5
    b_true = b0_true + tau * rng.normal(size=n_shards)
    shards = []
    for i in range(n_shards):
        X = rng.normal(0.0, 1.0, size=(n_obs, n_features)).astype(np.float32)
        mu = np.exp(b_true[i] + X @ w_true)
        y = rng.gamma(alpha, mu / alpha)
        shards.append((X, y.astype(np.float32)))
    truth = {"w": w_true, "b0": b0_true, "b": b_true, "alpha": alpha}
    return pack_shards(shards, pad_to_multiple=8), truth


def gamma_logpdf(y, eta, alpha):
    """log Gamma(y | shape=alpha, rate=alpha/exp(eta)), in log space.

    ``log rate = log(alpha) - eta`` never forms ``exp(eta)`` directly,
    and the rate-term exponent is clamped (like poisson_logpmf) so an
    extreme proposal yields a huge-but-finite negative logp with
    finite gradients.  Padded rows carry y=0, where ``log y`` would be
    -inf; ``y`` is floored at the dtype's tiny so those rows stay
    FINITE (large-negative) and the ``ll * mask`` zeroing in the
    shared base cannot form ``0 * inf = NaN``.
    """
    log_rate = jnp.log(alpha) - eta
    safe_y = jnp.maximum(y, jnp.finfo(jnp.result_type(y)).tiny)
    log_y = jnp.log(safe_y)
    # rate*y computed as exp(log_rate + log y) with the WHOLE exponent
    # clamped — clamping log_rate alone still overflows for large y
    # (y * e^80 > f32 max for y > ~6e3), and overflow here means NaN
    # gradients, not a clean rejection.
    return (
        alpha * log_rate
        + (alpha - 1.0) * log_y
        - jnp.exp(jnp.minimum(log_rate + log_y, 80.0))
        - gammaln(alpha)
    )


@dataclasses.dataclass
class FederatedGammaGLM(HierarchicalGLMBase):
    """Hierarchical Gamma regression over federated shards."""

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        alpha = jnp.exp(params["log_alpha"])
        return gamma_logpdf(y, eta, alpha)

    def _sample_obs(self, params, key, eta):
        alpha = jnp.exp(params["log_alpha"])
        return jax.random.gamma(key, alpha, eta.shape) * (
            jnp.exp(eta) / alpha
        )

    def prior_logp(self, params: Any) -> jax.Array:
        lp = super().prior_logp(params)
        # HalfNormal(10) on alpha (log-param + Jacobian).
        alpha = jnp.exp(params["log_alpha"])
        lp += -0.5 * (alpha / 10.0) ** 2 + params["log_alpha"]
        return lp

    def init_params(self) -> Any:
        p = super().init_params()
        p["log_alpha"] = jnp.array(0.5)
        return p

    def _sample_extra_params(self, key) -> dict:
        # HalfNormal(10) on alpha, matching prior_logp.
        return {"log_alpha": log_halfnormal_draw(key, 10.0)}
