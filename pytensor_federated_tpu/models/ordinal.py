"""Federated ordinal regression: cumulative-logit (proportional odds).

Ordered categorical outcomes (severity grades, ratings, stages) over
federated shards.  Cumulative-logit model with shared slopes, ordered
cutpoints, and the usual non-centered per-shard intercept:

    P(y_ij <= c) = sigmoid(kappa_c - eta_ij),   c = 0..C-2
    eta_ij = x_ij . w + tau * b_raw_i           (no global intercept:
                                                 it is absorbed by the
                                                 cutpoints, which are
                                                 only identified
                                                 relative to eta)
    P(y = c) = P(y <= c) - P(y <= c-1)

Cutpoints are parameterized unconstrained as ``kappa_0`` plus
log-increments (``kappa_c = kappa_0 + Σ exp(delta)``) so every point
of the sampler's state space maps to a VALID ordered vector — no
rejection, no constrained optimizer, and the log-Jacobian of the
transform is just ``Σ delta`` (appended to the prior).

Per-observation likelihood in a numerically stable form:

    log P(y=c) = log( sigmoid(ku - eta) - sigmoid(kl - eta) )
               = ku' - softplus(ku') - softplus(kl') + log1p(-exp(ku'-kl'))
      with ku' = kappa_c - eta, kl' = kappa_{c-1} - eta  (kl' < ku')

evaluated via one-hot gather over the C categories (C is small and
static — a ``(n, C)`` matmul-free elementwise block the VPU eats; the
MXU matmul is still the shared ``X @ w``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from .hierbase import HierarchicalGLMBase
from .linear import _normal_logpdf

__all__ = [
    "FederatedOrdinalRegression",
    "cumulative_logit_loglik",
    "generate_ordinal_data",
]


def generate_ordinal_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 3,
    n_categories: int = 4,
    tau: float = 0.3,
    seed: int = 41,
):
    """Per-shard ordered outcomes in {0..C-1} with latent-logistic
    generation (so the cumulative-logit model is well-specified)."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0.0, 0.5, size=n_features)
    b_true = tau * rng.normal(size=n_shards)
    kappa_true = np.sort(rng.normal(0.0, 1.5, size=n_categories - 1))
    shards = []
    for i in range(n_shards):
        X = rng.normal(0.0, 1.0, size=(n_obs, n_features)).astype(np.float32)
        eta = X @ w_true + b_true[i]
        u = rng.logistic(size=n_obs)
        y = np.sum((eta + u)[:, None] > kappa_true[None, :], axis=1)
        shards.append((X, y.astype(np.float32)))
    truth = {"w": w_true, "b": b_true, "kappa": kappa_true}
    return pack_shards(shards, pad_to_multiple=8), truth


def cumulative_logit_loglik(y, eta, kappa):
    """log P(y | eta, kappa) per observation, branch-free.

    ``kappa`` is the ordered cutpoint vector ``(C-1,)``; categories are
    handled by padding with ∓inf-like sentinels and a one-hot gather:
    ``log[ sigmoid(ku-eta) - sigmoid(kl-eta) ]`` with the stable
    log-difference-of-sigmoids identity.
    """
    C = kappa.shape[0] + 1
    big = jnp.asarray(1e30, kappa.dtype)
    upper = jnp.concatenate([kappa, big[None]])  # (C,)
    lower = jnp.concatenate([-big[None], kappa])  # (C,)
    yi = y.astype(jnp.int32)
    ku = jnp.take(upper, yi) - eta
    kl = jnp.take(lower, yi) - eta
    # log[σ(ku) - σ(kl)] = -softplus(-ku) - softplus(kl)
    #                      + log1p(-exp(-(ku - kl)))      (kl < ku)
    gap = jnp.maximum(ku - kl, 1e-6)
    return (
        -jax.nn.softplus(-ku)
        - jax.nn.softplus(kl)
        + jnp.log1p(-jnp.exp(-gap))
    )


@dataclasses.dataclass
class FederatedOrdinalRegression(HierarchicalGLMBase):
    """Proportional-odds model over federated shards.

    Built on the shared hierarchical base with NO global intercept
    (``_has_global_intercept = False``): it is absorbed by the
    cutpoints, which are only identified relative to ``eta``.
    """

    data: ShardedData
    n_categories: int
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase
    _init_log_tau = -1.0
    _has_global_intercept = False

    def __post_init__(self):
        (_X, y), mask = self.data.tree()
        y_real = np.asarray(y)[np.asarray(mask) > 0]
        # jnp.take silently CLAMPS out-of-range indices, fitting a
        # confidently wrong model — validate the whole coding up front.
        if y_real.size and (
            y_real.max() >= self.n_categories or y_real.min() < 0
        ):
            raise ValueError(
                f"observed categories span [{y_real.min():.0f}, "
                f"{y_real.max():.0f}]; need 0..n_categories-1 with "
                f"n_categories={self.n_categories}"
            )
        if y_real.size and np.any(y_real != np.round(y_real)):
            raise ValueError("ordinal outcomes must be integer-coded")
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        return cumulative_logit_loglik(y, eta, self._kappa(params))

    def _sample_obs(self, params, key, eta):
        u = jax.random.logistic(key, eta.shape)
        kappa = self._kappa(params)
        y = jnp.sum((eta + u)[..., None] > kappa, axis=-1)
        return y.astype(eta.dtype)

    @staticmethod
    def _kappa(params):
        """Ordered cutpoints from the unconstrained parameterization:
        ``kappa_0`` free, increments strictly positive via exp."""
        k0 = params["kappa0"]
        incr = jnp.exp(params["log_incr"])
        return jnp.concatenate([k0[None], k0 + jnp.cumsum(incr)])

    def prior_logp(self, params: Any) -> jax.Array:
        lp = super().prior_logp(params)
        # Normal(0, 3) prior on each ordered cutpoint + the transform's
        # log-Jacobian (lower-triangular: det = prod exp(log_incr)).
        kappa = self._kappa(params)
        lp += jnp.sum(_normal_logpdf(kappa, 0.0, 3.0))
        lp += jnp.sum(params["log_incr"])
        return lp

    def init_params(self) -> Any:
        p = super().init_params()
        p["kappa0"] = jnp.array(-1.0)
        p["log_incr"] = jnp.zeros((self.n_categories - 2,))
        return p

    def _sample_extra_params(self, key) -> dict:
        # prior_logp scores Normal(0,3) on each ORDERED cutpoint plus
        # the transform Jacobian: the induced prior on kappa is iid
        # N(0,3) conditioned on being sorted, so the exact draw is
        # sort(iid draws) mapped back to (kappa0, log increments).
        k = jnp.sort(
            3.0 * jax.random.normal(key, (self.n_categories - 1,))
        )
        return {
            "kappa0": k[0],
            "log_incr": jnp.log(
                jnp.diff(k) + jnp.finfo(jnp.float32).tiny
            ),
        }
