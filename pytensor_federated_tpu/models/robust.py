"""Federated robust (Student-t) regression.

Gaussian likelihoods hand outliers quadratic influence; a Student-t
observation model caps it, so a few corrupted observations on one
federated shard cannot drag the shared slopes (a real failure mode for
federation: one node's bad sensor poisons everyone's posterior).

Model: the shared hierarchical-GLM structure (models/hierbase.py) with

    y_ij ~ StudentT(nu, loc=eta_ij, scale=sigma)

and log-parameterized ``sigma`` (HalfNormal(1) prior) and ``nu``
(shifted so nu > 1; Exponential(1/10) prior on nu - 1 keeps the mean
defined while letting the data choose tail weight — nu -> large
recovers the Gaussian model).

TPU notes: identical hot shape to the other families (batched
``X @ w`` matvec on the MXU); the t-density needs only ``log1p`` and
``gammaln`` — VPU transcendentals, no branches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from .hierbase import HierarchicalGLMBase, log_halfnormal_draw

__all__ = [
    "FederatedRobustRegression",
    "generate_robust_data",
    "student_t_logpdf",
]


def generate_robust_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    tau: float = 0.3,
    outlier_frac: float = 0.1,
    outlier_scale: float = 10.0,
    seed: int = 23,
):
    """Per-shard Gaussian data with a fraction of gross outliers —
    the scenario the t-likelihood exists for."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0.0, 0.5, size=n_features)
    b0_true = 0.5
    b_true = b0_true + tau * rng.normal(size=n_shards)
    shards = []
    for i in range(n_shards):
        X = rng.normal(0.0, 1.0, size=(n_obs, n_features)).astype(np.float32)
        y = b_true[i] + X @ w_true + 0.5 * rng.normal(size=n_obs)
        n_out = int(outlier_frac * n_obs)
        if n_out:
            idx = rng.choice(n_obs, size=n_out, replace=False)
            y[idx] += outlier_scale * rng.standard_cauchy(size=n_out)
        shards.append((X, y.astype(np.float32)))
    truth = {"w": w_true, "b0": b0_true, "b": b_true}
    return pack_shards(shards, pad_to_multiple=8), truth


def student_t_logpdf(y, loc, scale, nu):
    """log StudentT(y | nu, loc, scale), branch-free."""
    z = (y - loc) / scale
    half_nu = 0.5 * nu
    return (
        gammaln(half_nu + 0.5)
        - gammaln(half_nu)
        - 0.5 * jnp.log(nu * jnp.pi)
        - jnp.log(scale)
        - (half_nu + 0.5) * jnp.log1p(z * z / nu)
    )


@dataclasses.dataclass
class FederatedRobustRegression(HierarchicalGLMBase):
    """Hierarchical Student-t regression over federated shards."""

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        sigma = jnp.exp(params["log_sigma"])
        nu = 1.0 + jnp.exp(params["log_numinus1"])
        return student_t_logpdf(y, eta, sigma, nu)

    def _sample_obs(self, params, key, eta):
        sigma = jnp.exp(params["log_sigma"])
        nu = 1.0 + jnp.exp(params["log_numinus1"])
        return eta + sigma * jax.random.t(key, nu, eta.shape)

    def prior_logp(self, params: Any) -> jax.Array:
        lp = super().prior_logp(params)
        # HalfNormal(1) on sigma (log-param + Jacobian).
        sigma = jnp.exp(params["log_sigma"])
        lp += -0.5 * sigma**2 + params["log_sigma"]
        # Exponential(rate=1/10) on nu - 1 (log-param + Jacobian):
        # weakly favors heavy tails but lets nu grow if data are clean.
        numinus1 = jnp.exp(params["log_numinus1"])
        lp += -numinus1 / 10.0 + params["log_numinus1"]
        return lp

    def init_params(self) -> Any:
        p = super().init_params()
        p["log_sigma"] = jnp.zeros(())
        p["log_numinus1"] = jnp.array(1.0)
        return p

    def nu(self, params: Any) -> jax.Array:
        """The implied degrees of freedom."""
        return 1.0 + jnp.exp(params["log_numinus1"])

    def _sample_extra_params(self, key) -> dict:
        k1, k2 = jax.random.split(key)
        return {
            # HalfNormal(1) sigma; Exponential(1/10) on nu - 1.
            "log_sigma": log_halfnormal_draw(k1),
            "log_numinus1": jnp.log(
                10.0 * jax.random.exponential(k2)
                + jnp.finfo(jnp.float32).tiny
            ),
        }
