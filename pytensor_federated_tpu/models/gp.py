"""Federated sparse Gaussian-process regression (inducing points).

Net-new model family.  A full GP likelihood couples every observation
with every other — the one structure plain sum-of-shards federation
(reference: demo_model.py:34-36) cannot express.  The inducing-point
(SGPR/VFE, Titsias 2009) formulation factors that coupling through M
global inducing locations, and the collapsed bound decomposes into
per-shard *moment statistics*:

    A_i = K_zf^(i) K_fz^(i)      (M x M)
    b_i = K_zf^(i) y^(i)         (M,)
    c_i = Σ_j k(x_j, x_j),  y2_i = Σ_j y_j², n_i = |shard i|

which are exactly ``psum``-reducible — the same collective as the
linear model, but each shard's contribution is a dense MXU matmul
(M x n_i times n_i x M) instead of an elementwise reduction.  The
driver finishes with an M x M Cholesky (tiny, replicated).

Collapsed VFE bound (what :meth:`FederatedSparseGP.logp` returns, up to
the exact marginal of the Nyström approximation plus trace correction):

    L = -1/2 [ n log(2πσ²) + (y'y - β' B^{-1} β)/σ²
               + log|B| - log|K_zz| + trace_term ]
    B = K_zz + A/σ²,  β = b/σ,  trace_term = (c - tr(K_zz^{-1} A))/σ²

Kernels: squared-exponential (default), Matérn 3/2 and 5/2 (all
stationary with ``k(x, x) = variance``, which the VFE trace residual
relies on) plus the non-stationary ``linear`` trend kernel and
composite specs — ``"sqexp+linear"`` (sum), ``"sqexp*matern32"``
(product) with per-component hyperparameter slots (see
:func:`get_kernel`).  Single kernels support 1-D or (n, d) inputs with
ARD lengthscales.  Learned ``log_variance``, ``log_lengthscale``,
``log_noise`` (unconstrained; vector-shaped for composites, see
:func:`kernel_hyper_shape`).  All math float32, jitter-stabilized
Choleskys.  The sparse family rejects ``linear``-containing specs at
construction (non-constant prior diagonal breaks the VFE residual);
the exact family accepts every spec.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.mesh import SHARDS_AXIS
from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp
from ..utils import LOG_2PI

_JITTER = 1e-4  # float32 Cholesky needs real jitter (relative to variance)


def _jitter_scale(variance):
    """Scalar magnitude for jitter terms: composite kernels carry a
    VECTOR variance (one slot per component).  The jitter needs a
    positive scale AT LEAST the kernel diagonal's order: sum bounds
    sum-composites, product bounds product-composites (whose diagonal
    scales multiplicatively — summing alone under-jitters them), and
    max(sum, prod) covers both without knowing the spec; a slightly
    generous jitter is harmless, an undersized one NaNs the f32
    Cholesky.  Single kernels: sum == prod == variance, bit-identical
    to the scalar case."""
    v = jnp.atleast_1d(variance)
    return jnp.maximum(jnp.sum(v), jnp.prod(v))


#: Posterior covariances at or above this order route their draw
#: Cholesky through the ISSUE-19 blocked factorization when the values
#: are concrete (outside any trace).  Below it the dense jnp kernel
#: wins on dispatch overhead; tests shrink it to gate the two paths
#: against each other on the same matrix.
_BLOCKED_CHOL_MIN = 256


def _posterior_chol(cov, vjit, policy=None, *, block: int = 128):
    """Jitter-stabilized Cholesky of a posterior covariance.

    A CONCRETE 2-D covariance of order >= :data:`_BLOCKED_CHOL_MIN`
    factors through :func:`...linalg.cholesky` (the blocked
    right-looking path — distributable over a block-store pool, and
    policy-routed per f32-strict); traced values (inside
    ``jit``/``vmap``), batched covariances, and small matrices stay on
    ``jnp.linalg.cholesky``.  The two paths are equality-gated against
    each other in tests/test_gp.py, so the dispatch can never silently
    change the posterior draws.
    """
    from ..fed.primitives import is_tracer

    n = cov.shape[-1]
    if (
        cov.ndim == 2
        and n >= _BLOCKED_CHOL_MIN
        and not (is_tracer(cov) or is_tracer(vjit))
    ):
        from ..linalg import cholesky as _blocked_cholesky

        a = np.asarray(cov)
        a = a + np.asarray(vjit, dtype=a.dtype) * np.eye(n, dtype=a.dtype)
        return jnp.asarray(_blocked_cholesky(a, block=block, policy=policy))
    return jnp.linalg.cholesky(cov + vjit * jnp.eye(n, dtype=cov.dtype))


def _masked_cov(x, mask, variance, lengthscale, noise, kern=None):
    """Masked exact-GP covariance with identity rows on padded slots.

    Real block: K + (noise^2 + jitter*var) I; padded rows/cols become
    exact e_i rows (diag 1, off-diag 0) so each padded slot contributes
    logN(0|0,1) to a Gaussian quadratic/logdet — removable analytically.
    THE one implementation: the likelihood and the posterior must build
    the same matrix or predictions silently diverge from the fitted
    hyperparameters."""
    n = x.shape[0]
    mm = mask[:, None] * mask[None, :]
    kern = kern or _sqexp
    vjit = _JITTER * _jitter_scale(variance)
    k = kern(x, x, variance, lengthscale) * mm
    k = k + (noise**2 + vjit) * jnp.eye(n)
    return k + (1.0 - mask) * (1.0 - noise**2 - vjit) * jnp.eye(n)


def generate_gp_data(
    n_shards: int = 8,
    *,
    n_obs: int = 128,
    lengthscale: float = 0.4,
    variance: float = 1.0,
    noise: float = 0.1,
    seed: int = 42,
) -> tuple[ShardedData, np.ndarray]:
    """Per-shard (x, y) drawn from one global GP sample path.

    All shards observe the *same* latent function at private input
    locations — the federated-GP setting; returns the packed shards and
    the dense (x, y) pool for golden-model comparison.
    """
    rng = np.random.default_rng(seed)
    n_total = n_shards * n_obs
    x = np.sort(rng.uniform(-2.0, 2.0, size=n_total)).astype(np.float32)
    d2 = (x[:, None] - x[None, :]) ** 2
    k = variance * np.exp(-0.5 * d2 / lengthscale**2)
    # Eigh-based sampling: robust to the (numerically singular) kernel
    # of many closely spaced points, unlike Cholesky.
    w, q = np.linalg.eigh(k.astype(np.float64))
    f = q @ (np.sqrt(np.clip(w, 0.0, None)) * rng.normal(size=n_total))
    y = (f + noise * rng.normal(size=n_total)).astype(np.float32)
    order = rng.permutation(n_total)
    shards = [
        (x[order[i::n_shards]], y[order[i::n_shards]]) for i in range(n_shards)
    ]
    packed = pack_shards(shards)
    return packed, np.stack([x, y])


def _sq_dist(x1, x2, lengthscale, policy=None):
    """Pairwise SQUARED scaled distance — the one ndim-dispatch +
    validation + MXU-expansion implementation every kernel shares.
    ``policy``: f32 contraction policy (:mod:`..precision`) for the
    2-D branch's cross-term matmul (the 1-D branch has none)."""
    if x1.ndim != x2.ndim:
        raise ValueError(
            f"kernel inputs must have matching ndim, got {x1.ndim} and "
            f"{x2.ndim} — for ARD both must be (n, d); for scalar "
            "covariates both must be (n,)"
        )
    if x1.ndim == 1:
        ls = jnp.asarray(lengthscale)
        if ls.ndim != 0:
            raise ValueError(
                "1-D inputs take a scalar lengthscale; a vector "
                "lengthscale (ARD) needs (n, d) inputs"
            )
        return ((x1[:, None] - x2[None, :]) / ls) ** 2
    from ..precision import pdot

    s1 = x1 / lengthscale  # (n1, d) with (d,) or scalar lengthscale
    s2 = x2 / lengthscale
    sq1 = jnp.sum(s1**2, axis=1)
    sq2 = jnp.sum(s2**2, axis=1)
    d2 = sq1[:, None] + sq2[None, :] - 2.0 * pdot(s1, s2.T, policy)
    return jnp.maximum(d2, 0.0)


def _sqexp(x1, x2, variance, lengthscale, policy=None):
    """Squared-exponential kernel matrix, MXU-friendly distance form.

    Inputs may be 1-D ``(n,)`` (scalar covariate, the demo shape) or
    2-D ``(n, d)``; with 2-D inputs a ``(d,)`` ``lengthscale`` gives
    ARD — one learned scale per input dimension, so irrelevant
    covariates are pruned by their lengthscales growing.  The 2-D
    branch uses the ``|a-b|^2 = |a|^2 + |b|^2 - 2ab`` expansion: the
    cross term is one (n1, d) @ (d, n2) MXU matmul instead of an
    (n1, n2, d) broadcast living in memory.
    """
    return variance * jnp.exp(-0.5 * _sq_dist(x1, x2, lengthscale, policy))


def _unpack(params):
    return (
        jnp.exp(params["log_variance"]),
        jnp.exp(params["log_lengthscale"]),
        jnp.exp(params["log_noise"]),
    )


def _scaled_dist(x1, x2, lengthscale, policy=None):
    """Pairwise scaled Euclidean distance (shared by the Matérn
    kernels).  sqrt'(0) = inf, so the argument is nudged to keep
    zero-distance gradients finite (kernel value error ~1e-6 * ls)."""
    return jnp.sqrt(_sq_dist(x1, x2, lengthscale, policy) + 1e-12)


def _matern32(x1, x2, variance, lengthscale, policy=None):
    """Matérn 3/2: once-differentiable sample paths."""
    r = jnp.sqrt(3.0) * _scaled_dist(x1, x2, lengthscale, policy)
    return variance * (1.0 + r) * jnp.exp(-r)


def _matern52(x1, x2, variance, lengthscale, policy=None):
    """Matérn 5/2: twice-differentiable sample paths."""
    r = jnp.sqrt(5.0) * _scaled_dist(x1, x2, lengthscale, policy)
    return variance * (1.0 + r + r**2 / 3.0) * jnp.exp(-r)


def _linear(x1, x2, variance, lengthscale, policy=None):
    """(Non-stationary) linear kernel ``variance * (x1/ls)·(x2/ls)`` —
    the trend component for composite kernels.  NOTE its diagonal is
    ``variance * |x/ls|²``, not ``variance``, so the VFE trace residual
    of :class:`FederatedSparseGP` (which assumes ``k(x,x) = variance``)
    does not admit it; composites containing "linear" are for the
    exact-GP family (enforced in FederatedSparseGP).
    """
    if x1.ndim == 1:
        ls = jnp.asarray(lengthscale)
        if ls.ndim != 0:
            # Same contract (and message) as _sq_dist: silently
            # broadcasting a vector lengthscale over 1-D inputs would
            # compute a wrong kernel.
            raise ValueError(
                "1-D inputs take a scalar lengthscale; a vector "
                "lengthscale (ARD) needs (n, d) inputs"
            )
        s1 = (x1 / ls)[:, None]
        s2 = (x2 / ls)[:, None]
    else:
        s1 = x1 / lengthscale
        s2 = x2 / lengthscale
    from ..precision import pdot

    return variance * pdot(s1, s2.T, policy)


_KERNELS = {
    "sqexp": _sqexp,
    "matern32": _matern32,
    "matern52": _matern52,
    "linear": _linear,
}


def kernel_components(name: str) -> list:
    """Component names of a (possibly composite) kernel spec.

    Specs are ``"a"``, ``"a+b[+c...]"`` (sum) or ``"a*b[*c...]"``
    (product); mixing ``+`` and ``*`` in one spec is rejected — compose
    in one algebra per model (nesting would need a real expression
    grammar for little modeling gain).
    """
    if "+" in name and "*" in name:
        raise ValueError(
            f"kernel spec {name!r} mixes '+' and '*'; use one combinator"
        )
    parts = name.split("+") if "+" in name else name.split("*")
    for p in parts:
        if p not in _KERNELS:
            raise ValueError(
                f"unknown kernel {p!r} in spec {name!r}; choose from "
                f"{sorted(_KERNELS)}"
            )
    return parts


def kernel_hyper_shape(name: str) -> tuple:
    """Shape of ``log_variance``/``log_lengthscale`` for this spec:
    ``()`` for a single kernel, ``(C,)`` for a C-component composite
    (component i reads hyper slot i)."""
    c = len(kernel_components(name))
    return () if c == 1 else (c,)


def stationary_prior_diag(name: str, variance):
    """The constant ``k(x, x)`` of a STATIONARY kernel spec: the single
    variance, the sum of slots (sum composite) or their product
    (product composite).  Raises for specs containing "linear" — its
    diagonal varies with x, so callers relying on a constant prior
    diagonal (the VFE trace residual) must reject it instead of
    silently computing a wrong correction."""
    parts = kernel_components(name)
    if "linear" in parts:
        raise ValueError(
            f"kernel spec {name!r} contains the non-stationary 'linear' "
            "component: k(x,x) is not constant"
        )
    v = jnp.broadcast_to(jnp.asarray(variance), (len(parts),))
    return jnp.sum(v) if ("+" in name or len(parts) == 1) else jnp.prod(v)


def get_kernel(name: str, policy: str = None):
    """Kernel by spec — single name or "+"/"*" composite.

    Singles: "sqexp", "matern32", "matern52", "linear"; composites:
    "sqexp+linear" (sum), "sqexp*matern32" (product).

    Composite kernels take VECTOR hyperparameters: ``variance`` and
    ``lengthscale`` of shape ``(C,)``, component ``i`` consuming slot
    ``i`` (scalars broadcast to all components).  Composites are
    limited to scalar per-component lengthscales — ARD's per-dimension
    vector lengthscale and per-component slots would collide in one
    array.  Sum composites model additive structure (e.g.
    ``linear+sqexp``: trend plus wiggle); product composites modulate
    one kernel by another.

    ``policy`` (optional): bind an f32 contraction policy
    (:mod:`..precision`) into the kernels' cross-term matmuls; the
    returned callable keeps the 4-arg kernel signature either way.
    A CONCRETE policy (including "default") is bound as-is so the
    kernel never re-consults the env at trace time — models resolve
    the env exactly once, at construction.
    """
    import functools

    parts = kernel_components(name)
    if len(parts) == 1:
        kern = _KERNELS[name]
        if policy is None:
            return kern
        return functools.partial(kern, policy=policy)

    members = [
        _KERNELS[p]
        if policy is None
        else functools.partial(_KERNELS[p], policy=policy)
        for p in parts
    ]
    is_sum = "+" in name
    n = len(members)

    def composite(x1, x2, variance, lengthscale, **kw):
        v = jnp.broadcast_to(jnp.asarray(variance), (n,))
        ls = jnp.broadcast_to(jnp.asarray(lengthscale), (n,))
        out = None
        for i, member in enumerate(members):
            k_i = member(x1, x2, v[i], ls[i], **kw)
            if out is None:
                out = k_i
            elif is_sum:
                out = out + k_i
            else:
                out = out * k_i
        return out

    return composite


class FederatedSparseGP:
    """Collapsed sparse-GP (VFE) marginal likelihood over federated shards.

    ``data`` is a packed ``((x, y), mask)`` shard pytree
    (:func:`~pytensor_federated_tpu.parallel.packing.pack_shards`);
    ``inducing`` are the M global inducing inputs (driver-chosen,
    replicated).  With ``mesh=None`` everything runs single-device; the
    statistics/psum structure is identical either way.

    The per-shard statistic computation is one ``(M, n_i) @ (n_i, M)``
    matmul per shard — large, batched, MXU-shaped — and the only
    cross-shard communication is the psum of ``M² + M + 3`` scalars per
    evaluation, independent of the number of observations.
    """

    def __init__(
        self,
        data: ShardedData,
        inducing: np.ndarray,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = SHARDS_AXIS,
        kernel: str = "sqexp",
        f32_policy: Optional[str] = None,
    ):
        from ..precision import pdot, resolve_policy, wrap_policy

        # None consults PFTPU_F32_POLICY exactly ONCE, here — one
        # concrete policy string then flows to every contraction site
        # (kernel cross term, quadratic forms, decomposition context).
        policy = resolve_policy(f32_policy)
        self.f32_policy = policy
        self.inducing = jnp.asarray(inducing, jnp.float32)
        self.m = int(self.inducing.shape[0])
        self.mesh = mesh
        m = self.m
        z = self.inducing
        self.kernel = kernel
        # The VFE trace residual needs a constant prior diagonal —
        # raises here (at construction, loudly) for "linear"-containing
        # specs; the exact-GP family accepts those.
        stationary_prior_diag(kernel, 1.0)
        kern = get_kernel(kernel, policy=policy)

        def per_shard_stats(params, shard):
            """Whitened statistics — float32-stable by construction.

            With ``L = chol(K_zz)`` and ``V = L^{-1} K_zf`` (whitened
            cross-covariance): ``a = V V'`` (= L^{-1} A L^{-T}),
            ``b = V y``, and the VFE trace residual
            ``Σ_j (k_jj - q_jj)`` accumulated *pointwise* (each summand
            is small and positive — no catastrophic cancellation, unlike
            the naive ``n·var - tr(K_zz^{-1} A)`` difference of two
            O(n·var) quantities).
            """
            (x, y), mask = shard
            variance, lengthscale, _ = _unpack(params)
            kzz = kern(z, z, variance, lengthscale) + _JITTER * _jitter_scale(
                variance
            ) * jnp.eye(m)
            l_kzz = jnp.linalg.cholesky(kzz)
            # Masked (padding) columns are zeroed, so the matmuls below
            # exclude them without any gather/ragged handling.
            kzf = kern(z, x, variance, lengthscale) * mask[None, :]
            v = jax.scipy.linalg.solve_triangular(l_kzz, kzf, lower=True)
            a = pdot(v, v.T, policy)
            b = pdot(v, y * mask, policy)
            q_diag = jnp.sum(v**2, axis=0)  # Nyström diag, per point
            kxx = stationary_prior_diag(kernel, variance)
            resid = jnp.sum(mask * (kxx - q_diag))
            y2 = jnp.sum((y * mask) ** 2)
            n = jnp.sum(mask)
            return {"a": a, "b": b, "resid": resid, "y2": y2, "n": n}

        from ..parallel.sharded import sharded_compute

        stats_fn = sharded_compute(
            per_shard_stats, data.tree(), mesh=mesh, axis=axis
        )
        # Kept for the posterior-prediction path (which reuses the same
        # psum-reducible statistics the likelihood consumes).
        self._stats_fn = stats_fn
        self._kern = kern

        def logp(params):
            stats = stats_fn(params)
            # Leaves lead with n_shards — reduce over it (the psum
            # analog; under a mesh the leading axis is sharded and XLA
            # turns this sum into the collective).
            a = jnp.sum(stats["a"], axis=0)
            b = jnp.sum(stats["b"], axis=0)
            resid = jnp.sum(stats["resid"], axis=0)
            y2 = jnp.sum(stats["y2"], axis=0)
            n = jnp.sum(stats["n"], axis=0)

            _, _, noise = _unpack(params)
            s2 = noise**2
            # Whitened inner matrix: B' = I + a/σ² has eigenvalues >= 1,
            # so its Cholesky and logdet are float32-safe, and
            # log|B| - log|K_zz| = log|B'| exactly.
            bprime = jnp.eye(m) + a / s2
            l_b = jnp.linalg.cholesky(bprime)
            # Woodbury quadratic: y'Σ^{-1}y = (y'y - b' B'^{-1} b / σ²)/σ²
            quad = (
                y2
                - pdot(b, jax.scipy.linalg.cho_solve((l_b, True), b), policy)
                / s2
            ) / s2
            logdet = 2.0 * jnp.sum(jnp.log(jnp.diag(l_b)))
            trace_term = resid / s2

            return -0.5 * (
                n * (LOG_2PI + jnp.log(s2)) + quad + logdet + trace_term
            ) + self._prior_logp(params)

        # "highest"/"strict": the precision context must be active at
        # TRACE time so Cholesky/triangular-solve internals pick it up.
        self._logp = jax.jit(wrap_policy(logp, policy))
        self._logp_and_grad = jax.jit(
            wrap_policy(jax.value_and_grad(logp), policy)
        )

    @staticmethod
    def _prior_logp(params):
        """Weak N(0, 3²) priors on the log-hyperparameters (summed, so
        ARD's vector ``log_lengthscale`` reduces to a scalar too)."""
        return sum(
            jnp.sum(-0.5 * (params[k] / 3.0) ** 2)
            for k in ("log_variance", "log_lengthscale", "log_noise")
        )

    def init_params(self) -> dict:
        shape = kernel_hyper_shape(self.kernel)
        return {
            "log_variance": jnp.zeros(shape),
            "log_lengthscale": jnp.zeros(shape),
            "log_noise": jnp.asarray(-1.0),
        }

    def logp(self, params: Any) -> jax.Array:
        return self._logp(params)

    def logp_and_grad(self, params: Any):
        return self._logp_and_grad(params)

    __call__ = logp

    def posterior(self, params: Any, x_star, *, return_cov: bool = False):
        """GLOBAL sparse-GP posterior at ``x_star`` (collapsed SGPR
        predictive, Titsias 2009): unlike
        :meth:`FederatedExactGP.posterior` — independent per-shard GPs
        — every shard's data informs ONE latent function through the
        shared inducing statistics, so prediction needs only the same
        psum-reduced ``(a, b)`` the likelihood consumes; no shard's raw
        data leaves its device.

        With ``L = chol(K_zz)``, ``B' = I + a/σ²``, ``L_B = chol(B')``:

            μ* = K_*z L^{-T} B'^{-1} b / σ²
            Σ* = K** − V'V + W'W,  V = L^{-1}K_z*, W = L_B^{-1}V

        (the Nyström shrinkage plus the information recovered through
        the inducing posterior).  Returns ``(mean, var)`` with diagonal
        variance by default, or ``(mean, cov)`` with the FULL predictive
        covariance when ``return_cov=True`` (what coherent joint draws
        need — see :meth:`posterior_sample`).  ``x_star`` ndim must
        match the training inputs'.
        """
        from ..precision import matmul_precision_ctx, pdot

        # The SAME policy context as the logp path, live for the whole
        # computation — including the _stats_fn call, whose jitted
        # executable re-traces under this context (the precision config
        # is part of jax's trace cache key), so the statistics cannot
        # silently come back bf16-level while logp is strict.
        with matmul_precision_ctx(self.f32_policy):
            variance, lengthscale, noise = _unpack(params)
            s2 = noise**2
            stats = self._stats_fn(params)
            a = jnp.sum(stats["a"], axis=0)
            b = jnp.sum(stats["b"], axis=0)
            z = self.inducing
            m = self.m
            kzz = self._kern(z, z, variance, lengthscale) + _JITTER * _jitter_scale(
                variance
            ) * jnp.eye(m)
            l = jnp.linalg.cholesky(kzz)
            l_b = jnp.linalg.cholesky(jnp.eye(m) + a / s2)
            c = jax.scipy.linalg.cho_solve((l_b, True), b)
            beta = jax.scipy.linalg.solve_triangular(l.T, c, lower=False)
            xs = jnp.asarray(x_star, jnp.float32)
            ks = self._kern(z, xs, variance, lengthscale)  # (M, n_star)
            mean = pdot(ks.T, beta, self.f32_policy) / s2
            v = jax.scipy.linalg.solve_triangular(l, ks, lower=True)
            w = jax.scipy.linalg.solve_triangular(l_b, v, lower=True)
            if return_cov:
                kss = self._kern(xs, xs, variance, lengthscale)
                cov = (
                    kss
                    - pdot(v.T, v, self.f32_policy)
                    + pdot(w.T, w, self.f32_policy)
                )
                return mean, cov
            # k** from the spec's constant prior diagonal (composite
            # sums/products included; linear rejected at construction)
            kss = stationary_prior_diag(self.kernel, variance)
            var = kss - jnp.sum(v**2, axis=0) + jnp.sum(w**2, axis=0)
            return mean, var

    def posterior_sample(
        self, params: Any, key, x_star, *, num_draws: int = 1
    ) -> jax.Array:
        """Coherent joint draws ``(num_draws, n_star)`` from the global
        sparse-GP posterior over the LATENT function at ``x_star``
        (jitter-stabilized Cholesky of the full predictive covariance;
        add ``exp(log_noise)``-scaled white noise for observation
        draws)."""
        from ..precision import matmul_precision_ctx, pdot

        mean, cov = self.posterior(params, x_star, return_cov=True)
        # Same policy context as posterior(): the draw's Cholesky and
        # matmul must not silently drop to bf16 when the model is
        # strict.
        with matmul_precision_ctx(self.f32_policy):
            n = cov.shape[0]
            variance, _, _ = _unpack(params)
            chol = _posterior_chol(
                cov, _JITTER * _jitter_scale(variance), self.f32_policy
            )
            eps = jax.random.normal(key, (num_draws, n), mean.dtype)
            return mean[None, :] + pdot(eps, chol.T, self.f32_policy)


def dense_vfe_logp(params, x, y, inducing, kernel: str = "sqexp"):
    """Single-device dense VFE bound — golden-model ground truth.

    Computed directly from the textbook expression
    ``N(y | 0, Q + σ²I)`` with ``Q = K_fz K_zz^{-1} K_zf`` plus the
    ``-tr(K - Q)/(2σ²)`` VFE correction, using full n x n algebra.
    ``kernel`` selects the same covariance as the sparse class.
    """
    kern = get_kernel(kernel)
    variance, lengthscale, noise = _unpack(params)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    z = jnp.asarray(inducing, jnp.float32)
    n = x.shape[0]
    m = z.shape[0]
    s2 = noise**2
    kzz = kern(z, z, variance, lengthscale) + _JITTER * _jitter_scale(
        variance
    ) * jnp.eye(m)
    kzf = kern(z, x, variance, lengthscale)
    q = kzf.T @ jnp.linalg.solve(kzz, kzf)
    cov = q + s2 * jnp.eye(n)
    l = jnp.linalg.cholesky(cov)
    alpha = jax.scipy.linalg.cho_solve((l, True), y)
    marginal = -0.5 * (
        y @ alpha + 2.0 * jnp.sum(jnp.log(jnp.diag(l))) + n * LOG_2PI
    )
    kxx = stationary_prior_diag(kernel, variance)
    trace_corr = -0.5 * (n * kxx - jnp.trace(q)) / s2
    return marginal + trace_corr + FederatedSparseGP._prior_logp(params)


class FederatedExactGP:
    """Exact GP marginal likelihood per shard, shared hyperparameters.

    Multi-site GP regression: each federated shard owns an independent
    GP over its private ``(x, y)`` with the SAME kernel (``kernel=``:
    any :func:`get_kernel` spec — sqexp/matern32/matern52/linear and
    "+"/"*" composites; this is the family that accepts the
    non-stationary ``linear``) and hyperparameters — the
    exact-inference counterpart of :class:`FederatedSparseGP` for
    shard sizes where an n x n Cholesky is affordable.  Per-shard compute is one batched ``(n, n)``
    Cholesky + triangular solves (vmapped over shards; the heaviest
    dense-linear-algebra family in the package).

    Padding trick: masked rows/columns of the covariance are replaced
    by identity rows (diag 1, off-diag 0) and padded targets are 0, so
    each padded slot contributes exactly ``logN(0 | 0, 1) =
    -0.5 log 2π`` — added back analytically, making the masked
    evaluation EQUAL to the exact marginal likelihood of the real
    points (tested against a dense unpadded build).
    """

    def __init__(
        self,
        data: ShardedData,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = SHARDS_AXIS,
        kernel: str = "sqexp",
        f32_policy: Optional[str] = None,
    ):
        from ..precision import pdot, resolve_policy, wrap_policy

        # One env consultation at construction; see FederatedSparseGP.
        policy = resolve_policy(f32_policy)
        self.f32_policy = policy
        self.mesh = mesh
        self.kernel = kernel
        self._kern = get_kernel(kernel, policy=policy)
        kern = self._kern

        def per_shard_logp(params, shard):
            (x, y), mask = shard
            variance, lengthscale, noise = _unpack(params)
            n = x.shape[0]
            k = _masked_cov(x, mask, variance, lengthscale, noise, kern)
            ym = y * mask
            l = jnp.linalg.cholesky(k)
            alpha = jax.scipy.linalg.cho_solve((l, True), ym)
            # The n-term quadratic form is exactly the contraction size
            # the chip degrades (tools/diag_tpu.out: relerr ~1.4e-3 at
            # n=512) — policy-route it.
            ll = -0.5 * (
                pdot(ym, alpha, policy)
                + 2.0 * jnp.sum(jnp.log(jnp.diag(l)))
                + n * LOG_2PI
            )
            # remove the padded slots' logN(0|0,1) contributions
            return ll + 0.5 * LOG_2PI * jnp.sum(1.0 - mask)

        # The precision context must be live while the Cholesky /
        # cho_solve internals are traced ("highest"/"strict" policies).
        self.fed = FederatedLogp(
            wrap_policy(per_shard_logp, policy),
            data.tree(),
            mesh=mesh,
            axis=axis,
        )
        self.data = data

    def logp(self, params: Any) -> jax.Array:
        return self.fed.logp(params) + FederatedSparseGP._prior_logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> dict:
        shape = kernel_hyper_shape(self.kernel)
        return {
            "log_variance": jnp.zeros(shape),
            "log_lengthscale": jnp.zeros(shape),
            "log_noise": jnp.asarray(-1.0),
        }

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def posterior(self, params: Any, x_star, *, return_cov: bool = False):
        """Per-shard posterior at ``x_star`` — ``(n_star,)`` shared
        query points for scalar-covariate data, ``(n_star, d)`` when
        the training inputs are ``(n, d)`` (ARD): query ndim must match
        the training inputs'.  Returns ``(mean, var)`` each
        ``(n_shards, n_star)`` — one batched solve per shard — or,
        with ``return_cov=True``, ``(mean, cov)`` where ``cov`` is the
        FULL per-shard predictive covariance
        ``(n_shards, n_star, n_star)`` (what coherent joint draws
        need — see :meth:`posterior_sample`)."""
        (x, y), mask = self.data.tree()
        variance, lengthscale, noise = _unpack(params)
        xs = jnp.asarray(x_star, jnp.float32)

        from ..precision import pdot, wrap_policy

        def one(x_i, y_i, m_i):
            k = _masked_cov(
                x_i, m_i, variance, lengthscale, noise, self._kern
            )
            ks = self._kern(x_i, xs, variance, lengthscale) * m_i[:, None]
            l = jnp.linalg.cholesky(k)
            alpha = jax.scipy.linalg.cho_solve((l, True), y_i * m_i)
            mean = pdot(ks.T, alpha, self.f32_policy)
            v = jax.scipy.linalg.solve_triangular(l, ks, lower=True)
            if return_cov:
                return mean, kss_full - pdot(v.T, v, self.f32_policy)
            return mean, kss_diag - jnp.sum(v**2, axis=0)

        # k(x*, x*), valid for EVERY kernel spec (composites and the
        # non-stationary linear included) — the old ``variance - Σv²``
        # hardcoded stationarity.
        if return_cov:
            kss_full = self._kern(xs, xs, variance, lengthscale)
            kss_diag = None
        else:
            kss_full = None
            kss_diag = jax.vmap(
                lambda q: jnp.squeeze(
                    self._kern(q[None], q[None], variance, lengthscale)
                )
            )(xs)
        return jax.vmap(wrap_policy(one, self.f32_policy))(x, y, mask)

    def posterior_sample(
        self, params: Any, key, x_star, *, num_draws: int = 1
    ) -> jax.Array:
        """Coherent joint draws ``(num_draws, n_shards, n_star)`` from
        each shard's latent-function posterior at ``x_star``
        (jitter-stabilized; add ``exp(log_noise)``-scaled white noise
        for observation draws)."""
        from ..precision import matmul_precision_ctx

        mean, cov = self.posterior(params, x_star, return_cov=True)
        # Same policy context as posterior() — see FederatedSparseGP.
        with matmul_precision_ctx(self.f32_policy):
            variance, _, _ = _unpack(params)
            n = cov.shape[-1]
            # Batched (n_shards, n, n) covariances take the helper's
            # jnp fallback; a future per-shard blocked route would
            # loop shards through the same seam.
            chol = _posterior_chol(
                cov, _JITTER * _jitter_scale(variance), self.f32_policy
            )
            eps = jax.random.normal(
                key, (num_draws, mean.shape[0], n), mean.dtype
            )
            return mean[None] + jnp.einsum("dsn,smn->dsm", eps, chol)
