"""Federated survival analysis: censored Weibull regression (AFT).

Time-to-event data split across institutions that cannot pool patient
records is the canonical real-world federated-inference setting; this
family gives it first-class support.  Accelerated-failure-time (AFT)
Weibull model with right censoring:

    T_ij ~ Weibull(shape=k, scale=exp(eta_ij))
    eta_ij = x_ij . w + b0 + tau * b_raw_i       (per-shard frailty)
    observed: (t_ij, delta_ij),  delta = 1 event, 0 right-censored

With ``z = k (log t - eta)`` the per-observation log-likelihood is

    event    (delta=1):  log k - log t + z - e^z
    censored (delta=0):  -e^z                      (log survival)

(the event term expands to the Weibull logpdf
``log k - eta + (k-1)(log t - eta) - (t/e^eta)^k``).

Built on the shared hierarchical base (models/hierbase.py) with the
observation pytree ``y = (t, delta)``: the per-shard frailty term is
the non-centered shared-frailty analog, and ``compute_dtype`` /
``pointwise_loglik`` / ``predictive`` come from the base like every
sibling family.

TPU notes: identical hot shape (batched ``X @ w`` via the shared
``linear_predictor``); the density needs ``log``/``exp`` only, and
censoring is a multiply by ``delta`` — no branches, so the whole
posterior jits clean under vmap/shard_map.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from .hierbase import HierarchicalGLMBase
from .linear import _normal_logpdf

__all__ = [
    "FederatedWeibullAFT",
    "generate_survival_data",
    "weibull_censored_loglik",
]


def generate_survival_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 3,
    tau: float = 0.3,
    shape_k: float = 1.5,
    censor_frac: float = 0.3,
    seed: int = 37,
):
    """Per-shard ``(X, (t, delta))`` with administrative right
    censoring tuned to hit ``censor_frac`` on average."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0.0, 0.4, size=n_features)
    b0_true = 0.5
    b_true = b0_true + tau * rng.normal(size=n_shards)
    shards = []
    for i in range(n_shards):
        X = rng.normal(0.0, 1.0, size=(n_obs, n_features)).astype(np.float32)
        scale = np.exp(b_true[i] + X @ w_true)
        t_event = scale * rng.weibull(shape_k, size=n_obs)
        # censor times drawn so ~censor_frac of events are cut off
        c = np.quantile(t_event, 1.0 - censor_frac) * rng.uniform(
            0.5, 1.5, size=n_obs
        )
        delta = (t_event <= c).astype(np.float32)
        t = np.minimum(t_event, c).astype(np.float32)
        # padded-slot safety: keep times strictly positive
        t = np.maximum(t, 1e-6)
        shards.append((X, (t, delta)))
    truth = {"w": w_true, "b0": b0_true, "b": b_true, "k": shape_k}
    return pack_shards(shards, pad_to_multiple=8), truth


def weibull_censored_loglik(t, delta, eta, k):
    """Censored Weibull AFT log-likelihood per observation.

    ``z = k * (log t - eta)`` so the density term is
    ``log k - log t + z - exp(z)`` and the survival term is ``-exp(z)``
    — one shared ``exp(z)`` (clamped like the siblings so extreme
    proposals stay finite with finite gradients), censoring as a
    multiply, no branches.
    """
    log_t = jnp.log(jnp.maximum(t, jnp.finfo(jnp.result_type(t)).tiny))
    z = k * (log_t - eta)
    ez = jnp.exp(jnp.minimum(z, 80.0))
    event_term = jnp.log(k) - log_t + z - ez
    censor_term = -ez
    return delta * event_term + (1.0 - delta) * censor_term


@dataclasses.dataclass
class FederatedWeibullAFT(HierarchicalGLMBase):
    """Hierarchical Weibull AFT over federated shards."""

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase
    _init_log_tau = -1.0

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        t, delta = y
        k = jnp.exp(params["log_k"])
        return weibull_censored_loglik(t, delta, eta, k)

    def _sample_obs(self, params, key, eta):
        # UNCENSORED event times by inverse cdf: T = scale*(-log u)^(1/k)
        k = jnp.exp(params["log_k"])
        u = jax.random.uniform(
            key, eta.shape, minval=1e-7, maxval=1.0 - 1e-7
        )
        return jnp.exp(eta) * jnp.power(-jnp.log(u), 1.0 / k)

    def prior_logp(self, params: Any) -> jax.Array:
        lp = super().prior_logp(params)
        # LogNormal(0, 1)-ish prior on the Weibull shape via log_k.
        lp += _normal_logpdf(params["log_k"], 0.0, 1.0)
        return lp

    def init_params(self) -> Any:
        p = super().init_params()
        p["log_k"] = jnp.zeros(())
        return p

    def _sample_extra_params(self, key) -> dict:
        # LogNormal(0, 1) shape, matching prior_logp.
        return {"log_k": jax.random.normal(key)}
