"""Federated Bayesian linear regression — the flagship demo model.

TPU-native collapse of the reference's two demo processes
(reference: demo_node.py + demo_model.py): each "node" owns a private
``(x, y)`` dataset and contributes a partial log-likelihood with a
per-shard intercept offset; the driver places a hierarchical prior over
intercepts and samples the posterior with NUTS.  Where the reference runs
15 gRPC server processes and fans out one RPC per shard per leapfrog
step (reference: demo_node.py:118, demo_model.py:33-36), everything here
is one jitted SPMD program over the ``"shards"`` mesh axis.

Model (matches the reference's multilevel regression,
reference: demo_model.py:26-36):

    intercept   ~ Normal(0, prior_scale)
    offset_i    ~ Normal(0, offset_scale)      per shard i (fixed scale —
                  see FederatedLinearRegression; a learned group sigma is
                  the hierarchical GLM model's job, models/glm.py)
    slope       ~ Normal(0, prior_scale)
    sigma       ~ HalfNormal(1)  (via log_sigma + change of variables)
    y_ij        ~ Normal((intercept + offset_i) + slope * x_ij, sigma)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp

from ..utils import LOG_2PI  # single shared definition (re-exported here)


def generate_node_data(
    n_shards: int = 8,
    *,
    n_obs: int | Sequence[int] = 64,
    intercept: float = 1.5,
    slope: float = 2.0,
    sigma: float = 0.5,
    intercept_spread: float = 0.3,
    seed: int = 123,
) -> tuple[ShardedData, np.ndarray]:
    """Per-node private datasets (reference: demo_node.py:58-61 generates
    one seeded private dataset per worker process).

    Returns packed shard data and the true per-shard intercept offsets.
    """
    rng = np.random.default_rng(seed)
    if isinstance(n_obs, int):
        n_obs = [n_obs] * n_shards
    offsets = rng.normal(0.0, intercept_spread, size=n_shards)
    shards = []
    for i in range(n_shards):
        x = rng.uniform(-3.0, 3.0, size=n_obs[i]).astype(np.float32)
        y = (
            (intercept + offsets[i])
            + slope * x
            + rng.normal(0.0, sigma, size=n_obs[i])
        ).astype(np.float32)
        shards.append((x, y))
    return pack_shards(shards, pad_to_multiple=8), offsets


def _normal_logpdf(x, mu, sigma):
    z = (x - mu) / sigma
    return -0.5 * z * z - jnp.log(sigma) - 0.5 * LOG_2PI


def linreg_suffstats(x, y, mask) -> jnp.ndarray:
    """Per-shard sufficient statistics ``(S, 6)`` for the Gaussian
    linear likelihood: ``[n, x̄, ȳ, Cxx, Cxy, Cyy]`` (counts, masked
    means, and *centered* second moments).

    For a Gaussian linear model the data enter the likelihood only
    through these six numbers per shard, so a node can release them
    instead of raw observations — the federated-analytics analog of the
    reference's "private data stays on the node" contract (reference:
    demo_node.py:58-61) with an O(N) → O(1) per-eval cost drop.  The
    centered form keeps float32 well-conditioned: the raw-moment
    expansion ``Syy - 2A·Sy + ...`` cancels catastrophically when
    residuals are small relative to ``y``.

    Accumulation runs in float64 (one-time, off the hot path); the
    returned stats are float32 for the device hot loop.
    """
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    m = np.asarray(mask, np.float64)
    n = m.sum(axis=1)
    safe_n = np.where(n > 0, n, 1.0)
    xb = (m * x).sum(axis=1) / safe_n
    yb = (m * y).sum(axis=1) / safe_n
    dx = (x - xb[:, None]) * m
    dy = (y - yb[:, None]) * m
    cxx = (dx * dx).sum(axis=1)
    cxy = (dx * dy).sum(axis=1)
    cyy = (dy * dy).sum(axis=1)
    return jnp.asarray(
        np.stack([n, xb, yb, cxx, cxy, cyy], axis=1), jnp.float32
    )


def _suffstat_shard_logp(A, slope, log_sigma, stats):
    """Shard data-loglik from sufficient stats; ``A`` = intercept+offset.

    With ``d = ȳ - A - slope·x̄`` the masked residual sum of squares is
    ``Cyy - 2·slope·Cxy + slope²·Cxx + n·d²`` (cross terms vanish by
    centering), so the whole shard likelihood is O(1) regardless of the
    number of observations.
    """
    n, xb, yb, cxx, cxy, cyy = (stats[..., i] for i in range(6))
    d = yb - A - slope * xb
    ssr = cyy - 2.0 * slope * cxy + slope * slope * cxx + n * d * d
    inv_s2 = jnp.exp(-2.0 * log_sigma)
    return -0.5 * ssr * inv_s2 - (log_sigma + 0.5 * LOG_2PI) * n


@dataclasses.dataclass
class FederatedLinearRegression:
    """Hierarchical linear regression over federated shards.

    ``params`` pytree::

        intercept: ()      slope: ()      log_sigma: ()
        offsets: (n_shards,)

    The per-shard likelihood closes over that shard's private data; the
    shard picks out its own offset via the shard index carried in the
    data pytree (SPMD-friendly: no gather across devices).
    """

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 10.0
    offset_scale: float = 0.3
    use_suffstats: bool = False

    def __post_init__(self):
        n = self.data.n_shards
        shard_ids = jnp.arange(n, dtype=jnp.int32)
        (x, y), mask = self.data.tree()

        if self.use_suffstats:
            # Nodes release six sufficient statistics instead of raw
            # observations (see linreg_suffstats): same posterior, O(1)
            # per-shard eval cost, and a tighter privacy surface.
            tree = (linreg_suffstats(x, y, mask), shard_ids)

            def per_shard_logp(params, shard):
                stats, sid = shard
                A = params["intercept"] + jnp.take(params["offsets"], sid)
                return _suffstat_shard_logp(
                    A, params["slope"], params["log_sigma"], stats
                )

        else:
            tree = ((x, y), mask, shard_ids)

            def per_shard_logp(params, shard):
                (x, y), mask, sid = shard
                offset = jnp.take(params["offsets"], sid)
                mu = (params["intercept"] + offset) + params["slope"] * x
                sigma = jnp.exp(params["log_sigma"])
                ll = _normal_logpdf(y, mu, sigma)
                return jnp.sum(ll * mask)

        self.fed = FederatedLogp(per_shard_logp, tree, mesh=self.mesh)
        self.n_shards = n

    # -- prior + posterior ------------------------------------------------

    def prior_logp(self, params: Any) -> jax.Array:
        s = self.prior_scale
        lp = _normal_logpdf(params["intercept"], 0.0, s)
        lp += _normal_logpdf(params["slope"], 0.0, s)
        lp += jnp.sum(_normal_logpdf(params["offsets"], 0.0, self.offset_scale))
        # HalfNormal(1) on sigma via log_sigma with Jacobian |d sigma/d log_sigma|.
        sigma = jnp.exp(params["log_sigma"])
        lp += -0.5 * sigma**2 + params["log_sigma"]
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        """Posterior logp+grad fused into one executable — this is the
        callable the benchmark rates (BASELINE.json metric)."""
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "intercept": jnp.zeros(()),
            "slope": jnp.zeros(()),
            "log_sigma": jnp.zeros(()),
            "offsets": jnp.zeros((self.n_shards,)),
        }

    # -- driver conveniences (reference: demo_model.py:38-42) -------------

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
