"""Shared base for hierarchical-intercept federated GLMs.

One structure, three observation families (logistic.py Bernoulli,
countdata.py Poisson / negative-binomial):

    w ~ Normal(0, prior_scale)^d      (shared slopes)
    b0 ~ Normal(0, prior_scale)       (global intercept)
    tau ~ HalfNormal(1)               (via log_tau + Jacobian)
    b_raw_i ~ Normal(0, 1)            per shard i (NON-CENTERED)
    eta_ij = x_ij . w + b0 + tau * b_raw_i
    y_ij ~ family(eta_ij)

Subclasses supply ``_obs_logpmf(params, y, eta)`` (and may extend
``prior_logp``/``init_params`` for extra family parameters).  Keeping
the hierarchy in ONE place means the non-centered construction and the
HalfNormal Jacobian cannot drift between families (a round-2 review
finding: they previously existed in three hand-written copies).

Non-centering is the TPU-relevant choice throughout: the centered form
``b_i ~ N(b0, tau)`` has an unbounded log-posterior as ``tau -> 0``, so
its MAP is ill-defined and NUTS meets funnel geometry; non-centered
keeps step sizes uniform so one SPMD program serves every shard.

The radon GLM (glm.py) is intentionally NOT on this base: its public
parameterization (``mu_alpha``/``sigma_alpha``/``beta``, scalar
covariate) predates it and differs in surface, and silently renaming a
model's parameters is an API break, not a cleanup.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .linear import _normal_logpdf

__all__ = [
    "HierarchicalGLMBase",
    "linear_predictor",
    "log_halfnormal_draw",
]


def log_halfnormal_draw(key, scale=1.0):
    """log of one HalfNormal(scale) draw — THE one implementation for
    log-parameterized scale priors in sample_prior overrides."""
    return jnp.log(
        scale * jnp.abs(jax.random.normal(key))
        + jnp.finfo(jnp.float32).tiny
    )


def linear_predictor(X, w, b, compute_dtype=None):
    """``X @ w + b``, optionally with the matmul in ``compute_dtype``
    (e.g. bf16) and float32 accumulation — the MXU mixed-precision
    recipe.  THE one implementation; every model option routes here so
    the contraction recipe cannot drift between families.

    ``compute_dtype="float32_strict"`` goes the OTHER direction: a
    guaranteed true-f32 contraction via the 6-pass bf16x3 split
    (:mod:`..precision`) for chips whose plain-f32 matmul is silently
    bf16-accurate (tools/diag_tpu.out; ~6x the matmul FLOPs).
    """
    if compute_dtype is None:
        return X @ w + b
    if compute_dtype == "float32_strict":
        from ..precision import pdot

        return pdot(X, w, "strict") + b
    return (
        jnp.dot(
            X.astype(compute_dtype),
            w.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        + b
    )


class HierarchicalGLMBase:
    """Dataclass mixin: subclasses declare ``data``, ``mesh`` and
    ``prior_scale`` fields and call :meth:`_post_init` from their
    ``__post_init__``."""

    #: initial value for log_tau (families tune their own warm start)
    _init_log_tau: float = 0.0

    #: families whose global intercept is absorbed elsewhere (ordinal:
    #: the cutpoints) set this False — ``b0`` then vanishes from the
    #: param tree, the prior, and the implied intercepts.
    _has_global_intercept: bool = True

    #: None: scalar linear predictor (one eta per observation — the
    #: Bernoulli/Poisson/... families).  An int ``m``: VECTOR predictor
    #: with ``m`` columns (``w``: (d, m), ``b0``: (m,), ``b_raw``:
    #: (S, m), eta: (..., m)) — the multinomial family sets
    #: ``m = K - 1``.  Every broadcasting expression below works for
    #: both cases unchanged; only the parameter shapes differ.
    _coef_cols = None

    def _intercept_base(self, params):
        return params["b0"] if self._has_global_intercept else 0.0

    #: optional matmul compute dtype (e.g. ``jnp.bfloat16``): the
    #: X @ w contraction — where the FLOPs are — runs in this dtype
    #: with float32 accumulation (``preferred_element_type``), the
    #: MXU-native mixed-precision recipe.  Everything downstream
    #: (link transcendentals, reductions, priors) stays float32.
    #: None = pure float32.  Subclass dataclasses may expose it as a
    #: field; expect ~1e-2 relative logp divergence from f32 (bf16 has
    #: 8 mantissa bits), tested in tests/test_mixed_precision.py.
    #: The string ``"float32_strict"`` instead FORCES true-f32
    #: contractions via the 6-pass bf16x3 split (:mod:`..precision`) on
    #: chips whose plain f32 matmul is bf16-accurate.
    compute_dtype = None

    def _linear_predictor(self, X, w, b):
        return linear_predictor(X, w, b, self.compute_dtype)

    def _post_init(self):
        (X, y), mask = self.data.tree()
        n = X.shape[0]
        shard_ids = jnp.arange(n, dtype=jnp.int32)

        def per_shard_logp(params, shard):
            (X, y), mask, sid = shard
            tau = jnp.exp(params["log_tau"])
            b = self._intercept_base(params) + tau * jnp.take(
                params["b_raw"], sid, axis=0
            )
            eta = self._linear_predictor(X, params["w"], b)
            ll = self._obs_logpmf(params, y, eta)
            return jnp.sum(ll * mask)

        from ..parallel.sharded import FederatedLogp

        self.fed = FederatedLogp(
            per_shard_logp, ((X, y), mask, shard_ids), mesh=self.mesh
        )
        self.n_shards = n
        self.n_features = X.shape[-1]

    def _obs_logpmf(self, params, y, eta):  # pragma: no cover - abstract
        raise NotImplementedError

    def prior_logp(self, params: Any) -> jax.Array:
        s = self.prior_scale
        lp = jnp.sum(_normal_logpdf(params["w"], 0.0, s))
        if self._has_global_intercept:
            lp += jnp.sum(_normal_logpdf(params["b0"], 0.0, s))
        lp += jnp.sum(_normal_logpdf(params["b_raw"], 0.0, 1.0))
        # HalfNormal(1) on tau via the log-transform + Jacobian.
        tau = jnp.exp(params["log_tau"])
        lp += -0.5 * tau**2 + params["log_tau"]
        return lp

    def intercepts(self, params: Any) -> jax.Array:
        """The implied per-shard intercepts ``b0 + tau * b_raw``."""
        return (
            self._intercept_base(params)
            + jnp.exp(params["log_tau"]) * params["b_raw"]
        )

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def _shape(self, *lead):
        m = self._coef_cols
        return lead if m is None else lead + (m,)

    def init_params(self) -> Any:
        p = {
            "w": jnp.zeros(self._shape(self.n_features)),
            "log_tau": jnp.array(self._init_log_tau),
            "b_raw": jnp.zeros(self._shape(self.n_shards)),
        }
        if self._has_global_intercept:
            p["b0"] = jnp.zeros(self._shape())
        return p

    def _sample_obs(self, params, key, eta):  # pragma: no cover - abstract
        raise NotImplementedError

    def pointwise_loglik(self, params: Any) -> jax.Array:
        """``(n_shards, n_obs)`` per-observation data log-likelihoods
        (padded slots zeroed).  Feed to
        :func:`..samplers.model_comparison.pointwise_loglik_matrix`
        with ``mask=model.data.tree()[1]`` for WAIC / PSIS-LOO."""
        (X, y), mask = self.data.tree()
        b = self.intercepts(params)
        eta = self._linear_predictor(X, params["w"], b[:, None])
        return self._obs_logpmf(params, y, eta) * mask

    def predictive(self, params: Any, key) -> jax.Array:
        """Simulate one replicated dataset ``(n_shards, n_obs)`` from
        the observation model at ``params`` (padded slots zeroed).

        Shaped for :func:`..samplers.predictive.posterior_predictive`:
        ``posterior_predictive(model.predictive, res.samples, key)``
        sweeps it over every kept draw — the ``pm.sample_posterior_
        predictive`` workflow (reference consumers end with arviz
        predictive checks; here it is one vmapped executable).
        """
        (X, _y), mask = self.data.tree()
        b = self.intercepts(params)
        eta = self._linear_predictor(X, params["w"], b[:, None])
        return self._sample_obs(params, key, eta) * mask

    def _sample_extra_params(self, key) -> dict:
        """Family-specific extra parameter draws (override to match any
        extra ``prior_logp`` terms, e.g. NB dispersion)."""
        return {}

    def sample_prior(self, key) -> Any:
        """One draw from the prior, shaped like :meth:`init_params` —
        plugs into :func:`..samplers.predictive.prior_predictive`
        together with :meth:`predictive`."""
        ks = jax.random.split(key, 5)
        p = {
            "w": self.prior_scale * jax.random.normal(
                ks[0], self._shape(self.n_features)
            ),
            "log_tau": log_halfnormal_draw(ks[1]),  # HalfNormal(1)
            "b_raw": jax.random.normal(ks[2], self._shape(self.n_shards)),
        }
        if self._has_global_intercept:
            p["b0"] = self.prior_scale * jax.random.normal(
                ks[3], self._shape()
            )
        p.update(self._sample_extra_params(ks[4]))
        return p

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
