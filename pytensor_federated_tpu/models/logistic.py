"""Federated logistic regression — the many-shard scale config.

BASELINE.json config "64-shard federated logistic regression, full PyMC
NUTS posterior on v4-128": each shard owns a private design-matrix block
``(X_i, y_i)``; the global posterior is

    w ~ Normal(0, 5)^d,   b ~ Normal(0, 5)
    y_ij ~ Bernoulli(sigmoid(X_i w + b))

Per-shard compute is a single ``(n, d) @ (d,)`` matmul — exactly the
shape the MXU wants batched over shards.  With ``n_shards >> devices``
each device processes its shard block as one stacked
``(local_shards, n, d)`` batched matmul.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp, NoFederatedShards
from .hierbase import HierarchicalGLMBase, linear_predictor
from .linear import _normal_logpdf


def _simulate_logistic_shards(rng, n_shards, n_obs, n_features, intercepts):
    """Shared simulator: Bernoulli(sigmoid(X w + b_i)) with a per-shard
    intercept array (a broadcast scalar for the flat model)."""
    w_true = rng.normal(0, 1.0, size=n_features)
    intercepts = np.broadcast_to(intercepts, (n_shards,))
    shards = []
    for i in range(n_shards):
        X = rng.normal(size=(n_obs, n_features)).astype(np.float32)
        logits = X @ w_true + intercepts[i]
        y = (rng.uniform(size=n_obs) < 1.0 / (1.0 + np.exp(-logits))).astype(
            np.float32
        )
        shards.append((X, y))
    return pack_shards(shards), w_true


def generate_logistic_data(
    n_shards: int = 64,
    *,
    n_obs: int = 128,
    n_features: int = 8,
    seed: int = 21,
):
    rng = np.random.default_rng(seed)
    packed, w_true = _simulate_logistic_shards(
        rng, n_shards, n_obs, n_features, 0.5
    )
    return packed, {"w": w_true, "b": 0.5}


def generate_hier_logistic_data(
    n_shards: int = 16,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    tau: float = 0.8,
    seed: int = 31,
):
    """Per-shard data with shard-specific intercepts b_i ~ N(0.5, tau)."""
    rng = np.random.default_rng(seed)
    b_true = 0.5 + tau * rng.normal(size=n_shards)
    # Intercepts consume n_shards draws before w_true is sampled, so
    # the simulated w_true (and all shard data) depends on n_shards.
    packed, w_true = _simulate_logistic_shards(
        rng, n_shards, n_obs, n_features, b_true
    )
    return packed, {"w": w_true, "b": b_true}


@dataclasses.dataclass
class HierarchicalLogisticRegression(HierarchicalGLMBase):
    """Mixed-effects logistic regression: shared slopes, one random
    intercept per federated shard with a learned group scale.

    Model (NON-CENTERED, like :class:`..glm.HierarchicalRadonGLM` —
    the centered form ``b_i ~ N(b0, tau)`` has an unbounded
    log-posterior as ``tau -> 0`` with all ``b_i -> b0``, so its MAP is
    ill-defined and NUTS meets funnel geometry)::

        w ~ Normal(0, prior_scale)^d      (shared)
        b0 ~ Normal(0, prior_scale)
        tau ~ HalfNormal(1)               (via log_tau + Jacobian)
        b_raw_i ~ Normal(0, 1)            per shard i
        y_ij ~ Bernoulli(sigmoid(X_i w + b0 + tau * b_raw_i))

    The hierarchical analog of :class:`FederatedLogisticRegression`
    (whose single intercept it generalizes), completing the GLM grid:
    radon = hierarchical linear, this = hierarchical logistic.  Each
    shard picks out its own intercept via the shard id carried in the
    data tree — SPMD-friendly, no cross-device gather.
    """

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        # Bernoulli: y*eta - log(1 + e^eta), stable via logaddexp.
        return y * eta - jnp.logaddexp(0.0, eta)

    def _sample_obs(self, params, key, eta):
        return jax.random.bernoulli(key, jax.nn.sigmoid(eta)).astype(
            eta.dtype
        )


@dataclasses.dataclass
class FederatedLogisticRegression:
    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    #: see HierarchicalGLMBase.compute_dtype — bf16 matmul w/ f32
    #: accumulation when set; the MXU mixed-precision recipe.
    compute_dtype: Optional[Any] = None
    #: partial sufficient statistics: the Bernoulli loglik's
    #: y-interaction term is LINEAR in (w, b), so its coefficients
    #: ``(Σ y x, Σ y)`` fold into per-shard constants at build time and
    #: the hot loop evaluates only the softplus normalizer —
    #: ``Σ y·logits - Σ softplus(logits) = Syx·w + Sy·b - Σ sp`` —
    #: the logistic analog of the linear model's ``use_suffstats``
    #: (full compression is impossible: softplus still needs raw X).
    #: Exact same posterior; equality-tested.
    use_suffstats: bool = False
    #: collapse the shard axis at build time: with shared ``(w, b)`` the
    #: likelihood is invariant to which shard a row lives in, so the S
    #: batched ``(n, d)`` matvecs become ONE ``(S*n, d)`` matvec + one
    #: flat softplus reduction — a single fused loop instead of a
    #: batched one (measurably faster on small shards, where the batch
    #: dimension defeats fusion).  Single-program only: requires
    #: ``mesh=None`` (an SPMD run needs the shard axis to shard over).
    #: Exact same posterior; raced behind the bench equality gate.
    flatten: bool = False

    def __post_init__(self):
        if self.flatten:
            if self.mesh is not None:
                raise ValueError(
                    "flatten=True collapses the shard axis and cannot "
                    "be sharded over a mesh; use use_suffstats instead"
                )
            if self.use_suffstats:
                raise ValueError(
                    "flatten=True and use_suffstats=True are distinct "
                    "implementations of the same posterior — pick one "
                    "(flatten already folds the suffstats terms)"
                )
            (X, y), mask = self.data.tree()
            d = X.shape[-1]
            Xf = jnp.reshape(X, (-1, d))
            mf = jnp.reshape(mask, (-1,))
            ymf = jnp.reshape(y, (-1,)) * mf
            syx = ymf @ Xf  # (d,), build-time constant
            sy = jnp.sum(ymf)

            def flat_loglik(params):
                logits = linear_predictor(
                    Xf, params["w"], params["b"], self.compute_dtype
                )
                sp = jnp.sum(jnp.logaddexp(0.0, logits) * mf)
                return syx @ params["w"] + sy * params["b"] - sp

            self._loglik = flat_loglik
            self.fed = NoFederatedShards("flatten=True folds all shards")
        elif self.use_suffstats:
            (X, y), mask = self.data.tree()
            ym = y * mask
            syx = jnp.einsum("snd,sn->sd", X, ym)  # (S, D), build-time
            sy = jnp.sum(ym, axis=1)  # (S,)
            tree = ((X, syx, sy), mask)

            def per_shard_logp(params, shard):
                (X, syx, sy), mask = shard
                logits = linear_predictor(
                    X, params["w"], params["b"], self.compute_dtype
                )
                sp = jnp.sum(
                    jnp.logaddexp(0.0, logits) * mask
                )
                return syx @ params["w"] + sy * params["b"] - sp

            self.fed = FederatedLogp(per_shard_logp, tree, mesh=self.mesh)
            self._loglik = self.fed.logp
        else:

            def per_shard_logp(params, shard):
                (X, y), mask = shard
                logits = linear_predictor(
                    X, params["w"], params["b"], self.compute_dtype
                )
                # Numerically stable Bernoulli log-likelihood.
                ll = y * logits - jnp.logaddexp(0.0, logits)
                return jnp.sum(ll * mask)

            self.fed = FederatedLogp(
                per_shard_logp, self.data.tree(), mesh=self.mesh
            )
            self._loglik = self.fed.logp
        self.n_features = jax.tree_util.tree_leaves(self.data.data)[0].shape[-1]

    def prior_logp(self, params: Any) -> jax.Array:
        lp = jnp.sum(_normal_logpdf(params["w"], 0.0, self.prior_scale))
        lp += _normal_logpdf(params["b"], 0.0, self.prior_scale)
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self._loglik(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {"w": jnp.zeros((self.n_features,)), "b": jnp.zeros(())}

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
