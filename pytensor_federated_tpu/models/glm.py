"""Hierarchical (radon-style) GLM — one federated shard per county group.

The BASELINE.json config "PyMC hierarchical radon GLM, one shard per
county group": varying-intercept regression with partial pooling,

    mu_alpha      ~ Normal(0, 10)
    sigma_alpha   ~ HalfNormal(1)
    alpha_c       = mu_alpha + sigma_alpha * alpha_raw_c   (non-centered)
    alpha_raw_c   ~ Normal(0, 1)           per county c
    beta          ~ Normal(0, 10)
    sigma         ~ HalfNormal(1)
    log_radon_ij  ~ Normal(alpha_{county(ij)} + beta * floor_ij, sigma)

Each county's observations are one federated shard (heterogeneous
sizes — pad+mask via pack_shards).  The non-centered parameterization is
the TPU-relevant choice: it keeps NUTS step sizes uniform across
counties so one SPMD program serves all shards without per-shard
adaptation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp
from .linear import _normal_logpdf


def generate_radon_data(
    n_counties: int = 16,
    *,
    mean_obs: int = 24,
    seed: int = 11,
):
    """Synthetic radon-style data with per-county sizes drawn ~Poisson."""
    rng = np.random.default_rng(seed)
    true = {
        "mu_alpha": 1.5,
        "sigma_alpha": 0.4,
        "beta": -0.6,
        "sigma": 0.7,
    }
    alphas = rng.normal(true["mu_alpha"], true["sigma_alpha"], size=n_counties)
    shards = []
    for c in range(n_counties):
        n = max(3, int(rng.poisson(mean_obs)))
        floor = rng.integers(0, 2, size=n).astype(np.float32)
        y = (
            alphas[c] + true["beta"] * floor + rng.normal(0, true["sigma"], n)
        ).astype(np.float32)
        shards.append((floor, y))
    return pack_shards(shards, pad_to_multiple=8), true


@dataclasses.dataclass
class HierarchicalRadonGLM:
    """Partial-pooling GLM over county shards."""

    data: ShardedData
    mesh: Optional[Mesh] = None

    def __post_init__(self):
        n = self.data.n_shards
        (floor, y), mask = self.data.tree()
        county_ids = jnp.arange(n, dtype=jnp.int32)
        tree = ((floor, y), mask, county_ids)

        def per_shard_logp(params, shard):
            (floor, y), mask, cid = shard
            sigma_alpha = jnp.exp(params["log_sigma_alpha"])
            alpha = params["mu_alpha"] + sigma_alpha * jnp.take(
                params["alpha_raw"], cid
            )
            mu = alpha + params["beta"] * floor
            sigma = jnp.exp(params["log_sigma"])
            return jnp.sum(_normal_logpdf(y, mu, sigma) * mask)

        self.fed = FederatedLogp(per_shard_logp, tree, mesh=self.mesh)
        self.n_counties = n

    def prior_logp(self, params: Any) -> jax.Array:
        lp = _normal_logpdf(params["mu_alpha"], 0.0, 10.0)
        lp += _normal_logpdf(params["beta"], 0.0, 10.0)
        lp += jnp.sum(_normal_logpdf(params["alpha_raw"], 0.0, 1.0))
        # HalfNormal(1) via log-transform + Jacobian, for both scales.
        for name in ("log_sigma_alpha", "log_sigma"):
            s = jnp.exp(params[name])
            lp += -0.5 * s**2 + params[name]
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "mu_alpha": jnp.zeros(()),
            "log_sigma_alpha": jnp.array(-1.0),
            "beta": jnp.zeros(()),
            "log_sigma": jnp.zeros(()),
            "alpha_raw": jnp.zeros((self.n_counties,)),
        }

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
