"""Federated count-data GLMs: Poisson and negative-binomial regression.

Rounds out the GLM family (models/glm.py is the Gaussian varying-
intercept member; models/logistic.py the Bernoulli one).  The reference
framework is model-agnostic — any node function returning ``[logp,
*grads]`` works (reference: signatures.py:26-33) — so model families
are this framework's way of giving users the *built* thing the
reference leaves as an exercise.

Both models share the federated structure of the other families:

    w          ~ Normal(0, prior_scale)^d         shared slopes
    b0         ~ Normal(0, prior_scale)           global intercept
    b_raw_i    ~ Normal(0, 1)                     per shard (non-centered)
    tau        ~ HalfNormal(1)  (log-param)       intercept spread
    eta_ij     = b0 + tau * b_raw_i + x_ij . w
    Poisson:   y_ij ~ Poisson(exp(eta_ij))
    NegBin:    y_ij ~ NB(mean=exp(eta_ij), dispersion=phi)  (log-param)

The negative binomial uses the mean/dispersion ("NB2") parameterization
``Var[y] = mu + mu^2 / phi``; ``phi -> inf`` recovers Poisson.

TPU notes: the per-shard hot op is the ``(n, d) @ (d,)`` matvec batched
over shards (one MXU-friendly einsum under vmap/shard_map), and the
Poisson/NB terms need only ``exp``/``lgamma`` — VPU transcendentals, no
data-dependent control flow, so the whole posterior jits clean.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from .hierbase import HierarchicalGLMBase, log_halfnormal_draw


def generate_count_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    tau: float = 0.3,
    dispersion: Optional[float] = None,
    pi: float = 0.0,
    seed: int = 19,
):
    """Per-shard count data; ``dispersion=None`` draws Poisson, a float
    draws NB2 with that dispersion.  ``pi > 0`` mixes in that fraction
    of structural zeros (the zero-inflated DGP; the extra uniform draw
    happens only then, so ``pi=0`` streams stay bit-identical to the
    pre-ZI generator)."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(0.0, 0.4, size=n_features)
    b0_true = 0.8
    b_true = b0_true + tau * rng.normal(size=n_shards)
    shards = []
    for i in range(n_shards):
        X = rng.normal(0.0, 1.0, size=(n_obs, n_features)).astype(np.float32)
        eta = b_true[i] + X @ w_true
        mu = np.exp(eta)
        if dispersion is None:
            y = rng.poisson(mu)
        else:
            # NB2 as Gamma-Poisson mixture: rate ~ Gamma(phi, phi/mu)
            lam = rng.gamma(dispersion, mu / dispersion)
            y = rng.poisson(lam)
        if pi > 0:
            y = np.where(rng.uniform(size=n_obs) < pi, 0, y)
        shards.append((X, y.astype(np.float32)))
    truth = {"w": w_true, "b0": b0_true, "b": b_true}
    if pi > 0:
        truth["pi"] = pi
    return pack_shards(shards, pad_to_multiple=8), truth


def poisson_logpmf(y, eta):
    """log Poisson(y | mu=exp(eta)) with eta the linear predictor.

    The mean term ``-exp(eta)`` is evaluated with eta clamped to 80
    (exp(80) ~ 5.5e34, comfortably inside f32): beyond that the true
    logp is astronomically negative anyway, and the clamp keeps both
    the value and the gradient FINITE.  Unclamped, an overflowing
    proposal yields ``-inf`` whose chain rule forms ``0 * -inf = NaN``
    against exact-zero design entries or padded (mask=0) rows, and one
    NaN poisons the whole shard sum; a huge-but-finite negative logp is
    an ordinary rejected proposal instead."""
    return y * eta - jnp.exp(jnp.minimum(eta, 80.0)) - gammaln(y + 1.0)


def negbin_logpmf(y, eta, phi):
    """log NB2(y | mu=exp(eta), dispersion=phi).

    NB2 pmf: C(y+phi-1, y) (phi/(phi+mu))^phi (mu/(phi+mu))^y with
    Var = mu + mu^2/phi; written via gammaln and log1p for stability.
    """
    # log(phi + mu) via logaddexp keeps everything finite when eta
    # overflows exp (f32: eta > ~88) — otherwise 0 * -inf on zero-count
    # or padded rows turns the whole shard's logp into NaN mid-NUTS.
    log_phi_plus_mu = jnp.logaddexp(jnp.log(phi), eta)
    log_phi_mu = jnp.log(phi) - log_phi_plus_mu
    log_mu_phi = eta - log_phi_plus_mu
    return (
        gammaln(y + phi)
        - gammaln(phi)
        - gammaln(y + 1.0)
        + phi * log_phi_mu
        + y * log_mu_phi
    )


@dataclasses.dataclass
class FederatedPoissonGLM(HierarchicalGLMBase):
    """Hierarchical Poisson regression over federated shards."""

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase
    _init_log_tau = -1.0

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        return poisson_logpmf(y, eta)

    # Simulated-count ceiling: jax.random.poisson silently CLAMPS to
    # INT32_MAX (and maps lam=inf to 0), so wide-prior draws with
    # exp(eta) > 2^31 would corrupt prior-predictive moments with
    # sentinel garbage.  1e8 keeps every draw exact int32 Poisson.
    _MAX_SIM_MEAN = 1e8

    def _sample_obs(self, params, key, eta):
        lam = jnp.minimum(jnp.exp(eta), self._MAX_SIM_MEAN)
        return jax.random.poisson(key, lam).astype(eta.dtype)


@dataclasses.dataclass
class FederatedNegBinGLM(HierarchicalGLMBase):
    """Hierarchical negative-binomial (NB2) regression over federated
    shards, with a learned dispersion."""

    data: ShardedData
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    compute_dtype: Optional[Any] = None  # see HierarchicalGLMBase
    _init_log_tau = -1.0

    def __post_init__(self):
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        return negbin_logpmf(y, eta, jnp.exp(params["log_phi"]))

    def _sample_obs(self, params, key, eta):
        # NB2 as its Gamma-Poisson mixture: lam ~ Gamma(phi, mu/phi);
        # same INT32 clamp hazard as the Poisson family (see
        # FederatedPoissonGLM._MAX_SIM_MEAN).
        phi = jnp.exp(params["log_phi"])
        k_g, k_p = jax.random.split(key)
        lam = jax.random.gamma(k_g, phi, eta.shape) * (
            jnp.exp(eta) / phi
        )
        lam = jnp.minimum(lam, FederatedPoissonGLM._MAX_SIM_MEAN)
        return jax.random.poisson(k_p, lam).astype(eta.dtype)

    def prior_logp(self, params: Any) -> jax.Array:
        lp = super().prior_logp(params)
        # HalfNormal(10) on phi (weakly informative; log-param).
        phi = jnp.exp(params["log_phi"])
        lp += -0.5 * (phi / 10.0) ** 2 + params["log_phi"]
        return lp

    def init_params(self) -> Any:
        p = super().init_params()
        p["log_phi"] = jnp.array(1.0)
        return p

    def _sample_extra_params(self, key) -> dict:
        # HalfNormal(10) on phi, matching prior_logp.
        return {"log_phi": log_halfnormal_draw(key, 10.0)}


def zero_inflate_logpmf(y, base_logpmf, logit_pi):
    """Zero-inflated observation log-pmf from any count base family.

    A structural-zero component with probability ``pi = sigmoid(
    logit_pi)`` mixes with the base pmf:

        y = 0:  log(pi + (1 - pi) * base(0))
        y > 0:  log(1 - pi) + log base(y)

    computed entirely in log space (``log_sigmoid`` both ways — no
    catastrophic ``log(1 - sigmoid)``), elementwise and branch-free
    (``where``, not ``cond``), so the vmapped/shard_mapped posterior
    stays one fused program.  THE one implementation shared by the ZIP
    and ZINB families below.
    """
    log_pi = jax.nn.log_sigmoid(logit_pi)
    log1m_pi = jax.nn.log_sigmoid(-logit_pi)
    with_base = log1m_pi + base_logpmf
    return jnp.where(y == 0, jnp.logaddexp(log_pi, with_base), with_base)


def generate_zi_count_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    tau: float = 0.3,
    pi: float = 0.3,
    dispersion: Optional[float] = None,
    seed: int = 23,
):
    """Thin wrapper: :func:`generate_count_data` with ``pi`` structural
    zeros (one DGP implementation — a fix there propagates here).
    ``dispersion=None`` -> ZIP, a float -> ZINB."""
    if not 0.0 < pi < 1.0:
        raise ValueError(f"pi must be in (0, 1), got {pi}")
    return generate_count_data(
        n_shards,
        n_obs=n_obs,
        n_features=n_features,
        tau=tau,
        dispersion=dispersion,
        pi=pi,
        seed=seed,
    )


class _ZeroInflatedMixin:
    """The zero-inflation overlay (learned logit-parameterized
    structural-zero probability): wraps the BASE family's pmf and
    simulator via ``super()``, so ZIP/ZINB cannot drift from their
    base families or from each other — one implementation of the
    logit prior, the warm start, and the structural-zero mask."""

    def _obs_logpmf(self, params, y, eta):
        return zero_inflate_logpmf(
            y, super()._obs_logpmf(params, y, eta), params["logit_pi"]
        )

    def _sample_obs(self, params, key, eta):
        k_z, k_y = jax.random.split(key)
        y = super()._sample_obs(params, k_y, eta)
        pi = jax.nn.sigmoid(params["logit_pi"])
        structural = jax.random.uniform(k_z, eta.shape) < pi
        return jnp.where(structural, 0.0, y)

    def prior_logp(self, params: Any) -> jax.Array:
        # Normal(0, 1.5) on the logit keeps pi away from the 0/1
        # boundaries a priori without forbidding them.
        lp = super().prior_logp(params)
        return lp + jnp.sum(-0.5 * (params["logit_pi"] / 1.5) ** 2)

    def init_params(self) -> Any:
        p = super().init_params()
        p["logit_pi"] = jnp.array(-1.0)  # pi ~ 0.27 warm start
        return p

    def _sample_extra_params(self, key) -> dict:
        k_base, k_pi = jax.random.split(key)
        extra = super()._sample_extra_params(k_base)
        extra["logit_pi"] = 1.5 * jax.random.normal(k_pi)
        return extra


@dataclasses.dataclass
class FederatedZeroInflPoissonGLM(_ZeroInflatedMixin, FederatedPoissonGLM):
    """Hierarchical zero-inflated Poisson (ZIP) regression: excess
    zeros beyond what the Poisson rate explains get a learned
    structural-zero probability ``pi`` (global, logit-parameterized) —
    the standard fix when count data has more zeros than any
    log-linear rate can produce."""


@dataclasses.dataclass
class FederatedZeroInflNegBinGLM(_ZeroInflatedMixin, FederatedNegBinGLM):
    """Hierarchical zero-inflated NB2 regression: overdispersion AND
    excess zeros, each with its own learned parameter (``log_phi``,
    ``logit_pi``)."""
