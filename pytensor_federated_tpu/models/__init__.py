"""Model families covering the BASELINE.json benchmark configs."""

from .countdata import (
    FederatedNegBinGLM,
    FederatedPoissonGLM,
    FederatedZeroInflNegBinGLM,
    FederatedZeroInflPoissonGLM,
    generate_count_data,
    generate_zi_count_data,
)
from .gamma import FederatedGammaGLM, gamma_logpdf, generate_gamma_data
from .glm import HierarchicalRadonGLM, generate_radon_data
from .gp import (
    FederatedExactGP,
    FederatedSparseGP,
    dense_vfe_logp,
    generate_gp_data,
    get_kernel,
)
from .linear import FederatedLinearRegression, generate_node_data
from .logistic import (
    FederatedLogisticRegression,
    HierarchicalLogisticRegression,
    generate_hier_logistic_data,
    generate_logistic_data,
)
from .mixture import (
    FederatedGaussianMixture,
    generate_mixture_data,
    mixture_loglik,
)
from .multinomial import (
    FederatedSoftmaxRegression,
    HierarchicalSoftmaxRegression,
    generate_hier_multinomial_data,
    generate_multinomial_data,
)
from .ode import (
    LotkaVolterraModel,
    generate_lv_data,
    make_lv_model,
    rk4_integrate,
)
from .ordinal import (
    FederatedOrdinalRegression,
    cumulative_logit_loglik,
    generate_ordinal_data,
)
from .robust import (
    FederatedRobustRegression,
    generate_robust_data,
    student_t_logpdf,
)
from .statespace import (
    FederatedLGSSMPanel,
    SeqShardedLGSSM,
    ekf_logp,
    generate_lgssm_data,
    kalman_forecast,
    kalman_logp_parallel,
    kalman_logp_seq,
    kalman_smoother_parallel,
    kalman_smoother_seq,
    kalman_smoother_with_lag1,
    lgssm_em,
    panel_em,
    sample_latents,
)
from .survival import (
    FederatedWeibullAFT,
    generate_survival_data,
    weibull_censored_loglik,
)
from .timeseries import SeqShardedAR1, generate_ar1_data

__all__ = [
    "FederatedGammaGLM",
    "FederatedGaussianMixture",
    "FederatedSoftmaxRegression",
    "HierarchicalSoftmaxRegression",
    "generate_hier_multinomial_data",
    "generate_multinomial_data",
    "FederatedExactGP",
    "FederatedNegBinGLM",
    "FederatedOrdinalRegression",
    "FederatedPoissonGLM",
    "FederatedZeroInflNegBinGLM",
    "FederatedZeroInflPoissonGLM",
    "FederatedRobustRegression",
    "FederatedSparseGP",
    "FederatedWeibullAFT",
    "cumulative_logit_loglik",
    "gamma_logpdf",
    "generate_count_data",
    "generate_zi_count_data",
    "get_kernel",
    "generate_gamma_data",
    "generate_mixture_data",
    "mixture_loglik",
    "generate_ordinal_data",
    "generate_robust_data",
    "generate_survival_data",
    "weibull_censored_loglik",
    "student_t_logpdf",
    "SeqShardedAR1",
    "FederatedLGSSMPanel",
    "SeqShardedLGSSM",
    "generate_lgssm_data",
    "ekf_logp",
    "kalman_forecast",
    "kalman_logp_parallel",
    "kalman_logp_seq",
    "kalman_smoother_parallel",
    "kalman_smoother_seq",
    "kalman_smoother_with_lag1",
    "lgssm_em",
    "panel_em",
    "sample_latents",
    "dense_vfe_logp",
    "generate_ar1_data",
    "generate_gp_data",
    "FederatedLinearRegression",
    "FederatedLogisticRegression",
    "HierarchicalLogisticRegression",
    "HierarchicalRadonGLM",
    "LotkaVolterraModel",
    "generate_hier_logistic_data",
    "generate_logistic_data",
    "generate_lv_data",
    "generate_node_data",
    "generate_radon_data",
    "make_lv_model",
    "rk4_integrate",
]
