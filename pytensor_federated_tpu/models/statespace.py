"""Linear-Gaussian state-space models with parallel-in-time inference.

Net-new model family (the reference has no sequence models at all —
SURVEY §5: "long context / sequence parallelism: N/A"), designed
TPU-first: the Kalman filter is a *sequential* recursion, which is the
worst possible shape for an accelerator, so this module implements the
temporal-parallelization construction of Särkkä & García-Fernández
(IEEE TAC 2021): filtering rewritten as an **associative** operator so
``lax.associative_scan`` evaluates all T filtered states in O(log T)
depth on one device — and, combined with a segment-summary exclusive
scan over the ``"seq"`` mesh axis, across devices.

Model (``m0``/``P0`` are the moments of a *time-0* latent, so the
first observed state is ``z_1 ~ N(F m0, F P0 Fᵀ + Q)``)::

    z_0 ~ N(m0, P0)            latent, dim d
    z_t = F z_{t-1} + N(0, Q)  t = 1..T
    y_t = H z_t     + N(0, R)  observed, dim k, t = 1..T

Three evaluation paths, all exact and mutually equivalent (tested):

- :func:`kalman_logp_seq` — classic ``lax.scan`` filter (the golden
  reference; O(T) depth).
- :func:`kalman_logp_parallel` — ``lax.associative_scan`` over the
  5-tuple filtering elements ``(A, b, C, J, eta)``; O(log T) depth,
  all matmuls batched over T (MXU-friendly).
- :class:`SeqShardedLGSSM` — the distributed version: each device
  associative-scans its local segment, segment summaries (one element
  each, O(d²)) are all-gathered and prefix-composed, and the prefix is
  folded into every local result.  One ``all_gather`` of n tiny
  matrices is the entire communication cost.

The marginal likelihood is assembled from the filtered means/covs: the
one-step predictive ``p(y_t | y_{1:t-1})`` is Gaussian with moments
computed from the *previous* filtered state, so after the scan all T
terms evaluate in one vmapped batch.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import SEQ_AXIS, mark_varying as _mark_varying


def _mvn_logpdf(x, mean, cov):
    d = x.shape[-1]
    diff = x - mean
    chol = jnp.linalg.cholesky(cov)
    sol = jax.scipy.linalg.solve_triangular(chol, diff, lower=True)
    return (
        -0.5 * jnp.sum(sol**2, axis=-1)
        - jnp.sum(jnp.log(jnp.diagonal(chol, axis1=-2, axis2=-1)), axis=-1)
        - 0.5 * d * jnp.log(2.0 * jnp.pi)
    )


def generate_lgssm_data(
    T: int = 128,
    *,
    d: int = 2,
    k: int = 1,
    seed: int = 7,
):
    """A stable rotation-plus-decay latent with noisy 1-D observations."""
    rng = np.random.default_rng(seed)
    th = 0.3
    rot = np.array([[np.cos(th), -np.sin(th)], [np.sin(th), np.cos(th)]])
    F = 0.95 * (rot if d == 2 else np.eye(d))
    H = rng.normal(size=(k, d)) / np.sqrt(d)
    Q = 0.1 * np.eye(d)
    R = 0.5 * np.eye(k)
    z = rng.normal(size=d)
    ys = []
    for _ in range(T):
        z = F @ z + rng.multivariate_normal(np.zeros(d), Q)
        ys.append(H @ z + rng.multivariate_normal(np.zeros(k), R))
    params = {
        "F": jnp.asarray(F, jnp.float32),
        "H": jnp.asarray(H, jnp.float32),
        "log_q": jnp.asarray(np.log(0.1), jnp.float32),
        "log_r": jnp.asarray(np.log(0.5), jnp.float32),
        "m0": jnp.zeros((d,), jnp.float32),
    }
    return jnp.asarray(np.stack(ys), jnp.float32), params


def default_lgssm_params(d: int = 2, k: int = 1) -> dict:
    """Default parameter pytree (the keys ``_unpack`` expects)."""
    return {
        "F": 0.9 * jnp.eye(d),
        "H": jnp.ones((k, d)) / d,
        "log_q": jnp.asarray(-1.0),
        "log_r": jnp.asarray(-0.5),
        "m0": jnp.zeros((d,)),
    }


def _unpack(params):
    F = params["F"]
    H = params["H"]
    d = F.shape[0]
    k = H.shape[0]
    Q = jnp.exp(params["log_q"]) * jnp.eye(d, dtype=F.dtype)
    R = jnp.exp(params["log_r"]) * jnp.eye(k, dtype=F.dtype)
    m0 = params["m0"]
    P0 = jnp.eye(d, dtype=F.dtype)
    return F, H, Q, R, m0, P0


# ---------------------------------------------------------------------------
# Sequential reference filter (golden model; O(T) depth)
# ---------------------------------------------------------------------------


def _as_mask(mask, T, dtype):
    """Normalize an optional observation mask to a float (T,) array
    (1 = observed, 0 = missing)."""
    if mask is None:
        return jnp.ones((T,), dtype)
    return jnp.asarray(mask, dtype)


def _sanitize(y, mask):
    """Zero out masked rows so NaN-encoded missing observations (the
    canonical pandas form) cannot poison the filter: 0 * NaN = NaN, so
    masked values must be *replaced*, not just weight-zeroed."""
    return jnp.where(mask[:, None] > 0, y, jnp.zeros_like(y))


def kalman_logp_seq(
    params: Any, y: jax.Array, mask: Any = None, *, precision: Any = None
) -> jax.Array:
    """Marginal log-likelihood via the classic sequential Kalman filter.

    ``mask`` (optional, shape ``(T,)``): 1 where ``y_t`` is observed,
    0 where missing.  Missing steps contribute no likelihood term and
    perform a pure prediction (no measurement update) — the standard
    missing-data treatment, which also serves ragged/padded series.
    Masked rows of ``y`` may hold any value, including NaN.

    ``precision``: f32 contraction policy name (:mod:`..precision`).
    ``"highest"``/``"strict"`` trace every internal matmul and solve at
    ``Precision.HIGHEST`` — the verified TPU mitigation for the chip's
    bf16-accurate plain-f32 contractions (the context demonstrably
    engages for this filter's dot_generals: 15.5 -> 220 ms on chip,
    tools/diag_tpu.out).  The filter's matrices are tiny (d x d), so
    the split-dot mechanism does not apply here.
    """
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        return _kalman_logp_seq_body(params, y, mask)


def _kalman_logp_seq_body(params, y, mask):
    F, H, Q, R, m0, P0 = _unpack(params)
    mask = _as_mask(mask, y.shape[0], F.dtype)
    y = _sanitize(y, mask)

    def step(carry, inp):
        y_t, obs = inp
        m, Pcov = carry
        # predict
        mp = F @ m
        Pp = F @ Pcov @ F.T + Q
        # observe
        S = H @ Pp @ H.T + R
        v = y_t - H @ mp
        ll = _mvn_logpdf(v, jnp.zeros_like(v), S)
        K = jnp.linalg.solve(S, H @ Pp).T
        m_new = jnp.where(obs > 0, mp + K @ v, mp)
        P_new = jnp.where(obs > 0, Pp - K @ S @ K.T, Pp)
        return (m_new, P_new), obs * ll

    (_, _), lls = lax.scan(step, (m0, P0), (y, mask))
    return jnp.sum(lls)


# ---------------------------------------------------------------------------
# Associative filtering elements (Särkkä & García-Fernández 2021, §III)
# ---------------------------------------------------------------------------


def _generic_elements(F, H, Q, R, y, mask):
    """Generic (non-prior) elements for every row of ``y``: the
    conditioning of one transition on its observation.  Masked-out rows
    degrade to the pure prediction element ``(F, 0, Q, 0, 0)``.
    ``mask`` must be a normalized float array and ``y`` sanitized."""
    d = F.shape[0]
    eye = jnp.eye(d, dtype=F.dtype)

    def generic(y_t, obs):
        S = H @ Q @ H.T + R  # innovation cov given exact previous state
        K = jnp.linalg.solve(S, H @ Q).T
        A = jnp.where(obs > 0, (eye - K @ H) @ F, F)
        b = jnp.where(obs > 0, K @ y_t, jnp.zeros((d,), F.dtype))
        C = jnp.where(obs > 0, (eye - K @ H) @ Q, Q)
        HF = H @ F
        zero = jnp.zeros((d, d), F.dtype)
        J = jnp.where(obs > 0, HF.T @ jnp.linalg.solve(S, HF), zero)
        eta = jnp.where(
            obs > 0,
            HF.T @ jnp.linalg.solve(S, y_t),
            jnp.zeros((d,), F.dtype),
        )
        return A, b, C, J, eta

    return jax.vmap(generic)(y, mask)


def _prior_element(F, H, Q, R, m0, P0, y1, obs1):
    """Element for global t=1: condition the prior predictive
    ``N(F m0, F P0 F' + Q)`` on ``y_1`` directly (or, when ``y_1`` is
    masked out, keep the prior predictive unconditioned).  Its ``A`` is
    zero, so composition discards the dependence on the non-existent
    state 0."""
    d = F.shape[0]
    Pp = F @ P0 @ F.T + Q
    mp = F @ m0
    S1 = H @ Pp @ H.T + R
    K1 = jnp.linalg.solve(S1, H @ Pp).T
    b1 = jnp.where(obs1 > 0, mp + K1 @ (y1 - H @ mp), mp)
    C1 = jnp.where(obs1 > 0, Pp - K1 @ S1 @ K1.T, Pp)
    zero = jnp.zeros((d, d), F.dtype)
    return zero, b1, C1, zero, jnp.zeros((d,), F.dtype)


def _filter_elements(F, H, Q, R, m0, P0, y, mask=None):
    """Per-step elements ``(A, b, C, J, eta)`` such that composing
    elements 1..t yields the filtered mean/cov at t in ``(b, C)``.
    Normalizes the mask and sanitizes ``y`` (single entry point for the
    parallel paths)."""
    mask = _as_mask(mask, y.shape[0], F.dtype)
    y = _sanitize(y, mask)
    elems = _generic_elements(F, H, Q, R, y, mask)
    prior = _prior_element(F, H, Q, R, m0, P0, y[0], mask[0])
    return jax.tree_util.tree_map(
        lambda g, p: g.at[0].set(p), elems, prior
    )


def _combine(e1, e2):
    """Associative composition of filtering elements (batched)."""
    A1, b1, C1, J1, eta1 = e1
    A2, b2, C2, J2, eta2 = e2
    d = A1.shape[-1]
    eye = jnp.eye(d, dtype=A1.dtype)
    # (I + C1 J2)^{-1}, applied from the right to A2 / to (b1 + C1 eta2).
    M = eye + C1 @ J2
    A2M = jnp.swapaxes(
        jnp.linalg.solve(jnp.swapaxes(M, -1, -2), jnp.swapaxes(A2, -1, -2)),
        -1,
        -2,
    )  # = A2 @ M^{-1}
    b = (A2M @ (b1 + (C1 @ eta2[..., None])[..., 0])[..., None])[..., 0] + b2
    C = A2M @ C1 @ jnp.swapaxes(A2, -1, -2) + C2
    A = A2M @ A1
    # (I + J2 C1)^{-1}
    N = eye + J2 @ C1
    A1T = jnp.swapaxes(A1, -1, -2)
    eta = (
        A1T @ jnp.linalg.solve(N, (eta2 - (J2 @ b1[..., None])[..., 0])[..., None])
    )[..., 0] + eta1
    J = A1T @ jnp.linalg.solve(N, J2 @ A1) + J1
    return A, b, C, J, eta


def _predictive_one(F, H, Q, R, y_t, m, Pcov):
    """``log p(y_t | y_{1:t-1})`` from the filtered moments at t-1."""
    mp = F @ m
    Pp = F @ Pcov @ F.T + Q
    S = H @ Pp @ H.T + R
    return _mvn_logpdf(y_t - H @ mp, jnp.zeros(y_t.shape[-1]), S)


def _predictive_logp(F, H, Q, R, m0, P0, y, means, covs, mask=None):
    """Σ_t log p(y_t | y_{1:t-1}) from filtered moments at t-1 (masked
    steps contribute nothing)."""
    mask = _as_mask(mask, y.shape[0], F.dtype)
    y = _sanitize(y, mask)
    prev_m = jnp.concatenate([m0[None], means[:-1]], axis=0)
    prev_P = jnp.concatenate([P0[None], covs[:-1]], axis=0)
    one = functools.partial(_predictive_one, F, H, Q, R)
    return jnp.sum(mask * jax.vmap(one)(y, prev_m, prev_P))


def kalman_logp_parallel(
    params: Any, y: jax.Array, mask: Any = None, *, precision: Any = None
) -> jax.Array:
    """Marginal log-likelihood with O(log T)-depth associative scan.
    ``mask`` and ``precision`` as in :func:`kalman_logp_seq` (the scan
    COMPOSES d x d products over T steps, so reduced-precision error
    compounds — the associative form is the one that degenerated on
    chip, tools/diag_tpu.out)."""
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        F, H, Q, R, m0, P0 = _unpack(params)
        means, covs = _filtered_moments(params, y, mask)
        return _predictive_logp(F, H, Q, R, m0, P0, y, means, covs, mask)


# ---------------------------------------------------------------------------
# Smoothing (RTS): sequential golden + parallel associative scan
# ---------------------------------------------------------------------------


def _filtered_moments(params, y, mask=None):
    """All filtered means/covs via the associative scan."""
    F, H, Q, R, m0, P0 = _unpack(params)
    elems = _filter_elements(F, H, Q, R, m0, P0, y, mask)
    _, means, covs, _, _ = lax.associative_scan(_combine, elems)
    return means, covs


def _smoother_gain(F, Q, Pf):
    """RTS smoother gain ``G = Pf F' (F Pf F' + Q)^{-1}`` — the single
    definition shared by every smoothing path."""
    Pp = F @ Pf @ F.T + Q
    return jnp.linalg.solve(Pp, F @ Pf).T, Pp


def kalman_smoother_seq(
    params: Any, y: jax.Array, mask: Any = None, *, precision: Any = None
):
    """Smoothed marginals ``(means, covs)`` via the classic backward
    Rauch-Tung-Striebel recursion (golden reference; O(T) depth).
    ``precision`` as in :func:`kalman_logp_seq`."""
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        return _kalman_smoother_seq_body(params, y, mask)


def _kalman_smoother_seq_body(params, y, mask):
    F, H, Q, R, m0, P0 = _unpack(params)
    means, covs = _filtered_moments(params, y, mask)

    def back(carry, mc):
        ms_next, Ps_next = carry
        m, Pcov = mc
        G, Pp = _smoother_gain(F, Q, Pcov)
        ms = m + G @ (ms_next - F @ m)
        Ps = Pcov + G @ (Ps_next - Pp) @ G.T
        return (ms, Ps), (ms, Ps)

    last = (means[-1], covs[-1])
    _, (sm, sP) = lax.scan(
        back, last, (means[:-1], covs[:-1]), reverse=True
    )
    sm = jnp.concatenate([sm, means[-1:]], axis=0)
    sP = jnp.concatenate([sP, covs[-1:]], axis=0)
    return sm, sP


def _smooth_elements(F, Q, means, covs, *, terminal: bool = True):
    """Per-step smoothing elements ``(E, g, L)``: the backward kernel
    ``z_t | z_{t+1} ~ N(E_t z_{t+1} + g_t, L_t)`` for t < T, and (with
    ``terminal=True``) the filtered terminal ``(0, m_T, P_T)`` at T.
    The distributed smoother passes ``terminal=False`` — its last local
    row is only the global terminal on the last device, selected there
    per-device rather than re-deriving the kernel."""

    def one(m, Pcov):
        G, Pp = _smoother_gain(F, Q, Pcov)
        E = G
        g = m - G @ (F @ m)
        L = Pcov - G @ Pp @ G.T
        return E, g, L

    E, g, L = jax.vmap(one)(means, covs)
    if not terminal:
        return E, g, L
    d = F.shape[0]
    E = E.at[-1].set(jnp.zeros((d, d), F.dtype))
    g = g.at[-1].set(means[-1])
    L = L.at[-1].set(covs[-1])
    return E, g, L


def _smooth_combine(e1, e2):
    """Associative composition of backward kernels (e1 earlier)."""
    E1, g1, L1 = e1
    E2, g2, L2 = e2
    E = E1 @ E2
    g = (E1 @ g2[..., None])[..., 0] + g1
    L = E1 @ L2 @ jnp.swapaxes(E1, -1, -2) + L1
    return E, g, L


def _smooth_from_filtered(F, Q, means, covs):
    """Smoothed marginals from precomputed filtered moments (one
    reverse associative scan; no second filter pass)."""
    elems = _smooth_elements(F, Q, means, covs)
    # reverse=True passes the accumulated *suffix* (the later
    # composition) as the first argument; _smooth_combine expects
    # (earlier, later), so flip.
    _, sm, sP = lax.associative_scan(
        lambda a, b: _smooth_combine(b, a), elems, reverse=True
    )
    return sm, sP


def kalman_smoother_parallel(
    params: Any, y: jax.Array, mask: Any = None, *, precision: Any = None
):
    """Smoothed marginals with O(log T)-depth associative scans (one
    forward for filtering, one reverse for smoothing).  The backward
    kernels depend on observations only through the filtered moments,
    so masking enters via the filter alone.  ``precision`` as in
    :func:`kalman_logp_seq`."""
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        F, H, Q, R, m0, P0 = _unpack(params)
        means, covs = _filtered_moments(params, y, mask)
        return _smooth_from_filtered(F, Q, means, covs)


def _lag1_from_moments(F, Q, f_covs, sP):
    """Lag-one smoothed cross-covs: ``P^s_{t+1,t} = P^s_{t+1} G_t'``."""
    Gs = jax.vmap(lambda Pf: _smoother_gain(F, Q, Pf)[0])(f_covs[:-1])
    return sP[1:] @ jnp.swapaxes(Gs, -1, -2)


def kalman_smoother_with_lag1(
    params: Any, y: jax.Array, mask: Any = None, *, precision: Any = None
):
    """Smoothed marginals plus lag-one smoothed cross-covariances.

    Returns ``(means, covs, lag1)`` with ``lag1[t] =
    Cov(z_{t+2}, z_{t+1} | y_{1:T})`` for ``t = 0..T-2`` — the standard
    RTS identity ``P^s_{t+1,t} = P^s_{t+1} G_t'``.  These are exactly
    the cross-moments the EM M-step needs (see :func:`lgssm_em`);
    verified against the dense joint-Gaussian conditional in tests.
    ``precision`` as in :func:`kalman_logp_seq`.
    """
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        F, H, Q, R, m0, P0 = _unpack(params)
        f_means, f_covs = _filtered_moments(params, y, mask)
        sm, sP = _smooth_from_filtered(F, Q, f_means, f_covs)
        return sm, sP, _lag1_from_moments(F, Q, f_covs, sP)


def lgssm_em(
    params: Any,
    y: jax.Array,
    *,
    num_iters: int = 20,
    mask: Any = None,
    fit_H: bool = False,
    precision: Any = None,
):
    """Closed-form EM for the LGSSM (Shumway-Stoffer): each iteration
    runs the O(log T)-depth smoother as the E-step and updates
    ``F`` (and optionally ``H``) plus the isotropic noise scales
    ``log_q``/``log_r`` in closed form.

    Conventions matching :func:`_unpack`: ``Q = exp(log_q) I`` and
    ``R = exp(log_r) I`` (full matrix M-step solutions are projected to
    their isotropic part via the trace); the prior ``(m0, P0)`` is held
    fixed and the transition sum runs over ``t = 2..T`` (the first
    transition involves the unsmoothed ``z_0``, so the update maximizes
    the expected complete-data likelihood of transitions 2..T — the
    exact-EM monotonicity guarantee therefore holds up to that one
    excluded term, i.e. monotone in practice for moderate ``T`` but not
    a theorem for tiny series).  Masked steps drop out of the emission
    update; the transition update uses all smoothed states (exact —
    smoothing already accounts for missingness).

    Returns ``(params, loglik_history)`` where the history is the exact
    marginal log-likelihood BEFORE each iteration's update.

    (Implemented as the single-series case of :func:`panel_em` — the
    pooled M-step over one series IS the classic update.)
    """
    y = jnp.asarray(y)
    if y.ndim == 1:
        y = y[:, None]
    return panel_em(
        params,
        y[None],
        num_iters=num_iters,
        masks=None if mask is None else jnp.asarray(mask)[None],
        fit_H=fit_H,
        precision=precision,
    )


def panel_em(
    params: Any,
    ys: jax.Array,
    *,
    num_iters: int = 20,
    masks: Any = None,
    fit_H: bool = False,
    precision: Any = None,
):
    """Federated EM: one set of LGSSM parameters fit to a whole panel
    of series (the :class:`FederatedLGSSMPanel` layout).

    The E-step smooths every series independently (vmapped — each an
    O(log T) scan); the M-step *pools* the sufficient statistics
    (A, B, C, emission moments) across series before the closed-form
    update — the federated-analytics shape: every node contributes a
    handful of d x d matrices, never its raw series.  Same conventions
    and caveats as :func:`lgssm_em`.

    ``ys``: ``(n_series, T)`` or ``(n_series, T, k)``; ``masks``
    (optional) ``(n_series, T)``.  Returns ``(params, loglik_history)``
    with the pooled marginal loglik before each update.
    ``precision`` as in :func:`kalman_logp_seq` (the E-step runs the
    same smoother compositions).
    """
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        return _panel_em_body(
            params, ys, num_iters=num_iters, masks=masks, fit_H=fit_H
        )


def _panel_em_body(params, ys, *, num_iters, masks, fit_H):
    ys = jnp.asarray(ys)
    if ys.ndim == 2:
        ys = ys[..., None]
    S, T, k = ys.shape
    if masks is None:
        masks = jnp.ones((S, T), ys.dtype)
    else:
        masks = jnp.asarray(masks, ys.dtype)
    ys = jax.vmap(_sanitize)(ys, masks)

    def one_iter(params, _):
        F, H, Q, R, m0, P0 = _unpack(params)
        d = F.shape[0]

        def estep(y_i, mask_i):
            f_means, f_covs = _filtered_moments(params, y_i, mask_i)
            ll = _predictive_logp(
                F, H, Q, R, m0, P0, y_i, f_means, f_covs, mask_i
            )
            sm, sP = _smooth_from_filtered(F, Q, f_means, f_covs)
            lag1 = _lag1_from_moments(F, Q, f_covs, sP)
            Ezz = sP + sm[:, :, None] * sm[:, None, :]
            Ezz1 = lag1 + sm[1:, :, None] * sm[:-1, None, :]
            A = jnp.sum(Ezz[:-1], axis=0)
            B = jnp.sum(Ezz1, axis=0)
            C = jnp.sum(Ezz[1:], axis=0)
            # Emission statistics in RESIDUAL form (against the current
            # H): the raw-moment identity yy - 2tr(H Syz') + tr(H Szz H')
            # cancels catastrophically in float32 when |y| is large
            # relative to the noise — residuals and sP traces stay at
            # noise scale.
            resid = y_i - sm @ H.T
            rr = jnp.sum(mask_i * jnp.sum(resid**2, axis=-1))
            Rz = jnp.sum(
                mask_i[:, None, None]
                * (resid[:, :, None] * sm[:, None, :]),
                axis=0,
            )
            Mzz = jnp.sum(
                mask_i[:, None, None] * (sm[:, :, None] * sm[:, None, :]),
                axis=0,
            )
            SP_obs = jnp.sum(mask_i[:, None, None] * sP, axis=0)
            return ll, A, B, C, (rr, Rz, Mzz, SP_obs, jnp.sum(mask_i) * k)

        lls, As, Bs, Cs, rs = jax.vmap(estep)(ys, masks)
        ll = jnp.sum(lls)
        A, B, C = jnp.sum(As, 0), jnp.sum(Bs, 0), jnp.sum(Cs, 0)
        rr, Rz, Mzz, SP_obs, n_obs = (jnp.sum(r, 0) for r in rs)
        F_new = jnp.linalg.solve(A.T, B.T).T
        q_new = jnp.trace((C - F_new @ B.T) / (S * (T - 1))) / d
        if fit_H:
            # Σ y sm' = Rz + H Mzz;  Σ E[z z']|obs = Mzz + SP_obs.
            H_new = jnp.linalg.solve(
                (Mzz + SP_obs).T, (Rz + H @ Mzz).T
            ).T
        else:
            H_new = H
        # E Σ||y - H_new z||^2 via the residual stats and dH = H_new - H:
        # Σ||y - H_new sm||^2 = rr - 2 tr(dH Rz') + tr(dH Mzz dH'),
        # plus the covariance term tr(H_new SP_obs H_new') — every term
        # stays at noise/update scale, no large-moment cancellation.
        dH = H_new - H
        r_new = (
            rr
            - 2.0 * jnp.trace(dH @ Rz.T)
            + jnp.trace(dH @ Mzz @ dH.T)
            + jnp.trace(H_new @ SP_obs @ H_new.T)
        ) / jnp.maximum(n_obs, 1.0)
        new = dict(
            params,
            F=F_new,
            H=H_new,
            log_q=jnp.log(jnp.maximum(q_new, 1e-12)),
            log_r=jnp.log(jnp.maximum(r_new, 1e-12)),
        )
        return new, ll

    params_out, lls = lax.scan(one_iter, params, None, length=num_iters)
    return params_out, lls


def kalman_forecast(
    params: Any,
    y: jax.Array,
    horizon: int,
    mask: Any = None,
    *,
    precision: Any = None,
):
    """h-step-ahead predictive moments of future observations.

    Returns ``(means, covs)`` with shapes ``(horizon, k)`` and
    ``(horizon, k, k)``: the Gaussian moments of
    ``y_{T+h} | y_{1:T}`` for h = 1..horizon.  One filter pass (the
    O(log T) associative scan) plus an affine associative scan over the
    horizon — no sequential propagation anywhere.  ``precision`` as in
    :func:`kalman_logp_seq`.
    """
    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(precision):
        return _kalman_forecast_body(params, y, horizon, mask)


def _kalman_forecast_body(params, y, horizon, mask):
    y = jnp.asarray(y)
    if y.ndim == 1:
        y = y[:, None]
    F, H, Q, R, m0, P0 = _unpack(params)
    means, covs = _filtered_moments(params, y, mask)
    return _forecast_from_terminal(
        F, H, Q, R, means[-1], covs[-1], horizon
    )


def _forecast_from_terminal(F, H, Q, R, m_T, P_T, horizon):
    """Predictive observation moments for h = 1..horizon given the
    terminal filtered state — shared by the single-device and
    distributed forecasters.

    Latent moments at T+h: ``m = F^h m_T``; ``P = F^h P_T (F^h)' +
    Σ F^j Q F^j'`` — both prefix compositions of the affine-moment
    element ``(F, Q)``: compose((A1,B1),(A2,B2)) = (A2 A1,
    A2 B1 A2' + B2)."""
    d = F.shape[0]
    A = jnp.broadcast_to(F, (horizon, d, d))
    B = jnp.broadcast_to(Q, (horizon, d, d))

    def moment(e1, e2):
        A1, B1 = e1
        A2, B2 = e2
        return A2 @ A1, A2 @ B1 @ jnp.swapaxes(A2, -1, -2) + B2

    Fh, Vh = lax.associative_scan(moment, (A, B))
    mz = (Fh @ m_T[..., None])[..., 0]
    Pz = Fh @ P_T @ jnp.swapaxes(Fh, -1, -2) + Vh
    my = mz @ H.T
    Py = jnp.einsum("ij,hjk,lk->hil", H, Pz, H) + R
    return my, Py


# ---------------------------------------------------------------------------
# Nonlinear models: extended Kalman filter (autodiff Jacobians)
# ---------------------------------------------------------------------------


def ekf_logp(
    f,
    h,
    params: Any,
    y: jax.Array,
    *,
    Q: jax.Array,
    R: jax.Array,
    m0: jax.Array,
    P0: jax.Array,
    mask: Any = None,
) -> jax.Array:
    """Approximate marginal log-likelihood of a *nonlinear* state-space
    model via the extended Kalman filter.

    ``z_t = f(params, z_{t-1}) + N(0, Q)``,
    ``y_t = h(params, z_t) + N(0, R)``.

    The per-step linearization Jacobians come from ``jax.jacfwd`` — no
    hand-derived derivatives, the JAX-native replacement for the
    hand-linearized EKFs of classical toolboxes.  The recursion is
    inherently sequential (each linearization point depends on the
    previous posterior), so this runs as a ``lax.scan``; for *linear*
    models use :func:`kalman_logp_parallel`, which this function matches
    exactly when ``f``/``h`` are affine (tested).

    Differentiable in ``params`` (and ``Q``/``R``/``m0``/``P0`` if
    traced): grad flows through the Jacobians (second-order autodiff).
    """
    y = jnp.asarray(y)
    if y.ndim == 1:
        y = y[:, None]
    Q = jnp.asarray(Q)
    R = jnp.asarray(R)
    m0 = jnp.asarray(m0)
    P0 = jnp.asarray(P0)
    mask_arr = _as_mask(mask, y.shape[0], y.dtype)
    y = _sanitize(y, mask_arr)

    f_jac = jax.jacfwd(f, argnums=1)
    h_jac = jax.jacfwd(h, argnums=1)

    def step(carry, inp):
        y_t, obs = inp
        m, Pcov = carry
        # predict through the nonlinear transition, linearized at m
        Fm = f_jac(params, m)
        mp = f(params, m)
        Pp = Fm @ Pcov @ Fm.T + Q
        # observe through the nonlinear emission, linearized at mp
        Hm = h_jac(params, mp)
        v = y_t - h(params, mp)
        S = Hm @ Pp @ Hm.T + R
        ll = _mvn_logpdf(v, jnp.zeros_like(v), S)
        K = jnp.linalg.solve(S, Hm @ Pp).T
        m_new = jnp.where(obs > 0, mp + K @ v, mp)
        P_new = jnp.where(obs > 0, Pp - K @ S @ K.T, Pp)
        return (m_new, P_new), obs * ll

    (_, _), lls = lax.scan(step, (m0, P0), (y, mask_arr))
    return jnp.sum(lls)


# ---------------------------------------------------------------------------
# Federated panel of time series (shards axis x parallel-in-time filter)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class FederatedLGSSMPanel:
    """A panel of time series: each federated shard owns one private
    series, all sharing the LGSSM parameters.

    ``logp(params) = Σ_shards kalman_logp(params, y_shard)`` — the
    federated sum-of-potentials contract (reference: demo_model.py:34-36)
    with a sequence model inside each node: the ``shards`` mesh axis
    carries the panel, and within every shard the filter itself is the
    O(log T)-depth associative scan.  Composes the two scale axes this
    framework adds (shard count x sequence length).

    ``ys``: ``(n_series, T)`` or ``(n_series, T, k)``.  ``masks``
    (optional, ``(n_series, T)``): 1 = observed — supports ragged
    panels (pad shorter series and mask the padding) and irregular
    sampling, the same convention as ``parallel.packing.pack_shards``.
    """

    ys: jax.Array
    mesh: Any = None
    axis: str = "shards"
    masks: Any = None

    def __post_init__(self):
        from ..parallel.sharded import FederatedLogp

        ys = jnp.asarray(self.ys)
        if ys.ndim not in (2, 3):
            raise ValueError(
                f"expected ys of shape (n_series, T) or (n_series, T, k), "
                f"got {ys.shape}"
            )
        if ys.ndim == 2:
            ys = ys[..., None]
        self.ys = ys
        if self.masks is None:
            self.masks = jnp.ones(ys.shape[:2], ys.dtype)
        else:
            self.masks = jnp.asarray(self.masks, ys.dtype)
            if self.masks.shape != ys.shape[:2]:
                raise ValueError(
                    f"masks shape {self.masks.shape} != (n_series, T) "
                    f"{ys.shape[:2]}"
                )

        def per_shard_logp(params, shard):
            y_shard, mask_shard = shard
            return kalman_logp_parallel(params, y_shard, mask_shard)

        self.fed = FederatedLogp(
            per_shard_logp,
            (self.ys, self.masks),
            mesh=self.mesh,
            axis=self.axis,
        )

    def logp(self, params: Any) -> jax.Array:
        return self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return self.fed.logp_and_grad(params)

    def init_params(self, d: int = 2) -> Any:
        return default_lgssm_params(d, self.ys.shape[-1])


# ---------------------------------------------------------------------------
# Posterior latent sampling (Durbin-Koopman simulation smoother)
# ---------------------------------------------------------------------------


def _affine_combine(e1, e2):
    """Composition of affine recurrence elements (e1 earlier):
    ``z -> A2(A1 z + b1) + b2``."""
    A1, b1 = e1
    A2, b2 = e2
    return A2 @ A1, (A2 @ b1[..., None])[..., 0] + b2


def _draw_noise(params, key, T):
    """The model's noise draws — shared by the single-device and
    distributed simulation smoothers so their generative conventions
    can never diverge.  Returns ``(z0, w, v)``."""
    F, H, Q, R, m0, P0 = _unpack(params)
    d, k = F.shape[0], H.shape[0]
    kz, kw, kv = jax.random.split(key, 3)
    z0 = m0 + jnp.linalg.cholesky(P0) @ jax.random.normal(kz, (d,), F.dtype)
    w = jax.random.normal(kw, (T, d), F.dtype) @ jnp.linalg.cholesky(Q).T
    v = jax.random.normal(kv, (T, k), F.dtype) @ jnp.linalg.cholesky(R).T
    return z0, w, v


def _simulate(params, key, T):
    """One unconditional draw ``(z*, y*)`` from the model.  The latent
    recurrence ``z_t = F z_{t-1} + w_t`` is itself evaluated with an
    associative scan over affine elements ``(A, b)`` — O(log T) depth,
    keeping the whole simulation smoother parallel-in-time."""
    F, H, Q, R, m0, P0 = _unpack(params)
    d = F.shape[0]
    z0, w, v = _draw_noise(params, key, T)
    b = w.at[0].add(F @ z0)
    A = jnp.broadcast_to(F, (T, d, d))
    _, z = lax.associative_scan(_affine_combine, (A, b))
    y = z @ H.T + v
    return z, y


def sample_latents(
    params: Any,
    y: jax.Array,
    key: jax.Array,
    num_draws: int = 1,
    mask: Any = None,
) -> jax.Array:
    """Joint posterior draws of the latent path ``z_{1:T} | y_{1:T}``.

    Durbin & Koopman's simulation smoother: draw an unconditional
    ``(z*, y*)`` from the model, then
    ``z_draw = E[z|y] + (z* - E[z|y*])`` — exact for linear-Gaussian
    models, and every ingredient here is an associative scan, so a draw
    costs two O(log T)-depth smoother passes instead of a sequential
    backward-sampling sweep (classic FFBS).  Returns ``(num_draws, T, d)``.
    """
    y = jnp.asarray(y)
    if y.ndim == 1:
        y = y[:, None]
    T = y.shape[0]
    # The synthetic draw conditions on the SAME observation pattern.
    sm_y, _ = kalman_smoother_parallel(params, y, mask)

    def one(k):
        z_star, y_star = _simulate(params, k, T)
        sm_star, _ = kalman_smoother_parallel(params, y_star, mask)
        return sm_y + z_star - sm_star

    return jax.vmap(one)(jax.random.split(key, num_draws))


# ---------------------------------------------------------------------------
# Sequence-sharded distributed filter
# ---------------------------------------------------------------------------


@dataclasses.dataclass(eq=False)
class SeqShardedLGSSM:
    """LGSSM likelihood with the time axis sharded over ``axis``.

    Each device associative-scans its local segment of filtering
    elements; the per-segment summaries (the fold of each segment — one
    ``(A, b, C, J, eta)`` element, O(d²) numbers) are ``all_gather``ed,
    every device composes the exclusive prefix of the segments before
    it, and folds that prefix into each local scan result.  The total
    communication is one all-gather of ``n_devices`` tiny elements per
    evaluation — the classic distributed prefix-scan, riding ICI.

    Differentiable end-to-end (``jax.grad`` through ``all_gather`` and
    the scans); use :meth:`logp_and_grad` for the fused pair.
    """

    y: jax.Array
    mesh: Mesh
    axis: str = SEQ_AXIS
    mask: Any = None

    def __post_init__(self):
        if self.axis not in self.mesh.axis_names:
            raise ValueError(
                f"mesh has no axis {self.axis!r}: {self.mesh.axis_names}"
            )
        n = self.mesh.shape[self.axis]
        self.y = jnp.asarray(self.y)
        if self.y.ndim == 1:
            self.y = self.y[:, None]
        if self.y.shape[0] % n != 0:
            raise ValueError(
                f"sequence length {self.y.shape[0]} not divisible by {n}"
            )
        self.mask = _as_mask(self.mask, self.y.shape[0], self.y.dtype)
        # Both builders are mesh-keyed lru_caches (Mesh hashes by
        # devices+axes), so every instance on an equal mesh shares ONE
        # compiled executable — the distributed VJP compile is the
        # expensive one.
        self._logp = _sharded_lgssm_logp(self.mesh, self.axis)
        self._logp_and_grad = _sharded_lgssm_vg(self.mesh, self.axis)

    def logp(self, params: Any) -> jax.Array:
        return self._logp(params, self.y, self.mask)

    def logp_and_grad(self, params: Any):
        return self._logp_and_grad(params, self.y, self.mask)

    def smoothed_moments(self, params: Any):
        """Distributed smoothed marginals ``(means, covs)``, sharded
        along ``axis`` like ``y`` — the reverse segment-summary scan
        mirroring the filter (see :func:`_sharded_lgssm_smoother`)."""
        return _sharded_lgssm_smoother(self.mesh, self.axis)(
            params, self.y, self.mask
        )

    def sample_latents(
        self, params: Any, key: jax.Array, num_draws: int = 1
    ) -> jax.Array:
        """Distributed Durbin-Koopman simulation smoother: joint
        posterior draws of ``z_{1:T} | y``, sharded along ``axis``.
        The unconditional simulation is an affine prefix scan over the
        mesh (same exclusive segment-fold as the filter); each draw
        costs two distributed smoother passes.  Returns
        ``(num_draws, T, d)``."""
        return _sharded_lgssm_sampler(self.mesh, self.axis)(
            params, self.y, self.mask, key, num_draws
        )

    def forecast(self, params: Any, horizon: int):
        """h-step-ahead predictive observation moments from the
        distributed filter: only the terminal filtered state crosses
        the mesh (one psum), then the affine-moment horizon scan runs
        replicated.  Matches :func:`kalman_forecast` exactly."""
        m_T, P_T = _sharded_lgssm_terminal_filtered(self.mesh, self.axis)(
            params, self.y, self.mask
        )
        F, H, Q, R, _, _ = _unpack(params)
        return _forecast_from_terminal(F, H, Q, R, m_T, P_T, horizon)

    def init_params(self, d: int = 2) -> Any:
        return default_lgssm_params(d, self.y.shape[-1])


def _exclusive_segment_fold(summary, combine, identity, axis, n, *, suffix):
    """Inside ``shard_map``: all_gather per-segment summaries and
    compose, for each device, the exclusive combination of the segments
    strictly BEFORE it (``suffix=False``) or strictly AFTER it
    (``suffix=True``).  ``combine(earlier, later)`` composes in time
    order either way — the fold always walks segments left to right, so
    ``acc`` is the earlier operand; only the take-predicate and bounds
    differ.  ``identity`` must already be ``mark_varying``'d over
    ``axis``.  This is the one copy of the trickiest SPMD logic in the
    file (uniform-control-flow exclusive scan), shared by the
    distributed filter and smoother."""
    idx = lax.axis_index(axis)
    gathered = jax.tree_util.tree_map(
        lambda a: lax.all_gather(a, axis), summary
    )

    def fold(r, acc):
        seg = jax.tree_util.tree_map(lambda a: a[r], gathered)
        take = (r > idx) if suffix else (r < idx)
        comp = combine(acc, seg)
        return jax.tree_util.tree_map(
            lambda c, a: jnp.where(take, c, a), comp, acc
        )

    start, stop = (1, n) if suffix else (0, n - 1)
    return lax.fori_loop(start, stop, fold, identity)


def _local_filter_prologue(params, y_local, mask_local, axis, n):
    """Shared first act of every distributed-LGSSM local body: unpack,
    sanitize, and run the distributed filter.  Returns
    ``(unpacked, y_local, means, covs, prefix)``."""
    unpacked = _unpack(params)
    F, H, Q, R, m0, P0 = unpacked
    y_local = _sanitize(y_local, mask_local)
    means, covs, prefix = _local_filtered(
        F, H, Q, R, m0, P0, y_local, mask_local, axis, n
    )
    return unpacked, y_local, means, covs, prefix


def _local_filtered(F, H, Q, R, m0, P0, y_local, mask_local, axis, n):
    """Distributed filtered moments inside ``shard_map``: local
    associative scan + all_gather of segment summaries + exclusive
    prefix composition.  Returns ``(means, covs, prefix)`` where
    ``prefix`` is the composed element of every segment strictly before
    this device (identity on device 0).  Shared by the distributed logp
    and the distributed smoother."""
    idx = lax.axis_index(axis)
    # Generic elements everywhere; the prior-conditioned element
    # only exists at global t=1, i.e. row 0 of device 0.
    elems = _generic_elements(F, H, Q, R, y_local, mask_local)
    prior = _prior_element(F, H, Q, R, m0, P0, y_local[0], mask_local[0])
    elems = jax.tree_util.tree_map(
        lambda g, p: g.at[0].set(jnp.where(idx == 0, p, g[0])),
        elems,
        prior,
    )
    local_scan = lax.associative_scan(_combine, elems)
    # Segment summary = last element of the local scan; compose the
    # exclusive prefix of the segments strictly before this device.
    summary = jax.tree_util.tree_map(lambda a: a[-1], local_scan)
    d = F.shape[0]
    identity = _mark_varying(
        (
            jnp.eye(d, dtype=F.dtype),
            jnp.zeros((d,), F.dtype),
            jnp.zeros((d, d), F.dtype),
            jnp.zeros((d, d), F.dtype),
            jnp.zeros((d,), F.dtype),
        ),
        axis,
    )
    prefix = _exclusive_segment_fold(
        summary, _combine, identity, axis, n, suffix=False
    )
    # Fold the prefix into every local result.
    pref_b = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (y_local.shape[0],) + a.shape),
        prefix,
    )
    full = _combine(pref_b, local_scan)
    _, means, covs, _, _ = full
    return means, covs, prefix


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_logp(mesh, axis):
    n = mesh.shape[axis]

    def local(params, y_local, mask_local):
        (F, H, Q, R, m0, P0), y_local, means, covs, prefix = (
            _local_filter_prologue(params, y_local, mask_local, axis, n)
        )
        idx = lax.axis_index(axis)
        # Predictive terms need the filtered state at t-1: element 0 of
        # this segment uses the prefix itself (last filtered state of
        # the previous segment; the prior on device 0).
        prev_m = jnp.concatenate([prefix[1][None], means[:-1]], axis=0)
        prev_P = jnp.concatenate([prefix[2][None], covs[:-1]], axis=0)
        prev_m = jnp.where(
            (idx == 0) & (jnp.arange(y_local.shape[0]) == 0).reshape(-1, 1),
            m0[None],
            prev_m,
        )
        prev_P = jnp.where(
            (idx == 0)
            & (jnp.arange(y_local.shape[0]) == 0).reshape(-1, 1, 1),
            P0[None],
            prev_P,
        )

        one = functools.partial(_predictive_one, F, H, Q, R)
        lp = jnp.sum(mask_local * jax.vmap(one)(y_local, prev_m, prev_P))
        return lax.psum(lp, axis)

    def logp(params, y, mask):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), params),
                P(axis),
                P(axis),
            ),
            out_specs=P(),
        )(params, y, mask)

    return jax.jit(logp)


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_vg(mesh, axis):
    """Fused (logp, grad) of the distributed filter, one compile per
    (mesh, axis)."""
    logp = _sharded_lgssm_logp(mesh, axis)
    return jax.jit(jax.value_and_grad(lambda p, y, m: logp(p, y, m)))


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_terminal_filtered(mesh, axis):
    """Terminal filtered moments ``(m_T, P_T)`` of the distributed
    filter — the only state forecasting needs.  The last device's last
    row is selected with a where+psum (uniform control flow)."""
    n = mesh.shape[axis]

    def local(params, y_local, mask_local):
        _, _, means, covs, _ = _local_filter_prologue(
            params, y_local, mask_local, axis, n
        )
        is_last = (lax.axis_index(axis) == n - 1).astype(means.dtype)
        m_T = lax.psum(is_last * means[-1], axis)
        P_T = lax.psum(is_last * covs[-1], axis)
        return m_T, P_T

    def terminal(params, y, mask):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), params),
                P(axis),
                P(axis),
            ),
            out_specs=(P(), P()),
        )(params, y, mask)

    return jax.jit(terminal)


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_simulate(mesh, axis):
    """Distributed unconditional simulation: the latent affine
    recurrence as a local scan + exclusive segment prefix fold."""
    n = mesh.shape[axis]

    def local(F, z0, w_local):
        idx = lax.axis_index(axis)
        d = F.shape[0]
        b = w_local.at[0].add(
            jnp.where(idx == 0, F @ z0, jnp.zeros((d,), F.dtype))
        )
        A = jnp.broadcast_to(F, (w_local.shape[0], d, d))
        local_scan = lax.associative_scan(_affine_combine, (A, b))
        summary = jax.tree_util.tree_map(lambda a: a[-1], local_scan)
        identity = _mark_varying(
            (jnp.eye(d, dtype=F.dtype), jnp.zeros((d,), F.dtype)), axis
        )
        prefix = _exclusive_segment_fold(
            summary, _affine_combine, identity, axis, n, suffix=False
        )
        pref_b = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (w_local.shape[0],) + a.shape),
            prefix,
        )
        _, z = _affine_combine(pref_b, local_scan)
        return z

    def simulate(F, z0, w):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(axis)),
            out_specs=P(axis),
        )(F, z0, w)

    return simulate


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_sampler(mesh, axis):
    smooth = _sharded_lgssm_smoother(mesh, axis)
    simulate = _sharded_lgssm_simulate(mesh, axis)

    def sample(params, y, mask, key, num_draws):
        F, H, Q, R, m0, P0 = _unpack(params)
        T = y.shape[0]
        d, k = F.shape[0], H.shape[0]
        sm_y, _ = smooth(params, y, mask)

        def one(dk):
            z0, w, v = _draw_noise(params, dk, T)
            z_star = simulate(F, z0, w)
            y_star = z_star @ H.T + v
            sm_star, _ = smooth(params, y_star, mask)
            return sm_y + z_star - sm_star

        return jax.vmap(one)(jax.random.split(key, num_draws))

    return jax.jit(sample, static_argnums=4)


@functools.lru_cache(maxsize=64)
def _sharded_lgssm_smoother(mesh, axis):
    """Distributed RTS smoother: the reverse mirror of the filter's
    segment-summary prefix scan.  Each device builds backward-kernel
    elements from its (distributed) filtered moments, reverse-scans its
    segment, all_gathers the per-segment suffix summaries, composes the
    exclusive suffix of the segments strictly AFTER itself, and folds
    it into each local result."""
    n = mesh.shape[axis]

    def local(params, y_local, mask_local):
        (F, H, Q, R, m0, P0), y_local, means, covs, _ = (
            _local_filter_prologue(params, y_local, mask_local, axis, n)
        )
        idx = lax.axis_index(axis)
        # Backward-kernel elements everywhere; the terminal (global T)
        # element only exists on the last row of the LAST device — swap
        # it in per-device instead of re-deriving any kernel.
        E, g, L = _smooth_elements(F, Q, means, covs, terminal=False)
        is_last = idx == n - 1
        d = F.shape[0]
        E = E.at[-1].set(
            jnp.where(is_last, jnp.zeros((d, d), F.dtype), E[-1])
        )
        g = g.at[-1].set(jnp.where(is_last, means[-1], g[-1]))
        L = L.at[-1].set(jnp.where(is_last, covs[-1], L[-1]))
        elems = (E, g, L)
        # Local suffix scan: row t holds elems[t] ∘ ... ∘ elems[last];
        # then compose the exclusive suffix of the segments strictly
        # after this device.
        local_scan = lax.associative_scan(
            lambda a, b: _smooth_combine(b, a), elems, reverse=True
        )
        summary = jax.tree_util.tree_map(lambda a: a[0], local_scan)
        identity = _mark_varying(
            (
                jnp.eye(d, dtype=F.dtype),
                jnp.zeros((d,), F.dtype),
                jnp.zeros((d, d), F.dtype),
            ),
            axis,
        )
        suffix = _exclusive_segment_fold(
            summary, _smooth_combine, identity, axis, n, suffix=True
        )
        suf_b = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (y_local.shape[0],) + a.shape),
            suffix,
        )
        _, sm, sP = _smooth_combine(local_scan, suf_b)
        return sm, sP

    def smooth(params, y, mask):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), params),
                P(axis),
                P(axis),
            ),
            out_specs=(P(axis), P(axis)),
        )(params, y, mask)

    return jax.jit(smooth)
