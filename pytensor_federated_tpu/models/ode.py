"""Lotka-Volterra ODE parameter estimation — [theta] -> [LL, dLL] per shard.

BASELINE.json config "Lotka-Volterra ODE param estimation: [theta] ->
[LL, dLL] per shard": each federated shard owns a noisy observed
predator/prey trajectory (e.g. replicate experiments or disjoint time
windows); the driver infers the shared dynamics parameters.

    du/dt = alpha*u - beta*u*v          (prey)
    dv/dt = -gamma*v + delta*u*v        (predator)
    y_obs ~ LogNormal(log(traj), sigma)

The integrator is fixed-step RK4 under ``lax.scan`` — static step count,
fully differentiable, and compiled once for all shards (the reference
would run a SciPy solver per node behind gRPC; here dLL/dtheta flows
through the integrator by autodiff, no adjoint hand-coding).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.sharded import FederatedLogp
from .linear import _normal_logpdf


def lv_vector_field(state, theta):
    u, v = state[0], state[1]
    alpha, beta, gamma, delta = theta
    du = alpha * u - beta * u * v
    dv = -gamma * v + delta * u * v
    return jnp.stack([du, dv])


def rk4_integrate(theta, y0, dt: float, n_steps: int) -> jax.Array:
    """Fixed-step RK4; returns trajectory (n_steps+1, 2)."""

    def step(y, _):
        k1 = lv_vector_field(y, theta)
        k2 = lv_vector_field(y + 0.5 * dt * k1, theta)
        k3 = lv_vector_field(y + 0.5 * dt * k2, theta)
        k4 = lv_vector_field(y + dt * k3, theta)
        y_next = y + (dt / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4)
        return y_next, y_next

    # The state is a 2-vector, so each scan iteration is ~10 scalar ops
    # behind a full loop-iteration latency — on TPU that latency IS the
    # cost (first live capture: 5.5 ms/eval, 300x slower than CPU), and
    # unrolling blocks of 16 statically-counted steps amortizes it.
    # On XLA:CPU the SAME unroll is a 100x LOSS (measured 85.7k -> 857
    # evals/s): the big unrolled body defeats the fusion/CSE that make
    # the tiny loop fast.  Backend-conditional because the tradeoff is
    # a property of the target's codegen, not of the model; numerics
    # are identical either way.
    unroll = (
        min(16, max(1, n_steps))
        if jax.default_backend() == "tpu"
        else 1
    )
    _, traj = jax.lax.scan(step, y0, None, length=n_steps, unroll=unroll)
    return jnp.concatenate([y0[None], traj], axis=0)


def generate_lv_data(
    n_shards: int = 8,
    *,
    n_obs: int = 32,
    dt: float = 0.1,
    obs_every: int = 4,
    seed: int = 31,
):
    """Noisy replicate observations of one true trajectory per shard."""
    rng = np.random.default_rng(seed)
    theta_true = np.array([0.8, 0.4, 0.6, 0.3], dtype=np.float32)
    y0 = jnp.array([1.5, 1.0], dtype=jnp.float32)
    n_steps = n_obs * obs_every
    traj = np.asarray(rk4_integrate(jnp.asarray(theta_true), y0, dt, n_steps))
    obs_idx = np.arange(1, n_obs + 1) * obs_every
    clean = traj[obs_idx]  # (n_obs, 2)
    sigma_true = 0.1
    shards = np.stack(
        [
            clean * np.exp(rng.normal(0, sigma_true, size=clean.shape))
            for _ in range(n_shards)
        ]
    ).astype(np.float32)
    meta = {
        "theta": theta_true,
        "sigma": sigma_true,
        "y0": np.asarray(y0),
        "dt": dt,
        "n_steps": n_steps,
        "obs_idx": obs_idx,
    }
    return jnp.asarray(shards), meta


@dataclasses.dataclass
class LotkaVolterraModel:
    """Infer shared ODE params from per-shard noisy trajectories.

    ``params``: ``log_theta`` (4,) — positivity via log-transform — and
    ``log_sigma``.  The trajectory is integrated ONCE per logp
    evaluation and shared across shards (it depends only on theta), so
    the per-shard work is just the observation likelihood.
    """

    observations: jax.Array  # (n_shards, n_obs, 2)
    y0: Any
    dt: float
    n_steps: int
    obs_idx: Any
    mesh: Optional[Mesh] = None

    def __post_init__(self):
        y0 = jnp.asarray(self.y0, dtype=jnp.float32)
        obs_idx = jnp.asarray(self.obs_idx)

        def per_shard_logp(params, shard_obs):
            # NOTE: integrated per shard under vmap, but XLA CSEs the
            # shard-invariant integration into one scan per program.
            theta = jnp.exp(params["log_theta"])
            traj = rk4_integrate(theta, y0, self.dt, self.n_steps)
            mu = jnp.log(jnp.maximum(traj[obs_idx], 1e-6))
            sigma = jnp.exp(params["log_sigma"])
            ll = _normal_logpdf(jnp.log(shard_obs), mu, sigma) - jnp.log(
                shard_obs
            )
            return jnp.sum(ll)

        self.fed = FederatedLogp(per_shard_logp, self.observations, mesh=self.mesh)

    def prior_logp(self, params: Any) -> jax.Array:
        # LogNormal(log 0.5, 1) on each theta; HalfNormal(1) on sigma.
        lp = jnp.sum(_normal_logpdf(params["log_theta"], jnp.log(0.5), 1.0))
        s = jnp.exp(params["log_sigma"])
        lp += -0.5 * s**2 + params["log_sigma"]
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        """[theta] -> [LL, dLL] — the reference's per-node contract,
        fused across all shards."""
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "log_theta": jnp.full((4,), jnp.log(0.5)),
            "log_sigma": jnp.array(-2.0),
        }

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)


def make_lv_model(n_shards: int = 8, *, mesh: Optional[Mesh] = None, **kwargs):
    obs, meta = generate_lv_data(n_shards, **kwargs)
    model = LotkaVolterraModel(
        observations=obs,
        y0=meta["y0"],
        dt=meta["dt"],
        n_steps=meta["n_steps"],
        obs_idx=meta["obs_idx"],
        mesh=mesh,
    )
    return model, meta
