"""Federated multinomial (softmax) regression — categorical outcomes.

Completes the everyday GLM grid (binary → logistic, counts → Poisson/
NB, ordered → ordinal, unordered categorical → THIS).  Each federated
shard owns private ``(X_i, y_i)`` with ``y ∈ {0..K-1}``; coefficients
are shared:

    W ~ Normal(0, prior_scale)  per entry, shape (d, K-1)
    b ~ Normal(0, prior_scale)  per entry, shape (K-1,)
    logits = [0, X w_1 + b_1, ..., X w_{K-1} + b_{K-1}]
    y ~ Categorical(softmax(logits))

Reference-class parameterization (class 0's logits pinned to zero)
keeps the model identifiable without constraints.  Per-shard compute
is one ``(n, d) @ (d, K-1)`` matmul — batched over shards, exactly the
MXU shape — and the normalizer is one logsumexp over K.

The hierarchical variant (:class:`HierarchicalSoftmaxRegression`) sits
on :class:`.hierbase.HierarchicalGLMBase` with ``_coef_cols = K - 1``:
the non-centered construction, HalfNormal Jacobian, and the
pointwise/predictive/prior machinery are the base's single
implementations, shared with every other hierarchical family.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp
from .hierbase import HierarchicalGLMBase
from .linear import _normal_logpdf

__all__ = [
    "FederatedSoftmaxRegression",
    "HierarchicalSoftmaxRegression",
    "generate_hier_multinomial_data",
    "generate_multinomial_data",
]


def _pinned_logits(free):
    """(…, K) logits from (…, K-1) free columns; class 0 pinned to 0."""
    zero = jnp.zeros(free.shape[:-1] + (1,), free.dtype)
    return jnp.concatenate([zero, free], axis=-1)


def _categorical_loglik(y, free):
    """Per-observation categorical log-likelihood from the free
    (unpinned) logit columns — THE one implementation, shared by the
    flat and hierarchical models (logp, pointwise, predictive all
    route here or through :func:`_pinned_logits`)."""
    eta = _pinned_logits(free)
    y_idx = y.astype(jnp.int32)
    picked = jnp.take_along_axis(eta, y_idx[..., None], axis=-1)[..., 0]
    return picked - jax.scipy.special.logsumexp(eta, axis=-1)


def _sample_categorical(key, free):
    return jax.random.categorical(
        key, _pinned_logits(free), axis=-1
    ).astype(jnp.float32)


def _simulate_softmax_shards(rng, n_shards, n_obs, n_features,
                             n_classes, W, intercepts):
    """Shared simulator: per-shard intercept rows (broadcast for the
    flat model), zero-pinned softmax draws."""
    intercepts = np.broadcast_to(
        intercepts, (n_shards, n_classes - 1)
    )
    shards = []
    for s in range(n_shards):
        X = rng.normal(size=(n_obs, n_features)).astype(np.float32)
        logits = np.concatenate(
            [np.zeros((n_obs, 1)), X @ W + intercepts[s]], axis=1
        )
        p = np.exp(logits - logits.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        y = np.array(
            [rng.choice(n_classes, p=pi) for pi in p], dtype=np.float32
        )
        shards.append((X, y))
    return pack_shards(shards)


def generate_multinomial_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    n_classes: int = 3,
    seed: int = 37,
):
    rng = np.random.default_rng(seed)
    W = rng.normal(0, 1.0, size=(n_features, n_classes - 1))
    b = rng.normal(0, 0.5, size=(n_classes - 1,))
    packed = _simulate_softmax_shards(
        rng, n_shards, n_obs, n_features, n_classes, W, b
    )
    return packed, {"W": W, "b": b}


def generate_hier_multinomial_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 3,
    n_classes: int = 3,
    tau: float = 0.8,
    seed: int = 47,
):
    """Per-shard data with shard-specific class intercepts
    ``b_s ~ N(b0, tau)`` (one per free class)."""
    rng = np.random.default_rng(seed)
    W = rng.normal(0, 1.0, size=(n_features, n_classes - 1))
    b0 = rng.normal(0, 0.5, size=(n_classes - 1,))
    b_s = b0[None, :] + tau * rng.normal(
        size=(n_shards, n_classes - 1)
    )
    packed = _simulate_softmax_shards(
        rng, n_shards, n_obs, n_features, n_classes, W, b_s
    )
    return packed, {"W": W, "b0": b0, "tau": tau}


@dataclasses.dataclass
class FederatedSoftmaxRegression:
    data: ShardedData
    n_classes: int
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0
    #: partial sufficient statistics, the softmax analog of the
    #: logistic family's fold: the picked-logit term is LINEAR in
    #: (W, b) — Σ_i eta[y_i] = Σ_k (Σ_{i: y_i=k} x_i)·w_k + n_k b_k —
    #: so its coefficients (per-shard per-class Σx and counts) fold
    #: into build-time constants and the hot loop evaluates only the
    #: logsumexp normalizer.  Exact same posterior; equality-tested.
    use_suffstats: bool = False

    def __post_init__(self):
        K = int(self.n_classes)
        if K < 2:
            raise ValueError(f"n_classes must be >= 2, got {K}")
        self._k = K

        if self.use_suffstats:
            (X, y), mask = self.data.tree()
            # one-hot over the K-1 FREE classes (class 0 contributes a
            # pinned-zero logit, so it needs no linear term)
            onehot = (
                jnp.asarray(y)[..., None]
                == jnp.arange(1, K, dtype=jnp.float32)
            ).astype(jnp.float32) * jnp.asarray(mask)[..., None]
            sx = jnp.einsum("snd,snk->sdk", jnp.asarray(X), onehot)
            sn = jnp.sum(onehot, axis=1)  # (S, K-1)
            tree = ((X, sx, sn), mask)

            def per_shard_logp(params, shard):
                (X_s, sx_s, sn_s), m_s = shard
                free = X_s @ params["W"] + params["b"]
                lse = jax.scipy.special.logsumexp(
                    _pinned_logits(free), axis=-1
                )
                picked = jnp.sum(sx_s * params["W"]) + jnp.sum(
                    sn_s * params["b"]
                )
                return picked - jnp.sum(lse * m_s)

            self.fed = FederatedLogp(per_shard_logp, tree, mesh=self.mesh)
        else:

            def per_shard_logp(params, shard):
                (X, y), mask = shard
                ll = _categorical_loglik(y, X @ params["W"] + params["b"])
                return jnp.sum(ll * mask)

            self.fed = FederatedLogp(
                per_shard_logp, self.data.tree(), mesh=self.mesh
            )
        self.n_features = jax.tree_util.tree_leaves(self.data.data)[
            0
        ].shape[-1]

    def prior_logp(self, params: Any) -> jax.Array:
        lp = jnp.sum(_normal_logpdf(params["W"], 0.0, self.prior_scale))
        lp += jnp.sum(_normal_logpdf(params["b"], 0.0, self.prior_scale))
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "W": jnp.zeros((self.n_features, self._k - 1)),
            "b": jnp.zeros((self._k - 1,)),
        }

    def pointwise_loglik(self, params: Any) -> jax.Array:
        """Flat per-observation log-likelihoods (masked slots -> 0),
        for PSIS-LOO / WAIC (samplers.model_comparison)."""
        (X, y), mask = self.data.tree()
        ll = _categorical_loglik(y, X @ params["W"] + params["b"])
        return (ll * mask).reshape(-1)

    def predictive(self, params: Any, key) -> jax.Array:
        """Simulate class labels for every design row (padded slots
        produce labels too; apply the mask downstream)."""
        (X, _y), _mask = self.data.tree()

        def one(X_s, k):
            return _sample_categorical(k, X_s @ params["W"] + params["b"])

        keys = jax.random.split(key, X.shape[0])
        return jax.vmap(one)(X, keys)

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)


@dataclasses.dataclass
class HierarchicalSoftmaxRegression(HierarchicalGLMBase):
    """Mixed-effects softmax: shared slopes, per-site class intercepts.

    On :class:`.hierbase.HierarchicalGLMBase` with vector coefficient
    columns (``_coef_cols = K - 1``)::

        w ~ Normal(0, prior_scale)          (d, K-1), shared
        b0 ~ Normal(0, prior_scale)         (K-1,)
        tau ~ HalfNormal(1)                 via log_tau + Jacobian
        b_raw_s ~ Normal(0, 1)              (S, K-1) per site
        logits = [0, X_s w + b0 + tau * b_raw_s]

    The base supplies the non-centered hierarchy, the HalfNormal
    Jacobian, pointwise_loglik, predictive, sample_prior, intercepts,
    and the MAP/NUTS front doors; this class supplies only the
    categorical observation family.  (Round-3 review: a standalone
    first version re-implemented the scaffolding the base exists to
    centralize; the base's lowercase ``w`` param name is kept for
    cross-family consistency.)
    """

    data: ShardedData = None
    n_classes: int = 2
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0

    def __post_init__(self):
        K = int(self.n_classes)
        if K < 2:
            raise ValueError(f"n_classes must be >= 2, got {K}")
        self._coef_cols = K - 1
        self._post_init()

    def _obs_logpmf(self, params, y, eta):
        # eta: (..., K-1) free logit columns from the base's X @ w + b
        return _categorical_loglik(y, eta)

    def _sample_obs(self, params, key, eta):
        return _sample_categorical(key, eta)
