"""Federated multinomial (softmax) regression — categorical outcomes.

Completes the everyday GLM grid (binary → logistic, counts → Poisson/
NB, ordered → ordinal, unordered categorical → THIS).  Each federated
shard owns private ``(X_i, y_i)`` with ``y ∈ {0..K-1}``; coefficients
are shared:

    W ~ Normal(0, prior_scale)  per entry, shape (d, K-1)
    b ~ Normal(0, prior_scale)  per entry, shape (K-1,)
    logits = [0, X w_1 + b_1, ..., X w_{K-1} + b_{K-1}]
    y ~ Categorical(softmax(logits))

Reference-class parameterization (class 0's logits pinned to zero)
keeps the model identifiable without constraints.  Per-shard compute
is one ``(n, d) @ (d, K-1)`` matmul — batched over shards, exactly the
MXU shape — and the normalizer is one logsumexp over K.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.packing import ShardedData, pack_shards
from ..parallel.sharded import FederatedLogp
from .linear import _normal_logpdf

__all__ = [
    "FederatedSoftmaxRegression",
    "HierarchicalSoftmaxRegression",
    "generate_hier_multinomial_data",
    "generate_multinomial_data",
]


def generate_multinomial_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 4,
    n_classes: int = 3,
    seed: int = 37,
):
    rng = np.random.default_rng(seed)
    W = rng.normal(0, 1.0, size=(n_features, n_classes - 1))
    b = rng.normal(0, 0.5, size=(n_classes - 1,))
    shards = []
    for _ in range(n_shards):
        X = rng.normal(size=(n_obs, n_features)).astype(np.float32)
        logits = np.concatenate(
            [np.zeros((n_obs, 1)), X @ W + b], axis=1
        )
        p = np.exp(logits - logits.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        y = np.array(
            [rng.choice(n_classes, p=pi) for pi in p], dtype=np.float32
        )
        shards.append((X, y))
    return pack_shards(shards), {"W": W, "b": b}


@dataclasses.dataclass
class FederatedSoftmaxRegression:
    data: ShardedData
    n_classes: int
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0

    def __post_init__(self):
        K = int(self.n_classes)
        if K < 2:
            raise ValueError(f"n_classes must be >= 2, got {K}")
        self._k = K

        def per_shard_logp(params, shard):
            (X, y), mask = shard
            eta = self._logits(params, X)  # (n, K)
            y_idx = y.astype(jnp.int32)
            ll = jnp.take_along_axis(
                eta, y_idx[:, None], axis=1
            )[:, 0] - jax.scipy.special.logsumexp(eta, axis=1)
            return jnp.sum(ll * mask)

        self.fed = FederatedLogp(
            per_shard_logp, self.data.tree(), mesh=self.mesh
        )
        self.n_features = jax.tree_util.tree_leaves(self.data.data)[
            0
        ].shape[-1]

    def _logits(self, params, X):
        """(n, K) logits with class 0 pinned to zero."""
        free = X @ params["W"] + params["b"]  # (n, K-1)
        zero = jnp.zeros(free.shape[:-1] + (1,), free.dtype)
        return jnp.concatenate([zero, free], axis=-1)

    def prior_logp(self, params: Any) -> jax.Array:
        lp = jnp.sum(_normal_logpdf(params["W"], 0.0, self.prior_scale))
        lp += jnp.sum(_normal_logpdf(params["b"], 0.0, self.prior_scale))
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "W": jnp.zeros((self.n_features, self._k - 1)),
            "b": jnp.zeros((self._k - 1,)),
        }

    def pointwise_loglik(self, params: Any) -> jax.Array:
        """Flat per-observation log-likelihoods (masked slots -> 0),
        for PSIS-LOO / WAIC (samplers.model_comparison)."""
        (X, y), mask = self.data.tree()

        def one(X_s, y_s, m_s):
            eta = self._logits(params, X_s)
            ll = jnp.take_along_axis(
                eta, y_s.astype(jnp.int32)[:, None], axis=1
            )[:, 0] - jax.scipy.special.logsumexp(eta, axis=1)
            return ll * m_s

        return jax.vmap(one)(X, y, mask).reshape(-1)

    def predictive(self, params: Any, key) -> jax.Array:
        """Simulate class labels for every design row (padded slots
        produce labels too; apply the mask downstream)."""
        (X, _y), _mask = self.data.tree()

        def one(X_s, k):
            eta = self._logits(params, X_s)
            return jax.random.categorical(k, eta, axis=-1).astype(
                jnp.float32
            )

        keys = jax.random.split(key, X.shape[0])
        return jax.vmap(one)(X, keys)

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)


def generate_hier_multinomial_data(
    n_shards: int = 8,
    *,
    n_obs: int = 64,
    n_features: int = 3,
    n_classes: int = 3,
    tau: float = 0.8,
    seed: int = 47,
):
    """Per-shard data with shard-specific class intercepts
    ``b_s ~ N(b0, tau)`` (one per free class)."""
    rng = np.random.default_rng(seed)
    W = rng.normal(0, 1.0, size=(n_features, n_classes - 1))
    b0 = rng.normal(0, 0.5, size=(n_classes - 1,))
    b_s = b0[None, :] + tau * rng.normal(
        size=(n_shards, n_classes - 1)
    )
    shards = []
    for s in range(n_shards):
        X = rng.normal(size=(n_obs, n_features)).astype(np.float32)
        logits = np.concatenate(
            [np.zeros((n_obs, 1)), X @ W + b_s[s]], axis=1
        )
        p = np.exp(logits - logits.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        y = np.array(
            [rng.choice(n_classes, p=pi) for pi in p], dtype=np.float32
        )
        shards.append((X, y))
    return pack_shards(shards), {"W": W, "b0": b0, "tau": tau}


@dataclasses.dataclass
class HierarchicalSoftmaxRegression:
    """Mixed-effects softmax: shared slopes, per-site class intercepts.

    Non-centered like the other hierarchical families
    (:class:`.logistic.HierarchicalLogisticRegression`)::

        W ~ Normal(0, prior_scale)          (d, K-1), shared
        b0 ~ Normal(0, prior_scale)         (K-1,)
        tau ~ HalfNormal(1)                 via log_tau + Jacobian
        b_raw_s ~ Normal(0, 1)              (S, K-1) per site
        logits = [0, X_s W + b0 + tau * b_raw_s]
    """

    data: ShardedData
    n_classes: int
    mesh: Optional[Mesh] = None
    prior_scale: float = 5.0

    def __post_init__(self):
        K = int(self.n_classes)
        if K < 2:
            raise ValueError(f"n_classes must be >= 2, got {K}")
        self._k = K
        (X, y), mask = self.data.tree()
        n = X.shape[0]
        shard_ids = jnp.arange(n, dtype=jnp.int32)

        def per_shard_logp(params, shard):
            (X_s, y_s), m_s, sid = shard
            tau = jnp.exp(params["log_tau"])
            b = params["b0"] + tau * jnp.take(
                params["b_raw"], sid, axis=0
            )
            free = X_s @ params["W"] + b
            eta = jnp.concatenate(
                [jnp.zeros(free.shape[:-1] + (1,), free.dtype), free],
                axis=-1,
            )
            ll = jnp.take_along_axis(
                eta, y_s.astype(jnp.int32)[:, None], axis=1
            )[:, 0] - jax.scipy.special.logsumexp(eta, axis=1)
            return jnp.sum(ll * m_s)

        self.fed = FederatedLogp(
            per_shard_logp, ((X, y), mask, shard_ids), mesh=self.mesh
        )
        self.n_shards = n
        self.n_features = X.shape[-1]

    def prior_logp(self, params: Any) -> jax.Array:
        lp = jnp.sum(_normal_logpdf(params["W"], 0.0, self.prior_scale))
        lp += jnp.sum(_normal_logpdf(params["b0"], 0.0, self.prior_scale))
        # HalfNormal(1) on tau via log_tau with the log|J| = log_tau
        tau = jnp.exp(params["log_tau"])
        lp += -0.5 * tau**2 + params["log_tau"]
        lp += jnp.sum(_normal_logpdf(params["b_raw"], 0.0, 1.0))
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self.fed.logp(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        return {
            "W": jnp.zeros((self.n_features, self._k - 1)),
            "b0": jnp.zeros((self._k - 1,)),
            "log_tau": jnp.zeros(()),
            "b_raw": jnp.zeros((self.n_shards, self._k - 1)),
        }

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)

    def sample(self, *, key=None, **kwargs):
        from ..samplers import sample

        if key is None:
            key = jax.random.PRNGKey(0)
        return sample(self.logp, self.init_params(), key=key, **kwargs)
