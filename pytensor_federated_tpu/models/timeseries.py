"""Sequence-parallel time-series models (long-context likelihoods).

Net-new model family: the reference has no sequence models (SURVEY §5 —
its only scale axis is shard count).  Here the *sequence* is the scale
axis: an AR(1) observation chain of length T is sharded along the
``"seq"`` mesh axis and its Markov-factored log-likelihood is computed
with one boundary ``ppermute`` per evaluation
(:func:`..parallel.ring.seq_sharded_markov_logp`) — communication is one
element per device per eval, regardless of T.

Model:

    y_0 ~ Normal(mu, sigma / sqrt(1 - phi^2))          (stationary init)
    y_t ~ Normal(mu + phi * (y_{t-1} - mu), sigma)     t >= 1

Parameters: ``mu``, ``arctanh_phi`` (unconstrained; phi = tanh), and
``log_sigma`` (unconstrained; sigma = exp), so samplers work in R^3.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..parallel.mesh import SEQ_AXIS
from ..parallel.ring import seq_sharded_markov_logp
from ..utils import LOG_2PI


def generate_ar1_data(
    n_steps: int = 4096,
    *,
    mu: float = 0.5,
    phi: float = 0.8,
    sigma: float = 0.3,
    seed: int = 7,
) -> np.ndarray:
    """Simulate one AR(1) path (float32, stationary start)."""
    rng = np.random.default_rng(seed)
    y = np.empty(n_steps, dtype=np.float32)
    y[0] = mu + rng.normal() * sigma / np.sqrt(1.0 - phi**2)
    eps = rng.normal(size=n_steps).astype(np.float32) * sigma
    for t in range(1, n_steps):
        y[t] = mu + phi * (y[t - 1] - mu) + eps[t]
    return y


def _unpack(params: Any):
    mu = params["mu"]
    phi = jnp.tanh(params["arctanh_phi"])
    sigma = jnp.exp(params["log_sigma"])
    return mu, phi, sigma


def _trans_logp(params, y_prev, y_curr):
    """Vectorized transition density log N(y_t | mu + phi (y_{t-1}-mu), sigma)."""
    mu, phi, sigma = _unpack(params)
    resid = y_curr - (mu + phi * (y_prev - mu))
    return -0.5 * (resid / sigma) ** 2 - jnp.log(sigma) - 0.5 * LOG_2PI


def _init_logp(params, y0):
    mu, phi, sigma = _unpack(params)
    s0 = sigma / jnp.sqrt(1.0 - phi**2)
    return -0.5 * ((y0 - mu) / s0) ** 2 - jnp.log(s0) - 0.5 * LOG_2PI


def _prior_logp(params):
    """Weak priors keeping the posterior proper: mu,arctanh_phi,log_sigma ~ N(0, 10)."""
    return sum(
        -0.5 * (params[k] / 10.0) ** 2 for k in ("mu", "arctanh_phi", "log_sigma")
    )


class SeqShardedAR1:
    """AR(1) likelihood with the sequence sharded across the mesh.

    With ``mesh=None`` the same model evaluates single-device via
    ``lax.scan``-free vectorized form (the ground-truth path used by the
    equivalence tests, mirroring the reference's golden-model pattern,
    reference: test_demo_node.py:29-65).
    """

    def __init__(
        self,
        y: np.ndarray,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = SEQ_AXIS,
    ):
        self.y = jnp.asarray(y)
        self.mesh = mesh
        self.axis = axis

        if mesh is not None:
            like = seq_sharded_markov_logp(
                _trans_logp, _init_logp, self.y, mesh=mesh, axis=axis
            )

            def logp(params):
                return like(params) + _prior_logp(params)

        else:
            y_ = self.y

            def logp(params):
                lp = _init_logp(params, y_[0])
                lp = lp + jnp.sum(_trans_logp(params, y_[:-1], y_[1:]))
                return lp + _prior_logp(params)

        self._logp = jax.jit(logp)
        self._logp_and_grad = jax.jit(jax.value_and_grad(logp))

    def init_params(self) -> dict:
        return {
            "mu": jnp.zeros(()),
            "arctanh_phi": jnp.zeros(()),
            "log_sigma": jnp.zeros(()),
        }

    def logp(self, params: Any) -> jax.Array:
        return self._logp(params)

    def logp_and_grad(self, params: Any):
        return self._logp_and_grad(params)

    __call__ = logp
