"""Observability: timers, eval counters, profiler hooks, load logging.

The reference's only observability surface is the ``GetLoad`` RPC
(psutil loadavg/RAM + client count, reference: service.py:88-96) plus
INFO logs on stream open/close (reference: service.py:107-111); timing
in its tests is ad-hoc ``time.perf_counter`` (reference:
test_op_async.py:166-195).  This module makes those first-class:

- :class:`Metrics` / :func:`timed` / :func:`count` — a process-local
  metrics registry: named wall-clock timers and counters with a
  structured :meth:`~Metrics.snapshot`.
- :func:`instrument_logp` — wrap any logp/logp_and_grad callable so
  every *host dispatch* is counted and timed (under jit the device may
  batch work asynchronously; timers measure dispatch-to-ready wall time
  by blocking on the result, enable only when diagnosing).
- :func:`profile_trace` — context manager around ``jax.profiler``
  start/stop_trace: dumps a TensorBoard-loadable trace of the XLA
  timeline (the deep equivalent of the reference's qualitative "much
  faster" claims, reference: README.md:9).
- :func:`log_device_load` — one JSON line per device from
  :func:`~pytensor_federated_tpu.parallel.mesh.get_load` (the GetLoad
  analog), to any logger.

Everything is dependency-free and safe to leave imported in
production; instrumentation only costs when explicitly wrapped around
a callable.
"""

from __future__ import annotations

import contextlib
import functools
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax

_log = logging.getLogger("pytensor_federated_tpu")


class Metrics:
    """Thread-safe named counters + wall-clock timers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._times: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self._times[name] = self._times.get(name, 0.0) + seconds
            self._calls[name] = self._calls.get(name, 0) + 1

    @contextlib.contextmanager
    def timed(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def snapshot(self) -> dict:
        """{"counters": {...}, "timers": {name: {total_s, calls, mean_s}}}"""
        with self._lock:
            timers = {
                k: {
                    "total_s": self._times[k],
                    "calls": self._calls[k],
                    "mean_s": self._times[k] / max(self._calls[k], 1),
                }
                for k in self._times
            }
            return {"counters": dict(self._counters), "timers": timers}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._times.clear()
            self._calls.clear()


#: Process-global default registry (import-and-go, like ``logging``).
metrics = Metrics()


def count(name: str, n: int = 1) -> None:
    metrics.count(name, n)


def timed(name: str):
    return metrics.timed(name)


def instrument_logp(
    fn: Callable,
    name: str,
    *,
    registry: Optional[Metrics] = None,
    block: bool = False,
) -> Callable:
    """Wrap a logp / logp_and_grad callable with dispatch counting+timing.

    ``block=True`` additionally calls ``jax.block_until_ready`` on the
    result so the timer covers device execution, not just async dispatch
    — use when diagnosing, not in the hot loop (it serializes the
    pipeline the way the reference's lock-step stream did, reference:
    service.py:150-158).
    """
    reg = registry or metrics

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with reg.timed(name):
            out = fn(*args, **kwargs)
            if block:
                out = jax.block_until_ready(out)
        reg.count(f"{name}.evals")
        return out

    return wrapped


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a TensorBoard/XPlane profiler trace of the enclosed block.

    View with ``tensorboard --logdir <log_dir>`` (Profile tab) or
    ``xprof``.  Covers XLA executable timelines, transfers, and host
    activity — per-op visibility the reference never had.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Name a region on the profiler timeline (TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def log_device_load(
    logger: Optional[logging.Logger] = None,
    *,
    devices=None,
) -> list:
    """Emit one structured JSON line per device — the GetLoad analog
    (reference: service.py:88-96 reports psutil load over RPC; here the
    'nodes' are devices and the report is local)."""
    from .parallel.mesh import get_load

    logger = logger or _log
    loads = get_load(devices)
    for l in loads:
        logger.info(
            "device_load %s",
            json.dumps(
                {
                    "device_id": l.device_id,
                    "platform": l.platform,
                    "process_index": l.process_index,
                    "bytes_in_use": l.bytes_in_use,
                    "bytes_limit": l.bytes_limit,
                    "percent_hbm": l.percent_hbm,
                }
            ),
        )
    return loads
