"""Automatic fan-out rewrite: independent federated applies run concurrently.

The reference registers ``AsyncFusionOptimizer`` in PyTensor's global
optimizer database so that *every* default-mode compile automatically
overlaps independent remote calls (reference: op_async.py:216-234,
proven by the wall-clock test at reference: test_op_async.py:153-195).
Round 1 shipped only the explicit ``ops.fuse`` for host callables; a
PyTensor graph with N independent ``Federated*Op`` applies on the
C/py linkers evaluated them sequentially — this module closes that gap.

Design differences from the reference, by construction:

- The reference fuses ``AsyncOp``s layer by layer and drives them with
  an asyncio gather inside a dedicated ``ParallelAsyncOp.perform``.
  Here there are no ``Async*`` op twins at all (SURVEY §7 table): the
  rewrite groups *any* independent ``FederatedArraysToArraysOp`` /
  ``FederatedLogpOp`` / ``FederatedLogpGradOp`` applies — grouping is
  by graph independence (no ancestor path between members), not depth
  equality, so a deep-and-shallow pair still overlaps.
- The fused apply's ``perform`` runs each member's own ``perform`` in a
  shared thread pool.  The member compute functions are network/host
  calls (gRPC, TCP, subprocess) that release the GIL while waiting, so
  threads give the same latency-hiding as the reference's event loop
  without imposing an async signature on user compute functions.
- The fused op works on every linker: ``perform`` serves the C/py
  linkers, and a registered ``jax_funcify`` dispatch inlines each
  member's ``jax_fn`` when a JAX-mode compile runs the rewrite (XLA
  then overlaps the members on its own), so the two paths cannot
  disagree.

Importing this module registers the rewrite in ``optdb`` under
``fast_run`` at position 90 — the slot the reference uses
(op_async.py:229-234): after canonicalize/specialize (which must see
the original applies for CSE/merge) and after the inplace passes
(which know nothing about these host-call ops).
"""

from __future__ import annotations

from pytensor.compile import optdb
from pytensor.graph.basic import Apply
from pytensor.graph.features import ReplaceValidate
from pytensor.graph.op import Op
from pytensor.graph.rewriting.basic import GraphRewriter

from ..fanout_exec import MemberExecutorPool, run_members
from .core import fused_jax_callable, plan_fusion
from .grouping import group_independent
from .pytensor_ops import (
    FederatedArraysToArraysOp,
    FederatedLogpGradOp,
    FederatedLogpOp,
)

__all__ = ["ParallelFederatedOp", "FederatedFusionRewriter"]

_FUSABLE = (FederatedArraysToArraysOp, FederatedLogpOp, FederatedLogpGradOp)



class ParallelFederatedOp(Op):
    """N independent federated applies as one apply; ``perform`` fans
    the members out over a thread pool and blocks for all of them
    (wall-clock = max member latency, not the sum — the reference's
    ``ParallelAsyncOp`` contract, reference: op_async.py:68-134).

    ``members`` are the original ops; ``in_splits``/``out_splits`` give
    each member's slice of the concatenated input/output lists.  No
    ``__props__``: like the member ops, identity is instance identity.
    """

    def __init__(self, members, in_counts, out_counts, node_pool=None):
        self.members = list(members)
        self.in_counts = list(in_counts)
        self.out_counts = list(out_counts)
        # Optional routing.NodePool: members whose clients ride the
        # pool fail over between retry attempts instead of surfacing
        # the first transient error (fanout_exec.run_members).  Not
        # picklable (locks/threads) — dropped with the executor pool on
        # pickle; a worker-process copy falls back to no-retry.
        self.node_pool = node_pool

    def make_node(self, *inputs):
        outputs = []
        i = 0
        member_nodes = []
        for op, n_in in zip(self.members, self.in_counts):
            node = op.make_node(*inputs[i : i + n_in])
            member_nodes.append(node)
            outputs.extend(out.type() for out in node.outputs)
            i += n_in
        if i != len(inputs):
            raise ValueError(
                f"ParallelFederatedOp got {len(inputs)} inputs, "
                f"members consume {i}"
            )
        # Template applies reused by perform (member perform signatures
        # need a node argument; these carry only type information).
        self._member_nodes = member_nodes
        return Apply(self, list(inputs), outputs)

    def _templates(self, node):
        # Rebuilt lazily so an op unpickled in another process (the
        # cross-process compile-cache path, tests/test_service.py
        # pattern) regains its member template applies.
        nodes = getattr(self, "_member_nodes", None)
        if nodes is None:
            i = 0
            nodes = []
            for op, n_in in zip(self.members, self.in_counts):
                nodes.append(op.make_node(*node.inputs[i : i + n_in]))
                i += n_in
            self._member_nodes = nodes
        return nodes

    def __getstate__(self):
        # Template applies reference graph variables, and executor pools
        # are not picklable; both rebuild lazily on the other side.
        state = self.__dict__.copy()
        state.pop("_member_nodes", None)
        state.pop("_pool", None)
        state.pop("node_pool", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.node_pool = None

    def _member_pool(self) -> MemberExecutorPool:
        # One PERSISTENT single-thread executor per member, shut down by
        # weakref.finalize when this op is collected (fanout_exec docs
        # explain the thread-pinning requirement).  Creation is lazy and
        # the pool's own lock makes the executor bring-up race-free; the
        # attribute write below is GIL-atomic, and a lost race merely
        # creates a pool whose threads start lazily too, so no leak.
        pool = getattr(self, "_pool", None)
        if pool is None:
            # setdefault is GIL-atomic: concurrent first calls agree on
            # one pool, preserving the thread-pinning contract; a losing
            # pool never starts threads (they are lazy) so nothing leaks.
            pool = self.__dict__.setdefault(
                "_pool", MemberExecutorPool(len(self.members))
            )
        return pool

    def perform(self, node, inputs, output_storage):
        # The scheduling contract (pinned threads, max-not-sum wall
        # clock, settle-all-then-raise-first, storage slicing) lives in
        # the pure, pytensor-free fanout_exec.run_members, where it is
        # tested directly (tests/test_fanout_exec.py).
        templates = self._templates(node)
        member_fns = [
            (lambda sub_in, sub_st, op=op, t=t: op.perform(t, sub_in, sub_st))
            for op, t in zip(self.members, templates)
        ]
        run_members(
            member_fns,
            self.in_counts,
            self.out_counts,
            inputs,
            output_storage,
            self._member_pool(),
            node_pool=getattr(self, "node_pool", None),
        )


class FederatedFusionRewriter(GraphRewriter):
    """Replace every maximal group of independent ``Federated*Op``
    applies with one :class:`ParallelFederatedOp` apply.

    Independence is transitive-closure based: apply B joins apply A's
    group only if neither (transitively) consumes the other's outputs.
    Greedy grouping over the toposort keeps this O(nodes x candidates).
    """

    def add_requirements(self, fgraph):
        fgraph.attach_feature(ReplaceValidate())

    def apply(self, fgraph):
        order = fgraph.toposort()
        groups = group_independent(
            order,
            parents=lambda n: (
                inp.owner for inp in n.inputs if inp.owner is not None
            ),
            is_candidate=lambda n: isinstance(n.op, _FUSABLE),
        )
        for g in groups:
            if len(g) < 2:
                continue
            self._fuse_group(fgraph, g)

    @staticmethod
    def _fuse_group(fgraph, group):
        # WHAT replaces what is planned in core.plan_fusion (pure,
        # tested without pytensor); only the Apply construction and the
        # validated replace remain here.
        plan = plan_fusion(
            group,
            op_of=lambda n: n.op,
            inputs_of=lambda n: n.inputs,
            outputs_of=lambda n: n.outputs,
        )
        fused_op = ParallelFederatedOp(
            plan["members"], plan["in_counts"], plan["out_counts"]
        )
        fused_node = fused_op.make_node(*plan["all_inputs"])
        repl = [
            (old, fused_node.outputs[pos])
            for old, pos in plan["replacements"]
        ]
        fgraph.replace_all_validate(
            repl, reason="federated_parallel_fusion"
        )


# JAX linker: inline each member's jax_fn; XLA overlaps them on its own.
try:  # pragma: no cover - depends on pytensor version layout
    from pytensor.link.jax.dispatch import jax_funcify

    from .pytensor_ops import _jax_funcify_for_member

    @jax_funcify.register(ParallelFederatedOp)
    def _jax_funcify_parallel(op, **kwargs):
        # Inlining order/flattening lives in core.fused_jax_callable,
        # tested without pytensor against real jax functions.
        return fused_jax_callable(
            [_jax_funcify_for_member(m) for m in op.members],
            op.in_counts,
        )

except ModuleNotFoundError:  # pragma: no cover
    pass


# Import-time registration, like the reference (op_async.py:228-234),
# in the same late slot (position 90: after canonicalize/specialize —
# which must see the original applies for CSE/merge — and after the
# inplace passes, which know nothing about these host-call ops).
if "federated_parallel_fusion" not in optdb:
    optdb.register(
        "federated_parallel_fusion",
        FederatedFusionRewriter(),
        "fast_run",
        position=90,
    )
