"""PyTensor Ops wrapping framework compute functions, JAX-dispatchable.

Parity map (all citations into /root/reference):

- :class:`FederatedArraysToArraysOp` — generic arrays->arrays Op
  (reference: wrapper_ops.py:14-33).
- :class:`FederatedLogpOp` — scalar log-potential Op
  (reference: wrapper_ops.py:44-69).
- :class:`FederatedLogpGradOp` — ``[logp, *grads]`` outputs with the
  symbolic ``.grad()`` bridge (reference: wrapper_ops.py:84-132),
  including the "no second-order autodiff through the federated
  boundary" restriction (reference: wrapper_ops.py:123-125).

The reference needs ``Async*`` twins of each op plus a global graph
rewrite to fan independent applies out concurrently
(reference: wrapper_ops.py:36-41, op_async.py:68-234).  Here the ops
carry an optional ``jax_fn``; when PyMC compiles via the PyTensor->JAX
linker, the registered ``jax_funcify`` dispatch inlines that function
into the traced program, and XLA schedules independent calls
concurrently on its own — the rewrite pass has no work left to do
(SURVEY §7 table, ``ParallelAsyncOp`` row).  The ``perform`` path (C/py
linkers) still works for host compute functions, so non-JAX "blackbox"
nodes keep first-class support (reference: README.md:34-35).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import pytensor
import pytensor.tensor as pt
from pytensor.gradient import DisconnectedType
from pytensor.graph.basic import Apply
from pytensor.graph.op import Op

from ..signatures import ComputeFn, LogpFn, LogpGradFn
from . import core

__all__ = [
    "FederatedArraysToArraysOp",
    "FederatedLogpGradOp",
    "FederatedLogpOp",
    "federated_potential",
]


def _as_tensors(inputs) -> list:
    # Coerce raw python ints/floats too — the reference's "issue #24"
    # regression (reference: wrapper_ops.py:25-31, test_wrapper_ops.py:284-289).
    return [pt.as_tensor_variable(i) for i in inputs]


class FederatedArraysToArraysOp(Op):
    """Generic arrays->arrays blackbox Op (reference: wrapper_ops.py:14-33).

    ``output_types`` gives the PyTensor types of the outputs (the
    reference infers them from ``FromFunctionOp`` construction args).

    No ``__props__``: op identity is instance identity, so two ops
    wrapping *different* node functions never compare equal and the merge
    optimizer cannot collapse distinct federated nodes into one apply
    (the reference keys identity on the wrapped function for the same
    reason, reference: wrapper_ops.py:20-23).  Re-applying the *same*
    instance on the same inputs (the ``grad()`` pattern below) still
    merges, because identity equality holds.
    """

    def __init__(
        self,
        compute_fn: ComputeFn,
        output_types: Sequence,
        *,
        jax_fn: Optional[Callable] = None,
    ):
        self.compute_fn = compute_fn
        self.output_types = list(output_types)
        self.jax_fn = jax_fn

    def make_node(self, *inputs):
        inputs = _as_tensors(inputs)
        outputs = [t() for t in self.output_types]
        return Apply(self, inputs, outputs)

    def perform(self, node, inputs, output_storage):
        results = self.compute_fn(*[np.asarray(i) for i in inputs])
        outs = core.coerce_outputs(
            results, [v.type.dtype for v in node.outputs]
        )
        for storage, out in zip(output_storage, outs):
            storage[0] = out


class FederatedLogpOp(Op):
    """Inputs -> scalar log-potential (reference: wrapper_ops.py:44-69).

    No ``__props__`` — see :class:`FederatedArraysToArraysOp`.
    """

    def __init__(self, logp_fn: LogpFn, *, jax_fn: Optional[Callable] = None):
        self.logp_fn = logp_fn
        self.jax_fn = jax_fn

    def make_node(self, *inputs):
        inputs = _as_tensors(inputs)
        # Scalar output typed like the reference's ``at.scalar()``
        # (reference: wrapper_ops.py:54).
        return Apply(self, inputs, [pt.scalar()])

    def perform(self, node, inputs, output_storage):
        logp = self.logp_fn(*[np.asarray(i) for i in inputs])
        output_storage[0][0] = core.coerce_logp(
            logp, node.outputs[0].type.dtype
        )


class FederatedLogpGradOp(Op):
    """Inputs -> ``[logp, *grads]`` with the symbolic grad bridge.

    Mirrors the reference op exactly (reference: wrapper_ops.py:84-132):
    node outputs are ``[scalar logp]`` plus one grad per input typed
    ``i.type()``; ``.grad()`` re-applies self on the same inputs (CSE
    dedups the double apply) and returns ``g_logp * grad_i``; connected
    gradients w.r.t. the grad outputs raise — no second-order autodiff
    through the federated boundary (reference: wrapper_ops.py:123-125).

    No ``__props__`` — see :class:`FederatedArraysToArraysOp`; instance
    identity keeps distinct nodes un-mergeable while ``grad()``'s
    re-apply of the same instance still CSEs.
    """

    def __init__(
        self, logp_grad_fn: LogpGradFn, *, jax_fn: Optional[Callable] = None
    ):
        self.logp_grad_fn = logp_grad_fn
        self.jax_fn = jax_fn

    def make_node(self, *inputs):
        inputs = _as_tensors(inputs)
        # Grad-output dtype policy (int inputs upcast to floatX so the
        # gradient is not silently truncated) lives in core.py where it
        # is tested without pytensor.
        outputs = [pt.scalar()]
        for i in inputs:
            dt = core.grad_output_dtype(
                i.type.dtype, pytensor.config.floatX
            )
            outputs.append(
                i.type()
                if dt == i.type.dtype
                else pt.TensorType(dt, i.type.shape)()
            )
        return Apply(self, inputs, outputs)

    def perform(self, node, inputs, output_storage):
        logp, grads = self.logp_grad_fn(*[np.asarray(i) for i in inputs])
        logp, grads = core.coerce_logp_grads(
            logp,
            grads,
            node.outputs[0].type.dtype,
            [v.type.dtype for v in node.outputs[1:]],
        )
        output_storage[0][0] = logp
        for storage, g in zip(output_storage[1:], grads):
            storage[0] = g

    def grad(self, inputs, output_grads):
        g_logp, *g_grads = output_grads
        for gg in g_grads:
            if not isinstance(gg.type, DisconnectedType):
                raise NotImplementedError(
                    "gradients with respect to the gradient outputs are not "
                    "supported (no second-order autodiff through the "
                    "federated boundary)"
                )
        outputs = self(*inputs)
        grads = outputs[1:]
        return [g_logp * g for g in grads]

    def connection_pattern(self, node):
        # logp depends on every input; each grad output is treated as
        # disconnected for further differentiation (first-order-only
        # contract, reference: wrapper_ops.py:119-132).
        n_in = len(node.inputs)
        return [[True] + [False] * n_in for _ in range(n_in)]


def federated_potential(logp_grad_fn: LogpGradFn, *inputs, jax_fn=None):
    """Apply a :class:`FederatedLogpGradOp` and return just the logp
    variable — ready for ``pm.Potential`` (reference: demo_model.py:33-36).

    A :class:`~pytensor_federated_tpu.fed.FederatedLogpGrad` evaluator
    routes BOTH lanes through its one ``fed.program``: it is itself the
    host ``LogpGradFn`` (perform path), and its ``.jax_fn`` — picked up
    automatically here — is the placement-lowered traced program for
    JAX-linker compiles, so mesh/pool/mixed execution and the window
    fusion pass apply without any per-op wiring.
    """
    if jax_fn is None:
        jax_fn = getattr(logp_grad_fn, "jax_fn", None)
    op = FederatedLogpGradOp(logp_grad_fn, jax_fn=jax_fn)
    return op(*inputs)[0]


# -- PyTensor->JAX linker dispatch ------------------------------------------
# Registering here (import side effect, like the reference's optdb
# registration at import, reference: op_async.py:228-234) means any
# PyMC/PyTensor compile with mode="JAX" inlines the op's jax_fn into the
# traced program: the whole NUTS step becomes one XLA executable.


def _member_kind(op) -> str:
    """Kind tag for :func:`..bridge.core.member_jax_callable`."""
    if isinstance(op, FederatedLogpGradOp):
        return "logp_grad"
    if isinstance(op, FederatedLogpOp):
        return "logp"
    return "arrays"


def _jax_funcify_for_member(op):
    """The jax callable for one federated op, with node-shaped output
    (a tuple matching the op's apply outputs).  Shared by the three
    ``jax_funcify`` registrations below and by the fused op's dispatch
    (fusion.py).  The wrapping itself lives in core.py, tested without
    pytensor."""
    return core.member_jax_callable(
        _member_kind(op), op.jax_fn, name=type(op).__name__
    )


try:  # pragma: no cover - depends on pytensor version layout
    from pytensor.link.jax.dispatch import jax_funcify

    @jax_funcify.register(FederatedArraysToArraysOp)
    @jax_funcify.register(FederatedLogpOp)
    @jax_funcify.register(FederatedLogpGradOp)
    def _jax_funcify_federated(op, **kwargs):
        return _jax_funcify_for_member(op)

except ModuleNotFoundError:  # pragma: no cover
    pass
