"""Pure grouping algorithm behind the federated fusion rewrite.

Factored out of fusion.py so the algorithm is testable without
PyTensor installed (tests/test_grouping.py runs everywhere; the
fusion rewrite itself can only execute where pytensor is present).
No pytensor imports belong in this module.

The problem: given applies in topological order and, for each node,
its input edges, partition the *candidate* applies into groups whose
members are pairwise independent (neither transitively consumes the
other's outputs).  Fusing such a group into one apply can never create
a graph cycle: a cycle would need a path between two members, which is
exactly what independence excludes — including paths through
non-candidate nodes, because dependence is propagated as a transitive
closure over ALL nodes.
"""

from __future__ import annotations

from typing import Callable, Hashable, Iterable, Sequence

__all__ = ["group_independent"]


def group_independent(
    order: Sequence[Hashable],
    parents: Callable[[Hashable], Iterable[Hashable]],
    is_candidate: Callable[[Hashable], bool],
) -> list[list[Hashable]]:
    """Greedy first-fit grouping of independent candidate nodes.

    ``order`` must be a topological order (parents before children);
    ``parents(n)`` yields the nodes whose outputs ``n`` consumes.
    Returns groups (lists of candidates, in topo order); singleton
    groups are included — the caller decides that fusing them is
    pointless.

    Only the forward direction needs checking when placing a node into
    a group: existing members precede it in topo order, so it can never
    be an ancestor of a member.
    """
    candidates = [n for n in order if is_candidate(n)]
    if len(candidates) < 2:
        # Nothing can group: skip the O(graph) transitive-deps pass —
        # this runs on EVERY default-mode compile (optdb fast_run).
        return [[c] for c in candidates]
    cand_set = set(candidates)
    # deps[n] = the candidate nodes n transitively depends on.
    deps: dict = {}
    for n in order:
        d = set()
        for p in parents(n):
            d |= deps.get(p, set())
            if p in cand_set:
                d.add(p)
        deps[n] = d
    groups: list[list] = []
    for c in candidates:
        for g in groups:
            if not any(m in deps[c] for m in g):
                g.append(c)
                break
        else:
            groups.append([c])
    return groups
