"""Pure, pytensor-free cores of the bridge layer — tested DIRECTLY.

pytensor/pymc are not installable in this build environment, so the
Apply/optdb glue in :mod:`.pytensor_ops` / :mod:`.fusion` cannot
execute here (tests/test_bridge.py, test_fusion.py skip at import).
Everything with actual LOGIC is therefore factored out where it runs
under test without pytensor — the same policy that produced
:mod:`..fanout_exec` (the fused perform's scheduling contract) and
:mod:`.grouping` (the rewrite's independence planning).  This module
holds the rest:

- the ``perform``-layer output coercion/validation contracts
  (reference: the implicit contracts of wrapper_ops.py:26-33, 57-69,
  106-118 — output arity, scalar logp, one grad per input, dtype cast);
- the grad-output dtype policy (int inputs upcast to floatX so the
  gradient is not silently truncated — the reference types them
  ``i.type()`` unconditionally, wrapper_ops.py:97-105, a trap this
  framework does not replicate);
- the JAX-dispatch composition: node-shaped output wrapping per op
  kind and the fused-op member inliner (what ``jax_funcify`` returns).

What remains pytensor-ONLY after this extraction is enumerated, with
measured line counts, in docs/migrating.md ("Pytensor-gated bridge
surface") — thin Apply/optdb adapter code whose failure mode is an
import/signature error on first use, not silent wrong numbers; since
round 5 it executes under the in-repo API shim
(tests/pytensor_shim.py), leaving only real-pytensor compatibility
unproven here.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import numpy as np

__all__ = [
    "coerce_outputs",
    "coerce_logp",
    "coerce_logp_grads",
    "grad_output_dtype",
    "plan_fusion",
    "member_jax_callable",
    "fused_jax_callable",
]


# ---------------------------------------------------------------------------
# perform-layer contracts
# ---------------------------------------------------------------------------


def coerce_outputs(
    results: Sequence, dtypes: Sequence[str]
) -> List[np.ndarray]:
    """Arrays->arrays output contract: arity must match, each output is
    cast to its declared dtype (reference: FromFunctionOp semantics,
    wrapper_ops.py:26-33)."""
    results = list(results)
    if len(results) != len(dtypes):
        raise ValueError(
            f"compute_fn returned {len(results)} outputs, "
            f"expected {len(dtypes)}"
        )
    return [np.asarray(r, dtype=d) for r, d in zip(results, dtypes)]


def coerce_logp(logp, dtype: str) -> np.ndarray:
    """Scalar log-potential contract (reference: wrapper_ops.py:57-69)."""
    out = np.asarray(logp, dtype=dtype)
    if out.ndim != 0:
        raise ValueError(f"logp must be scalar, got shape {out.shape}")
    return out


def coerce_logp_grads(
    logp, grads: Sequence, logp_dtype: str, grad_dtypes: Sequence[str]
) -> Tuple[np.ndarray, List[np.ndarray]]:
    """``[logp, *grads]`` contract: one grad per input, each cast to its
    declared (possibly upcast — see :func:`grad_output_dtype`) dtype
    (reference: wrapper_ops.py:106-118)."""
    if len(grads) != len(grad_dtypes):
        raise ValueError(
            f"logp_grad_fn returned {len(grads)} grads for "
            f"{len(grad_dtypes)} inputs"
        )
    return (
        coerce_logp(logp, logp_dtype),
        [np.asarray(g, dtype=d) for g, d in zip(grads, grad_dtypes)],
    )


def grad_output_dtype(input_dtype: str, floatX: str = "float64") -> str:
    """Dtype of the grad output for an input of ``input_dtype``.

    Integer/bool inputs (the raw-python-int coercion path) get floatX:
    an int-typed grad output would silently truncate the float gradient
    at the cast in ``coerce_logp_grads``.
    """
    if str(input_dtype).startswith(("int", "uint", "bool")):
        return floatX
    return str(input_dtype)


# ---------------------------------------------------------------------------
# rewrite replacement planning
# ---------------------------------------------------------------------------


def plan_fusion(
    group: Sequence,
    *,
    op_of: Callable,
    inputs_of: Callable,
    outputs_of: Callable,
):
    """Plan one group's fused replacement: the constructor arguments of
    the fused op plus the (old_output -> fused_output_index) pairing.

    Pure bookkeeping extracted from the rewriter (the part that decides
    WHAT replaces what; the two-line ``fgraph.replace_all_validate``
    call is the only pytensor left).  Returns a dict with:

    - ``members``: each node's op, in group order;
    - ``in_counts`` / ``out_counts``: per-member arities;
    - ``all_inputs``: concatenated member inputs (fused apply inputs);
    - ``replacements``: ``[(old_output, fused_output_position), ...]``
      — every member output paired with its index into the fused
      apply's outputs, order-preserving.
    """
    members = [op_of(n) for n in group]
    in_counts = [len(inputs_of(n)) for n in group]
    out_counts = [len(outputs_of(n)) for n in group]
    all_inputs = [i for n in group for i in inputs_of(n)]
    old_outputs = [o for n in group for o in outputs_of(n)]
    replacements = list(zip(old_outputs, range(len(old_outputs))))
    return {
        "members": members,
        "in_counts": in_counts,
        "out_counts": out_counts,
        "all_inputs": all_inputs,
        "replacements": replacements,
    }


# ---------------------------------------------------------------------------
# JAX-dispatch composition
# ---------------------------------------------------------------------------


def member_jax_callable(
    kind: str, fn: Callable, *, name: str = "op"
) -> Callable:
    """Node-shaped JAX callable for one federated op.

    ``kind``: ``"logp_grad"`` (fn returns ``(logp, [grads])`` ->
    flattened ``(logp, *grads)``), ``"logp"`` (scalar through), or
    ``"arrays"`` (sequence -> tuple).  This is exactly what the
    ``jax_funcify`` registrations return; dispatching on an explicit
    kind keeps it testable without pytensor op classes.  ``name`` goes
    into the missing-``jax_fn`` error so a fused graph with several
    federated ops points at the unconfigured one.
    """
    if fn is None:
        raise NotImplementedError(
            f"{name} has no jax_fn; pass jax_fn= to compile through "
            "the JAX linker"
        )
    if kind == "logp_grad":

        def logp_grad(*inputs):
            logp, grads = fn(*inputs)
            return (logp, *tuple(grads))

        # A fed.FederatedLogpGrad's bound ``jax_fn`` carries the whole
        # placement-lowered program; tag the wrapper so a FUSED apply
        # (fused_jax_callable) can compose several such members into
        # one fed.program and let the window-fusion pass coalesce
        # their independent fed_maps.
        ev = getattr(fn, "__self__", None)
        if (
            ev is not None
            and callable(getattr(ev, "fed_model", None))
            and getattr(ev, "placement", None) is not None
        ):
            logp_grad._fed_evaluator = ev
        return logp_grad
    if kind == "logp":

        def logp(*inputs):
            return fn(*inputs)

        return logp
    if kind == "arrays":

        def arrays_to_arrays(*inputs):
            return tuple(fn(*inputs))

        return arrays_to_arrays
    raise ValueError(f"unknown member kind {kind!r}")


def fused_jax_callable(
    member_fns: Sequence[Callable], in_counts: Sequence[int]
) -> Callable:
    """Inline N node-shaped member callables into one flat callable —
    the fused op's JAX dispatch (XLA overlaps the members on its own).
    Input/output flattening mirrors ``fanout_exec.run_members``'s
    storage slicing, so the jit path and the perform path cannot
    disagree about order.

    When every member is a ``fed.FederatedLogpGrad`` potential sharing
    one placement (the tag :func:`member_jax_callable` attaches), the
    members compose into ONE ``fed.program`` instead: the fed batching
    pass then fuses their independent ``fed_map`` calls into a single
    pipelined pool window — the AsyncFusionOptimizer rewrite landing
    at the primitive level (docs/migrating.md)."""
    member_fns = list(member_fns)
    in_counts = list(in_counts)
    if len(member_fns) != len(in_counts):
        raise ValueError(
            f"{len(member_fns)} member fns but {len(in_counts)} in_counts"
        )
    evs = [getattr(f, "_fed_evaluator", None) for f in member_fns]
    if len(evs) >= 2 and all(e is not None for e in evs):
        # Placement EQUIVALENCE, not object identity: each potential is
        # typically built with its own PoolPlacement over the shared
        # client, and those must still fuse into one window.
        keys = {
            getattr(
                e.placement, "fusion_key", lambda p=e.placement: id(p)
            )()
            for e in evs
        }
        if len(keys) == 1:
            return _fused_fed_callable(evs, in_counts)

    def parallel(*inputs):
        if len(inputs) != sum(in_counts):
            raise ValueError(
                f"fused callable got {len(inputs)} inputs, members "
                f"consume {sum(in_counts)}"
            )
        outs = []
        i = 0
        for fn, n_in in zip(member_fns, in_counts):
            res = fn(*inputs[i : i + n_in])
            outs.extend(res if isinstance(res, tuple) else (res,))
            i += n_in
        return tuple(outs)

    return parallel


def _fused_fed_callable(evaluators, in_counts) -> Callable:
    """Compose N fed logp+grad potentials into ONE placement-lowered
    program.  One ``value_and_grad`` over the joint program is one
    forward execution — on a pool placement that is ONE fused window
    for every member's shards, where per-member programs would each pay
    their own round trip.  Output layout per member is ``(logp,
    *grads)``, identical to the inlined path, so the perform lane and
    this lane cannot disagree."""
    import jax

    from ..fed import program as fed_program

    evaluators = list(evaluators)
    placement = evaluators[0].placement
    n_total = sum(in_counts)

    def joint_model(*inputs):
        lps, i = [], 0
        for ev, n_in in zip(evaluators, in_counts):
            lps.append(ev.fed_model(*inputs[i : i + n_in]))
            i += n_in
        return tuple(lps)

    prog = fed_program(joint_model, placement)

    def total_and_logps(*inputs):
        lps = prog(*inputs)
        total = lps[0]
        for lp in lps[1:]:
            total = total + lp
        return total, lps

    def parallel(*inputs):
        if len(inputs) != n_total:
            raise ValueError(
                f"fused fed callable got {len(inputs)} inputs, members "
                f"consume {n_total}"
            )
        (_, lps), grads = jax.value_and_grad(
            total_and_logps,
            argnums=tuple(range(n_total)),
            has_aux=True,
        )(*inputs)
        outs, i = [], 0
        for lp, n_in in zip(lps, in_counts):
            outs.append(lp)
            outs.extend(grads[i : i + n_in])
            i += n_in
        return tuple(outs)

    return parallel
