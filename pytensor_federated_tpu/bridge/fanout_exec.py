"""Compatibility shim — the pytensor-free scheduling core moved to
:mod:`pytensor_federated_tpu.fanout_exec` so that importing it (e.g.
from ops/fanout.py) does not execute this package's ``__init__``, which
imports pytensor when installed (round-3 review: the base package must
stay importable without paying for — or mutating the optdb of — an
installed PyTensor)."""

from ..fanout_exec import (  # noqa: F401
    CoalescingCaller,
    MemberExecutorPool,
    member_spans,
    run_members,
)

__all__ = [
    "CoalescingCaller",
    "MemberExecutorPool",
    "member_spans",
    "run_members",
]
