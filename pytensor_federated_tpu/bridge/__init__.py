"""PyTensor/PyMC bridge — the reference's front door, made JAX-compilable.

The reference *is* a PyTensor extension: its ops embed remote logp/grad
calls into PyMC graphs (reference: wrapper_ops.py:14-146).  This bridge
provides the same Op surface for users coming from PyMC, with one
TPU-critical addition: every op registers a ``jax_funcify`` dispatch, so
when PyMC compiles the model through the PyTensor->JAX linker
(``pm.sample(..., nuts_sampler="numpyro")`` or ``mode="JAX"``) the
*entire* step function — federated likelihood included — jits into one
XLA program with zero host callbacks in the loop (SURVEY §7 step 4).

Import-gated exactly like the reference's ``__init__`` (reference:
pytensor_federated/__init__.py:1-12): the rest of the framework is fully
usable without PyTensor installed.
"""

try:
    from .pytensor_ops import (
        FederatedArraysToArraysOp,
        FederatedLogpGradOp,
        FederatedLogpOp,
        federated_potential,
    )

    # Importing fusion registers the automatic fan-out rewrite in
    # PyTensor's optdb (reference: op_async.py:228-234 registers its
    # AsyncFusionOptimizer the same way, at import).
    from .fusion import FederatedFusionRewriter, ParallelFederatedOp

    HAS_PYTENSOR = True
    __all__ = [
        "HAS_PYTENSOR",
        "FederatedArraysToArraysOp",
        "FederatedFusionRewriter",
        "FederatedLogpGradOp",
        "FederatedLogpOp",
        "ParallelFederatedOp",
        "federated_potential",
    ]
except ModuleNotFoundError as e:
    # Only a missing THIRD-PARTY dep may soft-disable the bridge.  A
    # missing module of our own (e.g. a file dropped from a wheel) must
    # stay loud — swallowing it here would silently stub out every Op
    # in an environment where pytensor IS installed.
    if e.name is not None and e.name.split(".")[0] == "pytensor_federated_tpu":
        raise
    HAS_PYTENSOR = False
    __all__ = ["HAS_PYTENSOR"]

    def __getattr__(name):
        if name in (
            "FederatedArraysToArraysOp",
            "FederatedFusionRewriter",
            "FederatedLogpGradOp",
            "FederatedLogpOp",
            "ParallelFederatedOp",
            "federated_potential",
        ):
            raise ImportError(
                f"{name} requires PyTensor; install the 'pytensor' extra "
                "(pip install pytensor-federated-tpu[pytensor])"
            )
        raise AttributeError(name)
