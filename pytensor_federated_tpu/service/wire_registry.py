"""The single declared source of wire-format constants.

Three implementations speak this repo's wire formats — the npwire
codec (:mod:`.npwire`), the hand-rolled proto3 codec
(:mod:`.npproto_codec`), and the C++ node (``native/cpp_node.cpp``) —
and each necessarily carries its own literals (the C++ file cannot
import Python).  This module is the DECLARATION those literals are
checked against: the ``wire-registry`` graftlint rule
(:mod:`..analysis.rules_wire`) cross-parses all three implementations
and fails CI on any flag bit or field number that is undeclared here,
collides with another declaration, or lacks its decoder-side
rejection/dispatch arm.  Adding a wire feature therefore starts HERE;
the checker then points at every implementation that has not caught
up.

Nothing in this module imports anything, so any layer (including the
analysis package, which must not drag jax in for a wire check) can
read it.

npwire flag bits
----------------
One byte of flags in the frame header (npwire.py module docstring has
the full layout).  Every bit must be listed: the decoders reject a
frame whose flags contain any bit outside :data:`NPWIRE_KNOWN_FLAGS`
— the loud-failure contract that makes a version-skewed peer an error
instead of silent mis-parsing.

npproto field numbers
---------------------
Field numbers per proto3 message, including the four extension fields
(14-17) this package adds to the reference schema.  Unknown fields are
SKIPPED on decode (proto3 forward compatibility — deliberately the
opposite posture of npwire flags), so the decoder-side obligation
checked for each declared field is a dispatch arm, not a rejection.
"""

from __future__ import annotations

__all__ = [
    "NPWIRE_FLAGS",
    "NPWIRE_KNOWN_FLAGS",
    "NPPROTO_FIELDS",
    "NPPROTO_EXTENSION_FIELDS",
]

#: npwire frame flag bits, by canonical name.  npwire.py spells these
#: ``_FLAG_<NAME>``; native/cpp_node.cpp spells them ``kFlag<Name>``.
NPWIRE_FLAGS = {
    "ERROR": 1,   # in-band error string block follows the header
    "TRACE": 2,   # 16-byte telemetry trace id block
    "SPANS": 4,   # JSON span-tree tail (reply piggyback)
    "BATCH": 8,   # count field is n_items; body is nested frames
}

#: The full known-flags mask every npwire decoder must enforce
#: (``flags & ~KNOWN`` is a WireError, not a skip).
NPWIRE_KNOWN_FLAGS = 0
for _bit in NPWIRE_FLAGS.values():
    NPWIRE_KNOWN_FLAGS |= _bit
del _bit

#: proto3 field numbers by message.  ``arrays_msg`` covers InputArrays
#: and OutputArrays (identical layout, reference service.proto:6-19);
#: fields >= 14 are this package's extensions (npproto_codec.py module
#: docstring documents each).
NPPROTO_FIELDS = {
    "ndarray": {
        "data": 1,
        "dtype": 2,
        "shape": 3,
        "strides": 4,
    },
    "arrays_msg": {
        "items": 1,
        "uuid": 2,
        "error": 14,        # per-item error inside a batch reply
        "trace_id": 15,     # 16-byte telemetry correlation id
        "spans": 16,        # JSON span trees, reply piggyback
        "batch_items": 17,  # nested messages: the batch frame marker
    },
    "get_load_result": {
        "n_clients": 1,
        "percent_cpu": 2,
        "percent_ram": 3,
    },
}

#: The extension field numbers (unknown to the reference schema; a
#: reference peer skips them by wire type).  Kept as an explicit set so
#: the checker can demand a decode dispatch arm for each — we must
#: never emit a field we cannot read back.
NPPROTO_EXTENSION_FIELDS = frozenset(
    n for n in NPPROTO_FIELDS["arrays_msg"].values() if n >= 14
)
