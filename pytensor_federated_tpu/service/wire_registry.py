"""The single declared source of wire-format constants.

Three implementations speak this repo's wire formats — the npwire
codec (:mod:`.npwire`), the hand-rolled proto3 codec
(:mod:`.npproto_codec`), and the C++ node (``native/cpp_node.cpp``) —
and each necessarily carries its own literals (the C++ file cannot
import Python).  This module is the DECLARATION those literals are
checked against: the ``wire-registry`` graftlint rule
(:mod:`..analysis.rules_wire`) cross-parses all three implementations
and fails CI on any flag bit or field number that is undeclared here,
collides with another declaration, or lacks its decoder-side
rejection/dispatch arm.  Adding a wire feature therefore starts HERE;
the checker then points at every implementation that has not caught
up.

Nothing in this module imports anything, so any layer (including the
analysis package, which must not drag jax in for a wire check) can
read it.

npwire flag bits
----------------
One byte of flags in the frame header (npwire.py module docstring has
the full layout).  Every bit must be listed: the decoders reject a
frame whose flags contain any bit outside :data:`NPWIRE_KNOWN_FLAGS`
— the loud-failure contract that makes a version-skewed peer an error
instead of silent mis-parsing.

npproto field numbers
---------------------
Field numbers per proto3 message, including the four extension fields
(14-17) this package adds to the reference schema.  Unknown fields are
SKIPPED on decode (proto3 forward compatibility — deliberately the
opposite posture of npwire flags), so the decoder-side obligation
checked for each declared field is a dispatch arm, not a rejection.
"""

from __future__ import annotations

__all__ = [
    "NPWIRE_FLAGS",
    "NPWIRE_KNOWN_FLAGS",
    "NPPROTO_FIELDS",
    "NPPROTO_EXTENSION_FIELDS",
    "PARTITION_STRUCT",
    "PARTITION_FIELD_ORDER",
    "VERSION_STRUCT",
    "NPPROTO_PARTITION_FIELDS",
    "SHMWIRE_KINDS",
    "SHMWIRE_FLAGS",
    "SHMWIRE_KNOWN_FLAGS",
    "SHM_DESC_STRUCT",
    "SHM_DESC_FIELD_ORDER",
    "RING_HEADER_STRUCT",
    "RING_HEADER_FIELD_ORDER",
    "RING_DESC_STRUCT",
    "RING_DESC_FIELD_ORDER",
    "RING_HEADER_OFFSET",
    "RING_RECORDS_OFFSET",
    "RING_FUTEX_WORD_OFFSET",
    "RING_WAITING_WORD_OFFSET",
    "RING_EPOCH_WORD_OFFSET",
    "GETLOAD_PAYLOADS",
    "LINALG_OP_STRUCT",
    "LINALG_OP_FIELD_ORDER",
    "LINALG_TILE_STRUCT",
    "LINALG_TILE_FIELD_ORDER",
    "LINALG_OPCODES",
]

#: npwire frame flag bits, by canonical name.  npwire.py spells these
#: ``_FLAG_<NAME>``; native/cpp_node.cpp spells them ``kFlag<Name>``.
NPWIRE_FLAGS = {
    "ERROR": 1,     # in-band error string block follows the header
    "TRACE": 2,     # 16-byte telemetry trace id block
    "SPANS": 4,     # JSON span-tree tail (reply piggyback)
    "BATCH": 8,     # count field is n_items; body is nested frames
    "DEADLINE": 16,  # f64 remaining-budget block (service/deadline.py)
    "TENANT": 32,   # u16-len utf8 tenant id block (gateway/fairness.py)
    "PARTITION": 64,  # gradient-partition index block (routing/partition.py)
    "VERSION": 128,  # u64 monotonic step-version stamp (optim/sharded.py)
}

#: The full known-flags mask every npwire decoder must enforce
#: (``flags & ~KNOWN`` is a WireError, not a skip).
NPWIRE_KNOWN_FLAGS = 0
for _bit in NPWIRE_FLAGS.values():
    NPWIRE_KNOWN_FLAGS |= _bit
del _bit

#: proto3 field numbers by message.  ``arrays_msg`` covers InputArrays
#: and OutputArrays (identical layout, reference service.proto:6-19);
#: fields >= 14 are this package's extensions (npproto_codec.py module
#: docstring documents each).
NPPROTO_FIELDS = {
    "ndarray": {
        "data": 1,
        "dtype": 2,
        "shape": 3,
        "strides": 4,
    },
    "arrays_msg": {
        "items": 1,
        "uuid": 2,
        "error": 14,        # per-item error inside a batch reply
        "trace_id": 15,     # 16-byte telemetry correlation id
        "spans": 16,        # JSON span trees, reply piggyback
        "batch_items": 17,  # nested messages: the batch frame marker
        "deadline_s": 18,   # fixed64 double: remaining deadline budget
        "tenant_id": 19,    # utf8 string: per-tenant identity (gateway/)
        "partition": 20,    # nested message: gradient-partition index
                            # block (routing/partition.py; sub-fields in
                            # NPPROTO_PARTITION_FIELDS)
        "version": 21,      # varint u64: monotonic step-version stamp
                            # (optim/sharded.py; emitted explicitly even
                            # at 0 — field PRESENCE marks a versioned
                            # frame, so the zero stamp cannot be elided)
    },
    "get_load_result": {
        "n_clients": 1,
        "percent_cpu": 2,
        "percent_ram": 3,
    },
}

#: The extension field numbers (unknown to the reference schema; a
#: reference peer skips them by wire type).  Kept as an explicit set so
#: the checker can demand a decode dispatch arm for each — we must
#: never emit a field we cannot read back.
NPPROTO_EXTENSION_FIELDS = frozenset(
    n for n in NPPROTO_FIELDS["arrays_msg"].values() if n >= 14
)

#: shm doorbell frame kinds (``service/shm.py`` spells these
#: ``_KIND_<NAME>``).  The zero-copy lane's doorbell channel carries
#: DESCRIPTOR frames — ``(slot, delta, length, generation)`` pointers
#: into a mmap arena — instead of payload bytes; this table is the one
#: declared source of the frame-kind byte, cross-checked against the
#: implementation by the graftlint wire-registry rule.  Decoders REJECT
#: an unknown kind (same loud-failure posture as npwire flags: the
#: doorbell peers ship in lockstep).
SHMWIRE_KINDS = {
    "ATTACH": 1,       # client -> server: open the arena pair
    "ATTACH_OK": 2,    # server -> client: JSON {req,rep,size,arena_id}
    "EVAL": 3,         # one request: descriptor list into the req arena
    "REPLY": 4,        # one reply: descriptor list into the rep arena
    "EVAL_BATCH": 5,   # K requests in one doorbell frame (PR-3 analog)
    "REPLY_BATCH": 6,  # K replies, per-item error isolation
    "ACK": 7,          # reply-arena reclamation watermark (generation)
    "GETLOAD": 8,      # load probe request
    "LOAD": 9,         # JSON load reply
    "PING": 10,        # empty-arena-write doorbell round-trip probe
    "PONG": 11,        # ping reply
    "ERROR": 12,       # frame-level in-band error (undecodable frame)
}

#: shm doorbell frame flag bits (``service/shm.py`` spells these
#: ``_FLAG_<NAME>``).  Deliberately a SUBSET of the npwire flags with
#: the same bit assignments; the spans/batch features ride dedicated
#: frame kinds instead of flag bits on this lane.
SHMWIRE_FLAGS = {
    "ERROR": 1,     # in-band error string block follows the uuid
    "TRACE": 2,     # 16-byte telemetry trace id block
    "DEADLINE": 4,  # f64 remaining-budget block (service/deadline.py)
    "TENANT": 8,    # u16-len utf8 tenant id block (gateway/fairness.py)
    "PARTITION": 16,  # gradient-partition index block (routing/partition.py)
    "VERSION": 32,  # u64 monotonic step-version stamp (optim/sharded.py)
}

#: The full known-flags mask every shm decoder must enforce
#: (``flags & ~KNOWN`` is a WireError, not a skip).
SHMWIRE_KNOWN_FLAGS = 0
for _bit in SHMWIRE_FLAGS.values():
    SHMWIRE_KNOWN_FLAGS |= _bit
del _bit

#: The arena descriptor: one fixed-layout struct per array, pointing at
#: bytes that never ride the doorbell.  ``slot`` is the arena offset of
#: the slot HEADER (whose generation the reader validates before and
#: after touching payload bytes), ``delta`` the array's byte offset
#: inside the slot's payload (several arrays may share one slot — the
#: scatter/gather packing), ``length`` the payload byte length, and
#: ``generation`` the slot generation the descriptor was minted
#: against — a recycled or torn slot fails loudly as WireError.  The
#: struct format and field order are declared here so the graftlint
#: wire-registry rule can pin the implementation's literals to them.
SHM_DESC_STRUCT = "<QIQQ"
SHM_DESC_FIELD_ORDER = ("slot", "delta", "length", "generation")

#: The gradient-partition index block (ISSUE 13): one fixed-layout
#: struct describing which contiguous slice of a flat gradient vector
#: a frame carries (or requests).  ``index``/``count`` place the shard
#: among its siblings; ``offset``/``length`` are the element range of
#: the slice inside the flat vector; ``total`` is the flat vector's
#: full element count — the cross-check that makes a driver/node shape
#: disagreement a loud error instead of a silently mis-assembled
#: gradient.  On npwire the block rides flag bit 64 after the tenant
#: block; on the shm doorbell, flag bit 16 in the same position; on
#: npproto it is extension field 20, a nested message whose sub-field
#: numbers are :data:`NPPROTO_PARTITION_FIELDS` (a reference runtime
#: skips the whole field by wire type).  ``routing/partition.py`` owns
#: the semantics (slice/reduce rules, reassembly).
PARTITION_STRUCT = "<IIQQQ"
PARTITION_FIELD_ORDER = ("index", "count", "offset", "length", "total")

#: The step-version stamp (ISSUE 16): a monotonic u64 counting
#: optimizer updates applied to one gradient shard, carried on update
#: and param-refresh frames so a stale optimizer-state shard is a loud
#: ``WireError``-family refusal (``optim.StaleShardError``), never a
#: silently stale moment buffer.  On npwire the stamp rides flag bit
#: 128 as 8 little-endian bytes after the partition block; on the shm
#: doorbell, flag bit 32 in the same position; on npproto it is
#: extension field 21, a varint a reference runtime skips by wire
#: type.  Zero is a meaningful stamp (the init handshake), so every
#: codec signals the feature by PRESENCE (flag bit / field), never by
#: value.  ``optim/sharded.py`` owns the semantics (version check,
#: exactly-once update, restore-or-refuse).
VERSION_STRUCT = "<Q"

#: Sub-field numbers of the npproto partition message (field 20).
NPPROTO_PARTITION_FIELDS = {
    "index": 1,
    "count": 2,
    "offset": 3,
    "length": 4,
    "total": 5,
}

#: The in-arena descriptor ring (ISSUE 18): the zero-syscall colocated
#: lane embeds one SPSC ring per arena — submissions in the request
#: arena (client produces, node consumes), completions in the reply
#: arena (node produces, client consumes).  Records carry complete shm
#: doorbell frames (the SHMWIRE kinds/flags/blocks above, verbatim —
#: the ring is a CHANNEL, not a new frame format), so the preserialized
#: deadline/partition/version templates ride unchanged.  The layouts
#: below are declared here first; ``service/ring.py`` mirrors them and
#: the graftlint wire-registry rule pins the implementation literals.
#:
#: Ring header (one per arena, 64 bytes at arena offset
#: :data:`RING_HEADER_OFFSET`)::
#:
#:     produced(u64)  consumed(u64)  futex(u32)  waiting(u32)
#:     epoch(u32)     capacity(u32)  record_bytes(u32)
#:
#: ``produced``/``consumed`` are the two SPSC positions (each written
#: by exactly one side); ``futex`` is the consumer's park word (the
#: producer bumps it per commit and FUTEX_WAKEs only when ``waiting``
#: is set — the zero-syscall steady state); ``epoch`` is the liveness
#: word (nonzero while the ring is attached, zeroed on clean close so
#: a parked peer wakes to a classified ``ConnectionError``, never a
#: hang); ``capacity``/``record_bytes`` cross-check the arena file
#: header's ring geometry.
RING_HEADER_STRUCT = "<QQIIIII"
RING_HEADER_FIELD_ORDER = (
    "produced", "consumed", "futex", "waiting",
    "epoch", "capacity", "record_bytes",
)

#: One ring record header (records start at arena offset
#: :data:`RING_RECORDS_OFFSET`, each ``record_bytes`` wide; the frame
#: payload follows the 16-byte header inside the record).  ``seq`` is
#: the seqlock word: position ``p`` commits as ``2*p + 2``, is mid-
#: write as ``2*p + 1`` — like the arena slot generations, a torn,
#: stale, recycled, or scribbled record is a LOUD ``WireError`` (or a
#: bounded wait that times out as a classified transient), never a
#: wrong-answer descriptor.  ``length`` is the TOTAL frame length on a
#: frame's first record and the chunk length on continuation records
#: (frames larger than one record span consecutive records).
RING_DESC_STRUCT = "<QII"
RING_DESC_FIELD_ORDER = ("seq", "length", "reserved")

#: Arena byte offset of the ring header (immediately after the 64-byte
#: arena file header) and of record 0 (one alignment unit later).
RING_HEADER_OFFSET = 64
RING_RECORDS_OFFSET = 128

#: Byte offsets of the futex / waiting / epoch words INSIDE the ring
#: header — the futex shim addresses these words directly, so the
#: offsets are wire constants like any struct layout.
RING_FUTEX_WORD_OFFSET = 16
RING_WAITING_WORD_OFFSET = 20
RING_EPOCH_WORD_OFFSET = 24

#: GetLoad request payloads.  Both wire schemas define an EMPTY
#: GetLoad request, so every non-empty payload is an in-repo extension
#: riding the npwire-JSON GetLoad lane (server.py ``get_load``; the
#: npproto reply schema is fixed at its three fields and ignores
#: these).  Unknown payloads degrade to the plain load reply —
#: deliberately the proto3 skip posture, not the npwire flag-rejection
#: posture, because the payload selects reply ENRICHMENT, never a
#: different decode of the request.  Declared here so a new pull lane
#: starts in the registry like every other wire feature.
GETLOAD_PAYLOADS = {
    "LOAD": b"",            # plain load reply (both schemas)
    "TRACES": b"traces",    # + recent span trees (trace reunion pull)
    "TELEMETRY": b"telemetry",  # + full telemetry snapshot + flightrec
                                # tail + node wall clock (fleet collector)
}

#: Blocked linear algebra headers (ISSUE 19).  The ``linalg`` block
#: store is an ordinary arrays-in/arrays-out compute — tiles ride the
#: existing npwire/npproto/shm/ring frames unchanged, so no new flag
#: bits or field numbers exist.  What IS wire format is the two packed
#: headers the driver and the block-store node must agree on, carried
#: as the leading ``uint8`` request arrays of every linalg operation.
#: They are declared here and IMPORTED by ``linalg/blocks.py`` (one
#: source, drift impossible by construction — unlike the C++/cross-
#: codec tables above there is exactly one implementation).
#:
#: Operation header (first request array of every block-store call)::
#:
#:     opcode(u32)  step(u32)  count(u32)  flags(u32)
#:
#: ``opcode`` is a :data:`LINALG_OPCODES` value; ``step`` is the outer
#: factorization index ``k`` where one applies; ``count`` is the number
#: of (tile-header, tile) pairs or coordinate rows that follow;
#: ``flags`` is reserved (must be zero — the node refuses nonzero, the
#: npwire unknown-flag posture).
LINALG_OP_STRUCT = "<IIII"
LINALG_OP_FIELD_ORDER = ("opcode", "step", "count", "flags")

#: Tile header (precedes each shipped tile)::
#:
#:     grid_rows(u32) grid_cols(u32) row(u32) col(u32) rows(u64) cols(u64)
#:
#: ``grid_rows``/``grid_cols`` bind the tile to ONE block layout —
#: a driver/node geometry disagreement is a loud ``BlockError``
#: (⊂ ``WireError``), never a silently mis-placed tile; ``rows``/
#: ``cols`` are the tile's own extent, cross-checked against both the
#: layout's ``tile_shape(row, col)`` and the shipped array's shape.
LINALG_TILE_STRUCT = "<IIIIQQ"
LINALG_TILE_FIELD_ORDER = (
    "grid_rows", "grid_cols", "row", "col", "rows", "cols",
)

#: Block-store opcodes (``linalg/service.py`` owns the semantics).
#: Values are frozen wire constants: a driver built against one table
#: revision talking to a node built against another must fail loudly
#: (unknown opcode -> in-band BlockError), never run the wrong op.
LINALG_OPCODES = {
    "PUT": 1,          # store tiles: [op, (tile_hdr, tile)*] -> [stored]
    "GET": 2,          # fetch tiles: [op, coords i64 (n,2)] -> [tiles...]
    "GEMM_PANEL": 3,   # stateless partial product: [op, a, b] -> [a @ b]
    "CHOL_PANEL": 4,   # factor step k on the owner of block-row k:
                       # [op(k)] -> [L_kk, own_rows i64, L_ik...]
    "TRSM_PANEL": 5,   # panel solve on a non-owner: [op(k), L_kk]
                       # -> [own_rows i64, L_ik...]
    "SYRK_UPDATE": 6,  # trailing update: [op(k), rows i64, L_ik...]
                       # -> [n_updated]
    "RESET": 7,        # drop every stored tile -> [n_dropped]
    "STATS": 8,        # -> [n_tiles, n_bytes] (tests/accounting)
}
