"""Shared-memory arena transport — the zero-copy lane for colocated nodes.

The host-federation transports so far all MOVE the payload: npwire
frames concatenate array bytes, sockets copy them through the kernel,
decoders copy them back out.  For replicas on the SAME host that is
pure waste — the bytes end up in the same physical memory they started
next to.  This module keeps payloads in a shared mmap arena
(:mod:`.arena`) and sends only DESCRIPTORS — ``(slot, delta, length,
generation)`` pointers plus dtype/shape — over a lightweight TCP
"doorbell" channel:

- the driver writes request arrays into its half of the arena pair
  exactly once (arrays it passes repeatedly by identity — per-node
  data constants — are PINNED after their second appearance and never
  copied again: steady-state requests move descriptor bytes only);
- the node builds ``frombuffer`` views straight onto the shared pages
  (zero read copies), computes, writes replies into its half, and
  doorbells the reply descriptors back;
- generation-counted slots (:mod:`.arena`) make every stale, torn, or
  corrupt descriptor a loud :class:`~.npwire.WireError`, never torn
  data — the CLAUDE.md wire invariant, extended to shared memory.

The doorbell speaks u32-length-prefixed frames like the TCP lane, and
ALSO answers plain npwire frames (:func:`~.tcp.serve_npwire_payload`),
so the replica pool's existing zero-item batch probe — and therefore
`routing.NodePool` health checks, breakers, and failover — work
against an shm node unchanged.  :class:`ShmArraysClient` carries the
full pinned-client surface (``evaluate``, pipelined/batched
``evaluate_many``, ``evaluate_many_partial``, ``get_load``) so a pool
can mix shm replicas with grpc/tcp ones.

Frame layout (little-endian; constants declared in
:mod:`.wire_registry`, cross-checked by the graftlint wire-registry
rule)::

  header: MAGIC("SHM1") version(u8) kind(u8) flags(u8) pad(u8) uuid(16s)
          [flags&1 error: len(u32) utf8]
          [flags&2 trace: trace_id(16s)]
  descriptor (per array): slot(u64) delta(u32) length(u64) gen(u64)
          dtype_len(u16) dtype ndim(u8) shape(u64*ndim)
  bodies: ATTACH ()                    ATTACH_OK (json: req/rep/size/id)
          EVAL (ack_gen u64, n u32, n descriptors)
          REPLY (n u32, n descriptors)        [error rides flags&1]
          EVAL_BATCH (ack_gen u64, k u32, k × [uuid 16s, n u32, descs])
          REPLY_BATCH (k u32, k × [uuid 16s, elen u32, err? | n u32, descs])
          ACK (ack_gen u64)            GETLOAD ()    LOAD (json)
          PING (n u32, descs)          PONG ()       ERROR (flags&1)

Reclamation: request slots are freed by the client when their reply
arrives (the doorbell is lock-step FIFO per connection, so the node is
provably done with them); reply slots are freed by the node when the
client's next frame acknowledges their generations (``ack_gen``
watermark — acks piggyback on every EVAL, and a trailing ACK frame at
the end of each pipelined window releases its final replies without
waiting for the next call).  Telemetry trace ids ride flag bit 2; the spans piggyback
lane is not implemented on this transport (the gRPC/TCP lanes carry
reunion; an shm node is by definition colocated and observable).

No reference-runtime analog: the reference wire is untouched — this is
a driver-local extension (docs/migrating.md).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import uuid as uuid_mod
import weakref
from functools import lru_cache
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from ..telemetry import spans as _spans
from ..telemetry import watchdog as _watchdog
from . import _node_metrics
from . import _rpc_metrics
from . import deadline as _deadline
from .arena import DEFAULT_ARENA_BYTES, Arena
from .batching import execute_window_sync as _execute_window_sync
from .npwire import (
    WIRE_BYTES_COPIED,
    WireError,
    _encode_dtype,
    _encode_tenant,
    _parse_dtype,
    fast_uuid,
    normalize_arrays,
    payload_view,
)
from .tcp import (
    RemoteComputeError,
    _recv_frame,
    _send_frame,
    serve_npwire_payload,
)

# The partition lane (ISSUE 13) — shard math + loud reassembly rules
# (routing/ never imports service/ at module level, so no cycle).
from ..routing import partition as _partition

__all__ = ["ShmArraysClient", "serve_shm"]

MAGIC = b"SHM1"

# Frame kinds — mirrored from service/wire_registry.py SHMWIRE_KINDS
# (the declared source; graftlint cross-checks).  Decoders REJECT an
# unknown kind: a doorbell peer must ship in lockstep.
_KIND_ATTACH = 1
_KIND_ATTACH_OK = 2
_KIND_EVAL = 3
_KIND_REPLY = 4
_KIND_EVAL_BATCH = 5
_KIND_REPLY_BATCH = 6
_KIND_ACK = 7
_KIND_GETLOAD = 8
_KIND_LOAD = 9
_KIND_PING = 10
_KIND_PONG = 11
_KIND_ERROR = 12

_KNOWN_KINDS = frozenset(range(_KIND_ATTACH, _KIND_ERROR + 1))

# Flag bits — mirrored from service/wire_registry.py SHMWIRE_FLAGS.
_FLAG_ERROR = 1
_FLAG_TRACE = 2
_FLAG_DEADLINE = 4
_FLAG_TENANT = 8
_FLAG_PARTITION = 16
_FLAG_VERSION = 32
_KNOWN_FLAGS = (
    _FLAG_ERROR | _FLAG_TRACE | _FLAG_DEADLINE | _FLAG_TENANT
    | _FLAG_PARTITION | _FLAG_VERSION
)
#: The gradient-partition index block (flag bit 16): same 32-byte
#: layout as the npwire block (wire_registry.PARTITION_STRUCT);
#: routing/partition.py owns the semantics.
_PARTITION_STRUCT = struct.Struct("<IIQQQ")
#: The step-version stamp (flag bit 32): one u64 after the partition
#: block (wire_registry.VERSION_STRUCT); optim/sharded.py owns the
#: semantics (zero is a meaningful stamp — presence is the flag).
_VERSION_STRUCT = struct.Struct("<Q")

_HEADER = struct.Struct("<4sBBBB16s")
#: The arena descriptor — layout declared as SHM_DESC_STRUCT in
#: service/wire_registry.py (field order: slot, delta, length,
#: generation).
_DESC_STRUCT = struct.Struct("<QIQQ")

_BATCH_CHUNK = 32  # requests per EVAL_BATCH frame (tcp.py parity)

# Preserialized packers (ISSUE-13 satellite): literal-format
# struct.pack re-parses the format string per call — hoisted out of
# the hot doorbell send/decode paths (the PR-10 _run_compute class).
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_QI = struct.Struct("<QI")
_U16 = struct.Struct("<H")
_U8 = struct.Struct("<B")
_F64 = struct.Struct("<d")
#: The empty descriptor block (n=0) — a constant on the reply paths.
_EMPTY_DESCS = _U32.pack(0)

_CALL_S = _rpc_metrics.CALL_S
_RETRIES = _rpc_metrics.RETRIES
_DROPS = _rpc_metrics.DROPS
_BATCH_S = _rpc_metrics.BATCH_S
_WINDOW_DEPTH = _rpc_metrics.WINDOW_DEPTH
_FRAME_REQS = _rpc_metrics.BATCH_FRAME_REQS
_SHM_DECODE_COPIED = WIRE_BYTES_COPIED.labels(lane="shm", stage="decode_copy")


def _check_flags(flags: int) -> None:
    """Reject undeclared flag bits loudly (loud-failure contract)."""
    unknown = flags & ~_KNOWN_FLAGS
    if unknown:
        raise WireError(
            f"unknown shm flag bits 0x{unknown:02x} "
            f"(known mask 0x{_KNOWN_FLAGS:02x}) — version-skewed peer?"
        )


def encode_frame(
    kind: int,
    uuid: bytes,
    body: bytes = b"",
    *,
    error: Optional[str] = None,
    trace_id: Optional[bytes] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> bytes:
    """One doorbell frame.  Descriptor-only — payload bytes NEVER ride
    the doorbell; they live in the arena.  ``deadline_s`` (flag bit 4)
    carries the request's remaining deadline budget in relative
    seconds (:mod:`.deadline`); ``tenant`` (flag bit 8) the gateway
    tier's per-tenant identity (u16-length utf8, non-empty);
    ``partition`` (flag bit 16) the gradient-partition index block (a
    5-int sequence — routing/partition.py owns the semantics);
    ``version`` (flag bit 32) the u64 step-version stamp
    (optim/sharded.py owns the semantics; zero is meaningful);
    ``None`` for any emits the pre-feature byte-identical frame."""
    if len(uuid) != 16:
        raise WireError(f"uuid must be 16 bytes, got {len(uuid)}")
    flags = 0
    if error is None and trace_id is None and deadline_s is None \
            and tenant is None and partition is None \
            and version is None:
        # Hot-path template (ISSUE-13 satellite): the flag-free frame
        # — every ACK/GETLOAD/PING and most steady-state EVALs — is a
        # prefix join, no per-block branching.
        out = _plain_prefix(kind) + uuid + body
        if _fi.active_plan is not None:  # chaos seam
            out = _fi.filter_bytes("shm.encode", out)
        return out
    parts: List[bytes] = []
    if error is not None:
        flags |= _FLAG_ERROR
    if trace_id is not None:
        if len(trace_id) != 16:
            raise WireError(
                f"trace_id must be 16 bytes, got {len(trace_id)}"
            )
        flags |= _FLAG_TRACE
    if deadline_s is not None:
        flags |= _FLAG_DEADLINE
    tenant_block = None
    if tenant is not None:
        # The block layout is byte-identical to npwire's by design —
        # one validator/encoder (npwire._encode_tenant) for both.
        tenant_block = _encode_tenant(tenant)
        flags |= _FLAG_TENANT
    partition_block = None
    if partition is not None:
        partition_block = _encode_partition_block(partition)
        flags |= _FLAG_PARTITION
    version_block = None
    if version is not None:
        version_block = _encode_version_block(version)
        flags |= _FLAG_VERSION
    parts.append(_HEADER.pack(MAGIC, 1, kind, flags, 0, uuid))
    if error is not None:
        err = error.encode("utf-8")
        parts.append(_U32.pack(len(err)))
        parts.append(err)
    if trace_id is not None:
        parts.append(trace_id)
    if deadline_s is not None:
        parts.append(_F64.pack(float(deadline_s)))
    if tenant_block is not None:
        parts.append(tenant_block)
    if partition_block is not None:
        parts.append(partition_block)
    if version_block is not None:
        parts.append(version_block)
    parts.append(body)
    out = b"".join(parts)
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        out = _fi.filter_bytes("shm.encode", out)
    return out


@lru_cache(maxsize=16)
def _plain_prefix(kind: int) -> bytes:
    """Preserialized ``MAGIC ver kind flags=0 pad`` header prefix for
    the flag-free fast path (the uuid follows it)."""
    return _HEADER.pack(MAGIC, 1, kind, 0, 0, b"\0" * 16)[: _HEADER.size - 16]


def _encode_partition_block(partition: Sequence[int]) -> bytes:
    """Validate + pack one partition block — delegated to the single
    validator (:func:`..routing.partition.pack_partition`), so the shm
    and npwire lanes cannot drift apart in what they refuse
    (``PartitionError`` is a ``WireError`` subclass, preserving the
    loud-failure classification)."""
    try:
        return _partition.pack_partition(
            tuple(int(v) for v in partition)
        )
    except WireError:
        raise
    except (TypeError, ValueError) as e:
        raise WireError(f"partition must be 5 ints: {e}") from None


def _encode_version_block(version: int) -> bytes:
    """Validate + pack one step-version block (flag bit 32) — the same
    u64 range check the npwire lane applies, so the two lanes cannot
    drift apart in what they refuse."""
    try:
        v = int(version)
    except (TypeError, ValueError) as e:
        raise WireError(f"version must be an int: {e}") from None
    if not 0 <= v < (1 << 64):
        raise WireError(f"version {v} outside u64 range")
    return _VERSION_STRUCT.pack(v)


def decode_frame(
    buf: bytes,
) -> Tuple[
    int,
    bytes,
    Optional[str],
    Optional[bytes],
    Optional[float],
    Optional[tuple],
    Optional[int],
    int,
    bytes,
]:
    """Decode a doorbell frame header -> ``(kind, uuid, error,
    trace_id, deadline_s, partition, version, body_offset, frame)``;
    kind-specific body parsing is the caller's, offset-based against
    the RETURNED ``frame`` (which is ``buf`` unless the chaos seam
    transformed it — parsing the original after a filtered header
    would silently mix two byte streams).  ``deadline_s`` is the
    remaining deadline budget off the wire (flag bit 4), ``None`` when
    unbounded; ``partition`` the gradient-partition block's 5-int
    tuple (flag bit 16, ``None`` when clear — routing/partition.py
    owns the semantics); ``version`` the u64 step-version stamp (flag
    bit 32, ``None`` when clear — zero is a meaningful stamp;
    optim/sharded.py owns the semantics)."""
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        buf = _fi.filter_bytes("shm.decode", buf)
    try:
        magic, version, kind, flags, _pad, uuid = _HEADER.unpack_from(buf, 0)
    except struct.error as e:
        raise WireError(f"truncated shm header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad shm magic {magic!r}")
    if version != 1:
        raise WireError(f"unsupported shm version {version}")
    if kind not in _KNOWN_KINDS:
        raise WireError(f"unknown shm frame kind {kind}")
    _check_flags(flags)
    off = _HEADER.size
    error = None
    if flags & _FLAG_ERROR:
        try:
            (elen,) = _U32.unpack_from(buf, off)
            off += 4
            if off + elen > len(buf):
                raise WireError("truncated shm error block")
            error = buf[off : off + elen].decode("utf-8")
            off += elen
        except (struct.error, UnicodeDecodeError) as e:
            raise WireError(f"truncated shm error block: {e}") from None
    trace_id = None
    if flags & _FLAG_TRACE:
        if off + 16 > len(buf):
            raise WireError("truncated shm trace block")
        trace_id = buf[off : off + 16]
        off += 16
    deadline_s = None
    if flags & _FLAG_DEADLINE:
        try:
            (deadline_s,) = _F64.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(f"truncated shm deadline block: {e}") from None
        off += 8
    if flags & _FLAG_TENANT:
        # Consumed and dropped — :func:`frame_tenant` is the reader.
        try:
            (tlen,) = _U16.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(f"truncated shm tenant block: {e}") from None
        off += 2
        if off + tlen > len(buf):
            raise WireError("truncated shm tenant block")
        off += tlen
    partition = None
    if flags & _FLAG_PARTITION:
        try:
            partition = _PARTITION_STRUCT.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(
                f"truncated shm partition block: {e}"
            ) from None
        off += _PARTITION_STRUCT.size
    step_version = None
    if flags & _FLAG_VERSION:
        try:
            (step_version,) = _VERSION_STRUCT.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(
                f"truncated shm version block: {e}"
            ) from None
        off += _VERSION_STRUCT.size
    return (
        kind, uuid, error, trace_id, deadline_s, partition,
        step_version, off, buf,
    )


def frame_tenant(buf: bytes) -> Optional[str]:
    """The doorbell frame's tenant id (flag bit 8), or ``None`` when
    the flag is clear — the shm twin of ``npwire.peek_tenant`` (walks
    the same leading blocks ``decode_frame`` does, without the chaos
    seam: a peek must not double-fire byte-lane rules)."""
    try:
        magic, version, _kind, flags, _pad, _uuid = _HEADER.unpack_from(
            buf, 0
        )
    except struct.error as e:
        raise WireError(f"truncated shm header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad shm magic {magic!r}")
    _check_flags(flags)
    if not flags & _FLAG_TENANT:
        return None
    off = _HEADER.size
    if flags & _FLAG_ERROR:
        try:
            (elen,) = _U32.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(f"truncated shm error block: {e}") from None
        off += 4 + elen
    if flags & _FLAG_TRACE:
        off += 16
    if flags & _FLAG_DEADLINE:
        off += 8
    try:
        (tlen,) = _U16.unpack_from(buf, off)
        off += 2
        if off + tlen > len(buf):
            raise WireError("truncated shm tenant block")
        return buf[off : off + tlen].decode("utf-8")
    except (struct.error, UnicodeDecodeError) as e:
        raise WireError(f"corrupt shm tenant block: {e}") from None


#: One decoded descriptor: (slot, delta, length, generation, dtype, shape).
Desc = Tuple[int, int, int, int, np.dtype, Tuple[int, ...]]


# graftlint: disable=fault-shim-coverage -- sub-frame helper: chaos reaches these bytes one frame up (encode_frame's shm.encode filter + the shm.descriptor seam)
def encode_descs(descs: Sequence[Desc]) -> bytes:
    """Descriptor block: ``n(u32)`` + one fixed struct + dtype/shape
    per array."""
    parts: List[bytes] = [_U32.pack(len(descs))]
    for slot, delta, length, gen, dtype, shape in descs:
        parts.append(_DESC_STRUCT.pack(slot, delta, length, gen))
        dt = _encode_dtype(dtype)
        parts.append(_U16.pack(len(dt)))
        parts.append(dt)
        parts.append(_U8.pack(len(shape)))
        parts.append(struct.pack(f"<{len(shape)}Q", *shape))
    return b"".join(parts)


# graftlint: disable=fault-shim-coverage -- sub-frame helper: chaos reaches these bytes one frame up (decode_frame's shm.decode filter + the shm.descriptor seam)
def decode_descs(buf: bytes, off: int) -> Tuple[List[Desc], int]:
    """Parse a descriptor block at ``off`` -> (descs, new_offset)."""
    try:
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        descs: List[Desc] = []
        for _ in range(n):
            slot, delta, length, gen = _DESC_STRUCT.unpack_from(buf, off)
            off += _DESC_STRUCT.size
            (dtlen,) = _U16.unpack_from(buf, off)
            off += 2
            dtype = _parse_dtype(buf[off : off + dtlen])
            off += dtlen
            (ndim,) = _U8.unpack_from(buf, off)
            off += 1
            shape = struct.unpack_from(f"<{ndim}Q", buf, off)
            off += 8 * ndim
            descs.append((slot, delta, length, gen, dtype, shape))
    except struct.error as e:
        raise WireError(f"truncated shm descriptor block: {e}") from None
    return descs, off


def _desc_region_offset(
    kind: int,
    trace_id: Optional[bytes],
    deadline_s: Optional[float] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> int:
    """Byte offset where an OUTGOING EVAL/EVAL_BATCH frame's
    descriptor region starts (ack watermark preserved — corrupting it
    would fault the RECLAMATION protocol, a different seam) — where
    the ``corrupt_descriptor`` chaos shim starts flipping."""
    off = (
        _HEADER.size
        + (16 if trace_id is not None else 0)
        + (8 if deadline_s is not None else 0)
        + (_PARTITION_STRUCT.size if partition is not None else 0)
        + (_VERSION_STRUCT.size if version is not None else 0)
    )
    if kind == _KIND_EVAL:
        return off + 8  # past ack_gen
    if kind == _KIND_EVAL_BATCH:
        return off + 12  # past ack_gen + item count
    return off


def _read_arena_array(
    arena: Arena, desc: Desc, *, copy: bool
) -> np.ndarray:
    """One descriptor -> numpy array.  ``copy=False`` is a READ-ONLY
    view straight onto the shared pages (validated head+tail before
    return); ``copy=True`` re-validates after the copy so a recycle
    landing mid-copy is detected before the bytes are believed."""
    slot, delta, length, gen, dtype, shape = desc
    view = arena.read_view(slot, delta, length, gen)
    if dtype.itemsize == 0 or length % dtype.itemsize:
        raise WireError(
            f"descriptor length {length} is not a multiple of "
            f"itemsize {dtype.itemsize}"
        )
    try:
        arr = np.frombuffer(
            view, dtype=dtype, count=length // dtype.itemsize
        ).reshape(shape)
    except ValueError as e:
        raise WireError(f"corrupt descriptor shape: {e}") from None
    if copy:
        arr = arr.copy()
        arena.read_view(slot, delta, length, gen)  # no recycle mid-copy
        _SHM_DECODE_COPIED.inc(length)
    else:
        # The mmap is writable; the VIEW must not be — a consumer
        # scribbling on shared pages would corrupt the peer's slot.
        arr.flags.writeable = False
    return arr


def _write_arrays(
    arena: Arena, arrays: Sequence[np.ndarray], *, pinned: bool = False
) -> Tuple[Optional[int], List[Desc]]:
    """Pack arrays into ONE fresh slot -> (slot, descriptors).  The
    single arena write is each payload byte's only copy."""
    if not arrays:
        return None, []
    arrays = normalize_arrays(arrays)
    slot, gen, deltas = arena.write_many(
        [payload_view(a) for a in arrays], pinned=pinned
    )
    descs: List[Desc] = [
        (slot, delta, a.nbytes, gen, a.dtype, tuple(a.shape))
        for a, delta in zip(arrays, deltas)
    ]
    return slot, descs


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class ShmArraysClient:
    """Arrays-in → arrays-out over a shared-memory arena pair plus one
    persistent doorbell connection.  Sync surface parity with
    :class:`~.tcp.TcpArraysClient` (``evaluate``, pipelined/batched
    ``evaluate_many``, ``evaluate_many_partial``, plus ``get_load`` and
    ``ping``), so the replica pool drives both interchangeably.

    ``pin_arrays`` (default True): an ndarray passed by the SAME object
    identity a second time is promoted to the arena's pinned region —
    written once, referenced by descriptor forever after (per-node data
    constants stop moving bytes entirely).  The contract is the jax
    one: arrays you pass repeatedly are treated as immutable; disable
    with ``pin_arrays=False`` if you mutate request arrays in place.

    ``copy`` (default True): reply arrays are copied out of the arena
    (writable, owned).  ``copy=False`` returns read-only views onto the
    shared pages for SINGLE ``evaluate`` calls — zero-copy, valid until
    the node recycles the reply slot, which the ack watermark defers
    until your NEXT call on this client.  ``evaluate_many`` always
    copies its replies: within a window, acks ride later frames of the
    same call, so a view of an early reply could be recycled before
    the call even returns."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        retries: int = 2,
        copy: bool = True,
        pin_arrays: bool = True,
        max_inflight_bytes: Optional[int] = None,
        connect_timeout_s: float = 30.0,
        connect_retries: int = 1,
        connect_backoff_s: float = 0.05,
        timeout_s: Optional[float] = None,
    ) -> None:
        """``timeout_s`` bounds each reply read; with an ambient
        deadline bound (:mod:`.deadline`) the read is capped at the
        REMAINING budget regardless, so a node that accepts then never
        replies fails over within the caller's deadline instead of
        blocking until the watchdog fires.  A fired bound closes the
        (desynchronized) doorbell and surfaces as ``TimeoutError`` —
        the transient classification, so pools fail the work over."""
        self.host = host
        self.port = int(port)
        self.retries = int(retries)
        self.copy = bool(copy)
        self.pin_arrays = bool(pin_arrays)
        self.max_inflight_bytes = max_inflight_bytes
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.connect_retries = int(connect_retries)
        self.connect_backoff_s = float(connect_backoff_s)
        self._sock: Optional[socket.socket] = None
        self._rfile: Optional[Any] = None
        self._req_arena: Optional[Arena] = None
        self._rep_arena: Optional[Arena] = None
        self._consumed_gen = 0  # ack watermark piggybacked on sends
        # id(array) -> (hit count, weakref) — the promotion trigger.
        # The weakref VERIFIES identity across calls: CPython recycles
        # ids of freed per-call arrays constantly (the fresh-params-
        # every-step pattern), and a bare id counter would promote
        # unrelated arrays into the never-reclaimed pinned region.
        # id(array) -> (descs-per-array entry, strong ref).
        self._pin_hits: Dict[int, Tuple[int, "weakref.ref[np.ndarray]"]] = {}
        self._pinned: Dict[int, Tuple[Desc, np.ndarray]] = {}
        # All-pinned request signature -> (array refs, encoded
        # descriptor block): the steady-state fast path — one dict hit
        # and identity checks instead of re-encoding the same
        # descriptors every item (refs keep ids stable).
        self._block_cache: Dict[
            Tuple[int, ...], Tuple[Tuple[np.ndarray, ...], bytes]
        ] = {}

    @property
    def _peer(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection / attach ----------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is None:
            last_err: Optional[Exception] = None
            for attempt in range(self.connect_retries + 1):
                if attempt:
                    time.sleep(self.connect_backoff_s)
                try:
                    s = socket.create_connection(
                        (self.host, self.port),
                        timeout=self.connect_timeout_s,
                    )
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
            else:
                raise ConnectionError(
                    f"connect to {self._peer} failed after "
                    f"{self.connect_retries + 1} attempts "
                    f"(timeout {self.connect_timeout_s}s)"
                ) from last_err
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            self._rfile = s.makefile("rb")
            try:
                self._attach()
            except BaseException:
                self.close()
                raise
        return self._sock

    def _attach(self) -> None:
        assert self._sock is not None
        uid = fast_uuid()
        self._send(encode_frame(_KIND_ATTACH, uid))
        kind, ruid, error, _tid, _dl, _part, _ver, off, frame = decode_frame(
            self._read_frame()
        )
        if error is not None:
            raise WireError(f"shm attach refused: {error}")
        if kind != _KIND_ATTACH_OK or ruid != uid:
            raise WireError("shm attach: unexpected reply")
        try:
            (jlen,) = _U32.unpack_from(frame, off)
            spec = json.loads(
                frame[off + 4 : off + 4 + jlen].decode("utf-8")
            )
            req_path, rep_path = spec["req"], spec["rep"]
        except (struct.error, ValueError, KeyError, UnicodeDecodeError) as e:
            raise WireError(f"corrupt shm attach reply: {e}") from None
        self._req_arena = Arena.attach(req_path, writer=True)
        self._rep_arena = Arena.attach(rep_path)
        self._consumed_gen = 0
        _flightrec.record(
            "shm.attach", peer=self._peer, req=req_path, rep=rep_path,
            size=self._req_arena.capacity,
        )

    def _send(self, frame: bytes) -> None:
        assert self._sock is not None
        if _fi.active_plan is not None:  # chaos seam
            _fi.send_frame_through(
                "shm.send", self._sock.sendall, frame, peer=self._peer
            )
        else:
            _send_frame(self._sock, frame)

    def _read_frame(self) -> bytes:
        # Bounded read: the per-call timeout_s knob and the ambient
        # deadline, whichever is tighter, as a TOTAL bound across the
        # header+payload chunks; posture (expired-budget close,
        # TimeoutError close, socket-timeout restore) is the shared
        # _deadline.bounded_reader so the doorbell and the TCP socket
        # lane cannot diverge.
        assert self._rfile is not None
        assert self._sock is not None
        with _deadline.bounded_reader(
            self._sock,
            self._rfile,
            _deadline.recv_budget_s(self.timeout_s),
            self.close,
        ) as read_exact:
            (n,) = _U32.unpack(read_exact(4))
            buf = read_exact(n)
        if _fi.active_plan is not None:  # chaos seam
            buf = _fi.filter_bytes("shm.recv", buf, self._peer)
        return buf

    def close(self) -> None:
        if self._sock is not None:
            try:
                if self._rfile is not None:
                    try:
                        self._rfile.close()
                    except OSError:
                        pass
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None
        for arena in (self._req_arena, self._rep_arena):
            if arena is not None:
                arena.close()
        self._req_arena = None
        self._rep_arena = None
        self._pin_hits.clear()
        self._pinned.clear()
        self._block_cache.clear()
        self._consumed_gen = 0

    def __del__(self) -> None:  # best-effort, mirrors tcp teardown
        try:
            self.close()
        except Exception:
            pass

    # -- request encoding --------------------------------------------------

    def _maybe_pinned_desc(self, a: np.ndarray) -> Optional[Desc]:
        """The pin cache: returns the array's pinned descriptor when
        the SAME object was written before (zero bytes moved), promotes
        an array on its second sighting, and returns ``None`` for the
        transient path.  A full pinned region degrades gracefully —
        correctness never depends on pinning."""
        if not self.pin_arrays:
            return None
        key = id(a)
        cached = self._pinned.get(key)
        if cached is not None:
            desc, ref = cached
            # An ndarray's data pointer is fixed for the object's
            # lifetime, so identity + size is the whole hit check.
            if ref is a and a.nbytes == desc[2]:
                return desc
            del self._pinned[key]  # invalidated: falls through
        entry = self._pin_hits.get(key)
        # Same id AND same (still-alive) object = a real repeat; a
        # dead/mismatched weakref is id reuse and resets the count.
        hits = 1 if entry is None or entry[1]() is not a else entry[0] + 1
        if hits < 2:
            if len(self._pin_hits) >= 4096:
                # Fresh-array-per-call workloads churn ids without
                # ever repeating: bound the tracker (cheap reset — a
                # genuine constant re-earns its two sightings).
                self._pin_hits.clear()
            self._pin_hits[key] = (hits, weakref.ref(a))
            return None
        self._pin_hits.pop(key, None)
        assert self._req_arena is not None
        try:
            _slot, (desc,) = _write_arrays(
                self._req_arena, [a], pinned=True
            )
        except WireError:
            return None
        self._pinned[key] = (desc, a)
        return desc

    def _encode_request(
        self, arrays: Sequence[np.ndarray]
    ) -> Tuple[List[Desc], Optional[int], int]:
        """Write one request's arrays -> (descriptors in order,
        transient slot to free on reply or None, TRANSIENT payload
        bytes — the in-flight byte-cap contribution; pinned arrays
        consume no ring space and count nothing).  Pinned arrays (same
        object seen before) reuse their existing descriptors — zero
        bytes moved; the rest pack into one fresh transient slot."""
        assert self._req_arena is not None
        descs: List[Optional[Desc]] = [None] * len(arrays)
        transient: List[Tuple[int, np.ndarray]] = []
        for i, raw in enumerate(arrays):
            a = np.asarray(raw)
            pinned = self._maybe_pinned_desc(a)
            if pinned is not None:
                descs[i] = pinned
            else:
                transient.append((i, a))
        slot: Optional[int] = None
        nbytes = 0
        if transient:
            slot, tdescs = _write_arrays(
                self._req_arena, [a for _i, a in transient]
            )
            tdescs = self._request_write_chaos(slot, tdescs)
            for (i, _a), desc in zip(transient, tdescs):
                descs[i] = desc
            nbytes = sum(d[2] for d in tdescs)
        return [d for d in descs if d is not None], slot, nbytes

    def _request_write_chaos(
        self, slot: Optional[int], descs: List[Desc]
    ) -> List[Desc]:
        """The ``shm.arena.write`` chaos seam — the CLIENT-side twin of
        the node's ``shm.arena.reply``: ``truncate_slot`` scribbles the
        request slot's tail (the node's read fails loudly, answered
        in-band), ``stale_generation`` ages the descriptors."""
        if _fi.active_plan is None:
            return descs
        fault = _fi.arena_fault("shm.arena.write", self._peer)
        if fault == "truncate_slot" and slot is not None:
            assert self._req_arena is not None
            self._req_arena.scribble_tail(slot)
        elif fault == "stale_generation":
            descs = [
                (s, d, ln, g + 1, dt, sh)
                for s, d, ln, g, dt, sh in descs
            ]
        return descs

    def _eval_body(self, descs: Sequence[Desc]) -> bytes:
        return _U64.pack(self._consumed_gen) + encode_descs(descs)

    def _apply_descriptor_chaos(
        self,
        frame: bytes,
        kind: int,
        trace_id: Optional[bytes],
        deadline_s: Optional[float] = None,
        partition: Optional[Sequence[int]] = None,
        version: Optional[int] = None,
    ) -> bytes:
        """The ``corrupt_descriptor`` chaos seam: flip bytes inside the
        descriptor block only (header corruption is ``corrupt_bytes``
        at the byte-lane points)."""
        if _fi.active_plan is None:
            return frame
        return _fi.corrupt_descriptor_bytes(
            "shm.descriptor", frame,
            _desc_region_offset(
                kind, trace_id, deadline_s, partition, version
            ),
            peer=self._peer,
        )

    # -- reply decoding ----------------------------------------------------

    def _decode_reply_arrays(
        self, descs: Sequence[Desc], *, force_copy: bool = False
    ) -> List[np.ndarray]:
        """``force_copy`` overrides ``copy=False`` inside pipelined
        windows: acks piggybacked on LATER frames of the same window
        let the node recycle reply slots that earlier results still
        view — zero-copy replies are only safe on the lock-step
        single-evaluate path, whose ack defers to the next call."""
        assert self._rep_arena is not None
        copy = self.copy or force_copy
        out = [
            _read_arena_array(self._rep_arena, d, copy=copy)
            for d in descs
        ]
        if descs:
            self._consumed_gen = max(
                self._consumed_gen, max(d[3] for d in descs)
            )
        return out

    def _free_transient(self, slot: Optional[int]) -> None:
        if slot is not None and self._req_arena is not None:
            self._req_arena.free(slot)

    # -- single evaluation -------------------------------------------------

    def evaluate(
        self,
        *arrays: np.ndarray,
        partition: Optional[Sequence[int]] = None,
    ) -> List[np.ndarray]:
        """One lock-step evaluation; ``partition`` (keyword-only)
        requests the head/tail SLICED reply, tcp.py-evaluate parity."""
        outputs, _ver = self._evaluate_inner(arrays, partition, None)
        return outputs

    def evaluate_versioned(
        self,
        *arrays: np.ndarray,
        partition: Optional[Sequence[int]] = None,
        version: int,
    ) -> Tuple[List[np.ndarray], Optional[int]]:
        """One VERSIONED round trip (the sharded-optimizer lane,
        ISSUE 16) -> ``(outputs, reply_version)`` —
        tcp.py-evaluate_versioned parity: the node's
        ``versioned_update`` handler answers shard-shaped outputs
        stamped with the NEW version; a stale stamp surfaces as
        :class:`RemoteComputeError` (optim/sharded.py classifies)."""
        return self._evaluate_inner(arrays, partition, version)

    def _evaluate_inner(
        self,
        arrays: Sequence[np.ndarray],
        partition: Optional[Sequence[int]],
        version: Optional[int],
    ) -> Tuple[List[np.ndarray], Optional[int]]:
        with _spans.span("rpc.evaluate", transport="shm"):
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.retry", transport="shm", attempt=attempt
                    )
                t0 = time.perf_counter()
                try:
                    with _spans.span("call"):
                        _deadline.check_remaining("shm evaluate")
                        self._connect()
                        with _spans.span("encode"):
                            uid = fast_uuid()
                            trace_id = (
                                _spans.current_trace_id()
                                if _spans.enabled()
                                else None
                            )
                            budget = _deadline.wire_budget()
                            descs, slot, _nb = self._encode_request(
                                arrays
                            )
                            frame = encode_frame(
                                _KIND_EVAL,
                                uid,
                                self._eval_body(descs),
                                trace_id=trace_id,
                                deadline_s=budget,
                                partition=partition,
                                version=version,
                            )
                            frame = self._apply_descriptor_chaos(
                                frame, _KIND_EVAL, trace_id, budget,
                                partition, version,
                            )
                        self._send(frame)
                        reply = self._read_frame()
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.drop", transport="shm", peer=self._peer
                    )
                    self.close()
            else:
                raise ConnectionError(
                    f"shm node {self._peer} unreachable after "
                    f"{self.retries + 1} attempts"
                ) from last_err
            with _spans.span("decode"):
                try:
                    outputs, reply_version = self._consume_reply(
                        reply, uid, return_version=True
                    )
                except (RemoteComputeError, _deadline.DeadlineExceeded):
                    # In-band server error (deadline sheds included):
                    # the connection is still correlated — free the
                    # request slot (the node is done with it) and
                    # surface the error, no close.
                    self._free_transient(slot)
                    raise
                except (WireError, RuntimeError):
                    # Corrupt or desynchronized reply: close so the
                    # NEXT call re-attaches cleanly; the error
                    # surfaces loudly (CLAUDE.md invariant).
                    _DROPS.labels(transport="shm").inc()
                    self.close()
                    raise
            self._free_transient(slot)
            _CALL_S.labels(transport="shm", mode="lockstep").observe(
                time.perf_counter() - t0
            )
            return outputs, reply_version

    __call__ = evaluate

    def _consume_reply(
        self,
        reply: bytes,
        uid: bytes,
        *,
        force_copy: bool = False,
        return_version: bool = False,
    ):
        kind, ruid, error, _tid, _dl, _part, _ver, off, reply = decode_frame(reply)
        if kind == _KIND_ERROR:
            raise WireError(f"shm protocol error from node: {error}")
        if kind != _KIND_REPLY:
            raise WireError(
                f"unexpected shm frame kind {kind} (wanted REPLY)"
            )
        if error is not None:
            _flightrec.record(
                "rpc.error", transport="shm", error=error[:200]
            )
            if _deadline.is_deadline_error(error):
                raise _deadline.DeadlineExceeded(error)
            raise RemoteComputeError(error)
        if ruid != uid:
            raise RuntimeError(
                "uuid mismatch: reply does not match request"
            )
        descs, _off = decode_descs(reply, off)
        outputs = self._decode_reply_arrays(descs, force_copy=force_copy)
        if return_version:
            return outputs, _ver
        return outputs

    # -- pipelined / batched windows ---------------------------------------

    def _inflight_cap(self) -> int:
        if self.max_inflight_bytes is not None:
            return int(self.max_inflight_bytes)
        assert self._req_arena is not None
        # The doorbell cannot deadlock on payload bytes (they do not
        # ride it); the cap guards the ARENA — keep in-flight request
        # bytes under half the free transient region so the ring never
        # exhausts mid-window.
        return max(self._req_arena.transient_bytes_free() // 2, 1)

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Pipelined evaluation over the lock-step doorbell; same
        semantics as the TCP lane (FIFO correlation, all-or-nothing
        transport retry, deterministic errors raise after a drain).
        ``batch`` "auto"/True packs ``min(window, 32)`` requests per
        EVAL_BATCH frame — the shm lane always supports batch frames,
        so "auto" and True are equivalent; False sends per-request
        EVAL frames."""
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        requests = list(requests)
        if not requests:
            return []
        with _spans.span(
            "rpc.evaluate_many",
            transport="shm",
            n=len(requests),
            window=window,
        ):
            t0 = time.perf_counter()
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.retry", transport="shm", attempt=attempt,
                        batch=len(requests),
                    )
                try:
                    with _watchdog.armed(
                        "shm.batch_window", n=len(requests), window=window
                    ):
                        if batch is False:
                            results = self._evaluate_many_once(
                                requests, window
                            )
                        else:
                            results = self._evaluate_many_batched_once(
                                requests, window
                            )
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.drop", transport="shm", peer=self._peer
                    )
                    self.close()
                    continue
                except WireError:
                    # Client-side arena failure (e.g. exhaustion) with
                    # frames possibly in flight: the doorbell is
                    # desynchronized — close so the NEXT call starts
                    # clean, and surface the error (deterministic, no
                    # retry).  Reply-decode WireErrors already closed;
                    # close() is idempotent.
                    _DROPS.labels(transport="shm").inc()
                    self.close()
                    raise
                _BATCH_S.labels(transport="shm").observe(
                    time.perf_counter() - t0
                )
                return results
            raise ConnectionError(
                f"shm node {self._peer} unreachable after "
                f"{self.retries + 1} attempts"
            ) from last_err

    def evaluate_many_partial(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> Tuple[List[Optional[List[np.ndarray]]], Optional[BaseException]]:
        """ONE pipelined pass, no reconnect-retry, partial progress:
        ``(results_with_None_holes, transport_exc_or_None)`` — the
        replica pool's mid-window failover primitive (tcp.py parity)."""
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        requests = list(requests)
        if not requests:
            return [], None
        out: List[Optional[List[np.ndarray]]] = [None] * len(requests)
        with _spans.span(
            "rpc.evaluate_many",
            transport="shm",
            n=len(requests),
            window=window,
            partial=True,
        ):
            t0 = time.perf_counter()
            try:
                with _watchdog.armed(
                    "shm.batch_window", n=len(requests), window=window
                ):
                    if batch is False:
                        self._evaluate_many_once(requests, window, out=out)
                    else:
                        self._evaluate_many_batched_once(
                            requests, window, out=out
                        )
            except (ConnectionError, OSError) as e:
                _DROPS.labels(transport="shm").inc()
                _flightrec.record(
                    "rpc.drop", transport="shm", peer=self._peer
                )
                self.close()
                return out, e
            except WireError:
                _DROPS.labels(transport="shm").inc()
                self.close()  # desynchronized mid-window: start clean
                raise
            _BATCH_S.labels(transport="shm").observe(
                time.perf_counter() - t0
            )
            return out, None

    def evaluate_reduced(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        slices: int = 1,
        total: Optional[int] = None,
    ) -> List[np.ndarray]:
        """Reduce-scatter evaluation over the doorbell:
        ``[head_sum, flat_tail_sum]`` — the shm twin of
        :meth:`~.tcp.TcpArraysClient.evaluate_reduced` (same window
        semantics, partition blocks on the doorbell's flag bit 16,
        reply slices in index order under the outer uuid)."""
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if slices < 1:
            raise ValueError(f"slices must be >= 1, got {slices}")
        requests = list(requests)
        if not requests:
            raise _partition.PartitionError(
                "cannot reduce an empty request list"
            )
        with _spans.span(
            "rpc.evaluate_reduced",
            transport="shm",
            n=len(requests),
            slices=slices,
        ):
            t0 = time.perf_counter()
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.retry", transport="shm", attempt=attempt,
                        batch=len(requests),
                    )
                    _deadline.check_remaining("shm reduce retry")
                try:
                    with _watchdog.armed(
                        "shm.reduce_window",
                        n=len(requests),
                        window=window,
                    ):
                        result = self._evaluate_reduced_once(
                            requests, window, slices, total
                        )
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="shm").inc()
                    _flightrec.record(
                        "rpc.drop", transport="shm", peer=self._peer
                    )
                    self.close()
                    continue
                except WireError:
                    _DROPS.labels(transport="shm").inc()
                    self.close()
                    raise
                _BATCH_S.labels(transport="shm").observe(
                    time.perf_counter() - t0
                )
                return result
            raise ConnectionError(
                f"shm node {self._peer} unreachable after "
                f"{self.retries + 1} attempts"
            ) from last_err

    def _evaluate_reduced_once(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        window: int,
        slices: int,
        total: Optional[int],
    ) -> List[np.ndarray]:
        self._connect()
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        chunk = max(1, min(window, _BATCH_CHUNK))
        req_part = (0, slices, 0, 0, 0 if total is None else int(total))
        head: Optional[np.ndarray] = None
        flat: Optional[np.ndarray] = None
        # Lock-step per frame: reduce replies are tiny (one tail per
        # frame regardless of window width), so one-in-flight keeps
        # the drain/reclaim story trivial (tcp.py twin's rationale).
        for start in range(0, len(requests), chunk):
            part_reqs = requests[start : start + chunk]
            outer_uuid = fast_uuid()
            budget = _deadline.wire_budget()
            slots: List[Optional[int]] = []
            item_parts: List[bytes] = []
            for req in part_reqs:
                descs, slot, _nb = self._encode_request(req)
                slots.append(slot)
                item_parts.append(fast_uuid() + encode_descs(descs))
            body = (
                _QI.pack(self._consumed_gen, len(part_reqs))
                + b"".join(item_parts)
            )
            frame = encode_frame(
                _KIND_EVAL_BATCH, outer_uuid, body,
                trace_id=trace_id, deadline_s=budget,
                partition=req_part,
            )
            _FRAME_REQS.labels(transport="shm").observe(len(part_reqs))
            self._send(frame)
            reply = self._read_frame()
            try:
                f_head, f_flat = self._consume_reduce_reply(
                    reply, outer_uuid, slices, total
                )
            except (RemoteComputeError, _deadline.DeadlineExceeded):
                # In-band failure: connection stays correlated — free
                # the frame's slots (the node is done) and surface.
                for slot in slots:
                    self._free_transient(slot)
                raise
            except (WireError, RuntimeError):
                _DROPS.labels(transport="shm").inc()
                self.close()
                raise
            for slot in slots:
                self._free_transient(slot)
            if head is None:
                head, flat = f_head, f_flat
            else:
                if (
                    f_head.shape != head.shape
                    or f_flat.size != flat.size
                ):
                    self.close()
                    raise WireError(
                        "reduce frames disagree on reply geometry"
                    )
                head = head + f_head
                flat = flat + f_flat
        self._send_ack()
        assert head is not None and flat is not None
        return [head, flat]

    def _consume_reduce_reply(
        self,
        reply: bytes,
        outer_uuid: bytes,
        slices: int,
        total: Optional[int],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One REPLY_BATCH reduce reply -> (head_sum, flat_vector);
        items arrive in partition-index order under the outer uuid
        (the doorbell framing has no per-item partition blocks — both
        ends derive the same plan from (total, count))."""
        kind, ruid, outer_err, _tid, _dl, rpart, _ver, off, reply = (
            decode_frame(reply)
        )
        if kind == _KIND_ERROR:
            raise WireError(f"shm protocol error from node: {outer_err}")
        if kind != _KIND_REPLY_BATCH:
            raise WireError(
                f"unexpected shm frame kind {kind} (wanted REPLY_BATCH)"
            )
        if outer_err is not None:
            if _deadline.is_deadline_error(outer_err):
                raise _deadline.DeadlineExceeded(outer_err)
            raise RemoteComputeError(outer_err)
        if ruid != outer_uuid:
            raise RuntimeError(
                "batch reply does not correlate with its frame"
            )
        if rpart is None:
            raise _partition.PartitionError(
                "reduce reply carries no partition block"
            )
        _i, count, _o, _l, r_total = rpart
        if count != slices or (
            total is not None and r_total != int(total)
        ):
            raise _partition.PartitionError(
                f"reduce reply geometry ({count}, {r_total}) does not "
                f"match the request ({slices}, {total})"
            )
        try:
            (k,) = _U32.unpack_from(reply, off)
            off += 4
        except struct.error as e:
            raise WireError(
                f"truncated shm reduce reply: {e}"
            ) from None
        if k != slices:
            raise _partition.PartitionError(
                f"reduce reply carries {k} slices, requested {slices}"
            )
        plan = _partition.plan_partitions(r_total, slices)
        head: Optional[np.ndarray] = None
        reassembler: Optional[_partition.Reassembler] = None
        for j in range(k):
            iuid = reply[off : off + 16]
            if len(iuid) != 16:
                raise WireError("truncated shm batch item")
            off += 16
            try:
                (elen,) = _U32.unpack_from(reply, off)
            except struct.error as e:
                raise WireError(
                    f"truncated shm batch item: {e}"
                ) from None
            off += 4
            if elen:
                if off + elen > len(reply):
                    raise WireError("truncated shm batch item error")
                raise RemoteComputeError(
                    reply[off : off + elen].decode("utf-8", "replace")
                )
            descs, off = decode_descs(reply, off)
            # Identity = outer uuid + partition index (see the server's
            # construction): catches duplicated or reordered slices
            # even when their lengths agree with the plan.
            if iuid != outer_uuid[:12] + _U32.pack(j):
                raise _partition.PartitionError(
                    "reduce item identity mismatch (duplicated, "
                    "dropped, or reordered shard)"
                )
            arrays = self._decode_reply_arrays(descs, force_copy=True)
            if j == 0:
                if len(arrays) != 2:
                    raise _partition.PartitionError(
                        "reduce reply item 0 must be [head, slice]"
                    )
                head = arrays[0]
                slice_arr = arrays[1]
            else:
                if len(arrays) != 1:
                    raise _partition.PartitionError(
                        "reduce reply items 1.. must be [slice]"
                    )
                slice_arr = arrays[0]
            if reassembler is None:
                reassembler = _partition.Reassembler(
                    r_total,
                    slices,
                    np.asarray(slice_arr).dtype
                    if np.asarray(slice_arr).size
                    else np.dtype(np.float64),
                )
            reassembler.add(
                plan[j], np.asarray(slice_arr), iuid=iuid.hex()
            )
        assert reassembler is not None and head is not None
        return head, reassembler.result()

    def _evaluate_many_once(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        window: int,
        out: Optional[List[Optional[List[np.ndarray]]]] = None,
    ) -> List[Optional[List[np.ndarray]]]:
        self._connect()
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        n = len(requests)
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )
        max_inflight = self._inflight_cap()
        pending: List[Tuple[bytes, Optional[int], int]] = []  # uid, slot, bytes
        write_idx = read_idx = 0
        inflight_bytes = 0

        def write_one(i: int) -> int:
            uid = fast_uuid()
            # Transient bytes only: pinned descriptors consume no ring
            # space, and counting them would throttle a pinned
            # workload to lock-step depth (the byte cap guards the
            # ARENA, which only transient slots occupy).
            budget = _deadline.wire_budget()
            descs, slot, nbytes = self._encode_request(requests[i])
            frame = encode_frame(
                _KIND_EVAL, uid, self._eval_body(descs),
                trace_id=trace_id, deadline_s=budget,
            )
            frame = self._apply_descriptor_chaos(
                frame, _KIND_EVAL, trace_id, budget
            )
            self._send(frame)
            pending.append((uid, slot, nbytes))
            return nbytes

        while read_idx < n:
            while write_idx < n and (
                write_idx == read_idx
                or (
                    write_idx - read_idx < window
                    and inflight_bytes < max_inflight
                )
            ):
                inflight_bytes += write_one(write_idx)
                write_idx += 1
            _WINDOW_DEPTH.labels(transport="shm").observe(
                write_idx - read_idx
            )
            reply = self._read_frame()
            uid, slot, nbytes = pending[read_idx]
            inflight_bytes -= nbytes
            try:
                outputs = self._consume_reply(reply, uid, force_copy=True)
            except (RemoteComputeError, _deadline.DeadlineExceeded):
                # Drain in-flight replies so the connection stays
                # correlated for the NEXT call, then surface the
                # deterministic error (no retry) — tcp.py semantics.
                # Deadline sheds are in-band too: the node answered,
                # the connection is healthy.
                try:
                    for _ in range(write_idx - read_idx - 1):
                        self._read_frame()
                except (ConnectionError, OSError):
                    _DROPS.labels(transport="shm").inc()
                    self.close()
                else:
                    self._drain_free(pending, read_idx, write_idx)
                raise
            except (WireError, RuntimeError):
                _DROPS.labels(transport="shm").inc()
                self.close()
                raise
            self._free_transient(slot)
            results[read_idx] = outputs
            read_idx += 1
        self._send_ack()
        return results

    def _send_ack(self) -> None:
        """Fire-and-forget ACK of the consumed-generation watermark —
        sent at the end of a pipelined window so the node reclaims the
        window's final reply slots NOW instead of at this client's
        next call (window replies are always copied, so no view
        outlives the release).  Best-effort: a dead socket surfaces on
        the next call's own path."""
        if self._sock is None:
            return
        try:
            self._send(
                encode_frame(
                    _KIND_ACK,
                    fast_uuid(),
                    _U64.pack(self._consumed_gen),
                )
            )
        except (ConnectionError, OSError):
            self.close()

    def _drain_free(
        self,
        pending: Sequence[Tuple[bytes, Optional[int], int]],
        read_idx: int,
        write_idx: int,
    ) -> None:
        """After a drain, free the transient slots of the drained
        requests (FIFO order: the erroring one first, then the rest)."""
        for k in range(read_idx, write_idx):
            self._free_transient(pending[k][1])

    def _evaluate_many_batched_once(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        window: int,
        out: Optional[List[Optional[List[np.ndarray]]]] = None,
    ) -> List[Optional[List[np.ndarray]]]:
        self._connect()
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        n = len(requests)
        chunk = max(1, min(window, _BATCH_CHUNK))
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )
        # (outer_uuid, start, item_uids, transient_slot)
        frames: List[Tuple[bytes, int, List[bytes], Optional[int]]] = []
        max_inflight = self._inflight_cap()
        starts = list(range(0, n, chunk))
        write_idx = read_idx = 0
        inflight: List[int] = []  # request bytes per in-flight frame
        # Frames in flight are capped too: replies consume the NODE's
        # arena until the next frame's ack reclaims them, and an
        # all-pinned workload carries zero in-flight request bytes —
        # the byte cap alone would let the whole request list launch
        # and the unacked replies exhaust the reply arena.
        max_frames = max(2, window // chunk)
        while read_idx < len(starts):
            while write_idx < len(starts) and (
                write_idx == read_idx
                or (
                    write_idx - read_idx < max_frames
                    and sum(inflight) < max_inflight
                )
            ):
                start = starts[write_idx]
                part = requests[start : start + chunk]
                outer_uuid = fast_uuid()
                item_uids: List[bytes] = []
                item_blocks: List[Optional[bytes]] = []
                hole_descs: Dict[int, List[Optional[Desc]]] = {}
                # All transient arrays of the whole frame pack into ONE
                # slot: per-frame FIFO reclamation, one arena write;
                # pinned (repeat-identity) arrays move zero bytes, and
                # an all-pinned request reuses its whole encoded
                # descriptor block from the signature cache.
                flat: List[np.ndarray] = []
                holes: List[Tuple[int, int, int]] = []  # item, pos, flat
                for req in part:
                    item_uids.append(fast_uuid())
                    arrays = [np.asarray(raw) for raw in req]
                    key: Optional[Tuple[int, ...]] = None
                    if self.pin_arrays:
                        key = tuple(map(id, arrays))
                        hit = self._block_cache.get(key)
                        if hit is not None and all(
                            r is a for r, a in zip(hit[0], arrays)
                        ):
                            item_blocks.append(hit[1])
                            continue
                    descs: List[Optional[Desc]] = []
                    has_hole = False
                    for a in arrays:
                        pinned = self._maybe_pinned_desc(a)
                        if pinned is None:
                            holes.append(
                                (len(item_blocks), len(descs), len(flat))
                            )
                            flat.append(a)
                            descs.append(None)
                            has_hole = True
                        else:
                            descs.append(pinned)
                    if has_hole:
                        hole_descs[len(item_blocks)] = descs
                        item_blocks.append(None)
                    else:
                        block = encode_descs(descs)
                        if key is not None:
                            if len(self._block_cache) >= 512:
                                # id-tuple churn from fresh-array
                                # workloads: bound the cache.
                                self._block_cache.clear()
                            self._block_cache[key] = (
                                tuple(arrays), block
                            )
                        item_blocks.append(block)
                slot: Optional[int] = None
                nbytes = 0
                if flat:
                    assert self._req_arena is not None
                    slot, tdescs = _write_arrays(self._req_arena, flat)
                    tdescs = self._request_write_chaos(slot, tdescs)
                    nbytes = sum(d[2] for d in tdescs)
                    for item_i, pos, flat_i in holes:
                        hole_descs[item_i][pos] = tdescs[flat_i]
                for item_i, descs in hole_descs.items():
                    item_blocks[item_i] = encode_descs(
                        [d for d in descs if d is not None]
                    )
                body = (
                    _QI.pack(self._consumed_gen, len(part))
                    + b"".join(
                        uid + block
                        for uid, block in zip(item_uids, item_blocks)
                    )
                )
                budget = _deadline.wire_budget()
                frame = encode_frame(
                    _KIND_EVAL_BATCH, outer_uuid, body,
                    trace_id=trace_id, deadline_s=budget,
                )
                frame = self._apply_descriptor_chaos(
                    frame, _KIND_EVAL_BATCH, trace_id, budget
                )
                _FRAME_REQS.labels(transport="shm").observe(len(part))
                self._send(frame)
                frames.append((outer_uuid, start, item_uids, slot))
                inflight.append(nbytes)
                write_idx += 1
            _WINDOW_DEPTH.labels(transport="shm").observe(
                write_idx - read_idx
            )
            reply = self._read_frame()
            outer_uuid, start, item_uids, slot = frames[read_idx]
            inflight.pop(0)
            first_error: Optional[str] = None
            try:
                kind, ruid, outer_err, _tid, _dl, _part, _ver, off, reply = decode_frame(
                    reply
                )
                if kind == _KIND_ERROR:
                    raise WireError(
                        f"shm protocol error from node: {outer_err}"
                    )
                if kind != _KIND_REPLY_BATCH:
                    raise WireError(
                        f"unexpected shm frame kind {kind} "
                        "(wanted REPLY_BATCH)"
                    )
                first_error = outer_err
                if first_error is None and ruid != outer_uuid:
                    raise RuntimeError(
                        "batch reply does not correlate with its frame"
                    )
                if first_error is None:
                    (k,) = _U32.unpack_from(reply, off)
                    off += 4
                    if k != len(item_uids):
                        raise RuntimeError(
                            "batch reply does not correlate with its "
                            "frame"
                        )
                    for j in range(k):
                        iuid = reply[off : off + 16]
                        if len(iuid) != 16:
                            raise WireError("truncated shm batch item")
                        off += 16
                        try:
                            (elen,) = _U32.unpack_from(reply, off)
                        except struct.error as e:
                            raise WireError(
                                f"truncated shm batch item: {e}"
                            ) from None
                        off += 4
                        if elen:
                            if off + elen > len(reply):
                                raise WireError(
                                    "truncated shm batch item error"
                                )
                            err = reply[off : off + elen].decode(
                                "utf-8", "replace"
                            )
                            off += elen
                            if first_error is None:
                                first_error = err
                            continue
                        descs, off = decode_descs(reply, off)
                        if iuid != item_uids[j]:
                            raise RuntimeError(
                                "uuid mismatch: batch item does not "
                                "match its request"
                            )
                        if first_error is None:
                            results[start + j] = (
                                self._decode_reply_arrays(
                                    descs, force_copy=True
                                )
                            )
            except struct.error as e:
                # Truncated batch reply: classify as WireError (the
                # loud-failure contract) — a raw struct.error would
                # escape every handler and leave the doorbell
                # desynchronized (round-9 review finding).
                _DROPS.labels(transport="shm").inc()
                self.close()
                raise WireError(
                    f"truncated shm batch reply: {e}"
                ) from None
            except (WireError, RuntimeError):
                _DROPS.labels(transport="shm").inc()
                self.close()
                raise
            if first_error is not None:
                try:
                    for _ in range(write_idx - read_idx - 1):
                        self._read_frame()
                except (ConnectionError, OSError):
                    _DROPS.labels(transport="shm").inc()
                    self.close()
                else:
                    for k2 in range(read_idx, write_idx):
                        self._free_transient(frames[k2][3])
                if _deadline.is_deadline_error(first_error):
                    raise _deadline.DeadlineExceeded(first_error)
                raise RemoteComputeError(first_error)
            self._free_transient(slot)
            read_idx += 1
        self._send_ack()
        return results

    # -- control lanes ------------------------------------------------------

    def get_load(self) -> Optional[dict]:
        """The node's load dict over the doorbell (GETLOAD/LOAD) —
        ``None`` on an undecodable reply (probe-lane verdict)."""
        self._connect()
        uid = fast_uuid()
        self._send(encode_frame(_KIND_GETLOAD, uid))
        reply = self._read_frame()
        try:
            kind, ruid, error, _tid, _dl, _part, _ver, off, reply = decode_frame(reply)
            if kind != _KIND_LOAD or ruid != uid or error is not None:
                return None
            (jlen,) = _U32.unpack_from(reply, off)
            load = json.loads(
                reply[off + 4 : off + 4 + jlen].decode("utf-8")
            )
            return load if isinstance(load, dict) else None
        # A garbled LOAD reply is a FAILED PROBE — None is this lane's
        # loud in-band verdict, same posture as the GetLoad probe.
        except Exception:  # graftlint: disable=wire-loudness -- probe verdict lane
            return None

    def ping(self) -> float:
        """Doorbell round-trip seconds with one EMPTY arena write —
        the shm lane's idle-overhead probe (bench.py ``shm_overhead``
        gate): arena slot write + descriptor frame + node-side slot
        validation + reply, no compute."""
        self._connect()
        assert self._req_arena is not None
        t0 = time.perf_counter()
        slot, descs = _write_arrays(
            self._req_arena, [np.empty(0, np.uint8)]
        )
        uid = fast_uuid()
        self._send(
            encode_frame(_KIND_PING, uid, encode_descs(descs))
        )
        try:
            kind, ruid, error, _tid, _dl, _part, _ver, _off, _frame = decode_frame(
                self._read_frame()
            )
            if kind != _KIND_PONG or ruid != uid:
                raise WireError("shm ping: unexpected reply")
        except (WireError, RuntimeError):
            # Undecodable/desynchronized reply: close so the NEXT call
            # re-attaches cleanly — leaving the ping's transient slot
            # live would poison the FIFO free order forever.
            _DROPS.labels(transport="shm").inc()
            self.close()
            raise
        self._free_transient(slot)
        if error is not None:
            raise WireError(f"shm ping failed on the node: {error}")
        return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


def _load_dict(n_connections: int, transport: str = "shm") -> dict:
    return {
        "n_clients": n_connections,
        "transport": transport,
        "batch": {"max_batch": _BATCH_CHUNK, "queue_depth": 0},
    }


class _ShmConnection:
    """Server half of one doorbell connection: the arena pair, the
    reply-slot reclamation watermark, and the frame dispatch loop."""

    #: What GetLoad reports; the ring lane overrides to "ring".
    _transport = "shm"

    def __init__(
        self,
        conn: socket.socket,
        compute_fn: Callable[..., Sequence[np.ndarray]],
        arena_bytes: int,
        n_connections: Callable[[], int],
    ) -> None:
        self.conn = conn
        self.compute_fn = compute_fn
        self.arena_bytes = arena_bytes
        self.n_connections = n_connections
        self.req_arena: Optional[Arena] = None
        self.rep_arena: Optional[Arena] = None
        self._unlinked = False
        self._live_replies: List[Tuple[int, int]] = []  # (gen, slot)

    # -- arena plumbing ----------------------------------------------------

    def _attach_reply(self, uid: bytes) -> bytes:
        if self.req_arena is None:
            self.req_arena = Arena.create(self.arena_bytes, writer=False)
            self.rep_arena = Arena.create(self.arena_bytes, writer=True)
        spec = json.dumps(
            {
                "req": self.req_arena.path,
                "rep": self.rep_arena.path,
                "size": self.req_arena.capacity,
                "arena_id": uuid_mod.uuid4().hex,
            }
        ).encode("utf-8")
        return encode_frame(
            _KIND_ATTACH_OK, uid, _U32.pack(len(spec)) + spec
        )

    def _unlink_arenas(self) -> None:
        """The peer has proven it mapped the files (it sent a
        post-attach frame): unlink NOW so a SIGKILL'd process leaks
        nothing in /dev/shm."""
        if not self._unlinked and self.req_arena is not None:
            import os as _os

            for arena in (self.req_arena, self.rep_arena):
                if arena is not None:
                    try:
                        _os.unlink(arena.path)
                    except OSError:
                        pass
            self._unlinked = True

    def _reclaim(self, ack_gen: int) -> None:
        """Free reply slots the client acknowledged (FIFO: generations
        are allocation-ordered)."""
        assert self.rep_arena is not None
        while self._live_replies and self._live_replies[0][0] <= ack_gen:
            _gen, slot = self._live_replies.pop(0)
            self.rep_arena.free(slot)

    def _write_reply_arrays(
        self, arrays: Sequence[np.ndarray]
    ) -> List[Desc]:
        assert self.rep_arena is not None
        slot, descs = _write_arrays(self.rep_arena, arrays)
        if _fi.active_plan is not None:  # chaos seam: arena write
            fault = _fi.arena_fault("shm.arena.reply")
            if fault == "truncate_slot" and slot is not None:
                self.rep_arena.scribble_tail(slot)
            elif fault == "stale_generation":
                descs = [
                    (s, d, ln, g + 1, dt, sh)
                    for s, d, ln, g, dt, sh in descs
                ]
        if descs:
            self._live_replies.append((descs[0][3], descs[0][0]))
        elif slot is not None:
            self._live_replies.append((0, slot))
        return descs

    def _request_arrays(self, descs: Sequence[Desc]) -> List[np.ndarray]:
        assert self.req_arena is not None
        # copy=False: the node computes straight on the shared pages —
        # the zero-copy read that is this lane's whole point; the
        # client must not recycle until the reply (FIFO protocol), and
        # if it does anyway the generation check above fails loudly.
        return [
            _read_arena_array(self.req_arena, d, copy=False)
            for d in descs
        ]

    # -- dispatch ----------------------------------------------------------

    def serve(self) -> None:
        conn = self.conn
        try:
            with conn:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        payload = _recv_frame(conn)
                    except (ConnectionError, OSError):
                        break
                    if _fi.active_plan is not None:  # chaos seam
                        try:
                            payload = _fi.filter_bytes(
                                "shm.server.recv", payload
                            )
                        except (ConnectionError, OSError):
                            break
                    try:
                        reply = self._one_frame(payload)
                    except _fi.FaultPlanError:
                        raise  # plan-authoring bug: LOUD, not in-band
                    except Exception as e:
                        # An undecodable doorbell frame fails ITS reply
                        # in-band; the connection keeps serving.
                        _flightrec.record(
                            "server.error", stage="decode",
                            wire="shm", transport="shm",
                            error=str(e)[:200],
                        )
                        reply = encode_frame(
                            _KIND_ERROR, b"\0" * 16, error=str(e)
                        )
                    if reply is None:
                        continue
                    try:
                        if _fi.active_plan is not None:  # chaos seam
                            _fi.send_frame_through(
                                "shm.server.send", conn.sendall, reply
                            )
                        else:
                            _send_frame(conn, reply)
                    except (ConnectionError, OSError):
                        break
        finally:
            for arena in (self.req_arena, self.rep_arena):
                if arena is not None:
                    arena.close(unlink=not self._unlinked)

    def _one_frame(self, payload: bytes) -> Optional[bytes]:
        if payload[:4] != MAGIC:
            # npwire fallback lane: the pool's zero-item batch probe,
            # or a plain-frame peer — served with full parity.
            return serve_npwire_payload(
                self.compute_fn, payload, transport="shm"
            )
        (
            kind, uid, _err, trace_id, deadline_s, partition,
            step_version, off, payload,
        ) = decode_frame(
            payload
        )
        if kind == _KIND_ATTACH:
            return self._attach_reply(uid)
        if self.req_arena is None:
            return encode_frame(
                _KIND_ERROR, uid, error="shm frame before ATTACH"
            )
        self._unlink_arenas()
        if kind in (_KIND_EVAL, _KIND_EVAL_BATCH):
            # Admission enforcement: an expired budget is answered in
            # band and never computed (service/deadline.py vocabulary).
            err = _deadline.shed_expired_admission(
                deadline_s, transport="shm"
            )
            if err is not None:
                if kind == _KIND_EVAL:
                    return encode_frame(
                        _KIND_REPLY, uid, encode_descs([]), error=err
                    )
                return encode_frame(
                    _KIND_REPLY_BATCH, uid,
                    _U32.pack(0), error=err,
                )
            _node_metrics.INFLIGHT.inc()
            try:
                with _deadline.budget_scope(deadline_s):
                    if kind == _KIND_EVAL:
                        return self._serve_eval(
                            payload, uid, trace_id, off,
                            partition=partition,
                            version=step_version,
                        )
                    if partition is not None:
                        # Outer partition on a batch frame = a REDUCE
                        # window (routing/partition.py).
                        return self._serve_eval_reduce(
                            payload, uid, trace_id, off, partition
                        )
                    return self._serve_eval_batch(
                        payload, uid, trace_id, off
                    )
            finally:
                _node_metrics.INFLIGHT.dec()
        if kind == _KIND_ACK:
            try:
                (ack,) = _U64.unpack_from(payload, off)
            except struct.error as e:
                raise WireError(f"truncated shm ack: {e}") from None
            self._reclaim(ack)
            return None
        if kind == _KIND_GETLOAD:
            if _fi.active_plan is not None:  # chaos seam: getload lane
                garbage = _fi.getload_filter("shm.server.getload")
                if garbage is not None:
                    return encode_frame(
                        _KIND_LOAD, uid,
                        _U32.pack(len(garbage)) + garbage,
                    )
            spec = json.dumps(
                _load_dict(self.n_connections(), self._transport)
            ).encode("utf-8")
            return encode_frame(
                _KIND_LOAD, uid, _U32.pack(len(spec)) + spec
            )
        if kind == _KIND_PING:
            try:
                descs, _off = decode_descs(payload, off)
                for d in descs:
                    _read_arena_array(self.req_arena, d, copy=False)
            except WireError as e:
                return encode_frame(_KIND_PONG, uid, error=str(e))
            return encode_frame(_KIND_PONG, uid)
        return encode_frame(
            _KIND_ERROR, uid, error=f"unexpected shm frame kind {kind}"
        )

    def _serve_eval(
        self,
        payload: bytes,
        uid: bytes,
        trace_id: Optional[bytes],
        off: int,
        partition: Optional[tuple] = None,
        version: Optional[int] = None,
    ) -> bytes:
        # Same pftpu_server_* families as the gRPC/TCP lanes
        # (_node_metrics) so an shm node aggregates in the fleet view.
        _node_metrics.REQUESTS.labels(method="evaluate").inc()
        t_arrive = time.perf_counter()
        try:
            (ack,) = _U64.unpack_from(payload, off)
            self._reclaim(ack)
            descs, _off = decode_descs(payload, off + 8)
            arrays = self._request_arrays(descs)
        except WireError as e:
            _node_metrics.ERRORS.labels(kind="decode").inc()
            _flightrec.record(
                "server.error", stage="decode", wire="shm",
                transport="shm", error=str(e)[:200],
            )
            return encode_frame(
                _KIND_REPLY, uid, encode_descs([]),
                error=f"decode error: {e}",
            )
        t_decoded = time.perf_counter()
        _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate", wire="shm", transport="shm"
        ) as root:
            root.set_attr("decode_s", t_decoded - t_arrive)
            reply_version: Optional[int] = None
            try:
                if _fi.active_plan is not None:  # chaos seam
                    _fi.compute_filter("shm.compute")
                with _spans.span("compute") as c_span:
                    t_c0 = time.perf_counter()
                    queue_wait = max(0.0, t_c0 - t_decoded)
                    _node_metrics.QUEUE_S.observe(queue_wait)
                    c_span.set_attr("queue_wait_s", queue_wait)
                    if version is not None:
                        # Versioned sharded-optimizer lane (ISSUE 16;
                        # tcp.py has the twin dispatch): the handler
                        # owns slicing/versioning, the reply carries
                        # the NEW stamp.
                        handler = getattr(
                            self.compute_fn, "versioned_update", None
                        )
                        if handler is None:
                            raise WireError(
                                "versioned request (flag bit 32) but"
                                " this node's compute has no"
                                " versioned_update handler"
                            )
                        outputs, reply_version = handler(
                            arrays, partition, version
                        )
                        outputs = [np.asarray(o) for o in outputs]
                    else:
                        outputs = [
                            np.asarray(o)
                            for o in self.compute_fn(*arrays)
                        ]
                    _node_metrics.COMPUTE_S.observe(
                        time.perf_counter() - t_c0
                    )
                if partition is not None and version is None:
                    # Sliced reply (routing/partition.py head/tail
                    # rule); geometry disagreement is loud, in-band.
                    outputs = _partition.slice_reply(
                        outputs, _partition.GradPartition(*partition)
                    )
                with _spans.span("encode"):
                    t_e0 = time.perf_counter()
                    rdescs = self._write_reply_arrays(outputs)
                    _node_metrics.ENCODE_S.observe(
                        time.perf_counter() - t_e0
                    )
            except _fi.FaultPlanError:
                raise  # plan-authoring bug: LOUD, never in-band
            except Exception as e:
                _node_metrics.ERRORS.labels(kind="compute").inc()
                _flightrec.record(
                    "server.error", stage="compute", wire="shm",
                    transport="shm", error=str(e)[:200],
                )
                return encode_frame(
                    _KIND_REPLY, uid, encode_descs([]), error=str(e)
                )
        return encode_frame(
            _KIND_REPLY, uid, encode_descs(rdescs), partition=partition,
            version=reply_version,
        )

    def _serve_eval_batch(
        self,
        payload: bytes,
        uid: bytes,
        trace_id: Optional[bytes],
        off: int,
    ) -> bytes:
        _node_metrics.REQUESTS.labels(method="evaluate_batch").inc()
        t_arrive = time.perf_counter()
        try:
            ack, k = _QI.unpack_from(payload, off)
            self._reclaim(ack)
            off += 12
            items: List[Tuple[bytes, Optional[List[Desc]], Optional[str]]] = []
            for _ in range(k):
                iuid = payload[off : off + 16]
                if len(iuid) != 16:
                    raise WireError("truncated shm batch item")
                off += 16
                try:
                    descs, off = decode_descs(payload, off)
                except WireError as e:
                    # Frame-structure damage: cannot resync to later
                    # items — the whole frame fails (outer error).
                    raise WireError(f"batch item: {e}") from None
                items.append((iuid, descs, None))
        except (WireError, struct.error) as e:
            _node_metrics.ERRORS.labels(kind="decode").inc()
            return encode_frame(
                _KIND_REPLY_BATCH, b"\0" * 16,
                _U32.pack(0),
                error=f"decode error: {e}",
            )
        t_decoded = time.perf_counter()
        _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate_batch", wire="shm", transport="shm", n_items=k
        ):
            if _fi.active_plan is not None:  # chaos seam: compute path
                try:
                    _fi.compute_filter("shm.compute")
                except _fi.FaultPlanError:
                    raise
                except Exception as e:
                    return encode_frame(
                        _KIND_REPLY_BATCH, uid,
                        struct.pack("<I", 0), error=str(e),
                    )
            decoded: List[Tuple[int, List[np.ndarray], bytes]] = []
            item_errors: List[Optional[str]] = [None] * k
            t_i0 = time.perf_counter()
            for i, (iuid, descs, _e) in enumerate(items):
                try:
                    arrays = self._request_arrays(descs or [])
                    decoded.append((i, arrays, iuid))
                except WireError as e:
                    _node_metrics.ERRORS.labels(kind="decode").inc()
                    item_errors[i] = f"decode error: {e}"
            # Per-item arena reads are decode, not queue wait — same
            # attribution rule as the TCP batch lane, so the fleet
            # view names the right stage.
            item_decode_s = time.perf_counter() - t_i0
            _node_metrics.DECODE_S.observe(item_decode_s)
            batch_fn = getattr(self.compute_fn, "batch", None)
            t_c0 = time.perf_counter()
            _node_metrics.QUEUE_S.observe(
                max(0.0, t_c0 - t_decoded - item_decode_s)
            )
            outcomes = _execute_window_sync(
                self.compute_fn,
                batch_fn,
                [arrs for _i, arrs, _u in decoded],
            )
            _node_metrics.COMPUTE_S.observe(time.perf_counter() - t_c0)
            t_e0 = time.perf_counter()
            item_replies: List[bytes] = []
            outcome_by_slot: Dict[int, object] = {
                i: res for (i, _a, _u), res in zip(decoded, outcomes)
            }
            # All reply arrays of the whole batch pack into ONE arena
            # slot — one write, per-item descriptors carve it up.
            flat_outputs: List[np.ndarray] = []
            flat_plan: List[Tuple[int, int, List[np.ndarray]]] = []
            for i in range(k):
                res = outcome_by_slot.get(i)
                if item_errors[i] is not None or res is None:
                    continue
                if isinstance(res, Exception):
                    _node_metrics.ERRORS.labels(kind="compute").inc()
                    _flightrec.record(
                        "server.error", stage="compute", wire="shm",
                        transport="shm", error=str(res)[:200],
                    )
                    item_errors[i] = str(res)
                    continue
                outs = [np.asarray(o) for o in res]
                flat_plan.append((i, len(flat_outputs), outs))
                flat_outputs.extend(outs)
            all_descs: List[Desc] = []
            if flat_outputs:
                all_descs = self._write_reply_arrays(flat_outputs)
            descs_by_item: Dict[int, List[Desc]] = {}
            for i, begin, outs in flat_plan:
                descs_by_item[i] = all_descs[begin : begin + len(outs)]
            for i, (iuid, _d, _e) in enumerate(items):
                err = item_errors[i]
                if err is not None:
                    eb = err.encode("utf-8")
                    item_replies.append(
                        iuid + _U32.pack(len(eb)) + eb
                    )
                else:
                    item_replies.append(
                        iuid
                        + _U32.pack(0)
                        + encode_descs(descs_by_item.get(i, []))
                    )
        body = _U32.pack(k) + b"".join(item_replies)
        _node_metrics.ENCODE_S.observe(time.perf_counter() - t_e0)
        return encode_frame(_KIND_REPLY_BATCH, uid, body)

    def _serve_eval_reduce(
        self,
        payload: bytes,
        uid: bytes,
        trace_id: Optional[bytes],
        off: int,
        partition: tuple,
    ) -> bytes:
        """One REDUCE window over the doorbell (EVAL_BATCH + outer
        partition block): sum the items' replies (head whole, tails
        flat-concatenated — routing/partition.py), answer the sum as
        ``count`` partition-indexed REPLY_BATCH items in INDEX ORDER
        (item 0 = [head, slice 0], items 1.. = [slice i]; the doorbell
        item framing has no per-item flag bits, so order+outer-echo IS
        the correlation — both ends derive the same plan from
        ``(total, count)``).  All-or-nothing: any item failure fails
        the window in-band (no silent partial sums)."""
        _node_metrics.REQUESTS.labels(method="evaluate_reduce").inc()
        t_arrive = time.perf_counter()

        def outer_error(err: str) -> bytes:
            return encode_frame(
                _KIND_REPLY_BATCH, uid, _U32.pack(0), error=err
            )

        try:
            req_part = _partition.GradPartition(*partition).validate()
            ack, k = _QI.unpack_from(payload, off)
            self._reclaim(ack)
            off += 12
            windows: List[List[np.ndarray]] = []
            for _ in range(k):
                iuid = payload[off : off + 16]
                if len(iuid) != 16:
                    raise WireError("truncated shm batch item")
                off += 16
                descs, off = decode_descs(payload, off)
                windows.append(self._request_arrays(descs))
        except (WireError, struct.error) as e:
            _node_metrics.ERRORS.labels(kind="decode").inc()
            return outer_error(f"decode error: {e}")
        t_decoded = time.perf_counter()
        _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate_reduce", wire="shm", transport="shm",
            n_items=k, count=req_part.count,
        ):
            if _fi.active_plan is not None:  # chaos seam: compute path
                try:
                    _fi.compute_filter("shm.compute")
                except _fi.FaultPlanError:
                    raise
                except Exception as e:
                    return outer_error(str(e))
            try:
                if not windows:
                    raise _partition.PartitionError(
                        "cannot reduce an empty window"
                    )
                reduce_fn = getattr(self.compute_fn, "reduce", None)
                t_c0 = time.perf_counter()
                _node_metrics.QUEUE_S.observe(max(0.0, t_c0 - t_decoded))
                if reduce_fn is not None:
                    summed = [np.asarray(o) for o in reduce_fn(windows)]
                else:
                    outcomes = _execute_window_sync(
                        self.compute_fn,
                        getattr(self.compute_fn, "batch", None),
                        windows,
                    )
                    for res in outcomes:
                        if isinstance(res, Exception):
                            raise res
                    summed = _partition.reduce_replies(outcomes)
                _node_metrics.COMPUTE_S.observe(
                    time.perf_counter() - t_c0
                )
                _layout, total, _dtype = _partition.tail_layout(summed)
                if req_part.total and req_part.total != total:
                    raise _partition.PartitionError(
                        f"partition total {req_part.total} != window "
                        f"tail size {total} (driver/node shape "
                        "disagreement)"
                    )
                t_e0 = time.perf_counter()
                plan = _partition.plan_partitions(total, req_part.count)
                flat = _partition.concat_tail(summed)
                # All slices (plus the head) pack into ONE arena slot;
                # per-item descriptors carve it up, items in index
                # order under the outer uuid.
                flat_outputs: List[np.ndarray] = [np.asarray(summed[0])]
                for p in plan:
                    flat_outputs.append(
                        flat[p.offset : p.offset + p.length]
                    )
                all_descs = self._write_reply_arrays(flat_outputs)
                item_replies: List[bytes] = []
                for j, p in enumerate(plan):
                    descs = [all_descs[1 + j]]
                    if j == 0:
                        descs.insert(0, all_descs[0])
                    # Item identity = outer uuid + the partition index
                    # (doorbell items carry no per-item flag blocks):
                    # a duplicated/reordered slice — even one of equal
                    # length — fails the client's identity check
                    # loudly instead of reassembling silently wrong.
                    item_replies.append(
                        uid[:12]
                        + _U32.pack(p.index)
                        + _U32.pack(0)
                        + encode_descs(descs)
                    )
                    _partition.PARTITION_SHARDS.labels(
                        outcome="ok"
                    ).inc()
                if _fi.active_plan is not None:  # chaos seam: shards
                    item_replies = _fi.shard_filter(
                        "partition.reply", item_replies, block_off=20
                    )
                body = _U32.pack(len(item_replies)) + b"".join(
                    item_replies
                )
                _node_metrics.ENCODE_S.observe(
                    time.perf_counter() - t_e0
                )
                return encode_frame(
                    _KIND_REPLY_BATCH,
                    uid,
                    body,
                    partition=_partition.GradPartition(
                        0, req_part.count, 0, total, total
                    ),
                )
            except _fi.FaultPlanError:
                raise  # plan-authoring bug: LOUD, never in-band
            except Exception as e:
                if isinstance(e, _partition.PartitionError):
                    _partition.PARTITION_SHARDS.labels(
                        outcome="error"
                    ).inc()
                _node_metrics.ERRORS.labels(kind="compute").inc()
                _flightrec.record(
                    "server.error", stage="reduce", wire="shm",
                    transport="shm", error=str(e)[:200],
                )
                return outer_error(str(e))


def serve_shm(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ready_callback: Optional[Callable[[int], None]] = None,
    max_connections: Optional[int] = None,
    arena_bytes: int = DEFAULT_ARENA_BYTES,
    concurrent: bool = True,
    _connection_cls: Optional[Callable[..., "_ShmConnection"]] = None,
) -> None:
    """Blocking shm-lane node: doorbell accept loop + one arena pair
    per connection.  Mirrors :func:`~.tcp.serve_tcp_once`'s surface
    (``port=0`` + ``ready_callback``, ``max_connections``,
    ``concurrent``); also answers plain npwire frames, so pool probes
    and npwire peers work without knowing about arenas.  Corrupt
    frames and bad descriptors are answered in-band — a hostile or
    chaos-mangled request must never tear down the node.

    Contract: ``compute_fn`` receives READ-ONLY zero-copy views of the
    request arrays (they ARE the shared pages — that is the lane); a
    compute that mutates its inputs in place must copy first (or serve
    over :func:`~.tcp.serve_tcp_once`, whose default decodes owned
    copies).

    ``_connection_cls`` is a private seam for the ring lane
    (:func:`~.ring.serve_ring`): a factory with ``_ShmConnection``'s
    constructor signature that supplies the per-connection handler."""
    active = [0]
    lock = threading.Lock()
    conn_cls = _ShmConnection if _connection_cls is None else _connection_cls

    def n_connections() -> int:
        with lock:
            return active[0]

    def run(conn: socket.socket) -> None:
        with lock:
            active[0] += 1
        try:
            conn_cls(
                conn, compute_fn, arena_bytes, n_connections
            ).serve()
        finally:
            with lock:
                active[0] -= 1

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        if ready_callback is not None:
            ready_callback(srv.getsockname()[1])
        served = 0
        while max_connections is None or served < max_connections:
            conn, _ = srv.accept()
            served += 1
            if concurrent:
                threading.Thread(
                    target=run, args=(conn,), daemon=True
                ).start()
            else:
                run(conn)
