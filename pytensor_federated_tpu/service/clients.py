"""Modeling-signature client adapters over the generic transport client.

Parity with the reference's L3 client adapters
(reference: common.py:52-161): reshape the flat arrays reply into the
logp / (logp, grads) signatures, sync and async.  These are what plugs
into :func:`pytensor_federated_tpu.blackbox_logp_grad` /
:class:`~pytensor_federated_tpu.ParallelLogpGrad` to make a *remote*
federated node differentiable inside a JAX graph.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .client import ArraysToArraysServiceClient, HostPort


class LogpServiceClient:
    """Remote node returning a scalar logp (reference: common.py:52-102)."""

    def __init__(self, *args, **kwargs):
        self._client = ArraysToArraysServiceClient(*args, **kwargs)

    @staticmethod
    def _check_reply(outputs) -> np.ndarray:
        """The node's shape contract, single-sourced for the sync and
        batch paths."""
        if len(outputs) != 1:
            raise RuntimeError(
                f"logp node must return exactly one array, got {len(outputs)}"
            )
        logp = outputs[0]
        if np.shape(logp) != ():
            raise RuntimeError(f"logp must be scalar, got shape {np.shape(logp)}")
        return logp

    async def evaluate_async(self, *inputs: np.ndarray) -> np.ndarray:
        return self._check_reply(
            await self._client.evaluate_async(*inputs)
        )

    def evaluate(self, *inputs: np.ndarray) -> np.ndarray:
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(self.evaluate_async(*inputs))

    async def evaluate_many_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[np.ndarray]:
        """Pipelined batch of logp evaluations (one scalar each) —
        :meth:`ArraysToArraysServiceClient.evaluate_many_async` with
        this adapter's shape contract applied per reply.  The batch
        shape fits vectorized consumers (SMC particle weights, ensemble
        proposals) that score many points against one node.  ``batch``
        forwards to the transport client: "auto" coalesces the window
        into wire batch frames when the server advertises support."""
        batches = await self._client.evaluate_many_async(
            requests, window=window, batch=batch
        )
        return [self._check_reply(outputs) for outputs in batches]

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[np.ndarray]:
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(
            self.evaluate_many_async(requests, window=window, batch=batch)
        )

    __call__ = evaluate


class LogpGradServiceClient:
    """Remote node returning (logp, grads) (reference: common.py:105-161)."""

    def __init__(self, *args, **kwargs):
        self._client = ArraysToArraysServiceClient(*args, **kwargs)

    @staticmethod
    def _check_reply(outputs, n_inputs) -> Tuple[np.ndarray, List[np.ndarray]]:
        """The node's shape contract, single-sourced for the sync and
        batch paths."""
        if len(outputs) != 1 + n_inputs:
            raise RuntimeError(
                f"logp+grad node must return 1 + {n_inputs} arrays, "
                f"got {len(outputs)}"
            )
        logp, *grads = outputs
        if np.shape(logp) != ():
            raise RuntimeError(f"logp must be scalar, got shape {np.shape(logp)}")
        return logp, grads

    async def evaluate_async(
        self, *inputs: np.ndarray
    ) -> Tuple[np.ndarray, List[np.ndarray]]:
        return self._check_reply(
            await self._client.evaluate_async(*inputs), len(inputs)
        )

    def evaluate(self, *inputs):
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(self.evaluate_async(*inputs))

    async def evaluate_many_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[Tuple[np.ndarray, List[np.ndarray]]]:
        """Pipelined batch of (logp, grads) evaluations — see
        :meth:`LogpServiceClient.evaluate_many_async`."""
        # Materialize BEFORE forwarding: a one-shot iterable would be
        # consumed by the inner client's encode pass and the zip below
        # would silently drop every result.
        requests = list(requests)
        batches = await self._client.evaluate_many_async(
            requests, window=window, batch=batch
        )
        return [
            self._check_reply(outputs, len(args))
            for args, outputs in zip(requests, batches)
        ]

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[Tuple[np.ndarray, List[np.ndarray]]]:
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(
            self.evaluate_many_async(requests, window=window, batch=batch)
        )

    __call__ = evaluate
