"""Modeling-signature client adapters over the generic transport client.

Parity with the reference's L3 client adapters
(reference: common.py:52-161): reshape the flat arrays reply into the
logp / (logp, grads) signatures, sync and async.  These are what plugs
into :func:`pytensor_federated_tpu.blackbox_logp_grad` /
:class:`~pytensor_federated_tpu.ParallelLogpGrad` to make a *remote*
federated node differentiable inside a JAX graph.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .client import ArraysToArraysServiceClient, HostPort


class LogpServiceClient:
    """Remote node returning a scalar logp (reference: common.py:52-102)."""

    def __init__(self, *args, **kwargs):
        self._client = ArraysToArraysServiceClient(*args, **kwargs)

    async def evaluate_async(self, *inputs: np.ndarray) -> np.ndarray:
        outputs = await self._client.evaluate_async(*inputs)
        if len(outputs) != 1:
            raise RuntimeError(
                f"logp node must return exactly one array, got {len(outputs)}"
            )
        logp = outputs[0]
        if np.shape(logp) != ():
            raise RuntimeError(f"logp must be scalar, got shape {np.shape(logp)}")
        return logp

    def evaluate(self, *inputs: np.ndarray) -> np.ndarray:
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(self.evaluate_async(*inputs))

    __call__ = evaluate


class LogpGradServiceClient:
    """Remote node returning (logp, grads) (reference: common.py:105-161)."""

    def __init__(self, *args, **kwargs):
        self._client = ArraysToArraysServiceClient(*args, **kwargs)

    async def evaluate_async(
        self, *inputs: np.ndarray
    ) -> Tuple[np.ndarray, List[np.ndarray]]:
        outputs = await self._client.evaluate_async(*inputs)
        if len(outputs) != 1 + len(inputs):
            raise RuntimeError(
                f"logp+grad node must return 1 + {len(inputs)} arrays, "
                f"got {len(outputs)}"
            )
        logp, *grads = outputs
        if np.shape(logp) != ():
            raise RuntimeError(f"logp must be scalar, got shape {np.shape(logp)}")
        return logp, grads

    def evaluate(self, *inputs):
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(self.evaluate_async(*inputs))

    __call__ = evaluate
