"""Zero-syscall colocated lane: seqlock'd descriptor rings in the arena.

The shm lane (:mod:`.shm`) moved PAYLOAD bytes out of the kernel, but
every evaluate still pays a TCP doorbell round-trip (~66 µs in this
container) just to exchange a DESCRIPTOR frame.  This module embeds two
fixed-capacity SPSC descriptor rings in the version-2 arena mapping
(:mod:`.arena`) — a submission ring in the request arena (client
produces, node consumes) and a completion ring in the reply arena (node
produces, client consumes) — so a steady-state evaluate moves both the
request and the reply descriptor frames through shared memory with
**zero syscalls**: both ends spin a bounded, adaptive budget and only
then park on a futex word (a small ``ctypes`` syscall shim with a
pure-Python ``threading.Event``/sleep-poll fallback), and the producer
issues a ``FUTEX_WAKE`` only when the consumer has declared itself
parked via the waiting word.

Ring layout (constants declared in :mod:`.wire_registry`, cross-checked
by the graftlint wire-registry rule).  The 64-byte ring header lives at
arena offset 64, the records at offset 128::

  header: produced(u64) consumed(u64) futex(u32) waiting(u32)
          epoch(u32) capacity(u32) record_bytes(u32)
  record: seq(u64) length(u32) reserved(u32) payload(record_bytes-16)

Seqlock protocol — the arena slot-generation discipline, applied to
ring records so torn, stale, recycled, and out-of-bounds reads stay
loud :class:`~.npwire.WireError`\\ s (CLAUDE.md wire invariant):

- the record at ring position ``p`` (``slot = p % capacity``) is
  stamped ``2p+1`` before its payload is written (mid-write) and
  ``2p+2`` after (committed), so sequences increase monotonically
  across wraparound laps and a recycled or scribbled record is
  DETECTABLE, never silently re-read;
- a consumer at position ``p`` accepts exactly ``2p+2``; a lower
  same-slot sequence (older lap, or mid-write) means *wait*; any other
  value — a future lap, a wrong-slot residue, a zero after the first
  lap — raises ``WireError``;
- after copying a record's payload the sequence is RE-checked, so a
  recycle landing mid-copy is detected before the bytes are believed.

Frames larger than one record's payload span K consecutive records
(record 0 carries the TOTAL length; continuations their chunk length).
The producer commits records in order and publishes ``produced`` once
after all K; the consumer keys readiness off record 0 and waits
bounded for continuations — a producer dying mid-span surfaces as a
classified ``TimeoutError``, never a hang.

Liveness: the ring's PRODUCER owns the epoch word (stamped 1 by the
arena creator's :func:`init_ring_header`, zeroed on clean close with a
final wake), so a parked consumer observes peer departure; abrupt death
(SIGKILL) is covered by the bounded park slice plus the client's
doorbell EOF probe — a dead peer is a classified transient
(``ConnectionError``), never a hang (the PR-10 posture).  The TCP
doorbell remains the attach channel, the fallback when a ring is full
or a frame cannot fit, and the pool-probe lane: a ring-attached socket
still answers plain npwire frames unchanged.

Header words are read/written as aligned 8/4-byte stores through the
shared mapping; on every supported platform those are single-copy
atomic in practice, and the seqlock re-check converts any torn read
into a loud retry or ``WireError`` rather than silent corruption.
"""

from __future__ import annotations

import ctypes
import json
import math
import platform
import select
import socket
import struct
import threading
import time
import uuid as uuid_mod
from collections import deque
from typing import Callable, Deque, Dict, Optional, Sequence, Set, Tuple

import numpy as np

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from . import deadline as _deadline
from .arena import DEFAULT_ARENA_BYTES, Arena
from .npwire import WireError, fast_uuid
from .shm import (
    MAGIC,
    ShmArraysClient,
    _KIND_ACK,
    _KIND_ATTACH,
    _KIND_ATTACH_OK,
    _KIND_ERROR,
    _ShmConnection,
    _U32,
    _U64,
    decode_frame,
    encode_frame,
    serve_shm,
)

__all__ = [
    "Ring",
    "RingArraysClient",
    "serve_ring",
    "init_ring_header",
    "futex_available",
    "syscall_counts",
    "reset_syscall_counts",
    "DEFAULT_RING_SLOTS",
    "DEFAULT_RING_RECORD_BYTES",
]

# Ring constants — mirrored from service/wire_registry.py (the declared
# source; graftlint's wire-registry rule cross-checks these literals).
_RING_HEADER_STRUCT = struct.Struct("<QQIIIII")
_RING_DESC_STRUCT = struct.Struct("<QII")
_RING_HEADER_OFFSET = 64
_RING_RECORDS_OFFSET = 128
_RING_FUTEX_WORD_OFFSET = 16
_RING_WAITING_WORD_OFFSET = 20
_RING_EPOCH_WORD_OFFSET = 24

# Absolute word offsets inside the mapping (header base + field).
_PRODUCED_OFF = _RING_HEADER_OFFSET
_CONSUMED_OFF = _RING_HEADER_OFFSET + 8
_FUTEX_OFF = _RING_HEADER_OFFSET + _RING_FUTEX_WORD_OFFSET
_WAITING_OFF = _RING_HEADER_OFFSET + _RING_WAITING_WORD_OFFSET
_EPOCH_OFF = _RING_HEADER_OFFSET + _RING_EPOCH_WORD_OFFSET
_CAPACITY_OFF = _RING_HEADER_OFFSET + 28
_RECORD_BYTES_OFF = _RING_HEADER_OFFSET + 32

_RECORD_HEADER_BYTES = _RING_DESC_STRUCT.size  # seq + length + reserved
_LEN_STRUCT = struct.Struct("<II")  # length, reserved (record header tail)
_U32S = struct.Struct("<I")

#: Default ring geometry: 64 records x 4 KiB = 256 KiB of descriptor
#: space per direction — descriptor frames are small (payloads live in
#: the arena slots), so 64 in-flight frames outruns any pipelined
#: window the byte cap admits.
DEFAULT_RING_SLOTS = 64
DEFAULT_RING_RECORD_BYTES = 4096

#: Maximum single futex park before re-checking liveness (closing flag,
#: epoch word, peer probe, ambient deadline): a dead peer that never
#: wakes us is detected within one slice, never hung on.
_PARK_SLICE_S = 0.05
#: Producer backoff while the completion ring is full (server side —
#: the same-channel reply rule forbids switching a ring reply to TCP).
_PRODUCE_POLL_S = 0.0005
#: Server-side bound on producing one reply into a full completion
#: ring; a client that stopped draining for this long is gone.
_REPLY_PRODUCE_TIMEOUT_S = 60.0

# ---------------------------------------------------------------------------
# futex shim (+ syscall accounting)
# ---------------------------------------------------------------------------

_FUTEX_WAIT = 0
_FUTEX_WAKE = 1
# Per-arch syscall numbers; futexes on a SHARED mapping are keyed on
# (inode, offset), so no FUTEX_PRIVATE_FLAG — the wait/wake pair works
# across processes and across two mappings of the same file.
_FUTEX_NR = {
    "x86_64": 202,
    "aarch64": 98,
    "riscv64": 98,
    "i386": 240,
    "i686": 240,
    "armv7l": 240,
}.get(platform.machine())

#: Instrumented syscall accounting: every kernel entry this lane can
#: make on the descriptor path goes through the shim below, so
#: ``syscall_counts()`` IS the steady-state syscalls/eval measurement
#: (strace is not available in this container; bench/suite corroborate
#: with getrusage voluntary-context-switch deltas).
_syscall_counts: Dict[str, int] = {
    "futex_wait": 0,
    "futex_wake": 0,
    "fallback_poll": 0,
}


def syscall_counts() -> Dict[str, int]:
    """Snapshot of the ring lane's wait/wake syscall counters."""
    return dict(_syscall_counts)


def reset_syscall_counts() -> None:
    for k in _syscall_counts:
        _syscall_counts[k] = 0


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


_libc: Optional[ctypes.CDLL] = None
_futex_broken = False


def _get_libc() -> Optional[ctypes.CDLL]:
    global _libc, _futex_broken
    if _libc is None and not _futex_broken:
        try:
            lib = ctypes.CDLL(None, use_errno=True)
            lib.syscall  # probe: raises AttributeError on exotic libcs
            _libc = lib
        except (OSError, AttributeError):
            _futex_broken = True
    return _libc


def futex_available() -> bool:
    """True when the real futex syscall shim is usable on this
    platform; False routes waits through the pure-Python fallback
    (same-process ``threading.Event`` + bounded cross-process poll)."""
    return _FUTEX_NR is not None and _get_libc() is not None


# Same-process fallback wake channel, keyed by (arena path, word
# offset): both mappings of one arena share the event.  A peer in a
# DIFFERENT process never sees the event — its waits degrade to the
# bounded <=2 ms poll, which is slow but correct.
_event_registry: Dict[Tuple[str, int], threading.Event] = {}
_event_lock = threading.Lock()


def _fallback_event(path: str, off: int) -> threading.Event:
    with _event_lock:
        ev = _event_registry.get((path, off))
        if ev is None:
            ev = threading.Event()
            _event_registry[(path, off)] = ev
        return ev


# ---------------------------------------------------------------------------
# ring header init + the Ring
# ---------------------------------------------------------------------------


def init_ring_header(arena: Arena) -> None:
    """Stamp a freshly created version-2 arena's ring header: zeroed
    counters, epoch 1, and the geometry words mirroring the arena file
    header.  CREATOR side, exactly once, BEFORE the peer attaches —
    :class:`Ring` constructors only VALIDATE the header (a stamp at
    construction time could clobber the peer consumer's counters)."""
    if arena.ring_slots <= 0:
        raise WireError("arena has no ring region (version-1 layout)")
    _RING_HEADER_STRUCT.pack_into(
        arena.mm,
        _RING_HEADER_OFFSET,
        0,  # produced
        0,  # consumed
        0,  # futex
        0,  # waiting
        1,  # epoch (0 = closed/never initialized)
        arena.ring_slots,
        arena.ring_record_bytes,
    )


class Ring:
    """One SPSC seqlock ring embedded in an arena mapping.  Exactly one
    ``role="producer"`` end and one ``role="consumer"`` end exist per
    ring (the submission ring's producer is the client, the completion
    ring's producer is the node).  Module docstring for the protocol;
    every corrupt observation raises :class:`~.npwire.WireError`."""

    def __init__(
        self,
        arena: Arena,
        *,
        role: str,
        chaos_point: Optional[str] = None,
        chaos_peer: Optional[str] = None,
    ) -> None:
        if role not in ("producer", "consumer"):
            raise ValueError(f"role must be producer/consumer, got {role!r}")
        if arena.ring_slots <= 0:
            raise WireError(
                "arena has no ring region (version-1 layout?) — "
                "ring transport needs Arena.create(ring_slots=...)"
            )
        self._arena = arena
        self._mm = arena.mm
        self._path = arena.path
        self.role = role
        self._chaos_point = chaos_point
        self._chaos_peer = chaos_peer
        try:
            (
                _produced, _consumed, _futex, _waiting,
                epoch, cap, rb,
            ) = _RING_HEADER_STRUCT.unpack_from(self._mm, _RING_HEADER_OFFSET)
        except struct.error as e:
            raise WireError(f"truncated ring header: {e}") from None
        if cap != arena.ring_slots or rb != arena.ring_record_bytes:
            raise WireError(
                f"ring header geometry {cap} x {rb} contradicts the "
                f"arena file header {arena.ring_slots} x "
                f"{arena.ring_record_bytes} — corrupt or foreign mapping"
            )
        if epoch == 0:
            raise WireError(
                "ring header epoch is 0 — never initialized or the "
                "producer already closed"
            )
        self.slots = cap
        self.record_bytes = rb
        self.payload_cap = rb - _RECORD_HEADER_BYTES
        self._epoch = epoch
        #: Local position mirror: produced count (producer role) or
        #: consumed count (consumer role).  Rings are per-connection
        #: and both ends start at 0 — no resume protocol.
        self._pos = 0
        self._spin_budget = 100
        self._closed = False
        # Persistent ctypes view of the futex word for the syscall
        # (byref needs an addressable object).  This EXPORTS the mmap
        # buffer — close() releases it so the arena mapping can drop.
        self._c_futex: Optional[ctypes.c_uint32] = (
            ctypes.c_uint32.from_buffer(self._mm, _FUTEX_OFF)
            if futex_available()
            else None
        )

    # -- producer ----------------------------------------------------------

    def try_produce(self, frame: bytes) -> bool:
        """Write one frame into the ring (spanning records as needed)
        and wake a parked consumer.  Returns False — caller falls back
        to the doorbell — when the ring lacks space or the frame can
        never fit; never blocks."""
        if self._closed:
            raise WireError("ring closed")
        total = len(frame)
        if total == 0:
            raise WireError("empty ring frame")
        cap = self.payload_cap
        nrec = -(-total // cap)
        if nrec > self.slots:
            return False  # permanently too big: doorbell territory
        consumed = _U64.unpack_from(self._mm, _CONSUMED_OFF)[0]
        if self._pos + nrec - consumed > self.slots:
            return False  # full: transient, doorbell fallback
        fault = None
        if _fi.active_plan is not None and self._chaos_point is not None:
            fault = _fi.ring_record_fault(self._chaos_point, self._chaos_peer)
        mm = self._mm
        off_in = 0
        for i in range(nrec):
            pos = self._pos + i
            rec = _RING_RECORDS_OFFSET + (pos % self.slots) * self.record_bytes
            chunk = frame[off_in : off_in + cap]
            _U64.pack_into(mm, rec, 2 * pos + 1)  # mid-write stamp
            _LEN_STRUCT.pack_into(
                mm, rec + 8, total if i == 0 else len(chunk), 0
            )
            mm[rec + 16 : rec + 16 + len(chunk)] = chunk
            commit = 2 * pos + 2
            if i == nrec - 1:
                if fault == "torn_ring_word":
                    # Chaos: the last record stays mid-write forever —
                    # the consumer's bounded wait must classify it.
                    off_in += len(chunk)
                    continue
                if fault == "stale_generation":
                    commit = 2 * (pos + self.slots) + 2  # future lap
            _U64.pack_into(mm, rec, commit)
            off_in += len(chunk)
        self._pos += nrec
        _U64.pack_into(mm, _PRODUCED_OFF, self._pos)
        self._wake()
        return True

    def produce_blocking(
        self,
        frame: bytes,
        *,
        timeout_s: Optional[float] = None,
        closing: Optional[Callable[[], bool]] = None,
    ) -> None:
        """Produce, waiting (bounded poll) for ring space — the node's
        reply path, where the same-channel rule forbids a doorbell
        fallback.  Raises ``TimeoutError`` when the consumer never
        drains and ``WireError`` when the frame can never fit."""
        if len(frame) > self.payload_cap * self.slots:
            raise WireError(
                f"ring frame of {len(frame)} bytes exceeds the ring's "
                f"{self.payload_cap * self.slots}-byte capacity"
            )
        t_end = math.inf if timeout_s is None else time.monotonic() + timeout_s
        while not self.try_produce(frame):
            if closing is not None and closing():
                raise ConnectionError("ring closing")
            if time.monotonic() >= t_end:
                raise TimeoutError(
                    "ring full: consumer stopped draining the "
                    "completion ring"
                )
            time.sleep(_PRODUCE_POLL_S)

    def _wake(self) -> None:
        """Publish-then-wake: bump the futex word FIRST (a concurrent
        parker's value check then fails fast), issue the syscall only
        when the waiting word says someone is parked — the zero-syscall
        steady state."""
        if _fi.active_plan is not None:  # chaos seam: delayed wake
            _fi.ring_wake_fault("ring.wake", self._chaos_peer)
        mm = self._mm
        val = _U32S.unpack_from(mm, _FUTEX_OFF)[0]
        _U32S.pack_into(mm, _FUTEX_OFF, (val + 1) & 0xFFFFFFFF)
        if _U32S.unpack_from(mm, _WAITING_OFF)[0]:
            self._futex_wake()

    def _futex_wake(self) -> None:
        if self._c_futex is not None:
            lib = _get_libc()
            assert lib is not None
            _syscall_counts["futex_wake"] += 1
            lib.syscall(
                _FUTEX_NR, ctypes.byref(self._c_futex), _FUTEX_WAKE,
                0x7FFFFFFF, None, 0, 0,
            )
        else:
            _fallback_event(self._path, _FUTEX_OFF).set()

    # -- consumer ----------------------------------------------------------

    def recv(
        self,
        *,
        timeout_s: Optional[float] = None,
        peer_check: Optional[Callable[[], None]] = None,
        closing: Optional[Callable[[], bool]] = None,
    ) -> bytes:
        """Consume the next frame.  ``timeout_s=None`` waits
        indefinitely for a FRAME but still re-checks ``closing``, the
        epoch word, and ``peer_check`` every park slice — the unbounded
        posture is bounded-per-slice, so a dead peer is a classified
        ``ConnectionError`` within one slice, never a hang."""
        t_end = (
            math.inf if timeout_s is None else time.monotonic() + timeout_s
        )
        pos = self._pos
        self._wait_ready(pos, t_end, peer_check, closing, mid_span=False)
        mm = self._mm
        cap = self.payload_cap
        rec0 = _RING_RECORDS_OFFSET + (pos % self.slots) * self.record_bytes
        total, _reserved = _LEN_STRUCT.unpack_from(mm, rec0 + 8)
        if total == 0 or total > cap * self.slots:
            raise WireError(
                f"ring frame length {total} out of bounds "
                f"(ring holds at most {cap * self.slots})"
            )
        nrec = -(-total // cap)
        out = bytearray(total)
        off_out = 0
        for i in range(nrec):
            p = pos + i
            if i:
                # Continuations commit after record 0 was observed
                # ready: bounded wait — a producer dying mid-span is a
                # loud TimeoutError, not a hang.
                self._wait_ready(p, t_end, peer_check, closing, mid_span=True)
            rec = _RING_RECORDS_OFFSET + (p % self.slots) * self.record_bytes
            want = min(cap, total - off_out)
            if i:
                clen, _r = _LEN_STRUCT.unpack_from(mm, rec + 8)
                if clen != want:
                    raise WireError(
                        f"ring span continuation {i} declares {clen} "
                        f"bytes, expected {want}"
                    )
            out[off_out : off_out + want] = mm[rec + 16 : rec + 16 + want]
            seq = _U64.unpack_from(mm, rec)[0]
            if seq != 2 * p + 2:
                raise WireError(
                    f"ring record {p} recycled mid-copy (seq {seq})"
                )
            off_out += want
        self._pos = pos + nrec
        _U64.pack_into(mm, _CONSUMED_OFF, self._pos)
        return bytes(out)

    def _wait_ready(
        self,
        pos: int,
        t_end: float,
        peer_check: Optional[Callable[[], None]],
        closing: Optional[Callable[[], bool]],
        *,
        mid_span: bool,
    ) -> None:
        """Adaptive spin-then-park until record ``pos`` commits.  The
        spin budget grows (+8, cap 200) on spin hits and halves per
        park, so a same-core pair (this container has ONE core — a
        spinning consumer starves its producer) decays toward
        park-first while a true two-core pair stays in the spin-hit
        zero-syscall regime."""
        mm = self._mm
        rec = _RING_RECORDS_OFFSET + (pos % self.slots) * self.record_bytes
        want = 2 * pos + 2
        spin = self._spin_budget
        parked = False
        while True:
            seq = _U64.unpack_from(mm, rec)[0]
            if seq == want:
                if not parked and spin < self._spin_budget:
                    # The record committed WHILE we spun: spinning pays
                    # on this topology (a true second core) — grow.  A
                    # hit after a park means the peer needed our core
                    # (1-core/GIL lock-step): the halving below stands,
                    # decaying toward park-first with zero GIL burn.
                    self._spin_budget = min(self._spin_budget + 8, 200)
                return
            self._check_seq(seq, pos)
            produced = _U64.unpack_from(mm, _PRODUCED_OFF)[0]
            if produced > pos:
                # The producer publishes ``produced`` strictly AFTER
                # committing every record stamp it covers, so a
                # published-but-uncommitted record cannot be a slow
                # producer — it is a torn or scribbled seqlock word.
                # Re-read once: the commit may have landed between our
                # two loads (stamp first, counter second is the only
                # benign interleaving).
                if _U64.unpack_from(mm, rec)[0] == want:
                    continue
                raise WireError(
                    f"ring record {pos} published (produced={produced}) "
                    f"but its seqlock word reads {seq} — torn write "
                    "never committed"
                )
            if closing is not None and closing():
                raise ConnectionError("ring closing")
            epoch = _U32S.unpack_from(mm, _EPOCH_OFF)[0]
            if epoch == 0:
                raise ConnectionError(
                    "ring peer closed (epoch zeroed)"
                )
            if epoch != self._epoch:
                raise WireError(
                    f"ring epoch changed {self._epoch} -> {epoch} — "
                    "foreign remap or reinitialized header"
                )
            now = time.monotonic()
            if now >= t_end:
                if mid_span:
                    raise TimeoutError(
                        f"ring frame torn mid-span: record {pos} never "
                        "committed within the deadline"
                    )
                raise TimeoutError("ring recv timed out")
            if spin > 0:
                spin -= 1
                continue
            self._park(rec, want, min(_PARK_SLICE_S, t_end - now))
            parked = True
            if peer_check is not None:
                peer_check()
            self._spin_budget //= 2

    def _check_seq(self, seq: int, pos: int) -> None:
        """Classify a not-ready sequence observation: legal values are
        0 on the first lap, and same-slot mid-write/committed stamps of
        earlier-or-current positions.  Everything else is loud."""
        want = 2 * pos + 2
        if seq > want:
            raise WireError(
                f"ring record {pos} recycled: seq {seq} is past the "
                f"expected {want} — wraparound reuse or a scribbled "
                "seqlock word"
            )
        if seq == 0:
            if pos < self.slots:
                return  # first lap: record never written yet
            raise WireError(
                f"ring record {pos} zeroed after the first lap"
            )
        q = (seq - 1) // 2 if seq % 2 else (seq - 2) // 2
        if q % self.slots != pos % self.slots:
            raise WireError(
                f"ring record {pos}: seq {seq} belongs to slot "
                f"{q % self.slots}, expected {pos % self.slots}"
            )

    def _park(self, rec: int, want: int, max_wait_s: float) -> None:
        """One bounded park on the futex word: read value, declare
        waiting, RE-check the record (the lost-wake guard: a producer
        that committed between our check and the wait bumped the word,
        so the wait returns immediately), wait at most one slice."""
        if max_wait_s <= 0:
            return
        mm = self._mm
        val = _U32S.unpack_from(mm, _FUTEX_OFF)[0]
        _U32S.pack_into(mm, _WAITING_OFF, 1)
        try:
            if _U64.unpack_from(mm, rec)[0] == want:
                return  # lost-wake guard: committed while we armed
            if _U32S.unpack_from(mm, _EPOCH_OFF)[0] != self._epoch:
                return  # peer closed/changed: outer loop classifies
            if self._c_futex is not None:
                self._futex_wait(val, max_wait_s)
            else:
                ev = _fallback_event(self._path, _FUTEX_OFF)
                ev.clear()
                if _U64.unpack_from(mm, rec)[0] == want:
                    return
                _syscall_counts["fallback_poll"] += 1
                # Cross-process peers never set our event: the wait
                # degrades to a bounded poll, still never a hang.
                ev.wait(min(max_wait_s, 0.002))
        finally:
            _U32S.pack_into(mm, _WAITING_OFF, 0)

    def _futex_wait(self, expected: int, timeout_s: float) -> None:
        lib = _get_libc()
        assert lib is not None and self._c_futex is not None
        sec = int(timeout_s)
        ts = _Timespec(sec, int((timeout_s - sec) * 1e9))
        _syscall_counts["futex_wait"] += 1
        # EAGAIN (value changed), ETIMEDOUT, EINTR are all benign —
        # the caller's loop re-reads the record either way.
        lib.syscall(
            _FUTEX_NR, ctypes.byref(self._c_futex), _FUTEX_WAIT,
            expected, ctypes.byref(ts), 0, 0,
        )

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Producer: zero the epoch word and wake the peer (a parked
        consumer classifies the departure as ``ConnectionError``
        immediately).  Both roles release the ctypes buffer export so
        the arena mapping can actually close."""
        if self._closed:
            return
        self._closed = True
        try:
            if self.role == "producer":
                _U32S.pack_into(self._mm, _EPOCH_OFF, 0)
                val = _U32S.unpack_from(self._mm, _FUTEX_OFF)[0]
                _U32S.pack_into(self._mm, _FUTEX_OFF, (val + 1) & 0xFFFFFFFF)
                self._futex_wake()
        except (ValueError, struct.error):
            pass  # mapping already gone
        finally:
            self._c_futex = None


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RingArraysClient(ShmArraysClient):
    """:class:`~.shm.ShmArraysClient` whose descriptor frames ride the
    arena rings instead of the TCP doorbell.  Full surface parity is
    inherited — evaluate, pipelined/batched ``evaluate_many``,
    ``evaluate_many_partial``, reduce windows, ``get_load`` (incl.
    ``b"telemetry"``), ``ping`` — because every frame funnels through
    ``_send``/``_read_frame``, which this class reroutes.

    Channel discipline: each sent frame is tagged with the channel it
    took ("ring", or "tcp" when the ring was full/absent), and replies
    are read from the SAME channel in send order — the node answers on
    the channel a request arrived on, and each channel is individually
    FIFO, so correlation survives mixed fallback traffic.  Attaching to
    a plain shm node degrades gracefully: no ring spec in ATTACH_OK
    means every frame takes the doorbell, behavior identical to the
    parent class."""

    def __init__(self, host: str, port: int, **kwargs: object) -> None:
        super().__init__(host, port, **kwargs)  # type: ignore[arg-type]
        self._sub_ring: Optional[Ring] = None  # we produce (requests)
        self._com_ring: Optional[Ring] = None  # we consume (replies)
        self._chan_tags: Deque[str] = deque()
        # Contiguous-floor ack state: the node's two dispatch lanes
        # (ring thread + doorbell loop) complete replies out of client
        # read order, so the parent's max-watermark ack could release
        # a reply slot we have not read yet.  We ack only the floor of
        # contiguously-seen generations — monotone, never early.
        self._gen_seen: Set[int] = set()
        self._gen_floor = 0

    # -- attach ------------------------------------------------------------

    def _attach(self) -> None:
        assert self._sock is not None
        uid = fast_uuid()
        want = json.dumps({"ring": 1}).encode("utf-8")
        self._send(
            encode_frame(_KIND_ATTACH, uid, _U32.pack(len(want)) + want)
        )
        kind, ruid, error, _tid, _dl, _part, _ver, off, frame = decode_frame(
            self._read_frame()
        )
        if error is not None:
            raise WireError(f"shm attach refused: {error}")
        if kind != _KIND_ATTACH_OK or ruid != uid:
            raise WireError("shm attach: unexpected reply")
        try:
            (jlen,) = _U32.unpack_from(frame, off)
            spec = json.loads(
                frame[off + 4 : off + 4 + jlen].decode("utf-8")
            )
            req_path, rep_path = spec["req"], spec["rep"]
        except (struct.error, ValueError, KeyError, UnicodeDecodeError) as e:
            raise WireError(f"corrupt shm attach reply: {e}") from None
        self._req_arena = Arena.attach(req_path, writer=True)
        self._rep_arena = Arena.attach(rep_path)
        self._consumed_gen = 0
        ring_spec = spec.get("ring")
        if ring_spec:
            # Header geometry is validated against the arena file
            # header by the Ring constructor — a mismatch is loud.
            self._sub_ring = Ring(
                self._req_arena, role="producer",
                chaos_point="ring.record", chaos_peer=self._peer,
            )
            self._com_ring = Ring(self._rep_arena, role="consumer")
            _flightrec.record(
                "ring.attach", peer=self._peer,
                slots=self._sub_ring.slots,
                record_bytes=self._sub_ring.record_bytes,
            )
        else:
            _flightrec.record(
                "ring.fallback", peer=self._peer, reason="no-ring-peer"
            )
        _flightrec.record(
            "shm.attach", peer=self._peer, req=req_path, rep=rep_path,
            size=self._req_arena.capacity,
        )

    # -- channel routing ---------------------------------------------------

    @staticmethod
    def _expects_reply(frame: bytes) -> bool:
        # Header layout: magic(4) version(1) kind(1) ... — ACK is the
        # only client frame with no reply; tagging it would desync the
        # per-channel FIFO correlation.
        return not (
            len(frame) >= 6 and frame[:4] == MAGIC and frame[5] == _KIND_ACK
        )

    def _send(self, frame: bytes) -> None:
        ring = self._sub_ring
        if ring is not None:
            out = frame
            if _fi.active_plan is not None:  # chaos seam
                out = _fi.filter_bytes("ring.send", out, self._peer)
            try:
                sent = ring.try_produce(out)
            except WireError:
                self.close()
                raise
            if sent:
                if self._expects_reply(frame):
                    self._chan_tags.append("ring")
                return
            _flightrec.record(
                "ring.fallback", peer=self._peer, reason="ring-full",
                bytes=len(frame),
            )
        super()._send(frame)
        if self._expects_reply(frame):
            self._chan_tags.append("tcp")

    def _read_frame(self) -> bytes:
        tag = self._chan_tags.popleft() if self._chan_tags else "tcp"
        if tag != "ring" or self._com_ring is None:
            return super()._read_frame()
        budget = _deadline.recv_budget_s(self.timeout_s)
        if budget is None:
            # Doorbell-lane parity: a plain-socket read with no ambient
            # deadline is still bounded by the connect-era socket
            # timeout; the ring wait must not be looser than that, or a
            # producer that dies torn parks this consumer forever.
            budget = self.connect_timeout_s
        try:
            buf = self._com_ring.recv(
                timeout_s=budget, peer_check=self._peer_dead_check
            )
        except (TimeoutError, WireError, ConnectionError):
            # Same posture as the doorbell's bounded_reader: the
            # channel is desynchronized — close so the next call
            # re-attaches cleanly; the error classification (transient
            # timeout / loud wire / dead peer) surfaces unchanged.
            self.close()
            raise
        if _fi.active_plan is not None:  # chaos seam
            buf = _fi.filter_bytes("ring.recv", buf, self._peer)
        return buf

    def _peer_dead_check(self) -> None:
        """Abrupt-death probe run once per park slice: a SIGKILL'd node
        never zeroes its epoch, but the kernel closes its doorbell
        socket — EOF there classifies the parked wait as a transient
        ``ConnectionError`` instead of a deadline-long stall."""
        s = self._sock
        if s is None:
            raise ConnectionError("ring: doorbell closed underneath")
        try:
            # Zero-timeout readability poll (MSG_DONTWAIT alone would
            # make a timeout-mode socket block its full timeout in
            # CPython's sock_call retry loop).
            readable, _, _ = select.select([s], [], [], 0)
            if not readable:
                return  # open and quiet: peer alive
            # graftlint: disable=fault-shim-coverage,unbounded-wait -- non-blocking liveness peek (select said readable), not a data seam
            data = s.recv(1, socket.MSG_PEEK)
        except OSError as e:
            raise ConnectionError(f"ring: doorbell dead: {e}") from None
        if data == b"":
            raise ConnectionError("ring: peer closed the doorbell (EOF)")
        # Buffered bytes = a tcp-channel reply for a later tagged read.

    # -- contiguous-floor acks --------------------------------------------

    def _decode_reply_arrays(
        self, descs: Sequence[tuple], *, force_copy: bool = False
    ):
        if self._com_ring is None:
            return super()._decode_reply_arrays(descs, force_copy=force_copy)
        before = self._consumed_gen
        out = super()._decode_reply_arrays(descs, force_copy=force_copy)
        # Replace the parent's max-watermark with the contiguous floor
        # (arena generations are dense: +1 per slot write), so a
        # later-generation reply read first never acks an unread
        # earlier one.  Worst case a never-seen generation stalls the
        # floor — loud arena exhaustion on the node, never corruption.
        for d in descs:
            if d[3] > self._gen_floor:
                self._gen_seen.add(d[3])
        while self._gen_floor + 1 in self._gen_seen:
            self._gen_floor += 1
            self._gen_seen.discard(self._gen_floor)
        self._consumed_gen = max(before, self._gen_floor)
        return out

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        sub, com = self._sub_ring, self._com_ring
        self._sub_ring = self._com_ring = None
        for r in (sub, com):
            if r is not None:
                try:
                    r.close()  # producer side zeroes epoch + wakes
                except Exception:
                    pass
        self._chan_tags.clear()
        self._gen_seen.clear()
        self._gen_floor = 0
        super().close()


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _RingConnection(_ShmConnection):
    """Server half of one ring-capable connection: the doorbell serve
    loop runs unchanged (attach channel, npwire pool probes, tcp
    fallback traffic), and a second thread consumes the submission
    ring.  Both lanes funnel through ``_one_frame`` under one dispatch
    lock — the arenas, reply watermark, and compute are single-writer.
    Replies go out on the channel their request arrived on."""

    _transport = "ring"

    def __init__(
        self,
        conn: socket.socket,
        compute_fn: Callable[..., Sequence[np.ndarray]],
        arena_bytes: int,
        n_connections: Callable[[], int],
        *,
        ring_slots: int = DEFAULT_RING_SLOTS,
        ring_record_bytes: int = DEFAULT_RING_RECORD_BYTES,
    ) -> None:
        super().__init__(conn, compute_fn, arena_bytes, n_connections)
        self._ring_slots = int(ring_slots)
        self._ring_record_bytes = int(ring_record_bytes)
        self._dispatch_lock = threading.Lock()
        self._sub_ring: Optional[Ring] = None  # we consume (requests)
        self._com_ring: Optional[Ring] = None  # we produce (replies)
        self._ring_thread: Optional[threading.Thread] = None
        self._closing = threading.Event()
        self._ring_wanted = False

    # -- attach negotiation ------------------------------------------------

    @staticmethod
    def _peek_ring_request(payload: bytes) -> bool:
        """Does this pre-attach ATTACH frame request a ring?  Manual
        flag-free header walk — a ``decode_frame`` call here would
        double-fire the chaos byte seams (the ``frame_tenant``
        precedent)."""
        if len(payload) < 28 or payload[5] != _KIND_ATTACH or payload[6]:
            return False
        try:
            (jlen,) = _U32.unpack_from(payload, 24)
            spec = json.loads(payload[28 : 28 + jlen].decode("utf-8"))
        except (struct.error, ValueError, UnicodeDecodeError):
            return False
        return bool(isinstance(spec, dict) and spec.get("ring"))

    def _one_frame(self, payload: bytes) -> Optional[bytes]:
        if (
            self.req_arena is None
            and len(payload) >= 6
            and payload[:4] == MAGIC
        ):
            self._ring_wanted = self._peek_ring_request(payload)
        with self._dispatch_lock:
            return super()._one_frame(payload)

    def _attach_reply(self, uid: bytes) -> bytes:
        if self.req_arena is not None or not self._ring_wanted:
            # Plain client (no ring spec in ATTACH) or re-attach:
            # graceful degradation to the parent's doorbell-only lane.
            return super()._attach_reply(uid)
        self.req_arena = Arena.create(
            self.arena_bytes, writer=False,
            ring_slots=self._ring_slots,
            ring_record_bytes=self._ring_record_bytes,
        )
        self.rep_arena = Arena.create(
            self.arena_bytes, writer=True,
            ring_slots=self._ring_slots,
            ring_record_bytes=self._ring_record_bytes,
        )
        # The creator stamps both headers BEFORE the peer can map them;
        # Ring constructors (both sides) only validate.
        init_ring_header(self.req_arena)
        init_ring_header(self.rep_arena)
        self._sub_ring = Ring(self.req_arena, role="consumer")
        self._com_ring = Ring(
            self.rep_arena, role="producer", chaos_point="ring.record"
        )
        self._ring_thread = threading.Thread(
            target=self._ring_loop, daemon=True, name="pftpu-ring-serve"
        )
        self._ring_thread.start()
        _flightrec.record(
            "ring.attach", slots=self._ring_slots,
            record_bytes=self._ring_record_bytes,
        )
        spec = json.dumps(
            {
                "req": self.req_arena.path,
                "rep": self.rep_arena.path,
                "size": self.req_arena.capacity,
                "arena_id": uuid_mod.uuid4().hex,
                "ring": {
                    "slots": self._ring_slots,
                    "record_bytes": self._ring_record_bytes,
                },
            }
        ).encode("utf-8")
        return encode_frame(
            _KIND_ATTACH_OK, uid, _U32.pack(len(spec)) + spec
        )

    # -- the ring lane -----------------------------------------------------

    def _ring_loop(self) -> None:
        sub, com = self._sub_ring, self._com_ring
        assert sub is not None and com is not None
        closing = self._closing.is_set
        try:
            while not closing():
                # graftlint: disable=unbounded-wait -- server idle state (tcp.py::_recv_exact parity); Ring.recv re-checks closing/epoch every park slice
                frame = sub.recv(closing=closing)
                if _fi.active_plan is not None:  # chaos seam
                    frame = _fi.filter_bytes("ring.server.recv", frame)
                try:
                    reply = self._one_frame(frame)
                except _fi.FaultPlanError:
                    raise  # plan-authoring bug: LOUD, not in-band
                except Exception as e:
                    # Undecodable ring frames fail THEIR reply in-band;
                    # the lane keeps serving (doorbell-loop parity).
                    _flightrec.record(
                        "server.error", stage="decode", wire="ring",
                        transport="shm", error=str(e)[:200],
                    )
                    reply = encode_frame(
                        _KIND_ERROR, b"\0" * 16, error=str(e)
                    )
                if reply is None:
                    continue  # ACK frames answer nothing
                if _fi.active_plan is not None:  # chaos seam
                    reply = _fi.filter_bytes("ring.server.send", reply)
                com.produce_blocking(
                    reply,
                    timeout_s=_REPLY_PRODUCE_TIMEOUT_S,
                    closing=closing,
                )
        except (ConnectionError, OSError):
            pass  # peer gone / closing: normal teardown
        except (WireError, TimeoutError) as e:
            # Ring-protocol integrity lost (torn/stale/recycled record,
            # undrained completion ring): LOUD, then tear the
            # connection down — the client reads EOF/epoch-0 and
            # classifies a transient.
            _flightrec.record("ring.server.error", error=str(e)[:200])
        finally:
            self._closing.set()
            try:
                # Kick the doorbell loop so the whole connection (and
                # its arenas) tears down with the ring lane.
                self.conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    # -- teardown ----------------------------------------------------------

    def serve(self) -> None:
        try:
            super().serve()
        finally:
            self._closing.set()
            if self._com_ring is not None:
                try:
                    self._com_ring.close()  # epoch 0 + wake: unpark peer
                except Exception:
                    pass
            if self._ring_thread is not None:
                self._ring_thread.join(timeout=2.0)
            if self._sub_ring is not None:
                try:
                    self._sub_ring.close()
                except Exception:
                    pass
            self._sub_ring = self._com_ring = None
            # The parent's finally closed the arenas while ring ctypes
            # exports kept the mappings alive (tolerated BufferError);
            # with the rings closed, close again to actually release.
            for arena in (self.req_arena, self.rep_arena):
                if arena is not None:
                    arena.close(unlink=not self._unlinked)


def serve_ring(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ready_callback: Optional[Callable[[int], None]] = None,
    max_connections: Optional[int] = None,
    arena_bytes: int = DEFAULT_ARENA_BYTES,
    concurrent: bool = True,
    ring_slots: int = DEFAULT_RING_SLOTS,
    ring_record_bytes: int = DEFAULT_RING_RECORD_BYTES,
) -> None:
    """Blocking ring-lane node: :func:`~.shm.serve_shm`'s accept loop
    with ring-capable connections.  Plain shm clients, npwire pool
    probes, and the pool's zero-item batch probe all work unchanged
    (the doorbell socket is still answered); ring clients negotiate
    the rings in their ATTACH frame.  Same compute contract as
    ``serve_shm`` (read-only zero-copy request views)."""

    def _make(
        conn: socket.socket,
        fn: Callable[..., Sequence[np.ndarray]],
        ab: int,
        nc: Callable[[], int],
    ) -> _ShmConnection:
        return _RingConnection(
            conn, fn, ab, nc,
            ring_slots=ring_slots, ring_record_bytes=ring_record_bytes,
        )

    serve_shm(
        compute_fn,
        host,
        port,
        ready_callback=ready_callback,
        max_connections=max_connections,
        arena_bytes=arena_bytes,
        concurrent=concurrent,
        _connection_cls=_make,
    )
